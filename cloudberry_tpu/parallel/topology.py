"""Online topology changes — epoch-versioned placement, background
rebalance, failover-as-shrink (the gpexpand + FTS-promotion pair, made
online).

The reference treats cluster resize (gpexpand) and mirror failover (FTS,
ftsprobe.c) as operations a SERVING cluster survives: membership changes
roll forward under versioned state while statements keep running. Until
now this engine's topology was one mutable value — ``config.n_segments``
— and ``mgmt expand`` was a stop-the-world rewrite. This module makes
the topology engine-wide VERSIONED STATE:

- ``TopologyEpoch``: an immutable (epoch_id, nseg, device_ids, reason)
  record. Every statement PINS the current epoch at dispatch
  (``TopologyManager.pin``) and runs to completion against it; an
  expand/shrink creates a SUCCESSOR epoch instead of mutating the mesh
  in place.

- background rebalance (``TopologyManager.rebalance``): jump-consistent
  placement guarantees only ≈ |new−old|/max(new,old) of rows change
  segment on a resize (cdbhash.c:55's minimal-movement promise), and the
  rebalancer moves EXACTLY that delta while statements keep serving on
  the old epoch. In-RAM tables stage the successor epoch's row
  assignment chunk-by-chunk (throttled; the ``topo_rebalance_chunk``
  fault seam fires per chunk); store-backed tables additionally move the
  delta rows PHYSICALLY — each affected micro-partition's moved rows are
  rewritten into destination-tagged delta partitions and delete-vectored
  out of their source file, one OCC-checked atomic manifest commit per
  chunk, with progress journaled to ``_TOPOLOGY.json`` so an interrupted
  rebalance resumes where it stopped instead of re-moving rows.

- cutover (``TopologyManager.cutover``): a breaker-guarded atomic flip.
  New statements briefly pin against the drain barrier, in-flight
  statements either finish on their pinned epoch (placement is DERIVED,
  so an old-epoch program stays correct to completion) or — when the
  flip raced a device loss — resume through the PR-6 degraded re-shard
  path (exec/recovery.py re-places checkpoints at any nseg). The flip
  swaps the session config (one shared derived Config per (epoch, base)
  so per-connection backends keep sharing compiled programs), clears
  every placement-derived cache, and moves the TOPOLOGY EPOCH TOKEN that
  all shared-cache-tier keys carry (sched/sharedcache.py) — a stale-nseg
  compiled program can never serve after cutover even if every other
  identity check aliases. The first few replans after a flip are
  verified by the planck gate regardless of ``config.debug.verify_plans``
  (``session._verify_next_plans``).

- failover-as-shrink: when probes see PERSISTENT device loss
  (``config.topology.promote_after`` consecutive observations of the
  same survivor set — the FTS mark-down decision), the per-statement
  degrade (session.degrade_mesh) is PROMOTED to a formal shrink epoch:
  flip first (the devices are already gone), re-align storage after.
  Device recovery triggers the symmetric online expand back to the
  pre-failover segment count (``recover_after`` consecutive clean
  probes). Both ride the ``topo_promote`` seam.

Cross-process: a store-backed cluster persists its current epoch (and
any in-progress rebalance journal) in ``_TOPOLOGY.json`` under the store
root. A serving process notices a CLI-driven ``mgmt expand --online``
at its next statement pin (mtime check) and adopts the new epoch — the
gp_segment_configuration role, versioned.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from cloudberry_tpu.utils.faultinject import fault_point


class TopologyError(RuntimeError):
    """A topology change could not proceed (pending change in flight,
    breaker open, target larger than the visible device pool)."""


class TopologyRaceError(RuntimeError):
    """The topology epoch flipped between a statement's PLAN and its
    EXECUTE: the plan's baked capacities no longer match the session's
    placement, and compiling it would trace a mixed-shape program (or
    worse, cache one). Raised instead; the session's epoch-race retry
    re-plans the statement at the new epoch (session.sql
    epoch_recoverable)."""


@dataclass(frozen=True)
class TopologyEpoch:
    """One immutable cluster-shape generation. ``device_ids`` restricts
    the mesh to specific devices (a failover shrink leaves a hole
    mid-list); None means the first ``nseg`` devices."""

    epoch_id: int
    nseg: int
    device_ids: Optional[tuple] = None
    reason: str = "initial"      # initial|expand|shrink|failover|recover
    created: float = field(default_factory=time.time)

    def public(self) -> dict:
        return {"epoch": self.epoch_id, "nseg": self.nseg,
                "device_ids": list(self.device_ids)
                if self.device_ids else None,
                "reason": self.reason, "created": self.created}


@dataclass
class RebalanceState:
    """Progress of one epoch transition's data movement. Mutated only by
    the (single) rebalance driver; readers take point-in-time snapshots
    through TopologyManager.snapshot() — fields are scalars/dicts whose
    torn reads can only be momentarily stale, never wrong."""

    target: TopologyEpoch
    old_nseg: int
    total_rows: int = 0          # rows examined (hashed tables)
    moved_rows: int = 0          # rows whose segment changed
    moved_bytes: int = 0         # bytes physically rewritten / restaged
    chunks: int = 0              # rebalance chunks committed
    tables_done: int = 0
    tables_total: int = 0
    done: bool = False
    # store-layer resume journal: table -> [processed partition files]
    done_files: dict = field(default_factory=dict)

    def fraction(self) -> float:
        if self.done:
            return 1.0
        if not self.tables_total:
            return 0.0
        return min(self.tables_done / self.tables_total, 0.995)

    def minimal_bound(self) -> float:
        """The jump-hash minimal-movement bound: the expected moved-row
        fraction for old→new segments is |new−old|/max(new, old)."""
        hi = max(self.old_nseg, self.target.nseg)
        return abs(self.target.nseg - self.old_nseg) / max(hi, 1)

    def public(self) -> dict:
        return {"target_epoch": self.target.epoch_id,
                "target_nseg": self.target.nseg,
                "old_nseg": self.old_nseg,
                "fraction": round(self.fraction(), 4),
                "moved_rows": int(self.moved_rows),
                "total_rows": int(self.total_rows),
                "moved_bytes": int(self.moved_bytes),
                "chunks": int(self.chunks),
                "minimal_bound": round(self.minimal_bound(), 4),
                "done": self.done}


def topology_token(session) -> int:
    """The session's current topology-epoch id — the cache-key component
    every shared-cache-tier entry carries (sched/sharedcache.py). 0 when
    the session predates the subsystem (tests building bare objects)."""
    mgr = getattr(session, "_topology", None)
    if mgr is None:
        return 0
    return mgr.current.epoch_id


def _available_devices() -> int:
    import jax

    try:
        return len(jax.devices())
    except Exception:  # noqa: BLE001 — runtime not initialized yet
        return 0


class TopologyManager:
    """Engine-wide versioned topology for one session tree (a server's
    per-connection backends share the serving session's manager, like
    the breaker and the recovery store)."""

    def __init__(self, session):
        self._session = session          # the owning (serving) session
        self._lock = threading.Lock()
        cfg = session.config
        self.current = TopologyEpoch(1, cfg.n_segments,
                                     reason="initial")
        self.pending: Optional[TopologyEpoch] = None
        self.rebalance_state: Optional[RebalanceState] = None
        self.history: list[dict] = [self.current.public()]
        self.flips = 0
        self.promotions = 0
        # statements currently pinned, per epoch id (the cutover drain
        # barrier reads it)
        self._active: dict[int, int] = {}
        # quiesce gate: a planned cutover CLEARS it so new statements
        # wait at pin (bounded) while the in-flight tail drains — under
        # closed-loop load the old epoch's pin count would otherwise
        # never reach zero. Set = open (the steady state).
        self._flip_gate = threading.Event()
        self._flip_gate.set()
        # persistent-loss / recovery streak detectors (failover-as-shrink)
        self._loss_streak = 0
        self._loss_seen: Optional[tuple] = None
        self._recover_streak = 0
        self._pre_failover: Optional[int] = None
        # one derived Config per (epoch, base-config): per-connection
        # backends built from one base object keep SHARING a config
        # object after adoption, so config-identity cache guards keep
        # working across backends post-cutover
        self._epoch_cfgs: dict[tuple, object] = {}
        # store-file sync state (cross-process adoption)
        self._store_mtime = 0.0
        self._store_epoch_seen = 0
        if session.store is not None:
            self._sync_from_store(session.store, adopt=False)

    # ------------------------------------------------------------ pinning

    def pin(self, session) -> TopologyEpoch:
        """Pin the current epoch for one statement at dispatch. Adopts
        the epoch into ``session`` first when the session is behind (a
        backend that missed a flip, or a cross-process change committed
        through the store journal)."""
        if session.store is not None:
            self._sync_from_store(session.store)
        if not self._flip_gate.is_set():
            # a cutover is quiescing: wait for the flip (bounded — the
            # flip itself is bounded by cutover_wait_s) so this
            # statement pins the NEW epoch instead of extending the old
            # epoch's drain tail forever under closed-loop load
            self._flip_gate.wait(
                session.config.topology.cutover_wait_s + 1.0)
        with self._lock:
            ep = self.current
            self._active[ep.epoch_id] = self._active.get(ep.epoch_id,
                                                         0) + 1
        try:
            self._adopt(session, ep)
        except BaseException:
            self.unpin(ep)
            raise
        return ep

    def unpin(self, epoch: TopologyEpoch) -> None:
        with self._lock:
            n = self._active.get(epoch.epoch_id, 0) - 1
            if n > 0:
                self._active[epoch.epoch_id] = n
            else:
                self._active.pop(epoch.epoch_id, None)

    def active_on(self, epoch_id: int) -> int:
        with self._lock:
            return self._active.get(epoch_id, 0)

    def epoch_config(self, session, epoch: TopologyEpoch):
        """The (memoized) Config a session runs under at ``epoch``:
        derived once per (epoch, base config object) so every backend
        sharing a base shares the derived object too."""
        from cloudberry_tpu.sched import sharedcache

        base = session.config
        if base.n_segments == epoch.nseg:
            return base
        key = (epoch.epoch_id, sharedcache.config_uid(base))
        with self._lock:
            cfg = self._epoch_cfgs.get(key)
            if cfg is None:
                cfg = base.with_overrides(n_segments=epoch.nseg)
                self._epoch_cfgs[key] = cfg
                while len(self._epoch_cfgs) > 32:
                    self._epoch_cfgs.pop(next(iter(self._epoch_cfgs)))
            return cfg

    def _adopt(self, session, epoch: TopologyEpoch) -> bool:
        """Bring ``session`` onto ``epoch``: swap the config, install
        the epoch's device restriction, and drop every placement-derived
        cache. Idempotent; sessions already current return fast without
        taking the sync lock."""
        ids = list(epoch.device_ids) if epoch.device_ids else None
        if (getattr(session, "_topo_epoch_seen", None) or 0) \
                > epoch.epoch_id:
            # staleness guard: a delayed adoption racing a newer mint
            # (cascading 8→7→6 losses on two threads) must never swap
            # an OLDER epoch's config over the newer one
            return False
        if getattr(session, "_topo_epoch_seen", None) == epoch.epoch_id \
                and session.config.n_segments == epoch.nseg \
                and getattr(session, "_live_device_ids", None) == ids:
            return False
        cfg = self.epoch_config(session, epoch)
        with session._sync_lock:
            seen = getattr(session, "_topo_epoch_seen", None) or 0
            if seen > epoch.epoch_id:
                # staleness re-check UNDER the lock: the pre-lock check
                # races a concurrent newer adoption (TOCTOU) — an older
                # epoch's config must never overwrite a newer one
                return False
            if seen == epoch.epoch_id \
                    and session.config.n_segments == epoch.nseg:
                return False
            # placement unchanged (a fresh session's first pin, or an
            # epoch formalizing a degrade the session already applied):
            # stamp the epoch WITHOUT invalidating anything — clearing
            # the SHARED cache tier on every new backend would evict
            # every tenant's compiled programs for nothing
            if session.config.n_segments == epoch.nseg \
                    and getattr(session, "_live_device_ids", None) == ids:
                session._topo_epoch_seen = epoch.epoch_id
                return False
            if session.config is not cfg:
                session.config = cfg
            session._live_device_ids = ids
            session._shard_cache.clear()
            session._shard_count_cache.clear()
            session._store_scan_cache.clear()
            # HBM buffer pool: stale-epoch keys could never serve (the
            # epoch token is in every key), but the resident bytes are
            # placement-era garbage — free them with the rest of the
            # placement-derived caches (legal order: rank-1 sync lock
            # held, the pool lock is a rank-4 leaf)
            bufpool = getattr(
                getattr(session, "_cache_scope", None),
                "bufferpool", None)
            if bufpool is not None:
                bufpool.clear()
            with session._stmt_lock:
                session._stmt_cache.clear()
            with session._rung_lock:
                session._rung_cache.clear()
            with session._generic_lock:
                session._generic_cache.clear()
            # staged rebalance assignments for OTHER segment counts are
            # dead weight now (4 bytes/row per hashed table) — only the
            # stage matching this epoch stays, as the re-hash-skipping
            # cache it was built to be
            for t in session.catalog.tables.values():
                staged = getattr(t, "_topo_assign", None)
                if staged is not None and staged[1] != epoch.nseg:
                    t._topo_assign = None
            # stamped LAST: the pin fast path reads it without the sync
            # lock, and a stamp published before the cache clears could
            # let a racing pin skip adoption while stale entries remain
            session._topo_epoch_seen = epoch.epoch_id
            # post-cutover replan verification: the next few fresh plans
            # run through the planck gate even when the session's debug
            # gate is off — a topology flip is exactly when a stale
            # sharding assumption would produce a silently wrong answer
            session._verify_next_plans = max(
                getattr(session, "_verify_next_plans", 0),
                session.config.topology.verify_replans)
        return True

    # ----------------------------------------------------- change control

    def begin(self, new_nseg: int, reason: Optional[str] = None,
              device_ids=None) -> RebalanceState:
        """Create the successor epoch (state: rebalancing). Statements
        keep pinning the CURRENT epoch until cutover()."""
        new_nseg = int(new_nseg)
        if new_nseg < 1:
            raise TopologyError(f"invalid segment count {new_nseg}")
        avail = _available_devices()
        # the device-pool check only applies when THIS process plausibly
        # hosts the mesh (it can cover the current topology): a
        # control-plane process (`mgmt expand --online` from a plain
        # shell) sees its own tiny device list, not the serving
        # cluster's — the serving process validates at adoption
        if device_ids is None and avail and avail >= self.current.nseg \
                and new_nseg > avail:
            raise TopologyError(
                f"cannot expand to {new_nseg} segments: only {avail} "
                "devices visible")
        with self._lock:
            if self.pending is not None:
                raise TopologyError(
                    f"topology change to {self.pending.nseg} segments "
                    "already in flight — cut it over or abandon() first")
            old = self.current
            if new_nseg == old.nseg and device_ids is None:
                raise TopologyError(
                    f"cluster already at {new_nseg} segments")
            if reason is None:
                reason = "expand" if new_nseg > old.nseg else "shrink"
            ep = TopologyEpoch(
                self._next_epoch_id(), new_nseg,
                tuple(device_ids) if device_ids else None, reason)
            self.pending = ep
            state = RebalanceState(ep, old.nseg)
            self.rebalance_state = state
        self._restore_journal(state)
        return state

    def abandon(self) -> None:
        """Drop an un-cutover pending epoch (operator bail-out). Already
        moved store rows stay where they are — placement is derived, so
        a partially rebalanced table is merely partially pre-aligned."""
        with self._lock:
            self.pending = None
            self.rebalance_state = None
        self._journal(None)

    def _next_epoch_id(self) -> int:
        # store-backed clusters take max(local, journal) so independent
        # processes never mint the same epoch id (call under self._lock)
        nxt = self.current.epoch_id + 1
        if self.pending is not None:
            # a degrade/failover minted while a planned resize is in
            # flight must not reuse the pending epoch's id — duplicate
            # tokens would let a stale-nseg program match post-cutover
            nxt = max(nxt, self.pending.epoch_id + 1)
        store = self._session.store
        if store is not None:
            rec = _read_topology(store)
            if rec and rec.get("current"):
                nxt = max(nxt, int(rec["current"].get("epoch", 0)) + 1)
        return nxt

    # --------------------------------------------------------- rebalance

    def rebalance(self, chunk_rows: Optional[int] = None,
                  throttle_s: Optional[float] = None,
                  progress=None) -> RebalanceState:
        """Move the minimal micro-partition delta for the pending epoch.
        Safe to call again after an interruption — the store journal (and
        idempotent RAM staging) resumes where the last run stopped."""
        state = self.rebalance_state
        if state is None:
            raise TopologyError("no topology change in flight")
        tcfg = self._session.config.topology
        chunk_rows = chunk_rows or tcfg.rebalance_chunk_rows
        throttle_s = tcfg.throttle_s if throttle_s is None else throttle_s
        session = self._session
        session._sync_store()
        tables = [t for t in session.catalog.tables.values()
                  if t.policy.kind == "hashed"]
        state.tables_total = len(tables)
        state.tables_done = 0
        for t in tables:
            if session.store is not None \
                    and getattr(t, "backing", None) is not None:
                self._rebalance_store_table(t.name, state, chunk_rows,
                                            throttle_s)
                # the moved snapshot re-registers cold at the next sync;
                # staged RAM assignments would be stale by construction
            else:
                self._rebalance_ram_table(t, state, chunk_rows,
                                          throttle_s)
            state.tables_done += 1
            self._journal(state)
            if progress is not None:
                progress(state)
        state.done = True
        self._journal(state)
        return state

    def _chunk_seam(self, state: RebalanceState,
                    throttle_s: float) -> None:
        fault_point("topo_rebalance_chunk")
        state.chunks += 1
        self._bump("topo_rebalance_chunks")
        if throttle_s > 0:
            time.sleep(throttle_s)

    def _rebalance_ram_table(self, t, state: RebalanceState,
                             chunk_rows: int, throttle_s: float) -> None:
        """Stage the successor epoch's row assignment for one in-RAM
        table, chunked over rows (the hash is the whole cost). The
        staged assignment rides the Table (catalog.shard_assignment's
        fast path) so cutover's first shard layout skips the re-hash."""
        from cloudberry_tpu.utils import hashing

        t.ensure_loaded()
        n = t.num_rows
        new_nseg, old_nseg = state.target.nseg, state.old_nseg
        version = getattr(t, "_version", 0)
        staged = getattr(t, "_topo_assign", None)
        if staged is not None and staged[0] == version \
                and staged[1] == new_nseg:
            return  # already staged by an interrupted earlier run
        new_assign = np.zeros(n, dtype=np.int32)
        cols = [np.asarray(t.data[k]) for k in t.policy.keys]
        moved = 0
        nbytes_row = sum(a.dtype.itemsize for a in t.data.values()) or 1
        for lo in range(0, max(n, 1), max(chunk_rows, 1)):
            hi = min(lo + chunk_rows, n)
            if hi <= lo:
                break
            h = hashing.hash_columns_np([c[lo:hi] for c in cols])
            a_old = hashing.jump_consistent_hash_np(h, old_nseg)
            a_new = hashing.jump_consistent_hash_np(h, new_nseg)
            new_assign[lo:hi] = a_new
            moved += int((a_old != a_new).sum())
            self._chunk_seam(state, throttle_s)
        t._topo_assign = (version, new_nseg, new_assign)
        state.total_rows += n
        state.moved_rows += moved
        state.moved_bytes += moved * nbytes_row
        self._bump("topo_moved_rows", moved)
        self._bump("topo_moved_bytes", moved * nbytes_row)

    def _rebalance_store_table(self, name: str, state: RebalanceState,
                               chunk_rows: int,
                               throttle_s: float) -> None:
        """Physically move one stored table's delta rows: per source
        micro-partition (the chunk unit), rows whose jump-hash segment
        changes are rewritten into destination-tagged delta partitions
        and delete-vectored out of the source — ONE atomic, OCC-checked
        manifest commit per chunk. Partitions already tagged for the
        target epoch, and files in the resume journal, are skipped."""
        store = self._session.store
        done = set(state.done_files.get(name, ()))
        attempts = 0
        while True:
            man = store.read_manifest(name)
            if man["schema"] is None:
                return
            pol = man.get("policy")
            if not pol or pol.get("kind") != "hashed":
                return
            todo = [p for p in man["partitions"]
                    if p["file"] not in done
                    and p.get("seg_nseg") != state.target.nseg]
            if not todo:
                break
            part = todo[0]
            ok, moved, mbytes, nrows = _move_partition_delta(
                store, name, man, part, tuple(pol["keys"]),
                state.old_nseg, state.target.nseg)
            if not ok:
                # OCC conflict: another session committed between our
                # manifest read and the locked commit — re-read and
                # retry (bounded; the conflicting commit made progress,
                # so livelock needs an adversarial writer)
                attempts += 1
                if attempts > 20:
                    raise TopologyError(
                        f"rebalance of {name!r} kept losing the OCC "
                        "race; aborting chunk loop")
                continue
            attempts = 0
            done.add(part["file"])
            state.done_files[name] = sorted(done)
            state.total_rows += nrows
            state.moved_rows += moved
            state.moved_bytes += mbytes
            self._bump("topo_moved_rows", moved)
            self._bump("topo_moved_bytes", mbytes)
            self._chunk_seam(state, throttle_s)
            self._journal(state)

    # ----------------------------------------------------------- cutover

    def cutover(self, wait_s: Optional[float] = None) -> dict:
        """The atomic flip to the pending epoch. Breaker-guarded: a
        planned resize refuses while the engine is read-only-degraded
        (resizing a flapping cluster compounds the outage) — failover
        promotion bypasses the guard, it IS the outage response. Waits
        up to ``wait_s`` for statements pinned to the old epoch to
        finish; stragglers keep running on their pinned epoch (derived
        placement keeps them correct) or resume through the degraded
        re-shard path if the mesh actually changed under them."""
        with self._lock:
            pending = self.pending
        if pending is None:
            raise TopologyError("no topology change in flight")
        breaker = getattr(self._session, "_breaker", None)
        if breaker is not None and pending.reason not in ("failover",) \
                and getattr(breaker, "state", "closed") == "open":
            raise TopologyError(
                "circuit breaker open (engine read-only-degraded): "
                "refusing planned cutover while the mesh is flapping")
        fault_point("topo_cutover")
        tcfg = self._session.config.topology
        wait_s = tcfg.cutover_wait_s if wait_s is None else wait_s
        t0 = time.monotonic()
        old_id = self.current.epoch_id
        deadline = t0 + max(wait_s, 0.0)
        if wait_s > 0:
            self._flip_gate.clear()  # quiesce: new pins wait on the flip
        try:
            while self.active_on(old_id) > 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.002)
            stragglers = self.active_on(old_id)
            with self._lock:
                if self.pending is not pending:
                    raise TopologyError(
                        "pending epoch changed under cutover")
                if self.current.epoch_id >= pending.epoch_id:
                    # a degrade/failover minted during the drain window
                    # moved the epoch line past the pending id: re-mint
                    # so the flip NEVER moves the epoch id backward —
                    # adoption's staleness guard would (correctly)
                    # refuse a regressed epoch and strand the session
                    pending = TopologyEpoch(
                        self.current.epoch_id + 1, pending.nseg,
                        pending.device_ids, pending.reason,
                        pending.created)
                    if self.rebalance_state is not None:
                        self.rebalance_state.target = pending
                self.current = pending
                self.pending = None
                state = self.rebalance_state
                self.rebalance_state = None
                self.flips += 1
                self.history.append(pending.public())
                del self.history[:-16]
                if pending.reason in ("expand", "shrink", "recover"):
                    # a planned resize (or completed recovery) is the
                    # new HEALTHY baseline: stale failover state must
                    # not later "recover" the cluster to a size the
                    # operator already resized away from
                    self._pre_failover = None
                    self._loss_streak = 0
                    self._loss_seen = None
                    self._recover_streak = 0
        finally:
            self._flip_gate.set()
        self._persist_current()
        self._adopt(self._session, pending)
        ms = (time.monotonic() - t0) * 1000.0
        self._bump("epoch_flips")
        self._bump("topo_cutover_ms", int(ms))
        out = {"epoch": pending.epoch_id, "nseg": pending.nseg,
               "reason": pending.reason, "cutover_ms": round(ms, 2),
               "stragglers": stragglers}
        if state is not None:
            out["rebalance"] = state.public()
        return out

    def online_resize(self, new_nseg: int, chunk_rows=None,
                      throttle_s=None, wait_s=None,
                      progress=None) -> dict:
        """begin → rebalance → cutover in one call (the serve_bench /
        CLI driver). Returns the cutover record with rebalance totals."""
        state = self.begin(new_nseg)
        self.rebalance(chunk_rows=chunk_rows, throttle_s=throttle_s,
                       progress=progress)
        out = self.cutover(wait_s=wait_s)
        out.setdefault("rebalance", state.public())
        return out

    # ------------------------------------------- failover / recovery path

    def note_degrade(self, n: int, live_ids) -> Optional[TopologyEpoch]:
        """A per-statement degrade (session.degrade_mesh) just changed
        the mesh: mint a 'degrade' epoch so the change is VERSIONED.
        Every placement swap must move the epoch token — a statement
        planning while the swap lands builds a mixed-shape plan, and
        the epoch-race retry (session.sql epoch_recoverable) can only
        classify the resulting error if the epoch actually moved.
        Called OUTSIDE degrade_mesh's sync lock."""
        ids = tuple(live_ids) if live_ids else None
        with self._lock:
            cur = self.current
            if cur.nseg == n and cur.device_ids == ids:
                return None
            if self._pre_failover is None:
                # the healthy size the recovery expand returns to —
                # captured at the FIRST degrade, before churn shrinks
                # current.nseg
                self._pre_failover = cur.nseg
            ep = TopologyEpoch(self._next_epoch_id(), n, ids, "degrade")
            self.current = ep
            self.flips += 1
            self.history.append(ep.public())
            del self.history[:-16]
        self._bump("epoch_flips")
        return ep

    def note_probe(self, r) -> Optional[dict]:
        """Consume one health-probe result (the FTS state-machine input,
        parallel/health.py). Persistent loss of the SAME survivor set
        promotes the per-statement degrade to a formal failover-shrink
        epoch; a persistent return to health triggers the symmetric
        online expand back to the pre-failover segment count."""
        live = list(getattr(r, "live", None) or [])
        n_live = len(live) if live else int(getattr(r, "n_devices", 0))
        cur = self.current
        tcfg = self._session.config.topology
        with self._lock:
            healthy = self._pre_failover \
                if self._pre_failover is not None else cur.nseg
        # fewer answering devices than the HEALTHY segment count IS a
        # loss observation, whatever the ok flag says: a clean probe of
        # the 7 survivors reports ok=True — the hole is the signal (and
        # degrade epochs already shrank cur.nseg, so compare against
        # the pre-degrade size)
        if n_live and n_live < healthy:
            key = (n_live, tuple(live))
            with self._lock:
                if self._loss_seen == key:
                    self._loss_streak += 1
                else:
                    self._loss_seen = key
                    self._loss_streak = 1
                self._recover_streak = 0
                streak = self._loss_streak
            already = cur.reason == "failover" and cur.nseg == n_live
            if streak >= max(tcfg.promote_after, 1) \
                    and self.pending is None and not already:
                # not-already-formalized covers the DEEPER second loss:
                # an 8→7 failover followed by another dead device must
                # promote again to 6, not sit behind the first epoch
                return self._promote_shrink(n_live, live)
            return None
        if getattr(r, "ok", False):
            with self._lock:
                self._loss_seen = None
                self._loss_streak = 0
                want = self._pre_failover
                if want is None \
                        or cur.reason not in ("failover", "degrade"):
                    self._recover_streak = 0
                    return None
                if n_live < want:
                    self._recover_streak = 0
                    return None
                self._recover_streak += 1
                streak = self._recover_streak
            breaker = getattr(self._session, "_breaker", None)
            if breaker is not None \
                    and getattr(breaker, "state", "closed") == "open":
                # the engine is read-only-degraded: expanding back into
                # a flap is premature — the streak stays, so the next
                # clean probe after the breaker closes retries
                return None
            if tcfg.auto_recover and streak >= max(tcfg.recover_after, 1) \
                    and self.pending is None:
                return self._promote_recover(min(want, n_live))
        return None

    def _promote_shrink(self, n_live: int, live: list) -> Optional[dict]:
        if fault_point("topo_promote"):
            return None
        with self._lock:
            if self.pending is not None:
                return None
            cur = self.current
            if self._pre_failover is None:
                self._pre_failover = cur.nseg
            ids = tuple(live[:n_live]) \
                if live and list(live[:n_live]) != list(range(n_live)) \
                else None
            self.pending = TopologyEpoch(self._next_epoch_id(), n_live,
                                         ids, "failover")
            self.rebalance_state = RebalanceState(self.pending, cur.nseg)
            self.rebalance_state.done = True  # flip first, realign later
            self._loss_streak = 0
        # the devices are GONE: flip without a drain wait — in-flight
        # statements on the old epoch are exactly the ones mid-recovery,
        # and the PR-6 degraded re-shard resumes them on the survivors
        return self._promote_cutover()

    def _promote_recover(self, n: int) -> Optional[dict]:
        if fault_point("topo_promote"):
            return None
        with self._lock:
            if self.pending is not None:
                return None
            cur = self.current
            self.pending = TopologyEpoch(self._next_epoch_id(), n,
                                         None, "recover")
            self.rebalance_state = RebalanceState(self.pending, cur.nseg)
            self.rebalance_state.done = True  # lazy re-derive on adopt
        return self._promote_cutover()

    def _promote_cutover(self) -> Optional[dict]:
        """Flip a promotion epoch, never letting a refusal escape into
        the probe path (a TopologyError would kill a HealthMonitor's
        probe thread, or replace the device-loss error an in-flight
        retry is classifying). Promotions count only on success."""
        try:
            out = self.cutover(wait_s=0.0)
        except TopologyError:
            self.abandon()
            return None
        with self._lock:
            self.promotions += 1
        self._bump("topo_promotions")
        return out

    def probe_and_heal(self) -> Optional[dict]:
        """One explicit probe→state-machine round (what a HealthMonitor
        interval does; CLI/tests call it directly)."""
        from cloudberry_tpu.parallel.health import probe

        return self.note_probe(probe())

    # ------------------------------------------------------- persistence

    def _sync_from_store(self, store, adopt: bool = True) -> None:
        """Adopt a newer CURRENT epoch committed by another process
        (mgmt expand --online against a serving cluster). Cheap: one
        mtime stat per call, full read only on change."""
        path = os.path.join(store.root, "_TOPOLOGY.json")
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            return
        with self._lock:
            if mtime == self._store_mtime:
                return
            self._store_mtime = mtime
        rec = _read_topology(store)
        cur = (rec or {}).get("current")
        if not cur:
            return
        with self._lock:
            fe = int(cur.get("epoch", 0))
            # the FILE epoch line is tracked separately from the local
            # one: device-local epochs (degrade/failover/recover) are
            # never persisted, so the local counter can outrun the
            # store's without hiding a later planned change
            if fe <= self._store_epoch_seen:
                return
            self._store_epoch_seen = fe
            if fe == self.current.epoch_id \
                    and int(cur["nseg"]) == self.current.nseg:
                return  # this manager's own persisted flip
            ids = cur.get("device_ids")
            self.current = TopologyEpoch(
                max(fe, self.current.epoch_id + 1), int(cur["nseg"]),
                tuple(ids) if ids else None,
                str(cur.get("reason", "expand")),
                float(cur.get("created", time.time())))
            self.flips += 1
            self.history.append(self.current.public())
            del self.history[:-16]
        self._bump("epoch_flips")
        if adopt:
            self._adopt(self._session, self.current)

    def _persist_current(self) -> None:
        store = self._session.store
        if store is None:
            return
        if self.current.reason in ("degrade", "failover", "recover"):
            # device-local epochs never persist: this PROCESS lost (or
            # regained) devices — another process over the same store
            # has its own device pool and must not adopt the shrink
            return
        with store.lock():
            rec = _read_topology(store) or {}
            old = rec.get("current") or {}
            if int(old.get("epoch", 0)) < self.current.epoch_id:
                rec["current"] = self.current.public()
            rec["pending"] = None
            _write_topology(store, rec)
        try:
            mtime = os.path.getmtime(
                os.path.join(store.root, "_TOPOLOGY.json"))
        except OSError:
            return
        with self._lock:
            self._store_mtime = mtime
            self._store_epoch_seen = max(self._store_epoch_seen,
                                         self.current.epoch_id)

    def _journal(self, state: Optional[RebalanceState]) -> None:
        """Persist the in-flight rebalance (resume journal). No-op for
        storeless sessions — RAM staging is idempotent anyway. The
        read-modify-write runs under the store lock: an unlocked update
        racing _persist_current (this process or another) could
        re-publish a stale 'current' epoch line over a committed flip."""
        store = self._session.store
        if store is None:
            return
        with store.lock():
            self._journal_locked(store, state)

    def _journal_locked(self, store, state) -> None:
        rec = _read_topology(store) or {}
        rec.setdefault("current", self.current.public())
        if state is None:
            rec["pending"] = None
        else:
            rec["pending"] = {
                "epoch": state.target.epoch_id,
                "nseg": state.target.nseg,
                "reason": state.target.reason,
                "old_nseg": state.old_nseg,
                "moved_rows": int(state.moved_rows),
                "moved_bytes": int(state.moved_bytes),
                "total_rows": int(state.total_rows),
                "chunks": int(state.chunks),
                "done_files": {k: list(v)
                               for k, v in state.done_files.items()},
                "done": state.done,
            }
        _write_topology(store, rec)

    def _restore_journal(self, state: RebalanceState) -> None:
        store = self._session.store
        if store is None:
            return
        rec = _read_topology(store) or {}
        pend = rec.get("pending")
        if not pend or int(pend.get("nseg", -1)) != state.target.nseg \
                or int(pend.get("old_nseg", -1)) != state.old_nseg:
            self._journal(state)
            return
        # resume: a prior run's movement is already on disk — keep its
        # totals and skip its processed files
        state.moved_rows = int(pend.get("moved_rows", 0))
        state.moved_bytes = int(pend.get("moved_bytes", 0))
        state.total_rows = int(pend.get("total_rows", 0))
        state.chunks = int(pend.get("chunks", 0))
        state.done_files = {k: list(v) for k, v in
                            (pend.get("done_files") or {}).items()}

    # ---------------------------------------------------- observability

    def _bump(self, name: str, k: int = 1) -> None:
        log = getattr(self._session, "stmt_log", None)
        if log is not None:
            log.bump(name, k)

    def snapshot(self) -> dict:
        with self._lock:
            cur = self.current
            pend = self.pending
            state = self.rebalance_state
            out = {
                "epoch": cur.epoch_id,
                "nseg": cur.nseg,
                "reason": cur.reason,
                "device_ids": list(cur.device_ids)
                if cur.device_ids else None,
                "pending": pend.public() if pend is not None else None,
                "rebalance": state.public() if state is not None else None,
                "flips": self.flips,
                "promotions": self.promotions,
                "active_statements": dict(self._active),
                "history": list(self.history[-8:]),
            }
        return out


# ------------------------------------------------------ store data mover


def _move_partition_delta(store, name: str, man: dict, part: dict,
                          keys: tuple, old_nseg: int, new_nseg: int):
    """Move one source partition's delta rows into destination-tagged
    partitions, committed atomically with the source's delete-vector
    extension. Returns (committed, moved_rows, moved_bytes, live_rows);
    committed=False signals an OCC conflict (caller re-reads and
    retries). Rows that keep their segment are NOT touched — the
    jump-hash minimal-movement contract, measured not assumed."""
    from cloudberry_tpu.columnar.dictionary import StringDictionary
    from cloudberry_tpu.storage import micropartition as mp
    from cloudberry_tpu.types import BOOL, Field as TField, Schema
    from cloudberry_tpu.utils import hashing

    tdir = os.path.join(store.root, name)
    path = os.path.join(tdir, part["file"])
    cols = mp.read_columns(path, cipher=store.cipher,
                           verify=getattr(store, "verify_checksums", True))
    n_file = part["num_rows"]
    live = np.ones(n_file, dtype=bool)
    if part["deleted"]:
        live[np.asarray(part["deleted"], dtype=np.int64)] = False
    h = hashing.hash_columns_np([np.asarray(cols[k]) for k in keys])
    a_old = hashing.jump_consistent_hash_np(h, old_nseg)
    a_new = hashing.jump_consistent_hash_np(h, new_nseg)
    moved_mask = live & (a_old != a_new)
    moved_idx = np.flatnonzero(moved_mask)
    n_live = int(live.sum())
    if not len(moved_idx):
        return True, 0, 0, n_live
    # physical schema of the file's columns (data fields from the
    # manifest schema, "$nn:" validity companions as BOOL)
    fields = {f.name: f for f in
              (mp._field_from_json(j) for j in man["schema"])}
    phys_fields = []
    for cname in cols:
        if cname in fields:
            phys_fields.append(fields[cname])
        elif cname.startswith("$nn:"):
            phys_fields.append(TField(cname, BOOL))
    phys_schema = Schema(tuple(phys_fields))
    dicts = {k: StringDictionary(v)
             for k, v in man.get("dicts", {}).items()}
    import uuid as _uuid

    new_entries = []
    moved_bytes = 0
    for dest in np.unique(a_new[moved_idx]):
        idx = moved_idx[a_new[moved_idx] == dest]
        chunk = {k: np.ascontiguousarray(v[idx])
                 for k, v in cols.items()}
        moved_bytes += sum(int(a.nbytes) for a in chunk.values())
        fname = f"part-{_uuid.uuid4().hex}.cbmp"
        footer = mp.write_micropartition(
            os.path.join(tdir, fname), chunk, phys_schema, dicts,
            cipher=store.cipher)
        stats = {c["name"]: [c["min"], c["max"]]
                 for c in footer["columns"] if "min" in c}
        entry = {"file": fname, "num_rows": int(len(idx)),
                 "stats": stats, "deleted": [],
                 "seg": int(dest), "seg_nseg": int(new_nseg)}
        if part.get("pkey") is not None:
            entry["pkey"] = part["pkey"]
        new_entries.append(entry)
    with store.lock():
        if store.current_version(name) != man["version"]:
            # OCC conflict: a concurrent commit owns the snapshot now —
            # drop our delta files, re-read, retry
            for e in new_entries:
                try:
                    os.unlink(os.path.join(tdir, e["file"]))
                except OSError:
                    pass
            return False, 0, 0, n_live
        for p in man["partitions"]:
            if p["file"] == part["file"]:
                dead = set(p["deleted"]) | set(moved_idx.tolist())
                p["deleted"] = sorted(int(i) for i in dead)
                break
        man["partitions"] = man["partitions"] + new_entries
        store._commit(name, man)
    return True, int(len(moved_idx)), int(moved_bytes), n_live


# --------------------------------------------------- store journal io


def _read_topology(store) -> Optional[dict]:
    try:
        with open(os.path.join(store.root, "_TOPOLOGY.json")) as f:
            return json.load(f)
    except (FileNotFoundError, ValueError):
        return None


def _write_topology(store, rec: dict) -> None:
    # its own seam on top of io_atomic_json: the torture matrix kills at
    # the topology record specifically (mid-expand/cutover crash)
    fault_point("io_topology_write")
    store._atomic_json(os.path.join(store.root, "_TOPOLOGY.json"), rec)
