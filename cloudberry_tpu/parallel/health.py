"""Failure detection — the FTS analog.

The reference's fault-tolerance service probes every segment postmaster on an
interval, runs a per-segment state machine, and promotes mirrors on failure
(src/backend/fts/fts.c:118, ftsprobe.c:60-95). Mesh slots have no mirrors —
recovery is re-execution (immutable storage makes segments stateless, SURVEY
§7.1) — so the analog is:

- ``probe()``: run a tiny collective across every device and report per-slot
  health (the FTS_MSG_PROBE analog);
- ``HealthMonitor``: background interval prober with status history and a
  failure callback (the bgworker loop);
- ``run_with_retry``: re-dispatch a failed query (device loss surfaces as an
  XLA error; the job-restart recovery model).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional


@dataclass
class ProbeResult:
    ok: bool
    n_devices: int            # LIVE device count (the degraded-mesh input)
    latency_s: float
    error: Optional[str] = None
    # indices (into jax.devices()) of the devices that answered — a real
    # loss leaves a hole in the MIDDLE of the list, so recovery must mesh
    # over these exact survivors, not devices[:n]
    live: Optional[list] = None


def probe(n_devices: Optional[int] = None) -> ProbeResult:
    """One health probe: a tiny reduction PER DEVICE, each failure
    isolated — one dead device must report the n−1 survivors, not a
    whole-probe failure (the per-segment state machine of ftsprobe.c)."""
    import jax
    import jax.numpy as jnp

    from cloudberry_tpu.utils.faultinject import fault_point

    t0 = time.time()
    try:
        devices = list(enumerate(jax.devices()))
    except Exception as e:  # noqa: BLE001 — runtime itself is gone
        return ProbeResult(False, 0, time.time() - t0, str(e), live=[])
    if n_devices is not None:
        devices = devices[:n_devices]
    if fault_point("probe_degraded"):
        # chaos seam: report one device lost ('skip' action) — on the
        # virtual CPU mesh no device can really die, so degraded-mesh
        # recovery is provoked deterministically (faultinjector.c role)
        devices = devices[:-1]
    live: list[int] = []
    errors: list[str] = []
    for i, d in devices:
        try:
            x = jax.device_put(jnp.ones((8,), dtype=jnp.float32), d)
            if float(jnp.sum(x)) == 8.0:
                live.append(i)
            else:
                errors.append(f"device {i}: bad probe sum")
        except Exception as e:  # noqa: BLE001 — this device is a finding
            errors.append(f"device {i}: {e}")
    ok = not errors
    return ProbeResult(ok, len(live), time.time() - t0,
                       "; ".join(errors) or None, live=live)


@dataclass
class HealthMonitor:
    """Interval prober (FtsProbeMain loop analog). ``history`` is a
    BOUNDED ring: a long-lived server probing on an interval must never
    grow its status log without bound. ``history_maxlen`` 0 (the
    default) reads config.health.monitor_history."""

    interval_s: float = 30.0
    on_failure: Optional[Callable[[ProbeResult], None]] = None
    history_maxlen: int = 0
    history: "object" = None
    # optional TopologyManager (parallel/topology.py): every probe
    # result — healthy or not — feeds its persistence detector, so a
    # monitored server promotes PERSISTENT device loss to an automatic
    # failover-shrink epoch and device recovery to the symmetric expand
    # back (the FTS probe → configuration-update loop, versioned)
    topology: Optional[object] = None
    _stop: threading.Event = field(default_factory=threading.Event)
    _thread: Optional[threading.Thread] = None

    def __post_init__(self):
        import collections

        if not self.history_maxlen:
            from cloudberry_tpu.config import get_config

            self.history_maxlen = get_config().health.monitor_history
        self.history = collections.deque(self.history or (),
                                         maxlen=self.history_maxlen)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()  # allow stop() → start() restarts

        def loop():
            while not self._stop.wait(self.interval_s):
                r = probe()
                self.history.append(r)
                if self.topology is not None:
                    self.topology.note_probe(r)
                if not r.ok and self.on_failure is not None:
                    self.on_failure(r)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="cb-fts-probe")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def probe_now(self) -> ProbeResult:
        r = probe()
        self.history.append(r)
        if self.topology is not None:
            self.topology.note_probe(r)
        if not r.ok and self.on_failure is not None:
            self.on_failure(r)
        return r


def recoverable(e: Exception) -> bool:
    """Failures worth a re-dispatch: device/runtime loss (XLA surfaces
    dead devices as runtime errors), never semantic errors (bind, OCC
    serialization, resource refusals). InjectedFault device-loss seams
    (names containing 'device_lost') count — that is how the virtual CPU
    mesh provokes a loss deterministically."""
    name = type(e).__name__
    if "XlaRuntimeError" in name or "JaxRuntimeError" in name:
        return True
    return "device_lost" in str(e)


def run_with_retry(fn: Callable, retries: int = 1,
                   backoff_s: float = 0.5,
                   on_retry: Optional[Callable] = None,
                   max_backoff_s: float = 5.0,
                   budget_s: float = 0.0,
                   jitter: float = 0.5,
                   recoverable_fn: Optional[Callable] = None) -> object:
    """Re-dispatch on device/runtime failure (the recovery model: stateless
    segments over immutable storage → failed statements simply re-run;
    mid-statement checkpoints make the re-run incremental,
    exec/recovery.py).

    - backoff between attempts is EXPONENTIAL with up to ``jitter``
      proportional randomization (a lost device fails every statement on
      it at once — synchronized retries would stampede the survivors),
      capped at ``max_backoff_s``;
    - ``budget_s`` is the per-statement retry budget: once that much
      wall clock has gone to failed attempts + backoff, the next
      recoverable failure raises instead of retrying (0 = no budget);
    - the backoff honors the statement lifecycle: it waits on the
      current statement's cancel token (interruptible — a cancel or
      watchdog timeout cuts it short), never sleeps past the deadline,
      and re-checks the deadline before dispatching the next attempt, so
      an in-progress recovery counts as LIVENESS while the DEADLINE
      stays enforced (lifecycle.py Watchdog contract);
    - ``on_retry(exc, backoff_s)`` runs between attempts — the Session
      passes its probe-and-degrade hook there (fts.c probe →
      configuration update) and surfaces both args in the activity row;
    - ``recoverable_fn`` overrides the re-dispatch classifier — the
      Session widens it for statements whose pinned topology epoch was
      cut over mid-flight (parallel/topology.py): a flip between plan
      and launch can surface as a shape error rather than device loss,
      and re-planning at the new epoch is exactly the recovery.
    """
    import random

    rec = recoverable if recoverable_fn is None else recoverable_fn
    t0 = time.monotonic()
    last: Exception | None = None
    for attempt in range(retries + 1):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001
            if not rec(e) or attempt == retries:
                raise
            if budget_s and time.monotonic() - t0 >= budget_s:
                raise
            last = e
            delay = min(backoff_s * (2 ** attempt)
                        * (1.0 + jitter * random.random()),
                        max_backoff_s)
            if on_retry is not None:
                on_retry(e, delay)
            from cloudberry_tpu.lifecycle import current_handle
            from cloudberry_tpu.obs import trace as OT

            h = current_handle()
            token = getattr(h, "token", None)
            # the recovery attempt + its backoff are spans on the
            # statement's trace: a recovery storm reads as exactly that
            # in the exported timeline, not as unexplained dead time
            with OT.span("recovery-backoff", attempt=attempt + 1,
                         error=type(e).__name__):
                if token is not None:
                    rem = h.remaining()
                    if rem is not None:
                        delay = min(delay, max(rem, 0.0))
                    if delay > 0:
                        token.wait(delay)
                    # raises StatementTimeout/StatementCancelled when
                    # the deadline passed (or a cancel landed) during
                    # the wait: the statement dies of its deadline, not
                    # as a "hang"
                    h.check()
                elif delay > 0:
                    time.sleep(delay)
    raise last  # unreachable
