from cloudberry_tpu.parallel.mesh import segment_mesh

__all__ = ["segment_mesh"]
