"""Statement lifecycle — cancellation, timeouts, watchdog, breaker.

The reference treats every statement as an interruptible unit:
``statement_timeout`` arms a SIGALRM, ``pg_cancel_backend()`` sets
QueryCancelPending, and executor nodes poll CHECK_FOR_INTERRUPTS() at row
boundaries (src/backend/tcop/postgres.c, miscadmin.h). An XLA program
cannot be interrupted mid-launch, so the poll points move to the HOST-SIDE
seams this engine already owns — the per-tile step loop, the adaptive
grow-and-retry loop, the OCC commit window, the dispatcher flush — which
bound how long a statement can run past its deadline by one device launch.

Pieces:

- a retryable-vs-semantic error taxonomy (``StatementError`` subclasses
  plus a name registry for the sched errors) shared by the server, which
  stamps every wire error with ``retryable``, and the client, which may
  auto-retry idempotent reads;
- ``CancelToken`` / ``StatementHandle``: one per statement, registered in
  the engine's StatementLog (the pg_stat_activity row), cancellable from
  any thread (the pg_cancel_backend analog);
- ``statement_scope`` / ``check_cancel``: a thread-local current-statement
  registry so deep execution seams poll without threading a handle through
  every signature (CHECK_FOR_INTERRUPTS reads a global for the same
  reason);
- ``Watchdog``: a background thread cancelling over-deadline statements —
  the asynchronous SIGALRM role; a statement wedged at a seam that only
  polls its token (the interruptible ``hang`` fault) still dies on time;
- ``CircuitBreaker``: admission breaker that trips to read-only-degraded
  after K consecutive device-loss recoveries and half-opens via health
  probes (the FTS "mark down and stop dispatching" decision, scoped to
  writes — reads stay safe to serve from a flapping mesh because
  re-execution cannot change state).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


# ------------------------------------------------------------- taxonomy


class StatementError(RuntimeError):
    """Base of the lifecycle taxonomy. ``retryable`` is the contract the
    serving layer exports on the wire: True means the failure is about
    WHEN the statement ran (load, shutdown, a flapping mesh), so an
    idempotent retry may succeed; False means it is about the statement
    itself (explicitly cancelled, semantically wrong)."""

    retryable = False


class StatementCancelled(StatementError):
    """Explicitly cancelled (the pg_cancel_backend analog) — semantic:
    retrying would defeat the cancel."""

    retryable = False


class StatementTimeout(StatementError):
    """Deadline/statement_timeout exceeded — transient (deadline
    pressure, a wedged seam): a retry under lighter load may fit."""

    retryable = True


class ServerDraining(StatementError):
    """The server refused or abandoned the statement because it is
    draining for shutdown — retry against the promoted standby."""

    retryable = True


class BreakerOpen(StatementError):
    """The admission circuit breaker is open (read-only-degraded):
    writes are refused until health probes close it."""

    retryable = True


class ServerBusy(StatementError):
    """The accept-path connection cap refused the connection (one
    SERVER_BUSY line, then close) — pure load shedding, retry after
    backoff. The server writes this refusal as a dict literal at accept
    time (no exception crosses the wire), but the class must EXIST so
    the by-name contract round-trips: the client retries the etype
    ``ServerBusy`` because this name is in the taxonomy, and graftlint's
    tax-name-unknown rule holds the registry to names that resolve."""

    retryable = True


class IngestQueueFull(StatementError):
    """The streaming ingest buffer for a (table, tenant) is at its
    ``config.ingest.max_buffered_rows`` cap — pure write backpressure,
    the SchedQueueFull analog for the append plane: back off and retry
    once a flush drains the buffer."""

    retryable = True


class StorageIOError(StatementError):
    """A storage write/read failed at the OS layer (ENOSPC, EIO, a torn
    or short write the shim surfaced) — about the ENVIRONMENT the
    statement ran in, not the statement: the commit protocol left the
    previous snapshot intact, so an idempotent retry may succeed once
    the device/space condition clears. Counted in ``storage_io_errors``
    (storage/iofault.py) and breaker-visible like every retryable
    refusal."""

    retryable = True


class StorageCorruptionError(StatementError):
    """Stored bytes failed their content checksum (or a container parsed
    as garbage) — semantic and sticky: retrying re-reads the same bad
    bytes. The read path raises this INSTEAD of returning a wrong
    answer; ``mgmt fsck`` finds the same file offline. The pg_checksums
    verdict class."""

    retryable = False


# errors raised OUTSIDE this module that belong to the retryable side:
# the dispatcher's backpressure/deadline pair (sched/dispatcher.py) and
# the per-tenant admission refusal (exec/resource.py TenantQueueFull)
# are about load and WHEN the statement ran, not the statement itself
_RETRYABLE_NAMES = frozenset({
    "StatementTimeout", "ServerDraining", "BreakerOpen",
    "SchedQueueFull", "SchedDeadline",
    "TenantQueueFull", "ServerBusy", "IngestQueueFull",
    "CompactionError", "StorageIOError",
})


def is_retryable(err) -> bool:
    """One classifier for server and client: accepts an exception or an
    etype name string."""
    if isinstance(err, BaseException):
        if isinstance(err, StatementError):
            return err.retryable
        err = type(err).__name__
    return str(err) in _RETRYABLE_NAMES


# ---------------------------------------------------------- cancel token


_REASON_EXC = {
    "cancelled": StatementCancelled,
    "timeout": StatementTimeout,
    "drain": ServerDraining,
}


class CancelToken:
    """One statement's cancellation flag, settable from any thread.
    First cancel wins; the recorded reason picks which taxonomy error
    the statement's own thread raises at its next poll point."""

    def __init__(self):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self.reason: Optional[str] = None
        self.message: Optional[str] = None

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def cancel(self, reason: str = "cancelled",
               message: Optional[str] = None) -> bool:
        """Request cancellation; returns True if this call was the first
        (later calls never overwrite the reason — the statement dies of
        whatever killed it first)."""
        with self._lock:
            if self._event.is_set():
                return False
            self.reason = reason
            self.message = message
            self._event.set()
            return True

    def raise_if_cancelled(self) -> None:
        if not self._event.is_set():
            return
        exc = _REASON_EXC.get(self.reason or "cancelled",
                              StatementCancelled)
        raise exc(self.message or f"statement {self.reason}")

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._event.wait(timeout)


class StatementHandle:
    """Identity + deadline + token for one executing statement — the
    per-backend PGPROC slot analog. ``deadline`` is a MONOTONIC absolute
    (time.monotonic()), or None for no limit."""

    def __init__(self, statement_id: int,
                 deadline: Optional[float] = None,
                 token: Optional[CancelToken] = None):
        self.statement_id = statement_id
        self.deadline = deadline
        self.token = token if token is not None else CancelToken()
        self.started = time.monotonic()
        # the statement's trace span collection (obs/trace.py), set by
        # whoever begins the statement; spans follow the handle across
        # threads exactly like cancellation does (obs.trace reads it via
        # current_handle())
        self.trace = None
        # the statement's live progress gauge (obs/progress.py), set by
        # whoever begins the statement when the telemetry plane is on;
        # the tiled executors' tile loops feed it through the same
        # thread-local scope channel
        self.progress = None

    def remaining(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def check(self) -> None:
        """The CHECK_FOR_INTERRUPTS analog: raise the taxonomy error when
        cancelled or past deadline. Crossing the deadline here records it
        on the token too, so every other seam (and the wire response)
        agrees on why the statement died."""
        self.token.raise_if_cancelled()
        if self.deadline is not None and time.monotonic() > self.deadline:
            self.token.cancel(
                "timeout",
                f"statement timed out after "
                f"{time.monotonic() - self.started:.2f}s "
                "(deadline/statement_timeout exceeded)")
            self.token.raise_if_cancelled()


# ------------------------------------------------- current-statement scope


class CompositeHandle:
    """Scope handle polling several member handles: the dispatcher's
    stacked batch executes as ONE launch under one scope, but every
    member keeps its own token/deadline — cancelling any member aborts
    the launch, and the dispatcher then re-routes the innocent
    batchmates through the sequential path."""

    def __init__(self, handles):
        self.handles = list(handles)
        # the batch head's trace records the stacked launch's spans (one
        # launch, many statements — attributing it to the head matches
        # how the compile counter attributes batch compiles)
        self.trace = next((h.trace for h in self.handles
                           if getattr(h, "trace", None) is not None),
                          None)
        # batched statements are stacked point reads — no tile loop, so
        # the composite scope carries no progress feed of its own (each
        # member's Progress still completes at its finish)
        self.progress = None

    def check(self) -> None:
        for h in self.handles:
            h.check()


_tls = threading.local()


class statement_scope:
    """Context manager installing ``handle`` as the thread's current
    statement. Nests (the dispatcher's batch scope around a sequential
    session.sql): inner statements shadow, exit restores."""

    def __init__(self, handle: StatementHandle):
        self._handle = handle

    def __enter__(self) -> StatementHandle:
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(self._handle)
        return self._handle

    def __exit__(self, *exc) -> bool:
        _tls.stack.pop()
        return False


def current_handle() -> Optional[StatementHandle]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def check_cancel() -> None:
    """Poll point for execution seams: no-op outside a statement scope
    (library callers without lifecycle management lose nothing), raises
    StatementCancelled/StatementTimeout/ServerDraining inside one."""
    h = current_handle()
    if h is not None:
        h.check()


# --------------------------------------------------------------- watchdog


class Watchdog:
    """Background canceller for over-deadline statements (the SIGALRM /
    statement_timeout enforcement role). Cooperative checks already raise
    at seams that compare the deadline; the watchdog covers statements
    wedged where only the TOKEN is polled (the interruptible ``hang``
    fault point, a blocking wait) and makes the timeout visible in the
    activity view (state flips to 'cancelling') while the serving thread
    survives to run the next statement."""

    def __init__(self, stmt_log, interval_s: float = 0.05):
        self.stmt_log = stmt_log
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="cbtpu-watchdog")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.scan()

    def scan(self) -> int:
        """One pass; returns how many statements it cancelled (exposed
        for deterministic tests)."""
        now = time.monotonic()
        n = 0
        for sid, handle in self.stmt_log.active_handles():
            if handle.deadline is None or now <= handle.deadline \
                    or handle.token.cancelled:
                continue
            if handle.token.cancel(
                    "timeout",
                    f"statement {sid} cancelled by watchdog "
                    f"{now - handle.started:.2f}s after start "
                    "(deadline exceeded)"):
                self.stmt_log.mark_cancelling(sid)
                self.stmt_log.bump("watchdog_timeouts")
                n += 1
        return n


# --------------------------------------------------------- circuit breaker


class CircuitBreaker:
    """Admission breaker over device-loss recoveries (the FTS
    mark-down decision as flow control): K CONSECUTIVE statements that
    needed a device-loss recovery trip it open — the mesh is flapping,
    and a write retried into a flap can neither be replayed (DML is
    never re-dispatched) nor trusted to commit. Open refuses WRITES with
    the retryable BreakerOpen (read-only-degraded: reads stay safe —
    re-execution cannot change state). After ``cooldown_s`` the next
    write HALF-OPENS: one health probe decides — a clean probe lets that
    write through, and its success closes the breaker; a dirty probe
    re-arms the cooldown."""

    def __init__(self, threshold: int = 3, cooldown_s: float = 30.0,
                 probe_fn: Optional[Callable] = None):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._probe_fn = probe_fn
        self._lock = threading.Lock()
        self.state = "closed"            # closed | open | half-open
        self.consecutive = 0
        self.trips = 0
        self._opened_at = 0.0

    def _probe(self):
        if self._probe_fn is not None:
            return self._probe_fn()
        from cloudberry_tpu.parallel.health import probe

        return probe()

    def record_recovery(self) -> None:
        """One statement needed a device-loss recovery — counted whether
        the statement ultimately succeeded or exhausted its retries (a
        hard outage must trip the breaker too, not just a flap mild
        enough for retries to win)."""
        with self._lock:
            self.consecutive += 1
            if self.state == "closed" and self.threshold \
                    and self.consecutive >= self.threshold:
                self.state = "open"
                self._opened_at = time.monotonic()
                self.trips += 1

    def record_success(self) -> None:
        """One statement completed without needing recovery. Resets the
        streak when closed; a half-open breaker is NOT closed here —
        only the trial write's own success closes it (a concurrent read
        succeeding proves nothing about writes on a flapping mesh)."""
        with self._lock:
            if self.state == "closed":
                self.consecutive = 0

    def check_write(self) -> bool:
        """Admission gate for a write statement. Returns True when this
        write is the half-open TRIAL: the caller owns the verdict and
        MUST report it back via trial_succeeded()/trial_failed()."""
        with self._lock:
            if self.state == "closed":
                return False
            if self.state == "half-open":
                # another write is mid-trial; stay degraded until it lands
                raise BreakerOpen(
                    "circuit breaker half-open: a trial write is in "
                    "flight; retry shortly")
            if time.monotonic() - self._opened_at < self.cooldown_s:
                raise BreakerOpen(
                    "circuit breaker open after "
                    f"{self.consecutive} consecutive device-loss "
                    "recoveries: engine is read-only-degraded; retry "
                    f"after the {self.cooldown_s:.0f}s cooldown")
            self.state = "half-open"
        # a RAISING probe counts as a failed one: the half-open slot
        # must always resolve (back open with a fresh cooldown), never
        # wedge waiting for a trial that no longer exists
        try:
            r = self._probe()
            detail = getattr(r, "error", None)
        except Exception as e:  # noqa: BLE001 — the probe IS the verdict
            r, detail = None, f"probe raised {type(e).__name__}: {e}"
        if getattr(r, "ok", False):
            return True  # this write is the trial
        with self._lock:
            self.state = "open"
            self._opened_at = time.monotonic()
        raise BreakerOpen(
            "circuit breaker: health probe failed during half-open "
            f"({detail}); staying read-only-degraded")

    def trial_succeeded(self) -> None:
        with self._lock:
            if self.state == "half-open":
                self.state = "closed"
                self.consecutive = 0

    def trial_failed(self) -> None:
        """The trial write failed for ANY reason (device loss, semantic
        error, cancellation): back to open with a fresh cooldown — the
        half-open slot must never wedge waiting for a verdict that
        already arrived."""
        with self._lock:
            if self.state == "half-open":
                self.state = "open"
                self._opened_at = time.monotonic()

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self.state,
                    "consecutive_recoveries": self.consecutive,
                    "trips": self.trips,
                    "threshold": self.threshold,
                    "cooldown_s": self.cooldown_s}
