"""Typed configuration tree — the GUC system analog.

The reference keeps ~6k lines of GUCs (``src/backend/utils/misc/guc_gp.c``,
e.g. ``gp_interconnect_type`` at :5124, ``enable_parallel`` at :3209) plus a
QD-vs-dispatched classification. Here configuration is a typed, immutable
dataclass tree; a session carries one, and ``with_overrides`` produces a
modified copy (the dispatch analog: the whole tree is part of the compiled
plan's static context, so every "segment" — mesh slot — sees the same values
by construction).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any


@dataclass(frozen=True)
class InterconnectConfig:
    """Motion transport knobs (reference: gp_interconnect_* GUCs,
    contrib/interconnect/ic_modules.c:26-160 vtable selection)."""

    # Per-destination bucket capacity for hash redistribute, as a multiple of
    # fair share (local_rows / n_segments). The moral equivalent of the UDP
    # interconnect's capacity-based flow control (ic_udpifc.c:3018-3040):
    # rows over capacity are detected and reported, not silently dropped.
    capacity_factor: float = 2.0
    # Motion transport (the ic_modules.c vtable selection): "xla" lets the
    # compiler schedule native collectives; "ring" composes them from
    # neighbor ppermutes (parallel/transport.py) — the ICI-friendly
    # systolic formulation, and an independent cross-check of the first.
    backend: str = "xla"
    # Packed wire format (exec/kernels.py wire_layout): every motion
    # bitcasts ALL its columns plus the row-validity mask into one
    # (rows, W) uint32 buffer, so gather/broadcast/redistribute each cost
    # exactly ONE collective instead of one per column. False falls back
    # to the per-column launches (the parity/debug path; results are
    # bit-identical either way — tests pin it).
    packed_wire: bool = True
    # Ring-transport software pipelining: split each all_to_all block into
    # this many slices, one ppermute per (hop, slice), so hop k's rotation
    # overlaps hop k-1's placement. 1 disables (whole-block hops).
    ring_chunks: int = 1
    # Topology-aware two-level motion (parallel/transport.py
    # HierarchicalCollectives): collectives split into an intra-host ICI
    # hop and ONE aggregated inter-host DCN hop, with rows re-bucketed by
    # destination host between them (results stay bit-identical to flat).
    # "auto" enables it on uniform multi-host meshes for motions whose
    # blocks clear hier_min_block_bytes; "on" forces it wherever the
    # topology allows; "off" keeps every motion flat. Single-host meshes
    # are ALWAYS flat — the gate never fires there.
    hierarchical: str = "auto"
    # auto-mode per-motion floor: a redistribute whose per-destination
    # block (bucket_cap x wire row bytes) is below this stays flat — the
    # extra intra-host launches would cost more than the DCN bytes saved.
    hier_min_block_bytes: int = 1 << 16


@dataclass(frozen=True)
class ExecConfig:
    """Executor shape/dtype discipline (XLA: static shapes only).

    Planned-but-unwired knobs live in docs/DESIGN.md's gap list, not here —
    every field below is read by the engine."""

    # Fused Pallas aggregation/join kernels (exec/pallas_kernels.py):
    # dense one-hot agg (int64/DECIMAL sums EXACT via 13-bit f32 limbs),
    # sorted-segment mid-cardinality agg (exact via 8-bit int32 limbs),
    # and the probe join. Off by default until re-measured on hardware;
    # bench.py BENCH_PALLAS=ab A/Bs per query and keeps the winner.
    use_pallas: bool = False


@dataclass(frozen=True)
class JoinFilterConfig:
    """Runtime join-filter digests + the join-index cache (the
    semijoin-reduction / runtime-filter-pushdown pair: ORCA's semijoin
    transforms, nodeRuntimeFilter.c's bloom mode).

    The EXACT runtime filter (planner.runtime_filter_threshold) all-gathers
    every packed build key and is preferred for small builds; the DIGEST
    filter here covers the builds too big for that: a fixed-size bloom
    bitmap plus packed-key min/max, broadcast as ONE tiny collective and
    applied to probe rows BEFORE their redistribute. Bloom false positives
    only let extra rows through — results stay bit-identical; min/max and
    the join itself remain exact."""

    # Digest (bloom + min/max) runtime filters on probe-side redistributes
    # whose estimated wire savings exceed the digest broadcast cost.
    enabled: bool = True
    # Bloom bitmap size in bits (rounded to a power of two ≥ 64). 2^18
    # bits = 32 KiB on the wire per segment — noise next to a typical
    # shuffle, sized for ~100k-key builds at k=3 probes.
    bloom_bits: int = 1 << 18
    # Hash probes per key (false-positive rate ≈ (1 - e^{-k·n/m})^k).
    bloom_k: int = 3
    # Join-index (sorted-build) cache entries per session: cached
    # (sort order, sorted packed keys, packing ranges) per build table
    # version — repeated statements skip the build-side argsort entirely.
    # 0 disables the cache.
    index_cache: int = 32


@dataclass(frozen=True)
class PlannerConfig:
    """Cost-model analog of cdbpath.c's motion choices."""

    # Broadcast the smaller join side instead of redistributing both when its
    # (estimated) row count is below this (reference: cdbpath_motion_for_join
    # cdbpath.c:1346 chooses broadcast vs redistribute by cost).
    broadcast_threshold: int = 100_000
    # Cascades-lite memo exploration (plan/memo.py, the gporca role): cost
    # and compare motion strategies over whole join trees — including the
    # GROUP BY's final redistribute — instead of deciding greedily per
    # join. Off falls back to the cdbpath.c-style rules alone.
    enable_memo: bool = True
    # sorted-sidecar point lookups for WHERE col = const on big RAM
    # tables (plan/pointlookup.py — the index/block-directory analog)
    enable_point_lookup: bool = True
    # Prune dispatch to a single segment for point predicates on the
    # distribution key (reference: cdbtargeteddispatch.c).
    enable_direct_dispatch: bool = True
    # Push a semi-join runtime filter below the probe's redistribute when
    # the estimated build side is at most this many rows (0 disables) —
    # the nodeRuntimeFilter.c analog, exact rather than bloom.
    runtime_filter_threshold: int = 1_000_000
    # Final grouped aggregation runs on ONE segment via gather when the
    # group capacity is at most this (the GATHER_SINGLE motion analog,
    # plannodes.h:1638): immune to hash-space skew across destinations,
    # and cheaper than an all_to_all for small partials. 0 disables.
    gather_single_threshold: int = 8192
    # Answer-query-using-matview rewrite (aqumv.c): SELECTs subsumed by a
    # FRESH aggregate materialized view read the view instead.
    enable_aqumv: bool = True
    # Auto-ANALYZE after DML (the gp_autostats_mode analog,
    # autostats.c:283): "none" | "on_no_stats" (first DML on an
    # unanalyzed table) | "on_change" (row count drifted more than
    # autostats_threshold since the last ANALYZE).
    autostats: str = "on_no_stats"
    autostats_threshold: float = 0.2


@dataclass(frozen=True)
class ScanPipelineConfig:
    """Asynchronous tiled-scan pipeline (exec/scanpipe.py) — the input-
    pipeline discipline of a training loop applied to the out-of-core
    scan path: a background reader stages the NEXT micro-partitions
    (read + decode + pad) into a bounded prefetch queue while the device
    computes the current tile, with the host→device transfer of tile
    k+1 double-buffered behind the dispatch of tile k. Results are
    bit-identical pipeline on/off (same tiles, same order — tests pin
    it); the knobs only move decode/pad/transfer off the critical
    path. Queue memory is charged into the statement's capacity
    estimate (obs/capacity.py record_tiled: prefetch_tiles × tile
    working set rides est_pipeline_bytes)."""

    enabled: bool = True
    # Tiles staged ahead of the consumer (the bounded queue depth). The
    # queue holds HOST numpy buffers; 1 still overlaps read/decode of
    # tile k+1 with compute of tile k.
    prefetch_tiles: int = 2
    # Reader-pool threads for column-parallel micro-partition decode
    # (zstd/zlib/dvarint release the GIL; each thread keeps its own
    # decompression context). <=1 decodes serially in the reader.
    decode_workers: int = 2
    # Double-buffered jax.device_put: the pipeline stages the next
    # host tile onto the device while the current tile's step program
    # is still dispatched (single-node tiled path; the distributed
    # path feeds shard_map directly and stages host-side only).
    device_buffer: bool = True


@dataclass(frozen=True)
class TilePipelineConfig:
    """Windowed in-flight tile dispatch (exec/tilepipe.py) — the
    device-side twin of the scan pipeline above: the tiled loops keep
    up to ``inflight_tiles`` step launches in flight and fetch each
    tile's overflow-check/skew-stat scalars via async copy, draining
    them up to W tiles late instead of synchronizing the accelerator
    after every step. A deferred failure (overflow, skew alarm, device
    loss) replays ≤ W+K tiles through the recovery checkpoint store —
    results are bit-identical window on/off by construction (tests pin
    it); the knob only moves when the host LEARNS of a failure. The
    extra in-flight tiles are charged into the statement's capacity
    estimate (tilepipe.window_charge_bytes → est_pipeline_bytes)."""

    enabled: bool = True
    # In-flight tile steps. 1 reproduces the legacy synchronous loop
    # EXACTLY (checks forced per tile). <= 0 means auto: 1 on the CPU
    # backend (nothing to overlap on a single-threaded host), 4 on
    # accelerators (TPU/GPU async dispatch).
    inflight_tiles: int = 0


@dataclass(frozen=True)
class BufferPoolConfig:
    """HBM-resident micro-partition buffer pool (exec/bufferpool.py) —
    the shared-buffer-pool analog with device residency: decoded, packed
    columnar partition chunks stay on-chip across statements, so a
    repeat scan of a hot table starts from HBM instead of paying
    read + decode + transfer again. Keys carry the store version, the
    topology epoch, and the config epoch (the shared-cache-tier token
    discipline, sched/sharedcache.py), so results are bit-identical
    pool on/off by construction and stale entries can never serve."""

    enabled: bool = True
    # Engine-wide resident budget in bytes (per cache scope — sessions
    # over the same store root share one pool). Admission refuses
    # oversize chunks and never evicts a hotter entry for a colder one
    # (the RecoveryStore byte-budget discipline). 0 disables.
    max_bytes: int = 256 << 20
    # Admission threshold: a partition is admitted once it has been
    # scanned this many times (observed per-partition frequency — the
    # obs-plane signal); 1 admits on first touch.
    admit_min_scans: int = 2


@dataclass(frozen=True)
class ResourceConfig:
    """Memory governance analog (vmem_tracker.c:94, workfile_mgr.c)."""

    # Per-segment device-memory budget for one query's intermediates (bytes).
    query_mem_bytes: int = 4 << 30
    # Admission: max concurrent statements (resgroup slot pool analog,
    # resgroup.c:135-171).
    max_concurrency: int = 8
    # Tiled out-of-core execution when a plan exceeds the budget (the
    # workfile-manager / spill analog, exec/tiled.py); off = hard refusal.
    enable_spill: bool = True
    # Engine-wide memory red line across CONCURRENT statements (the vmem
    # tracker / red-zone analog, redzone_handler.c): admissions reserve
    # their estimate against it; adaptive growth crossing it terminates
    # the growing statement (runaway_cleaner.c).
    total_mem_bytes: int = 16 << 30
    # The resource queue this session's statements run in (resqueue.c);
    # queues are created with CREATE RESOURCE QUEUE.
    queue: str = "default"


@dataclass(frozen=True)
class SchedConfig:
    """Statement scheduler — generic plans + the micro-batch dispatcher
    (sched/paramplan.py, sched/dispatcher.py; the plan_cache.c /
    gang-dispatch analog)."""

    # Parameterized generic plans: hoist constant literals out of repeated
    # statements so same-shape SQL shares ONE compiled XLA program with
    # literals fed as device inputs (zero recompiles after the first
    # execution of a statement shape). Plans that fold literals at plan
    # time (nextval, changed point-lookup row counts, literal-dependent
    # partition pruning) detect the fold via plan-signature mismatch and
    # keep today's compile-per-text path.
    generic_plans: bool = True
    # Continuous micro-batch dispatcher in front of the server's session:
    # coalesce same-skeleton statements per tick into one launch. Off by
    # default — the server (or tools/serve_bench.py) opts in.
    enabled: bool = False
    # Statements coalesced into one stacked launch per skeleton per tick.
    max_batch: int = 16
    # Bounded request queue (backpressure): submits beyond this block
    # briefly, then fail with SchedQueueFull — the admission-gate feed.
    max_queue: int = 256
    # Coalescing window: after the first request arrives, wait this long
    # for same-skeleton company before flushing.
    tick_s: float = 0.002
    # Default per-request deadline; expired requests fail without
    # executing (SchedDeadline).
    deadline_s: float = 30.0
    # Generic-plan variants kept per statement skeleton (distinct plan
    # shapes: capacity rungs, 0-vs-1 point matches, per-segment counts).
    max_variants: int = 4
    # Process-wide shared cache tier (sched/sharedcache.py): sessions over
    # the SAME durable store share one generic-plan / rung / join-index
    # cache scope, so tenant B re-binds tenant A's compiled skeleton with
    # zero recompiles. Invalidation rides the existing signature
    # discipline: store table VERSIONs key every entry and the config
    # object identity is the config epoch. False keeps every session's
    # caches private (the pre-tier behavior).
    shared_cache: bool = True


@dataclass(frozen=True)
class TenantSpec:
    """One declared workload tenant (the named-resource-group analog,
    extended from admission to throughput scheduling)."""

    name: str
    # Deficit-weighted-round-robin share: under saturation a tenant's
    # dispatch throughput is proportional to its weight.
    weight: int = 1
    # Concurrent statements of this tenant in flight (0 = unlimited).
    max_concurrency: int = 0
    # Bounded per-tenant request queue: submits beyond this depth refuse
    # with the retryable TenantQueueFull (backpressure, never silent).
    max_queue: int = 64


@dataclass(frozen=True)
class TenancyConfig:
    """Per-tenant workload governance (sched/tenancy.py): tenants are
    named resource groups picked in deficit-weighted-round-robin order
    inside the dispatcher tick, with starvation-free aging and per-tenant
    admission/backpressure — the CPU-share side of resource groups the
    admission-only queues (exec/resource.py) do not cover."""

    enabled: bool = False
    # Declared tenants; requests carrying an unknown (or no) tenant name
    # fall into an auto-created group with the defaults below.
    tenants: tuple = ()          # tuple[TenantSpec, ...]
    default_weight: int = 1
    default_max_queue: int = 256
    # DWRR quantum multiplier: each scheduling round a tenant's deficit
    # grows by weight * quantum requests.
    quantum: int = 1
    # Starvation bound: a request waiting longer than this is picked
    # ahead of deficit order (oldest first), so a starved tenant's tail
    # latency stays bounded no matter how heavy its neighbors are.
    aging_s: float = 0.5
    # Grace period a blocking submit waits for queue space / a
    # concurrency slot before refusing with TenantQueueFull.
    slot_wait_s: float = 0.25


@dataclass(frozen=True)
class ServeConfig:
    """Serving front end (serve/server.py + serve/asyncore.py).

    The default transport is the EVENT-LOOP core: a handful of I/O
    threads multiplex every connection through selectors with
    non-blocking newline-JSON framing, and parsed requests execute on a
    bounded worker pool (dispatcher-bound reads complete asynchronously,
    so a worker never blocks on a queued batch). ``threaded=True`` keeps
    the legacy thread-per-connection path."""

    # Legacy thread-per-connection transport (socketserver). The event
    # loop is the default: thousands of connections on io_threads.
    threaded: bool = False
    # Accepted-connection cap across the whole server (0 = unlimited):
    # past it, new connections get ONE retryable SERVER_BUSY refusal line
    # and close — bounded fds/threads instead of unbounded accept growth.
    max_connections: int = 4096
    # listen(2) backlog for the accept socket.
    listen_backlog: int = 512
    # Event-loop I/O threads; connections are sharded across them.
    io_threads: int = 2
    # Worker threads executing parsed requests (0 = auto:
    # max(4, resource.max_concurrency)).
    workers: int = 0
    # Per-connection pipelined-request cap: a client that streams
    # requests without reading responses is paused (its socket leaves
    # the read set) once this many parsed requests are pending.
    pipeline_depth: int = 64
    # Longest accepted request line in bytes: a client streaming bytes
    # with no newline would otherwise grow the framing buffer without
    # bound (the pipelining cap only sees COMPLETE lines). Oversized
    # lines get one fatal error response, then the connection closes.
    max_line_bytes: int = 64 << 20


@dataclass(frozen=True)
class StorageConfig:
    """Durable storage (PAX/AOCS analog, storage/table_store.py).

    With ``root`` set, the session's tables live in micro-partition files:
    DDL/DML persist through snapshot manifests, scans read only referenced
    columns from partitions that survive footer-stats pruning, and a fresh
    session on the same root sees every committed table."""

    root: str | None = None
    # Rows per micro-partition file — smaller means finer pruning
    # granularity, more files (the AO blocksize / PAX partition-size knob).
    rows_per_partition: int = 1 << 20
    # Dynamic partition elimination (nodePartitionSelector.c analog): when
    # an inner/semi join probes a PARTITION BY table on its partition
    # column and the build side is at most this many rows, the build side
    # runs host-side first and its key values prune probe partitions
    # before any fact-table IO. 0 disables.
    partition_selector_max_build: int = 1 << 17
    # Store-wide disk quota in bytes (the diskquota extension analog):
    # once on-disk usage reaches the quota, further writes are refused
    # (reads, deletes, and drops still work — the way out). 0 = unlimited.
    quota_bytes: int = 0
    # TDE cluster key (utils/tde.py): when set, micro-partition files and
    # manifests encrypt at rest (Fernet: AES-CBC + HMAC). Feed this from
    # a secret manager; None = plaintext storage.
    encryption_key: str | None = None
    # Verify column-blob content checksums at decode (pg_checksums
    # analog): a mismatch raises StorageCorruptionError instead of
    # decoding garbage into an answer. crc32 over the compressed blob —
    # cheap next to decompression; `mgmt fsck --deep` uses the same
    # checksums offline. Off only for benchmarking the overhead.
    verify_checksums: bool = True


@dataclass(frozen=True)
class RecoveryConfig:
    """Mid-statement fault recovery (exec/recovery.py).

    The tiled executors snapshot their compact carried state (agg
    partials / top-N heaps / sort-merge run stores — small by
    construction) to a host-side, statement-scoped checkpoint every
    ``checkpoint_every`` tiles. A device-loss retry resumes from the
    last snapshot — on the degraded survivor mesh when devices are gone
    — replaying at most ``checkpoint_every`` tiles instead of the whole
    stream (the immutable-storage analog of FTS + mirror promotion:
    checkpointed re-execution)."""

    enabled: bool = True
    # Tiles between snapshots (K): tiles_replayed after a loss is ≤ K.
    # Smaller = cheaper replay, more (tiny) host copies.
    checkpoint_every: int = 4
    # Statements whose checkpoints the store retains at once (LRU;
    # entries are discarded when their statement finishes anyway).
    max_statements: int = 8
    # Host bytes the checkpoint store may pin across ALL statements
    # (LRU by bytes; 0 = unbounded). Recovery is an optimization, so an
    # eviction only costs the victim a full replay on its next device
    # loss — counted as ``ckpt_evictions``, and the live pin total shows
    # as the ``mem_recovery_pins_bytes`` gauge (obs/capacity.py).
    max_bytes: int = 256 << 20


@dataclass(frozen=True)
class FeedbackConfig:
    """Feedback-driven re-optimization (plan/feedback.py).

    After every statement the motion stats the executors already psum
    (per-destination demand vectors, runtime-filter survivor counts)
    fold into per-(table, key-set) sketches keyed by the shared cache
    tier's content-stable tokens — DML version bumps, topology epoch
    flips, and relevant config swaps invalidate by construction. The
    planner consumes them three ways: the memo re-ranks join order /
    motion choice when an observed skew alarm contradicts the histogram,
    the distributor seeds capacity rungs at the observed demand rung
    (exact skew bounds stay the authoritative ceiling; overflow still
    promotes up the ladder), and long tiled statements replan
    MID-STATEMENT through the PR-6 checkpoint store when per-tile motion
    stats cross the skew alarm."""

    enabled: bool = True
    # Multiplier over observed per-destination demand when seeding a
    # rung (rung_up gives pow2 headroom on top); >1 absorbs tile-order
    # and bloom-false-positive jitter between executions.
    headroom: float = 1.25
    # Persist sketches alongside ANALYZE stats (store-backed sessions
    # only) so fresh sessions inherit them.
    persist: bool = True
    # Mid-statement adaptive replan for tiled statements. Needs
    # health.retries > 0 (the replan rides the statement retry loop).
    adaptive: bool = True
    # Per-tile cumulative skew ratio (max/mean destination rows) that
    # triggers the mid-statement replan; 0 = inherit obs.skew_ratio.
    replan_skew_ratio: float = 0.0
    # Tiles observed before the skew alarm may fire (one hot tile is
    # noise; a sustained hot destination is a plan problem).
    min_tiles: int = 2
    # Mid-statement replans allowed per statement (the retry loop must
    # terminate even if the replanned statement stays skewed).
    max_replans: int = 1


@dataclass(frozen=True)
class HealthConfig:
    """Failure detection / recovery knobs (the FTS analog, fts.c:118).

    Segments are stateless (placement is recomputed from shared storage),
    so recovery is re-execution rather than mirror promotion: a failed
    statement probes the devices and re-dispatches — on a shrunken mesh
    when devices are gone (degraded-mesh replanning, the n−1 payoff of
    derived placement)."""

    # Re-dispatches of a statement that failed with a device/runtime error.
    retries: int = 1
    # Probe every device before a retry (the FTS_MSG_PROBE analog).
    probe_on_error: bool = True
    # Shrink the segment mesh to the live device count before retrying.
    degrade: bool = True
    # First-retry backoff; attempt n waits backoff_s·2^n plus up to 50%
    # jitter (thundering-herd protection when many statements lose the
    # same device), capped at backoff_max_s. The wait is interruptible:
    # cancellation/deadline cut it short (lifecycle.py).
    backoff_s: float = 0.2
    backoff_max_s: float = 5.0
    # Per-statement retry budget in seconds: once this much wall clock
    # has gone to failed attempts + backoff, the next recoverable
    # failure is raised instead of retried. 0 = no budget (the
    # statement deadline still bounds everything).
    retry_budget_s: float = 0.0
    # Admission circuit breaker (lifecycle.CircuitBreaker): this many
    # CONSECUTIVE statements needing a device-loss recovery trip the
    # engine to read-only-degraded — writes refuse with the retryable
    # BreakerOpen until a health probe closes it. 0 disables.
    breaker_threshold: int = 3
    # Seconds the breaker stays open before a write may half-open it
    # (one health probe decides).
    breaker_cooldown_s: float = 30.0
    # HealthMonitor probe-history ring size (bounded: a long-lived server
    # probing on an interval must not leak).
    monitor_history: int = 256


@dataclass(frozen=True)
class TopologyConfig:
    """Online topology changes (parallel/topology.py): epoch-versioned
    placement, background minimal-movement rebalance, breaker-guarded
    cutover, and failover-as-shrink (the gpexpand + FTS-promotion pair
    made online). Statements pin a TopologyEpoch at dispatch; an
    expand/shrink creates a successor epoch and statements keep serving
    on the old one until cutover."""

    # Consecutive probe observations of the SAME survivor set before the
    # per-statement degrade is promoted to a formal failover-shrink
    # epoch (the FTS mark-down hysteresis; 1 = promote on first loss).
    promote_after: int = 2
    # Consecutive clean probes (devices back) before a failover-shrunk
    # cluster expands back to its pre-failover segment count.
    recover_after: int = 2
    # Automatic expand-back on device recovery (the symmetric half of
    # failover-as-shrink). Off leaves the shrunken epoch serving until
    # an operator resizes.
    auto_recover: bool = True
    # Seconds a planned cutover waits for statements pinned to the old
    # epoch to finish before flipping anyway (stragglers stay correct —
    # placement is derived — or resume through the degraded re-shard
    # path). Failover promotion never waits: the devices are gone.
    cutover_wait_s: float = 5.0
    # Rows hashed per rebalance chunk (the throttle/fault-seam unit for
    # in-RAM staging; store-backed tables chunk per micro-partition).
    rebalance_chunk_rows: int = 1 << 16
    # Sleep between rebalance chunks — the background-rebalance throttle
    # (a serving cluster's foreground traffic outranks the move).
    throttle_s: float = 0.0
    # Fresh plans verified by the planck gate (plan/verify.py) right
    # after an epoch adoption, even when config.debug.verify_plans is
    # off — a topology flip is exactly when a stale sharding assumption
    # would produce a silently wrong answer. 0 disables.
    verify_replans: int = 4


@dataclass(frozen=True)
class ObsConfig:
    """Observability plane (cloudberry_tpu/obs/): statement trace spans,
    the engine-wide metrics registry, and the pg_stat_statements-class
    aggregate table. ON by default — the budget is <3% on the TPC-H
    bench (bench.py's "obs" record measures it every run) and every
    ring/table below is explicitly bounded."""

    # Master switch for the OPTIONAL telemetry (trace spans, stage
    # histograms, per-skeleton aggregates). The counter registry itself
    # stays on — engine counters pre-date this subsystem and other
    # features read them.
    enabled: bool = True
    # Keep every Nth statement's span tree (1 = all). Sampling bounds
    # tracing cost under high QPS without losing the aggregate plane.
    trace_sample: int = 1
    # Completed traces retained in the server-wide ring (meta "trace").
    trace_ring: int = 64
    # Spans per statement trace; past it spans drop (counted).
    max_spans: int = 512
    # Skeleton rows in the pg_stat_statements analog (LRU dealloc).
    statements_max: int = 256
    # Slow-statement flight recorder (obs/flightrec.py): a statement
    # slower than this many milliseconds — or one that errors — captures
    # a bounded debug bundle (trace spans, plan, skeleton + param
    # fingerprint, counter deltas, config epoch, result digest) into the
    # engine-wide ring read by ``meta "flight"`` and replayed offline by
    # tools/flight_replay.py. 0 disables capture.
    slow_ms: float = 5000.0
    # Flight bundles retained engine-wide (ring; oldest drop).
    flight_ring: int = 16
    # Per-motion skew alarm (obs capacity plane): a redistribute whose
    # global rows-per-destination max/mean ratio reaches this bumps
    # ``skew_events`` and stamps the ratio on EXPLAIN ANALYZE's motion
    # annotation. 0 disables the counter (histograms still record).
    skew_ratio: float = 3.0


@dataclass(frozen=True)
class DebugConfig:
    """Engine self-checks (cost wall clock; default-on only in tests).

    ``verify_plans`` is the planck gate (plan/verify.py): every plan
    the planner or memo emits is verified — derived vs required
    distribution properties, capacity-rung discipline, param-slot and
    runtime-filter placement contracts — right before compile, and a
    finding raises PlanVerifyError instead of executing a plan whose
    sharding assumptions are wrong (a silently-wrong answer at 8
    segments). The memo/distributed/golden test suites run with it ON;
    measured overhead is a few percent of PLANNING time, so production
    sessions may enable it too when plan provenance matters more than
    the margin."""

    verify_plans: bool = False


@dataclass(frozen=True)
class IngestConfig:
    """Streaming ingest plane (storage/ingest.py): per-(table, tenant)
    buffers batching wire appends into micro-partition-sized commits —
    the AO-table small-write absorber. Durability is acknowledged only
    when the covering flush commits through the one SQL write path."""

    enabled: bool = True
    # Pending rows that trip an immediate (size-threshold) flush.
    flush_rows: int = 512
    # Oldest-pending-row age (milliseconds) that trips an age flush —
    # the commit-latency bound a trickle writer sees.
    flush_ms: float = 25.0
    # Per-buffer pending-row cap; past it append refuses with the
    # retryable IngestQueueFull (write backpressure, not data loss).
    max_buffered_rows: int = 8192


@dataclass(frozen=True)
class CompactConfig:
    """Background compaction service (storage/compact.py): the VACUUM
    analog for store-backed tables — merges delta partitions (including
    the rebalancer's destination-tagged ones), applies delete vectors,
    re-sorts toward the table's partition column, and re-packs toward
    rows_per_partition. OFF by default: a plain session/server pays
    nothing; the ingest-heavy deployment opts in."""

    enabled: bool = False
    # Seconds the worker sleeps between scans when nothing is due
    # (commits wake it immediately via IngestService.on_commit).
    interval_s: float = 2.0
    # Sleep between chunks — the background throttle (foreground reads
    # outrank the rewrite; the acceptance bench pins the QPS hold).
    throttle_s: float = 0.0
    # Source partitions merged per chunk (one OCC commit per chunk).
    chunk_partitions: int = 8
    # The bounded-delta invariant: a table whose delta-partition count
    # (dirty parts + mergeable small tails) exceeds this is compacted
    # back toward 0 (hysteresis: once triggered, drive to clean).
    max_delta_parts: int = 8
    # A clean partition counts as a mergeable small tail below
    # target_fill * storage.rows_per_partition live rows.
    target_fill: float = 0.5


@dataclass(frozen=True)
class Config:
    n_segments: int = 1
    # Per-statement wall-clock limit in seconds (the statement_timeout
    # GUC): every statement gets a deadline this far out; cooperative
    # checks at execution seams (and the server watchdog) convert an
    # overrun into the retryable StatementTimeout. 0 disables. A
    # per-request deadline (dispatcher deadline_s / wire "deadline_s")
    # tightens but never loosens this.
    statement_timeout_s: float = 0.0
    interconnect: InterconnectConfig = field(default_factory=InterconnectConfig)
    exec: ExecConfig = field(default_factory=ExecConfig)
    planner: PlannerConfig = field(default_factory=PlannerConfig)
    join_filter: JoinFilterConfig = field(default_factory=JoinFilterConfig)
    resource: ResourceConfig = field(default_factory=ResourceConfig)
    scan_pipeline: ScanPipelineConfig = field(
        default_factory=ScanPipelineConfig)
    tile_pipeline: TilePipelineConfig = field(
        default_factory=TilePipelineConfig)
    bufferpool: BufferPoolConfig = field(default_factory=BufferPoolConfig)
    sched: SchedConfig = field(default_factory=SchedConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    health: HealthConfig = field(default_factory=HealthConfig)
    recovery: RecoveryConfig = field(default_factory=RecoveryConfig)
    feedback: FeedbackConfig = field(default_factory=FeedbackConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    tenancy: TenancyConfig = field(default_factory=TenancyConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    topology: TopologyConfig = field(default_factory=TopologyConfig)
    ingest: IngestConfig = field(default_factory=IngestConfig)
    compact: CompactConfig = field(default_factory=CompactConfig)
    debug: DebugConfig = field(default_factory=DebugConfig)

    def with_overrides(self, **kv: Any) -> "Config":
        """Return a copy with dotted-path overrides, e.g.
        ``cfg.with_overrides(**{"exec.use_pallas": True})``."""
        out = self
        for path, value in kv.items():
            parts = path.split(".")
            out = _replace_path(out, parts, value)
        return out


def _replace_path(node: Any, parts: list[str], value: Any) -> Any:
    if len(parts) == 1:
        return dataclasses.replace(node, **{parts[0]: value})
    child = getattr(node, parts[0])
    return dataclasses.replace(node, **{parts[0]: _replace_path(child, parts[1:], value)})


_global_config = Config()


def get_config() -> Config:
    return _global_config


def set_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg
