"""Engine-wide metrics registry — the gpperfmon / pg_stat_* counter plane.

The reference ships statement and system counters through a dedicated
collector (query_info_collect_hook → metrics_collector, plus the
pg_stat_* views); here the analog is ONE in-process registry per engine
(it hangs off the shared StatementLog, so a server's backends all write
the same instance) holding three metric kinds:

- counters  — monotonically increasing ints (``bump``), optionally with
  a tenant label: the labeled series rides NEXT TO the unlabeled total,
  so ``counter(name)`` stays O(1) and per-tenant attribution is opt-in;
- gauges    — last-write-wins scalars (queue depth, ring occupancy);
- histograms — bounded log2-bucket distributions for latencies/bytes
  (``observe``): bucket i counts values in [2^(i-1), 2^i) microunits,
  so p50/p95/p99 come from ~40 ints per series with no sample storage.

Everything is explicitly bounded: past ``max_series`` distinct names the
registry drops new series and counts the drops on itself
(``obs_series_dropped``) — observability must never become the leak.

Snapshots ship over the wire via ``meta "metrics"`` (serve/meta.py) and
as a Prometheus-style text exposition (``exposition()``).
"""

from __future__ import annotations

import threading


# histogram bucket i holds values v with 2^(i-1) <= v/unit < 2^i; the
# unit is 1e-6 (microseconds / micro-units) so sub-millisecond latencies
# still resolve. 48 buckets cover up to ~2^47 µs — beyond any real value.
_HIST_BUCKETS = 48
_HIST_UNIT = 1e-6


def _bucket_of(value: float) -> int:
    v = int(value / _HIST_UNIT)
    if v <= 0:
        return 0
    return min(v.bit_length(), _HIST_BUCKETS - 1)


def bucket_upper(i: int) -> float:
    """Upper bound of bucket ``i`` in base units (seconds/bytes)."""
    return (1 << i) * _HIST_UNIT


class _Hist:
    __slots__ = ("counts", "n", "total")

    def __init__(self):
        self.counts = [0] * _HIST_BUCKETS
        self.n = 0
        self.total = 0.0

    def add(self, value: float) -> None:
        self.counts[_bucket_of(value)] += 1
        self.n += 1
        self.total += value

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile: the upper bound of the bucket the
        q-th sample lands in (conservative — never under-reports)."""
        if self.n == 0:
            return 0.0
        target = max(1, -int(-q * self.n // 1))  # ceil: p99 of 4 is #4
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target:
                return bucket_upper(i)
        return bucket_upper(_HIST_BUCKETS - 1)

    def snapshot(self) -> dict:
        # sparse bucket dict: most of the 48 buckets are empty
        return {
            "count": self.n,
            "sum": round(self.total, 6),
            "mean": round(self.total / self.n, 6) if self.n else 0.0,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
            "buckets": {i: c for i, c in enumerate(self.counts) if c},
        }


class MetricsRegistry:
    """Thread-safe, bounded metric store. The lock is a leaf: nothing is
    called while it is held (graftlint witness rank 4)."""

    def __init__(self, max_series: int = 4096):
        self.max_series = max_series
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        # (name, tenant) -> int: per-tenant attribution next to the total
        self._labeled: dict[tuple, int] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, _Hist] = {}
        self._dropped = 0

    # ------------------------------------------------------------ writes

    def _admit(self, table, key) -> bool:
        """Series-cardinality bound (callers hold the lock)."""
        if key in table or len(table) < self.max_series:
            return True
        self._dropped += 1
        return False

    def bump(self, name: str, n: int = 1, tenant: str | None = None) -> None:
        with self._lock:
            if self._admit(self._counters, name):
                self._counters[name] = self._counters.get(name, 0) + n
            if tenant is not None:
                key = (name, tenant)
                if self._admit(self._labeled, key):
                    self._labeled[key] = self._labeled.get(key, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            if self._admit(self._gauges, name):
                self._gauges[name] = float(value)

    def gauge_max(self, name: str, value: float) -> None:
        """High-water-mark gauge: keeps the max ever written (the
        peak-statement-memory gauge the capacity plane maintains).
        Atomic under the registry lock — concurrent writers cannot
        lose a peak to a read-modify-write race."""
        with self._lock:
            if self._admit(self._gauges, name):
                v = float(value)
                cur = self._gauges.get(name)
                if cur is None or v > cur:
                    self._gauges[name] = v

    def observe(self, name: str, value: float,
                tenant: str | None = None) -> None:
        """One histogram sample (seconds or bytes). The tenant label
        folds into the series name — per-tenant histograms are a
        cardinality product, so they ride the same series bound."""
        if tenant is not None:
            name = f"{name}{{tenant={tenant}}}"
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                if not self._admit(self._hists, name):
                    return
                h = self._hists[name] = _Hist()
            h.add(value)

    # ------------------------------------------------------------- reads

    def counter(self, name: str) -> int:
        with self._lock:
            return int(self._counters.get(name, 0))

    def counter_snapshot(self) -> dict:
        with self._lock:
            return {k: int(v) for k, v in sorted(self._counters.items())}

    def hist(self, name: str) -> dict | None:
        with self._lock:
            h = self._hists.get(name)
            return h.snapshot() if h is not None else None

    def series_count(self) -> int:
        with self._lock:
            return (len(self._counters) + len(self._labeled)
                    + len(self._gauges) + len(self._hists))

    def snapshot(self) -> dict:
        """JSON-safe full snapshot (the ``meta "metrics"`` payload)."""
        with self._lock:
            labeled = {f"{n}{{tenant={t}}}": v
                       for (n, t), v in sorted(self._labeled.items())}
            return {
                "counters": {k: int(v)
                             for k, v in sorted(self._counters.items())},
                "labeled_counters": labeled,
                "gauges": {k: v for k, v in sorted(self._gauges.items())},
                "histograms": {k: h.snapshot()
                               for k, h in sorted(self._hists.items())},
                "series": (len(self._counters) + len(self._labeled)
                           + len(self._gauges) + len(self._hists)),
                "series_dropped": self._dropped,
            }

    def exposition(self) -> str:
        """Prometheus-style text exposition. Histogram buckets emit
        cumulative ``le`` bounds in base units, the way a scraper
        expects; names are sanitized to the metric charset."""

        def _san(name: str) -> str:
            return "".join(c if (c.isalnum() or c == "_") else "_"
                           for c in name)

        snap = self.snapshot()
        lines = []
        for name, v in snap["counters"].items():
            lines.append(f"# TYPE cbtpu_{_san(name)} counter")
            lines.append(f"cbtpu_{_san(name)} {v}")
        # tenant-labeled series under a DISTINCT name (<name>_by_tenant):
        # the unlabeled series above is already the all-up total, and a
        # Prometheus sum() over one name must never double-count a
        # metric that mixes a total with its partitioning labels
        seen_by_tenant = set()
        for (series, v) in snap["labeled_counters"].items():
            name, _, label = series.partition("{")
            tenant = label.rstrip("}").partition("=")[2]
            m = f"cbtpu_{_san(name)}_by_tenant"
            if m not in seen_by_tenant:
                seen_by_tenant.add(m)
                lines.append(f"# TYPE {m} counter")
            lines.append(f'{m}{{tenant="{tenant}"}} {v}')
        for name, v in snap["gauges"].items():
            lines.append(f"# TYPE cbtpu_{_san(name)} gauge")
            lines.append(f"cbtpu_{_san(name)} {v}")
        for name, h in snap["histograms"].items():
            base, _, label = name.partition("{")
            tenant = label.rstrip("}").partition("=")[2] if label else ""
            sel = f'{{tenant="{tenant}",le="%s"}}' if tenant \
                else '{le="%s"}'
            m = f"cbtpu_{_san(base)}"
            lines.append(f"# TYPE {m} histogram")
            cum = 0
            for i, c in sorted(h["buckets"].items()):
                cum += c
                lines.append(f"{m}_bucket" + sel % bucket_upper(int(i))
                             + f" {cum}")
            lines.append(f"{m}_bucket" + sel % "+Inf" + f" {h['count']}")
            suffix = f'{{tenant="{tenant}"}}' if tenant else ""
            lines.append(f"{m}_sum{suffix} {h['sum']}")
            lines.append(f"{m}_count{suffix} {h['count']}")
        return "\n".join(lines) + "\n"


class CounterView:
    """Read-only mapping view over the registry's unlabeled counters —
    the compatibility shim for ``StatementLog.counters`` (previously a
    collections.Counter). Mutations go through ``StatementLog.bump``;
    the view exists so existing readers (snapshots, tests) keep
    working against the registry as the single home."""

    __slots__ = ("_reg",)

    def __init__(self, registry: MetricsRegistry):
        self._reg = registry

    def get(self, name: str, default: int = 0) -> int:
        if default == 0:
            return self._reg.counter(name)
        return self._reg.counter_snapshot().get(name, default)

    def __getitem__(self, name: str) -> int:
        return self._reg.counter(name)

    def __contains__(self, name: str) -> bool:
        return name in self._reg.counter_snapshot()

    def __iter__(self):
        return iter(self._reg.counter_snapshot())

    def __len__(self) -> int:
        return len(self._reg.counter_snapshot())

    def items(self):
        return self._reg.counter_snapshot().items()

    def keys(self):
        return self._reg.counter_snapshot().keys()

    def values(self):
        return self._reg.counter_snapshot().values()


def observe_stage(log, stage: str, dt: float,
                  tenant: str | None = None) -> None:
    """One per-stage latency sample (``stage_seconds.<stage>``) on the
    engine registry — the serve_bench time-share columns read these.
    ``log`` is a StatementLog (or None); a disabled obs config
    (log.obs_enabled False) makes this a no-op."""
    if log is None or not getattr(log, "obs_enabled", False):
        return
    log.registry.observe(f"stage_seconds.{stage}", dt, tenant=tenant)
