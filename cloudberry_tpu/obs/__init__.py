"""Unified observability plane: statement trace spans (obs/trace.py),
the engine-wide metrics registry (obs/metrics.py), per-skeleton
statement aggregates (obs/statements.py), and — the capacity &
forensics layer (ISSUE 12) — per-statement device-memory accounting +
engine memory gauges (obs/capacity.py), live statement progress
(obs/progress.py), and the slow-statement flight recorder
(obs/flightrec.py). The shared StatementLog (exec/instrument.py) owns
one instance of each, so a server's backends write one telemetry plane;
``meta "metrics"/"statements"/"trace"/"progress"/"flight"`` ship
snapshots over the wire."""

from cloudberry_tpu.obs.metrics import (CounterView,  # noqa: F401
                                        MetricsRegistry, observe_stage)
from cloudberry_tpu.obs.progress import (Progress,  # noqa: F401
                                         current_progress)
from cloudberry_tpu.obs.statements import StatementStats  # noqa: F401
from cloudberry_tpu.obs.trace import (Trace, chrome_trace,  # noqa: F401
                                      current_trace, device_annotation,
                                      mark, span)
