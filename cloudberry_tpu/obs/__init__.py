"""Unified observability plane (ISSUE 9): statement trace spans
(obs/trace.py), the engine-wide metrics registry (obs/metrics.py), and
per-skeleton statement aggregates (obs/statements.py). The shared
StatementLog (exec/instrument.py) owns one instance of each, so a
server's backends write one telemetry plane; ``meta
"metrics"/"statements"/"trace"`` ship snapshots over the wire."""

from cloudberry_tpu.obs.metrics import (CounterView,  # noqa: F401
                                        MetricsRegistry, observe_stage)
from cloudberry_tpu.obs.statements import StatementStats  # noqa: F401
from cloudberry_tpu.obs.trace import (Trace, chrome_trace,  # noqa: F401
                                      current_trace, device_annotation,
                                      mark, span)
