"""Statement trace spans — where one statement's time went.

The reference answers "where did the time go" with per-node
Instrumentation shipped QE→QD (cdbexplain_sendExecStats) plus gpperfmon;
here a statement's host-side journey is a SPAN TREE riding the existing
thread-local statement scope (lifecycle.py): the handle a scope installs
carries the statement's ``Trace``, so any seam on any thread — the
session's parse/plan, a dispatcher worker's flush, the tiled step loop,
a recovery backoff — records spans against the statement it is serving
without threading a context object through every signature. Crossing
threads is exactly the lifecycle-handle mechanism: whoever enters a
``statement_scope`` with the handle inherits its trace.

Span taxonomy (docs/DESIGN.md "Observability"): statement (root), parse,
plan, param-bind, compile, queue-wait, tenant-slot-wait, launch,
tile-step, recovery-backoff, render. Spans are Chrome-trace "X"
(complete) events — ts/dur in µs, tid = recording thread — so the
export loads directly into Perfetto / chrome://tracing, where per-tid
time-nesting reproduces the call tree. Device launches additionally wrap
in ``jax.profiler`` annotations so an XLA profile correlates with the
host span names.

Bounds: each trace keeps at most ``max_spans`` spans (drops counted on
the trace), and completed traces land in a bounded ring on the shared
StatementLog (``meta "trace"`` reads it newest-first).
"""

from __future__ import annotations

import contextlib
import threading
import time


_current_handle = None  # resolved once; avoids a per-span import lookup


def current_trace():
    """The executing statement's Trace, from the thread's lifecycle
    scope — None outside a statement or when tracing is off/sampled
    out."""
    global _current_handle
    ch = _current_handle
    if ch is None:
        from cloudberry_tpu.lifecycle import current_handle

        ch = _current_handle = current_handle
    h = ch()
    return getattr(h, "trace", None) if h is not None else None


class Trace:
    """One statement's bounded span collection. Append-only under a leaf
    lock (multiple threads may serve one statement: dispatcher worker,
    handler thread, watchdog)."""

    def __init__(self, statement_id: int, sql: str,
                 max_spans: int = 512, tenant: str | None = None):
        self.statement_id = statement_id
        self.sql = sql[:200]
        self.tenant = tenant
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._spans: list[dict] = []
        self.dropped = 0
        self.attempt = 0
        self.t0 = time.perf_counter()
        self.wall_s = 0.0
        self.status = "running"

    def add(self, name: str, t_start: float, dur_s: float,
            args: dict | None = None) -> None:
        """Record one completed interval (perf_counter seconds)."""
        ev = {
            "name": name,
            "ph": "X",
            "ts": round(t_start * 1e6, 1),
            "dur": round(dur_s * 1e6, 1),
            "pid": 1,
            "tid": threading.get_ident() & 0xFFFFFF,
            "cat": "statement",
        }
        if args:
            ev["args"] = args
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.dropped += 1
                return
            self._spans.append(ev)

    def mark(self, name: str, t_start: float,
             args: dict | None = None) -> None:
        """Span from ``t_start`` to now (the measure-around-enter
        shape used for queue/admission waits)."""
        self.add(name, t_start, time.perf_counter() - t_start, args)

    def finish(self, status: str) -> None:
        """Close the root span; the statement's whole wall clock."""
        self.status = status
        self.wall_s = time.perf_counter() - self.t0
        self.add("statement", self.t0, self.wall_s,
                 {"sql": self.sql, "status": status,
                  "statement_id": self.statement_id,
                  "tenant": self.tenant, "attempt": self.attempt})

    def export(self) -> dict:
        """JSON-safe export: the ring entry / wire payload."""
        with self._lock:
            spans = list(self._spans)
        return {
            "statement_id": self.statement_id,
            "sql": self.sql,
            "tenant": self.tenant,
            "status": self.status,
            "wall_s": round(self.wall_s, 6),
            "attempt": self.attempt,
            "spans_dropped": self.dropped,
            "events": spans,
        }


class span:
    """Record a span around the body when the thread is inside a traced
    statement; a no-op (one thread-local read) otherwise. A plain class
    rather than a generator context manager — this sits on the
    per-statement hot path."""

    __slots__ = ("name", "args", "tr", "t0")

    def __init__(self, name: str, **args):
        self.name = name
        self.args = args

    def __enter__(self):
        self.tr = current_trace()
        self.t0 = time.perf_counter() if self.tr is not None else 0.0
        return self.tr

    def __exit__(self, *exc) -> bool:
        if self.tr is not None:
            self.tr.add(self.name, self.t0,
                        time.perf_counter() - self.t0, self.args or None)
        return False


def mark(name: str, t_start: float, **args) -> None:
    """Span from ``t_start`` (perf_counter) to now on the current
    trace, if any — for waits whose scope is awkward to wrap."""
    tr = current_trace()
    if tr is not None:
        tr.mark(name, t_start, args or None)


def device_annotation(name: str):
    """jax.profiler annotation around a device launch, so an XLA profile
    lines up with the host span names; a null context when the thread is
    untraced (or jax.profiler is unavailable)."""
    if current_trace() is None:
        return contextlib.nullcontext()
    try:
        from jax.profiler import TraceAnnotation

        return TraceAnnotation(f"cbtpu:{name}")
    except Exception:  # pragma: no cover - profiler API drift
        return contextlib.nullcontext()


def chrome_trace(exports: list[dict]) -> dict:
    """Assemble ring exports into ONE Chrome-trace JSON document
    (Perfetto-loadable): {"traceEvents": [...]} with every statement's
    events concatenated (ts values share the perf_counter timebase, so
    concurrent statements interleave truthfully)."""
    events = []
    for ex in exports:
        events.extend(ex.get("events", ()))
    return {"traceEvents": events, "displayTimeUnit": "ms"}
