"""Live statement progress — how far along a running statement is.

The reference answers "is it stuck or just slow" with
pg_stat_progress_* views; here a statement's progress is a monotone
fraction riding its lifecycle handle (the same cross-thread channel the
trace uses, lifecycle.py): the tiled executors' step loops — the ONLY
place a statement's work is countable — feed tiles-done / tiles-total
and consumed-row fractions after every tile, and the activity view plus
a dedicated ``meta "progress"`` verb read them live.

The monotonicity contract (pinned by tests): the reported fraction
NEVER decreases, even though a device-loss resume restarts the tile
loop (possibly from tile 0 when no checkpoint survived), an adaptive
retry halves the tile size (changing the total), and a degraded-mesh
re-shard re-plans the remaining stream at a smaller segment count.
``Progress`` clamps to the high-water mark, caps the streaming phase
below 1.0 (the finalize pass is still ahead), and only ``complete()``
— called when the statement FINISHES successfully — reports exactly
1.0. A failed statement therefore can never read as done.
"""

from __future__ import annotations

import threading

import numpy as np


# mid-stream fractions cap here: the finalize pass (merge collectives,
# post chain) is still ahead of a fully streamed statement, and a failed
# statement must never have reported completion
_STREAM_CAP = 0.995


class Progress:
    """One statement's monotone progress gauge (leaf lock — nothing is
    called while it is held)."""

    __slots__ = ("_lock", "tiles_done", "tiles_total", "rows_done",
                 "rows_total", "_frac", "done")

    def __init__(self):
        self._lock = threading.Lock()
        self.tiles_done = 0
        self.tiles_total = 0
        self.rows_done = 0
        self.rows_total = 0
        self._frac = 0.0
        self.done = False

    def update(self, tiles_done: int, tiles_total: int,
               rows_done: int | None = None,
               rows_total: int | None = None) -> None:
        """Record the CURRENT attempt's position. The raw tile/row
        numbers reflect this attempt (they may restart after a fresh
        re-run); the fraction is the high-water mark across attempts."""
        with self._lock:
            if self.done:
                return
            self.tiles_done = int(tiles_done)
            self.tiles_total = int(tiles_total)
            if rows_done is not None:
                self.rows_done = int(rows_done)
            if rows_total is not None:
                self.rows_total = int(rows_total)
            if tiles_total > 0:
                frac = min(tiles_done / tiles_total, _STREAM_CAP)
                if frac > self._frac:
                    self._frac = frac

    def complete(self) -> None:
        """The statement finished successfully: the fraction is exactly
        1.0 from here on (and frozen — a late tile-loop update from a
        racing thread cannot drag it back)."""
        with self._lock:
            self.done = True
            self._frac = 1.0

    @property
    def fraction(self) -> float:
        with self._lock:
            return self._frac

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "fraction": round(self._frac, 4),
                "tiles_done": self.tiles_done,
                "tiles_total": self.tiles_total,
                "rows_done": self.rows_done,
                "rows_total": self.rows_total,
            }


def current_progress():
    """The executing statement's Progress from the thread's lifecycle
    scope — None outside a statement or when the telemetry plane is
    off (the handle only carries one when obs is enabled)."""
    from cloudberry_tpu.lifecycle import current_handle

    h = current_handle()
    return getattr(h, "progress", None) if h is not None else None


class TileTracker:
    """Per-tile-loop feeder: precomputes the attempt's totals once so
    the per-tile cost is one clamp + one lock (or nothing when the
    statement is untracked).

    ``lane_rows``: remaining rows per stream lane this attempt (one
    lane single-node, one per segment distributed — the loop runs
    lock-step, so the LONGEST lane sets the tile count).
    ``base_rows``: rows already consumed by checkpointed prior attempts
    (the resume prefix / consumed-mask population).
    ``n_base``: tiles those prior attempts completed.
    """

    __slots__ = ("_prog", "_tile_rows", "_n_base", "_base_rows",
                 "_lanes", "_rows_total", "total_est")

    def __init__(self, lane_rows, tile_rows: int,
                 n_base: int = 0, base_rows: int = 0,
                 rows_total: int | None = None):
        self._prog = current_progress()
        lanes = np.atleast_1d(np.asarray(lane_rows, dtype=np.int64))
        self._lanes = lanes
        self._tile_rows = max(int(tile_rows), 1)
        self._n_base = int(n_base)
        self._base_rows = int(base_rows)
        longest = int(lanes.max()) if lanes.size else 0
        self.total_est = self._n_base + max(
            -(-longest // self._tile_rows), 1)
        self._rows_total = int(rows_total) if rows_total is not None \
            else self._base_rows + int(lanes.sum())

    def step(self, tiles_local: int) -> None:
        """Feed the statement's progress after tile ``tiles_local``
        (1-based count of tiles this attempt completed)."""
        if self._prog is None:
            return
        consumed = int(np.minimum(
            self._lanes, tiles_local * self._tile_rows).sum())
        self._prog.update(self._n_base + tiles_local,
                          max(self.total_est,
                              self._n_base + tiles_local),
                          rows_done=self._base_rows + consumed,
                          rows_total=self._rows_total)


def stream_rows(scan, session) -> int:
    """Total source rows a tile stream will feed: pruned
    micro-partition scans count their surviving parts, warm tables
    their catalog row count. Telemetry-grade (progress denominators),
    not an execution contract."""
    parts = getattr(scan, "_store_parts", None)
    if parts is not None:
        return sum(int(p.get("num_rows", 0)) - len(p.get("deleted", ()))
                   for p in parts)
    t = session.catalog.tables.get(scan.table_name)
    return int(t.num_rows) if t is not None else 0
