"""Slow-statement flight recorder — the one statement that blew its SLO,
captured while the evidence is still warm.

When a statement crosses ``config.obs.slow_ms`` (or errors), the finish
path captures a bounded debug bundle into an engine-wide ring
(``meta "flight"`` ships it newest-first):

- identity: sql, statement id, tenant, status, wall, capture reason;
- the full trace span tree when the statement was sampled
  (obs/trace.py) and the live progress snapshot (obs/progress.py);
- the plan WITH derived distribution properties (session.explain —
  at nseg>1 every node carries the verifier's ``dist:`` suffix) plus
  its itemized device-byte estimate (obs/capacity.py) and redistribute
  rung ladder;
- the generic-plan skeleton and a literal fingerprint (sha256 over the
  hoisted literal texts) — enough to find the skeleton's row in
  ``meta "statements"`` and its plan-cache entry without shipping user
  data;
- per-statement counter deltas (compiles / generic_hits / recoveries)
  and the shared-cache-tier occupancy at capture time — the
  rung/cache-hit state;
- the config epoch (sched/sharedcache.config_uid) + n_segments +
  storage root, and for successful reads a RESULT DIGEST (sha256 over
  the decoded result columns) — the replay contract:
  ``tools/flight_replay.py`` re-executes the bundle's sql against the
  same store and asserts the digest matches bit-for-bit.

Capture is exception-safe by contract: the recorder observes a
statement that already finished — a capture failure is COUNTED
(``flight_capture_errors``) and never surfaces to the client. The plan
re-derivation (an explain-only re-plan) runs only for captured
statements, which are slow or broken by definition — never on the hot
path.
"""

from __future__ import annotations

import hashlib
import time

import numpy as np


# bundles keep the FULL statement text up to this cap — the replay
# contract executes bundle["sql"] verbatim, so any truncation makes the
# bundle forensics-only (replayable=False, sql_truncated stamped)
_SQL_CAP = 100_000

# minimum spacing between ERROR captures (engine-wide): under a
# deadline-heavy overload every expired statement errors, and paying a
# bundle build (plus ring churn — the ring holds 16) per failure would
# amplify exactly the overload being diagnosed. Slow-statement captures
# are not limited — they are rare by definition of slow_ms.
_ERROR_CAPTURE_MIN_S = 0.05

# cancellation-taxonomy errors: the statement died of lifecycle policy
# (deadline/cancel/drain/backpressure), not of its plan — capture the
# light bundle (trace/progress/counters) but never pay a re-plan for it
_CANCEL_CLASSES = frozenset({
    "StatementCancelled", "StatementTimeout", "ServerDraining",
    "SchedDeadline", "SchedQueueFull", "TenantQueueFull",
})


def param_fingerprint(sql: str) -> dict:
    """(skeleton, literal fingerprint) for the bundle: the skeleton is
    the plan-cache key, the fingerprint hashes the hoisted literal
    texts — same statement shape + same literals ⇒ same fingerprint,
    without the bundle carrying the literal values themselves."""
    from cloudberry_tpu.obs.statements import skeleton_of
    from cloudberry_tpu.sched import paramplan

    out = {"skeleton": skeleton_of(sql)}
    try:
        norm = paramplan.normalize(sql)
    except Exception:  # pragma: no cover - lexer drift
        norm = None
    if norm is not None:
        lits = norm[1]
        out["param_count"] = len(lits)
        out["param_fingerprint"] = hashlib.sha256(
            "\x00".join(lits).encode()).hexdigest()[:16]
    return out


def result_digest(batch) -> dict | None:
    """Bit-identity digest of a result surface: sha256 over the DECODED
    columns (name, dtype, raw bytes — object/string columns hash their
    value list). Decoded, not raw codes: a replay session re-reads the
    store, and dictionary code assignment is load-order state while the
    decoded values are the answer."""
    if not hasattr(batch, "decoded_columns"):
        return None
    cols = batch.decoded_columns()
    h = hashlib.sha256()
    for name in sorted(cols):
        arr = np.asarray(cols[name])
        h.update(name.encode())
        h.update(str(arr.dtype).encode())
        if arr.dtype == object:
            h.update("\x00".join(map(repr, arr.tolist())).encode())
        else:
            h.update(np.ascontiguousarray(arr).tobytes())
    n = len(next(iter(cols.values()))) if cols else 0
    return {"rows": int(n), "columns": sorted(cols),
            "sha256": h.hexdigest()}


def should_capture(log, status: str, wall_s: float) -> str | None:
    """The capture gate: the reason string ("slow" | "error"), or None.
    ``slow_ms`` <= 0 disables the recorder entirely."""
    if log is None or not getattr(log, "obs_enabled", False):
        return None
    slow_ms = float(getattr(log, "slow_ms", 0.0))
    if slow_ms <= 0:
        return None
    if status == "error":
        return "error"
    if wall_s * 1000.0 >= slow_ms:
        return "slow"
    return None


def _plan_section(session, query: str,
                  error: BaseException | None = None) -> dict:
    """Plan text with derived properties + the itemized device-byte
    estimate + the redistribute rung ladder, via an explain-only
    re-plan. Best-effort: a statement that errored AT planning simply
    has no plan to show."""
    from cloudberry_tpu.exec.executor import all_nodes
    from cloudberry_tpu.obs import capacity
    from cloudberry_tpu.plan import nodes as N
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.classify import read_only
    from cloudberry_tpu.sql.parser import parse_sql

    out: dict = {}
    if error is not None and type(error).__name__ in _CANCEL_CLASSES:
        # lifecycle verdicts (deadline/cancel/drain/backpressure) say
        # nothing about the plan; skip the re-plan — it is the
        # expensive part of a capture, and overload produces these in
        # bulk
        out["plan_skipped"] = "lifecycle error: no re-plan at capture"
        return out
    if not read_only(query):
        # NEVER re-plan DML/DDL for forensics: planning a write is not
        # guaranteed side-effect free (folded sequence nextvals, the
        # mutation itself on some paths) — the bundle keeps the
        # statement text and counters, just no plan tree
        out["plan_skipped"] = "write statement: no re-plan at capture"
        return out
    try:
        # session.explain renders the derived ``dist:`` suffixes at
        # nseg>1 — the bundle's plan shows what the verifier DERIVES,
        # not just what the distributor stamped
        out["plan"] = session.explain(query)
    except Exception as e:
        out["plan_error"] = f"{type(e).__name__}: {e}"
        return out
    try:
        pr = plan_statement(parse_sql(query), session, {},
                            explain_only=True)
        if not pr.is_ddl and pr.plan is not None:
            out["device_bytes"] = capacity.plan_device_bytes(
                pr.plan, session)
            out["rungs"] = [
                {"kind": n.kind, "bucket_cap": int(n.bucket_cap or 0),
                 "out_capacity": int(n.out_capacity or 0)}
                for n in all_nodes(pr.plan)
                if isinstance(n, N.PMotion)]
    except Exception:  # the explain above already captured the shape
        pass
    return out


def build_bundle(session, query: str, status: str, wall_s: float,
                 handle, reason: str, params: dict | None = None,
                 error: BaseException | None = None, result=None,
                 counters: dict | None = None) -> dict:
    """Assemble one capture. Pure data out — JSON-safe by construction
    (the wire and the replay tool both consume it verbatim)."""
    from cloudberry_tpu.sched import sharedcache

    cfg = session.config
    json_params = None
    if params:
        try:
            import json

            json.dumps(params)
            json_params = dict(params)
        except (TypeError, ValueError):
            json_params = None  # non-JSON bind params: not replayable
    # replay re-executes bundle["sql"] VERBATIM, so a truncated text
    # would replay a different statement: keep the full text up to a
    # generous cap, and past it the bundle is forensics-only
    truncated = len(query) > _SQL_CAP
    bundle = {
        "statement_id": getattr(handle, "statement_id", None),
        "sql": query[:_SQL_CAP],
        "status": status,
        "reason": reason,
        "wall_s": round(float(wall_s), 6),
        "captured_at": time.time(),
        "config_epoch": sharedcache.config_uid(cfg),
        "n_segments": int(cfg.n_segments),
        "storage_root": cfg.storage.root,
        "cache_tier": sharedcache.tier_snapshot(session),
        "tiled_report": getattr(session, "last_tiled_report", None),
    }
    bundle.update(param_fingerprint(query))
    if params is not None:
        bundle["params"] = json_params
    if counters:
        bundle["counters"] = {k: int(v) for k, v in counters.items()}
    if error is not None:
        bundle["error"] = f"{type(error).__name__}: {error}"[:500]
    trace = getattr(handle, "trace", None)
    if trace is not None:
        bundle["trace"] = trace.export()
    prog = getattr(handle, "progress", None)
    if prog is not None:
        bundle["progress"] = prog.snapshot()
    # skew annotations captured by the motion layer ride the activity
    # entry's counters; the plan section re-derives the shuffle shape
    bundle.update(_plan_section(session, query, error=error))
    digest = result_digest(result) if result is not None else None
    if digest is not None:
        bundle["result"] = digest
    if truncated:
        bundle["sql_truncated"] = True
    bundle["replayable"] = bool(
        cfg.storage.root is not None
        and digest is not None
        and not truncated
        and (not params or json_params is not None))
    return bundle


def maybe_capture(session, query: str, status: str, wall_s: float,
                  handle, params: dict | None = None,
                  error: BaseException | None = None, result=None,
                  counters: dict | None = None) -> None:
    """The finish-path hook (session.sql): capture when the gate says
    so; NEVER raise — a broken recorder must not break the statement it
    observed."""
    log = getattr(session, "stmt_log", None)
    reason = should_capture(log, status, wall_s)
    if reason is None:
        return
    if reason == "error":
        # error-storm protection: under overload every expired
        # statement errors, and the 16-deep ring would discard most of
        # the bundles anyway — space error captures out and count the
        # skips (slow captures are rare by definition and not limited)
        now = time.monotonic()
        if now - getattr(log, "_flight_last_error", 0.0) \
                < _ERROR_CAPTURE_MIN_S:
            log.bump("flight_capture_ratelimited")
            return
        log._flight_last_error = now
    try:
        bundle = build_bundle(session, query, status, wall_s, handle,
                              reason, params=params, error=error,
                              result=result, counters=counters)
        log.add_flight(bundle)
    except Exception:  # noqa: BLE001 — observer failure is counted
        try:
            log.bump("flight_capture_errors")
        except Exception:  # noqa: BLE001
            pass
