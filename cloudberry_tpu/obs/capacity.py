"""Capacity accounting — where the bytes go, per statement and per holder.

Theseus (PAPERS.md) makes data-movement/memory accounting the core of
its scheduling story, and a device-memory-bound SQL engine must SEE
memory pressure before it can govern it. This module is the second
observability layer's memory plane:

- **per-statement device bytes**: ``plan_device_bytes`` walks a compiled
  statement's plan exactly the way the admission estimator does
  (capacity × Σ dtype widths per node — program inputs, intermediates
  and outputs are all shape-static) and ADDS the two costs admission
  does not itemize: packed-wire motion buffers (the (cap, W) uint32
  staging arrays, exec/kernels.py wire_layout) and redistribute rung
  capacities (bucket_cap × nseg receive buffers). Every dispatched
  statement records its estimate into the ``stmt_device_bytes`` (peak)
  and ``stmt_live_bytes`` (largest single node — the lower bound XLA
  cannot fuse away) histograms, plus the engine-wide
  ``stmt_device_bytes_peak`` high-water gauge;

- **engine memory gauges**: ``refresh_gauges`` snapshots every
  engine-wide memory holder — the shared plan-cache tier (generic
  skeletons / rung executables / join indexes, sched/sharedcache.py),
  RecoveryStore checkpoint pins (host bytes), the trace and flight
  rings, the statements table, the dispatcher queue, the per-session
  statement/store-scan caches — as ``mem_*`` gauges, so
  ``meta "metrics"`` answers "where does host+device memory actually
  sit" without a debugger. Gauges refresh at READ time (the meta verb
  calls this), so the steady-state hot path pays nothing.

Gauge writes live HERE by contract: graftlint's ``obs-gauge-home`` rule
(lint/passes/obs.py) flags ``gauge``/``gauge_max`` calls outside
``obs/`` — a point-in-time gauge scattered across the engine goes stale
invisibly; one refresh site cannot.
"""

from __future__ import annotations

import numpy as np


def _wire_row_bytes(node) -> int:
    """Bytes one row costs on a motion's wire: the packed-wire layout
    width when the dtypes pack, else the raw per-column itemsize sum
    (+1 for the validity mask) — the same fallback EXPLAIN ANALYZE's
    motion annotation uses."""
    from cloudberry_tpu.exec import kernels as K

    dtypes = {f.name: f.type.np_dtype for f in node.child.fields}
    try:
        return K.wire_layout(dtypes).row_bytes()
    except NotImplementedError:
        return sum(np.dtype(d).itemsize for d in dtypes.values()) + 1


def two_level_staging_bytes(node, row_bytes: int | None = None) -> int:
    """Per-segment staging bytes the TWO-LEVEL exchange adds on top of
    the flat wire buffer (parallel/transport.py hier_all_to_all): the
    hop-1/hop-3 lane buffers at the proven ceil(H/S)*S*B bound (send +
    receive each) and the H host-pair DCN blocks, every row carrying
    the two u32 route words. Zero for unstamped (flat) motions."""
    hh = int(getattr(node, "hier_hosts", 0) or 0)
    hb = int(getattr(node, "host_bucket_cap", 0) or 0)
    if hh < 2 or hb <= 0:
        return 0
    nseg = max(int(node.out_capacity or 0)
               // max(int(node.bucket_cap or 1), 1), 1)
    if nseg % hh:
        return 0
    from cloudberry_tpu.parallel.transport import two_level_lane_rows

    S = nseg // hh
    B = int(node.bucket_cap)
    lane_rows = two_level_lane_rows(nseg, hh, B)
    rb = (row_bytes if row_bytes is not None
          else _wire_row_bytes(node)) + 8      # + dest/slot route words
    # hop1 send + hop1 recv + hop3 send + hop3 recv, then the DCN blocks
    return (4 * S * lane_rows + hh * hb) * rb


def plan_device_bytes(plan, session=None) -> dict:
    """Itemized device-byte estimate for one compiled statement.

    Returns ``{"peak_bytes", "live_bytes", "wire_bytes", "rung_rows",
    "nodes"}``: peak is the admission estimator's
    all-intermediates-live upper bound PLUS the wire staging buffers
    (including the two-level exchange's lane/host-block staging when a
    motion is stamped hierarchical); live is the largest single node
    (the floor no fusion removes); rung_rows totals redistribute
    receive capacities (bucket_cap over every destination) — the
    skew-governed share of the peak."""
    from cloudberry_tpu.exec.executor import all_nodes
    from cloudberry_tpu.exec.resource import estimate_plan_memory
    from cloudberry_tpu.plan import nodes as N

    est = estimate_plan_memory(plan)
    live = max((b for _, b in est.per_node), default=0)
    wire = 0
    rung_rows = 0
    seen: set = set()
    for node in all_nodes(plan):
        if not isinstance(node, N.PMotion) or id(node) in seen:
            continue
        seen.add(id(node))
        rows = max(int(node.out_capacity or 0), 0)
        rb = _wire_row_bytes(node)
        wire += rows * rb
        if node.kind == "redistribute":
            rung_rows += rows  # bucket_cap × nseg by construction
            wire += two_level_staging_bytes(node, rb)
    return {
        "peak_bytes": int(est.peak_bytes + wire),
        "live_bytes": int(live),
        "wire_bytes": int(wire),
        "rung_rows": int(rung_rows),
        "nodes": len(est.per_node),
    }


def observe_stmt_bytes(log, peak_bytes: int, live_bytes: int = 0,
                       wire_bytes: int = 0) -> None:
    """Record one statement's device-byte estimate on the engine
    registry (histograms + the peak high-water gauge). No-op when the
    telemetry plane is off — the cached-statement hot path calls this
    with its cached admission cost."""
    if log is None or not getattr(log, "obs_enabled", False):
        return
    reg = log.registry
    reg.observe("stmt_device_bytes", int(peak_bytes))
    if live_bytes:
        reg.observe("stmt_live_bytes", int(live_bytes))
    if wire_bytes:
        reg.observe("stmt_wire_bytes", int(wire_bytes))
    reg.gauge_max("stmt_device_bytes_peak", int(peak_bytes))


def record_statement(log, plan, session, est=None) -> None:
    """Full itemized recording for a freshly planned statement. ``est``
    reuses the admission estimate when the caller already paid for it
    (the plan walk here only adds the wire/rung pass)."""
    if log is None or not getattr(log, "obs_enabled", False):
        return
    d = plan_device_bytes(plan, session)
    if est is not None:
        # the admission bound is the authoritative intermediates term;
        # the walk above re-derives it — keep whichever is larger so a
        # drift between the two never UNDER-reports
        d["peak_bytes"] = max(d["peak_bytes"],
                              int(est.peak_bytes) + d["wire_bytes"])
    observe_stmt_bytes(log, d["peak_bytes"], d["live_bytes"],
                       d["wire_bytes"])


def record_tiled(log, report: dict) -> None:
    """Tiled (out-of-core) statements: the carried working set — tile
    step intermediates plus the accumulator — IS the device peak; the
    report already itemizes it (exec/tiled.py _refresh_report). The
    scan pipeline's bounded prefetch queue (exec/scanpipe.py) pins
    prefetch_tiles × one tile's host working set on top — charged here
    (``est_pipeline_bytes``) so the staging memory is visible in the
    same histograms as the device estimate."""
    if log is None or not getattr(log, "obs_enabled", False):
        return
    peak = int(report.get("est_step_bytes", 0))
    fin = int(report.get("est_finalize_bytes", 0))
    pipe = int(report.get("est_pipeline_bytes", 0))
    # HBM buffer-pool residency for the streamed table
    # (exec/bufferpool.py, report stamp est_bufpool_bytes): charged
    # next to the pipeline's staging bytes — resident chunks occupy
    # device memory alongside the statement's working set
    bufp = int(report.get("est_bufpool_bytes", 0))
    observe_stmt_bytes(log, max(peak, fin) + pipe + bufp)


def record_tile_dispatch(log, report: dict) -> None:
    """POST-run gauge for the windowed tile dispatcher
    (exec/tilepipe.py): the statement's in-flight high-water mark,
    read off the freshly stamped report — record_tiled above runs at
    DISPATCH time when the report still carries the previous run's
    numbers. window=1 (the legacy loop) writes nothing, so the gauge
    only exists where a window was actually open."""
    if log is None or not getattr(log, "obs_enabled", False):
        return
    if int(report.get("tile_window", 1)) > 1:
        log.registry.gauge_max("tile_inflight",
                               float(report.get("inflight_depth", 0)))


# --------------------------------------------------------- memory gauges


def nbytes_of(obj) -> int:
    """Recursive host-byte count over numpy/JAX arrays nested in
    dicts/lists/tuples — the checkpoint-pin and cache accounting
    primitive. Non-array leaves count zero (compiled programs and
    closures have no portable size; they are counted as ENTRIES)."""
    nb = getattr(obj, "nbytes", None)
    if nb is not None and isinstance(nb, (int, np.integer)):
        return int(nb)
    if isinstance(obj, dict):
        return sum(nbytes_of(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(nbytes_of(v) for v in obj)
    return 0


def refresh_gauges(session) -> dict:
    """Refresh every engine-wide memory-holder gauge on the session's
    registry and return the values (the ``meta "metrics"`` read path
    calls this right before the snapshot ships). Each gauge names its
    residence: ``*_bytes`` gauges are HOST bytes measured from the live
    arrays; ``*_entries``/``*_rows``/``*_depth`` gauges count entries in
    holders whose per-entry size is a compiled program (device bytes
    retained by XLA, not addressable from here). Per-connection server
    backends anchor on the SERVING session (``_obs_root``) so the
    session-private holders (stmt/store-scan caches) report stable
    values, not whichever backend happened to answer the meta request;
    other backends' private caches are bounded per-session and
    deliberately not aggregated."""
    session = getattr(session, "_obs_root", session)
    log = getattr(session, "stmt_log", None)
    if log is None:
        return {}
    vals: dict[str, float] = {}

    scope = getattr(session, "_cache_scope", None)
    if scope is not None:
        snap = scope.snapshot()
        vals["mem_plan_cache_skeletons"] = snap["generic_skeletons"]
        vals["mem_rung_cache_entries"] = snap["rung_entries"]
        vals["mem_join_index_entries"] = snap["join_index_entries"]
        # join indexes are host numpy mirrors — byte-accountable
        with scope.joinindex_lock:
            jb = sum(nbytes_of(v) for v in scope.joinindex.values())
        vals["mem_join_index_bytes"] = jb
    rec = getattr(session, "_recovery", None)
    if rec is not None:
        vals["mem_recovery_pins_bytes"] = rec.pinned_bytes()
        vals["mem_recovery_pins"] = rec.pinned_count()
    rings = log.ring_sizes()
    vals["mem_trace_ring_entries"] = rings["traces"]
    vals["mem_flight_ring_entries"] = rings["flights"]
    vals["mem_statement_rows"] = len(log.statements)
    disp = getattr(session, "_dispatcher", None)
    if disp is not None:
        vals["mem_dispatcher_queue_depth"] = disp.queue_depth()
    stmt_cache = getattr(session, "_stmt_cache", None)
    if stmt_cache is not None:
        vals["mem_stmt_cache_entries"] = len(stmt_cache)
    scan_cache = getattr(session, "_store_scan_cache", None)
    if scan_cache is not None:
        vals["mem_store_scan_bytes"] = nbytes_of(
            list(scan_cache.values()))
        vals["mem_store_scan_entries"] = len(scan_cache)
    # HBM buffer pool (exec/bufferpool.py): resident device bytes and
    # entry count for this session's cache scope — the residency side
    # of the bufpool_* counters
    if scope is not None:
        pool = getattr(scope, "bufferpool", None)
        if pool is not None:
            psnap = pool.snapshot()
            vals["mem_bufpool_bytes"] = psnap["bytes"]
            vals["mem_bufpool_entries"] = psnap["entries"]
            vals["mem_bufpool_max_bytes"] = psnap["max_bytes"]
    # versioned topology (parallel/topology.py): the serving epoch id,
    # the in-flight rebalance fraction (1.0 when no change is pending),
    # and bytes moved by the current/most-recent rebalance — the
    # gpexpand-progress gauges next to the flip/promotion counters
    topo = getattr(session, "_topology", None)
    if topo is not None:
        snap = topo.snapshot()
        vals["topo_epoch"] = snap["epoch"]
        vals["topo_nseg"] = snap["nseg"]
        reb = snap.get("rebalance")
        vals["topo_rebalance_fraction"] = (
            reb["fraction"] if reb else 1.0)
        vals["topo_moved_bytes"] = float(
            log.counter("topo_moved_bytes"))
    # write plane (storage/ingest.py + storage/compact.py): host bytes
    # parked in ingest buffers awaiting group commit, and the worst
    # per-table delta-partition count from the compactor's last pass —
    # the bounded-invariant needle
    ing = getattr(session, "_ingest", None)
    if ing is not None:
        vals["mem_ingest_buffer_bytes"] = ing.buffered_bytes()
    comp = getattr(session, "_compactor", None)
    if comp is not None:
        vals["compact_delta_parts_max"] = comp.delta_parts_gauge()
    for name, v in vals.items():
        log.registry.gauge(name, v)
    return vals
