"""Per-skeleton statement aggregates — the pg_stat_statements analog.

The reference normalizes queries to a fingerprint and aggregates calls /
time / rows per fingerprint in shared memory; here the fingerprint is the
generic-plan SKELETON (sched/paramplan.normalize — the same key the plan
cache uses, so "one row" means "one compiled shape"), and the aggregates
ride the finished statement-history entries the StatementLog already
produces: every ``finish()`` feeds ``observe()``.

Per row: calls, errors, rows, total/mean wall (plus a bounded log2
histogram for p95), compiles, generic hits (zero-compile executions of a
parameterized skeleton), recoveries, and wire bytes (stamped by the
serving layer per response). The table is bounded: past ``max_rows``
skeletons the least-recently-updated row is evicted — like the
reference's pg_stat_statements.max dealloc.
"""

from __future__ import annotations

import threading

from cloudberry_tpu.obs.metrics import _Hist


# text → skeleton memo (repeated texts skip the tokenize; bounded by a
# wholesale clear — GIL-atomic dict ops, a racing clear only costs a
# re-tokenize)
_skel_cache: dict = {}
_SKEL_CACHE_MAX = 2048


def skeleton_of(sql: str) -> str:
    """The aggregation key: the generic-plan skeleton when the statement
    normalizes, else the (truncated) text itself."""
    hit = _skel_cache.get(sql)
    if hit is not None:
        return hit
    try:
        from cloudberry_tpu.sched.paramplan import normalize

        norm = normalize(sql)
    except Exception:  # pragma: no cover - lexer drift
        norm = None
    out = norm[0][:500] if norm is not None else sql.strip()[:500]
    if len(_skel_cache) >= _SKEL_CACHE_MAX:
        _skel_cache.clear()
    _skel_cache[sql] = out
    return out


class _Row:
    __slots__ = ("calls", "errors", "rows", "wall", "compiles",
                 "generic_hits", "recoveries", "wire_bytes", "hist")

    def __init__(self):
        self.calls = 0
        self.errors = 0
        self.rows = 0
        self.wall = 0.0
        self.compiles = 0
        self.generic_hits = 0
        self.recoveries = 0
        self.wire_bytes = 0
        self.hist = _Hist()


class StatementStats:
    """Bounded per-skeleton aggregate table (leaf lock — nothing is
    called while it is held)."""

    def __init__(self, max_rows: int = 256):
        self.max_rows = max_rows
        self._lock = threading.Lock()
        self._rows: dict[str, _Row] = {}
        self.evicted = 0

    def _row(self, key: str) -> _Row:
        """LRU row fetch/insert (callers hold the lock): a touch moves
        the row to the dict tail, inserts past the bound evict the
        head — the least recently UPDATED skeleton."""
        row = self._rows.pop(key, None)
        if row is None:
            row = _Row()
            while len(self._rows) >= self.max_rows:
                self._rows.pop(next(iter(self._rows)))
                self.evicted += 1
        self._rows[key] = row
        return row

    def observe(self, entry: dict) -> None:
        """Fold one finished statement-history entry (StatementLog
        finish()) into its skeleton's aggregates."""
        sql = entry.get("sql") or ""
        if not sql:
            return
        row_count = entry.get("rows", -1)
        wall = float(entry.get("wall_s", 0.0))
        key = skeleton_of(sql)  # tokenizes — stays outside the lock
        with self._lock:
            row = self._row(key)
            row.calls += 1
            if entry.get("status") == "error":
                row.errors += 1
            if isinstance(row_count, int) and row_count > 0:
                row.rows += row_count
            row.wall += wall
            row.hist.add(wall)
            row.compiles += int(entry.get("compiles", 0) or 0)
            row.generic_hits += int(entry.get("generic_hits", 0) or 0)
            row.recoveries += int(entry.get("attempts", 0) or 0)

    def add_wire(self, sql: str, nbytes: int) -> None:
        """Wire bytes for one response, attributed to the statement's
        skeleton (stamped by the serving layer after rendering)."""
        key = skeleton_of(sql)
        with self._lock:
            self._row(key).wire_bytes += int(nbytes)

    def snapshot(self, limit: int = 50) -> list[dict]:
        """Rows by total wall time, heaviest first (the
        pg_stat_statements ordering people actually use)."""
        with self._lock:
            items = [(k, r) for k, r in self._rows.items()]
            out = []
            for key, r in items:
                calls = max(r.calls, 1)
                out.append({
                    "query": key,
                    "calls": r.calls,
                    "errors": r.errors,
                    "rows": r.rows,
                    "total_wall_s": round(r.wall, 6),
                    "mean_wall_s": round(r.wall / calls, 6),
                    "p95_wall_s": r.hist.quantile(0.95),
                    "compiles": r.compiles,
                    "generic_hits": r.generic_hits,
                    "generic_hit_rate": round(r.generic_hits / calls, 4),
                    "recoveries": r.recoveries,
                    "wire_bytes": r.wire_bytes,
                })
        out.sort(key=lambda d: -d["total_wall_s"])
        return out[:limit]

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)
