"""Native codec bindings (ctypes; builds native/codec.cpp on demand).

The compute path is JAX/XLA; the runtime byte-work around it — storage
codecs, ingest parsing — is native C++ like the reference's
(cdbappendonlystorageformat.c, contrib/pax_storage), with bit-identical
numpy fallbacks so every environment works and tests can diff the two.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

import numpy as np

_lib = None
_tried = False


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def load_native():
    """Build (once) and load libcbcodec; None if no toolchain."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    src = os.path.join(_repo_root(), "native", "codec.cpp")
    if not os.path.exists(src):
        return None
    try:
        build_dir = os.path.join(_repo_root(), "native", "build")
        os.makedirs(build_dir, exist_ok=True)
        so = os.path.join(build_dir, "libcbcodec.so")
        if not os.path.exists(so) or \
                os.path.getmtime(so) < os.path.getmtime(src):
            tmp = tempfile.mktemp(suffix=".so", dir=build_dir)
            subprocess.run(
                ["g++", "-O3", "-fwrapv", "-shared", "-fPIC", src, "-o", tmp],
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
    except Exception:
        return None  # read-only fs / no toolchain → numpy fallback
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    lib.cb_dvarint_encode.restype = ctypes.c_int64
    lib.cb_dvarint_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
    lib.cb_dvarint_decode.restype = ctypes.c_int64
    lib.cb_dvarint_decode.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p]
    lib.cb_parse_int64_column.restype = ctypes.c_int64
    lib.cb_parse_int64_column.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_int64]
    lib.cb_parse_decimal_column.restype = ctypes.c_int64
    lib.cb_parse_decimal_column.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_void_p, ctypes.c_int64]
    _lib = lib
    return _lib


# ----------------------------------------------------------------- varint


def dvarint_encode(arr: np.ndarray) -> bytes:
    """int64 column → delta+zigzag+LEB128 bytes (native or numpy fallback,
    bit-identical)."""
    arr = np.ascontiguousarray(arr, dtype=np.int64)
    lib = load_native()
    if lib is not None:
        out = np.empty(arr.size * 10, dtype=np.uint8)
        n = lib.cb_dvarint_encode(arr.ctypes.data, arr.size, out.ctypes.data)
        return out[:n].tobytes()
    return _dvarint_encode_np(arr)


def dvarint_decode(buf: bytes, n: int) -> np.ndarray:
    lib = load_native()
    if lib is not None:
        src = np.frombuffer(buf, dtype=np.uint8)
        out = np.empty(n, dtype=np.int64)
        used = lib.cb_dvarint_decode(src.ctypes.data if src.size else 0,
                                     src.size, n, out.ctypes.data)
        if used < 0:
            raise ValueError("corrupt dvarint stream")
        return out
    return _dvarint_decode_np(buf, n)


def _dvarint_encode_np(arr: np.ndarray) -> bytes:
    deltas = np.diff(arr, prepend=np.int64(0)).astype(np.int64)
    z = (deltas.astype(np.uint64) << np.uint64(1)) ^ \
        (deltas >> np.int64(63)).astype(np.uint64)
    out = bytearray()
    for v in z.tolist():
        while v >= 0x80:
            out.append((v & 0x7F) | 0x80)
            v >>= 7
        out.append(v)
    return bytes(out)


def _dvarint_decode_np(buf: bytes, n: int) -> np.ndarray:
    out = np.empty(n, dtype=np.int64)
    prev = 0
    i = 0
    pos = 0
    L = len(buf)
    while i < n:
        z = 0
        shift = 0
        while True:
            if pos >= L:
                raise ValueError("corrupt dvarint stream")
            b = buf[pos]
            pos += 1
            z |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
            if shift > 63:
                raise ValueError("corrupt dvarint stream")
        d = (z >> 1) ^ -(z & 1)
        prev = (prev + d) & 0xFFFFFFFFFFFFFFFF
        if prev >= 1 << 63:
            out[i] = prev - (1 << 64)
        else:
            out[i] = prev
        i += 1
    return out


# -------------------------------------------------------------- CSV ingest


def parse_int64_column(buf: bytes, col_index: int, delim: str = "|",
                       max_rows: int | None = None) -> np.ndarray:
    """Fast single-column int64 extraction from a delimited file buffer
    (the gpfdist-style parallel loader's inner loop)."""
    max_rows = max_rows if max_rows is not None else buf.count(b"\n") + 1
    lib = load_native()
    if lib is not None:
        out = np.empty(max_rows, dtype=np.int64)
        n = lib.cb_parse_int64_column(buf, len(buf), delim.encode()[0:1],
                                      col_index, out.ctypes.data, max_rows)
        if n < 0:
            raise ValueError(f"malformed integer in column {col_index}")
        return out[:n]
    out = []
    d = delim.encode()
    for ln in buf.splitlines():
        if len(out) >= max_rows:
            break
        parts = ln.split(d)
        if not ln or len(parts) <= col_index:
            continue  # short line: skipped, matching the native parser
        out.append(int(parts[col_index]))
    return np.asarray(out, dtype=np.int64)


def parse_decimal_column(buf: bytes, col_index: int, scale: int = 2,
                         delim: str = "|",
                         max_rows: int | None = None) -> np.ndarray:
    """Decimal column → int64 fixed-point at the given scale."""
    max_rows = max_rows if max_rows is not None else buf.count(b"\n") + 1
    lib = load_native()
    if lib is not None:
        out = np.empty(max_rows, dtype=np.int64)
        n = lib.cb_parse_decimal_column(buf, len(buf), delim.encode()[0:1],
                                        col_index, scale, out.ctypes.data,
                                        max_rows)
        if n < 0:
            raise ValueError(f"malformed decimal in column {col_index}")
        return out[:n]
    pow10 = 10 ** scale
    vals = []
    d = delim.encode()
    for ln in buf.splitlines():
        if len(vals) >= max_rows:
            break
        parts = ln.split(d)
        if not ln or len(parts) <= col_index:
            continue
        # integer-exact parse (no float round-trip), matching the native path
        f = parts[col_index].decode()
        neg = f.startswith("-")
        if neg:
            f = f[1:]
        whole, _, frac = f.partition(".")
        frac = (frac + "0" * scale)[:scale]
        v = int(whole or "0") * pow10 + (int(frac) if frac else 0)
        vals.append(-v if neg else v)
    return np.asarray(vals, dtype=np.int64)
