"""Recursive-descent SQL parser for the TPC-H/TPC-DS-class surface.

The reference's grammar is bison (src/backend/parser/gram.y) with MPP
additions — DISTRIBUTED BY / REPLICATED / RANDOMLY on CREATE TABLE is the one
reproduced here (gram.y OptDistributedBy). Statements supported: SELECT
(joins, subqueries, CASE, EXTRACT, SUBSTRING, BETWEEN/IN/LIKE/EXISTS,
GROUP BY/HAVING/ORDER BY/LIMIT), CREATE/DROP TABLE, INSERT … VALUES, EXPLAIN.
"""

from __future__ import annotations

import math
from typing import Optional

from cloudberry_tpu.sql import ast
from cloudberry_tpu.sql.lexer import Token, tokenize


class ParseError(ValueError):
    pass


def parse_sql(sql: str) -> ast.Node:
    p = Parser(tokenize(sql))
    stmt = p.parse_statement()
    p.accept_op(";")
    p.expect_eof()
    # original text rides along for DDL that persists its definition
    # (materialized views re-parse it on load)
    stmt._sql_text = sql
    return stmt


class Parser:
    def __init__(self, tokens: list[Token]):
        self.toks = tokens
        self.i = 0

    # ------------------------------------------------------------- plumbing

    @property
    def cur(self) -> Token:
        return self.toks[self.i]

    def advance(self) -> Token:
        t = self.cur
        self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        return self.cur.kind == "ident" and self.cur.text in kws

    def accept_kw(self, *kws: str) -> Optional[str]:
        if self.at_kw(*kws):
            return self.advance().text
        return None

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise ParseError(f"expected {kw.upper()} at {self.cur.text!r} "
                             f"(pos {self.cur.pos})")

    def at_op(self, *ops: str) -> bool:
        return self.cur.kind == "op" and self.cur.text in ops

    def accept_op(self, *ops: str) -> Optional[str]:
        if self.at_op(*ops):
            return self.advance().text
        return None

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise ParseError(f"expected {op!r} at {self.cur.text!r} "
                             f"(pos {self.cur.pos})")

    def expect_ident(self) -> str:
        if self.cur.kind != "ident":
            raise ParseError(f"expected identifier at {self.cur.text!r} "
                             f"(pos {self.cur.pos})")
        return self.advance().text

    def expect_eof(self) -> None:
        if self.cur.kind != "eof":
            raise ParseError(f"unexpected trailing input at {self.cur.text!r} "
                             f"(pos {self.cur.pos})")

    # ----------------------------------------------------------- statements

    def parse_statement(self) -> ast.Node:
        if self.at_kw("select", "with") or self.at_op("("):
            return self.parse_query()
        if self.at_kw("explain"):
            self.advance()
            analyze = bool(self.accept_kw("analyze"))
            return ast.Explain(self.parse_query(), analyze)
        if self.at_kw("create"):
            return self.parse_create_table()
        if self.at_kw("drop"):
            self.advance()
            kind = "table"
            if self.accept_kw("materialized"):
                self.expect_kw("view")
                kind = "matview"
            elif self.accept_kw("view"):
                kind = "view"
            elif self.accept_kw("sequence"):
                kind = "sequence"
            elif self.accept_kw("resource"):
                self.expect_kw("queue")
                kind = "resqueue"
            else:
                self.expect_kw("table")
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            name = self.expect_ident()
            if kind == "view":
                return ast.DropView(name, if_exists)
            if kind == "matview":
                return ast.DropMatView(name, if_exists)
            if kind == "sequence":
                return ast.DropSequence(name, if_exists)
            if kind == "resqueue":
                return ast.DropResourceQueue(name, if_exists)
            return ast.DropTable(name, if_exists)
        if self.at_kw("refresh"):
            self.advance()
            self.expect_kw("materialized")
            self.expect_kw("view")
            return ast.RefreshMatView(self.expect_ident())
        if self.at_kw("declare"):
            self.advance()
            name = self.expect_ident()
            self.expect_kw("parallel")
            self.expect_kw("retrieve")
            self.expect_kw("cursor")
            self.expect_kw("for")
            return ast.DeclareParallelCursor(name, self.parse_query())
        if self.at_kw("close"):
            self.advance()
            return ast.CloseCursor(self.expect_ident())
        if self.at_kw("insert"):
            return self.parse_insert()
        if self.at_kw("begin", "commit", "rollback", "abort", "start", "end"):
            w = self.advance().text
            if w == "start":
                self.expect_kw("transaction")
                w = "begin"
            else:
                self.accept_kw("transaction", "work")
                w = {"abort": "rollback", "end": "commit"}.get(w, w)
            return ast.TxnStmt(w)
        if self.at_kw("analyze"):
            self.advance()
            return ast.Analyze(self.expect_ident())
        if self.at_kw("cluster"):
            # CLUSTER t BY (a, b) — z-order write clustering
            self.advance()
            table = self.expect_ident()
            self.expect_kw("by")
            self.expect_op("(")
            cols = [self.expect_ident()]
            while self.accept_op(","):
                cols.append(self.expect_ident())
            self.expect_op(")")
            return ast.Cluster(table, cols)
        if self.at_kw("copy"):
            return self.parse_copy()
        if self.at_kw("update"):
            return self.parse_update()
        if self.at_kw("delete"):
            self.advance()
            self.expect_kw("from")
            table = self.expect_ident()
            where = self.parse_expr() if self.accept_kw("where") else None
            return ast.Delete(table, where)
        raise ParseError(f"unsupported statement start {self.cur.text!r}")

    def parse_create_table(self):
        self.expect_kw("create")
        if self.at_kw("materialized", "incremental"):
            incremental = bool(self.accept_kw("incremental"))
            self.expect_kw("materialized")
            self.expect_kw("view")
            name = self.expect_ident()
            self.expect_kw("as")
            return ast.CreateMatView(name, self.parse_query(), incremental)
        if self.accept_kw("view"):
            name = self.expect_ident()
            self.expect_kw("as")
            return ast.CreateView(name, self.parse_query())
        if self.accept_kw("resource"):
            self.expect_kw("queue")
            name = self.expect_ident()
            opts = {}
            if self.accept_kw("with"):
                self.expect_op("(")
                while True:
                    key = self.expect_ident()
                    self.expect_op("=")
                    if self.cur.kind == "string":
                        opts[key] = self.advance().text
                    else:
                        opts[key] = self._signed_int()
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            return ast.CreateResourceQueue(name, opts)
        if self.accept_kw("sequence"):
            if_not_exists = False
            if self.accept_kw("if"):
                self.expect_kw("not")
                self.expect_kw("exists")
                if_not_exists = True
            name = self.expect_ident()
            start, inc = 1, 1
            while True:
                if self.accept_kw("start"):
                    self.accept_kw("with")
                    start = self._signed_int()
                elif self.accept_kw("increment"):
                    self.accept_kw("by")
                    inc = self._signed_int()
                else:
                    break
            return ast.CreateSequence(name, start, inc, if_not_exists)
        if self.accept_kw("external"):
            return self._parse_create_external()
        if self.accept_kw("directory"):
            self.expect_kw("table")
            return ast.CreateDirectoryTable(self.expect_ident())
        if self.accept_kw("foreign"):
            # CREATE FOREIGN TABLE name (cols) SERVER srv
            # OPTIONS (key 'value', ...) — the FDW surface
            self.expect_kw("table")
            name = self.expect_ident()
            cols = self._parse_column_defs()
            self.expect_kw("server")
            server = self.expect_ident()
            options: dict = {}
            if self.accept_kw("options"):
                self.expect_op("(")
                while True:
                    k = self.expect_ident()
                    if self.cur.kind != "string":
                        raise ParseError(
                            "OPTIONS values must be quoted strings")
                    options[k] = self.advance().text
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            return ast.CreateForeignTable(name, cols, server, options)
        self.expect_kw("table")
        if_not_exists = False
        if self.accept_kw("if"):
            self.expect_kw("not")
            self.expect_kw("exists")
            if_not_exists = True
        name = self.expect_ident()
        if self.at_kw("as") or self.at_kw("distributed"):
            # CREATE TABLE name [DISTRIBUTED ...] AS query  /  name AS query
            distribution, keys = self._parse_distribution()
            self.expect_kw("as")
            q = self.parse_query()
            if distribution is None:
                distribution, keys = self._parse_distribution()
            return ast.CreateTableAs(name, q, distribution or "random",
                                     keys or (), if_not_exists)
        cols = self._parse_column_defs()
        distribution, keys = self._parse_distribution()
        partition = self._parse_partition()
        if distribution is None:
            # DISTRIBUTED may follow PARTITION too (order is free)
            distribution, keys = self._parse_distribution()
        return ast.CreateTable(name, cols, distribution or "random",
                               keys or (), if_not_exists, partition)

    def _parse_column_defs(self) -> list:
        self.expect_op("(")
        cols = []
        while True:
            cname = self.expect_ident()
            tname = self.expect_ident()
            scale = None
            if self.accept_op("("):
                self.advance()  # precision (ignored)
                if self.accept_op(","):
                    scale = int(self.advance().text)
                self.expect_op(")")
            not_null = False
            if self.accept_kw("not"):
                self.expect_kw("null")
                not_null = True
            self.accept_kw("primary") and self.expect_kw("key")
            cols.append(ast.ColumnDef(cname, tname, scale, not_null))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return cols

    def _parse_create_external(self):
        """CREATE EXTERNAL TABLE name (cols) LOCATION('url')
        [FORMAT 'csv'] [DELIMITER 'c'] [HEADER]
        [SEGMENT REJECT LIMIT n [ROWS|PERCENT]] [LOG ERRORS]"""
        self.expect_kw("table")
        name = self.expect_ident()
        cols = self._parse_column_defs()
        self.expect_kw("location")
        self.expect_op("(")
        if self.cur.kind != "string":
            raise ParseError("LOCATION takes a quoted URL")
        url = self.advance().text
        self.expect_op(")")
        delim, header = "|", False
        reject_limit, reject_percent, log_errors = None, False, False
        while True:
            if self.accept_kw("format"):
                if self.cur.kind != "string":
                    raise ParseError("FORMAT takes a quoted name")
                fmt = self.advance().text.lower()
                if fmt not in ("csv", "text"):
                    raise ParseError(f"unsupported FORMAT {fmt!r}")
            elif self.accept_kw("delimiter"):
                if self.cur.kind != "string" or len(self.cur.text) != 1:
                    raise ParseError("DELIMITER must be a 1-char string")
                delim = self.advance().text
            elif self.accept_kw("header"):
                header = True
            elif self.accept_kw("log"):
                self.expect_kw("errors")
                log_errors = True
            elif self.accept_kw("segment"):
                self.expect_kw("reject")
                self.expect_kw("limit")
                reject_limit = self._signed_int()
                if self.accept_kw("percent"):
                    reject_percent = True
                else:
                    self.accept_kw("rows")
            else:
                break
        return ast.CreateExternalTable(name, cols, url, delim, header,
                                       reject_limit, reject_percent,
                                       log_errors)

    def _parse_partition(self):
        """PARTITION BY RANGE (col) (START a END b EVERY s) | LIST (col)
        — the gram.y partition-clause analog, numeric bounds only."""
        if not self.at_kw("partition"):
            return None
        self.advance()
        self.expect_kw("by")
        if self.accept_kw("range"):
            self.expect_op("(")
            col = self.expect_ident()
            self.expect_op(")")
            self.expect_op("(")
            self.expect_kw("start")
            start = self._signed_int()
            self.expect_kw("end")
            end = self._signed_int()
            self.expect_kw("every")
            every = self._signed_int()
            self.expect_op(")")
            if every <= 0 or end <= start:
                raise ParseError("PARTITION BY RANGE needs END > START "
                                 "and EVERY > 0")
            return ("range", col, start, end, every)
        if self.accept_kw("list"):
            self.expect_op("(")
            col = self.expect_ident()
            self.expect_op(")")
            return ("list", col)
        raise ParseError("PARTITION BY expects RANGE or LIST")

    def _signed_int(self) -> int:
        neg = bool(self.accept_op("-"))
        tok = self.advance()
        try:
            v = int(tok.text)
        except ValueError:
            raise ParseError(
                f"expected an integer, got {tok.text!r}")
        return -v if neg else v

    def _parse_interval_literal(self) -> tuple:
        """INTERVAL '<n>' <unit> (cursor on the INTERVAL keyword):
        returns (n, singular unit)."""
        self.advance()
        tok = self.advance()
        try:
            n = int(tok.text)
        except ValueError:
            raise ParseError(
                f"expected an integer interval value, got {tok.text!r} "
                "(write the unit outside the string: interval '2' day)")
        return n, self.expect_ident().rstrip("s")

    def _signed_number(self):
        """int when the literal is integral, float otherwise (RANGE frame
        offsets may be fractional on float ORDER BY keys)."""
        neg = bool(self.accept_op("-"))
        tok = self.advance()
        try:
            v = int(tok.text)
        except ValueError:
            try:
                v = float(tok.text)
            except ValueError:
                raise ParseError(f"expected a number, got {tok.text!r}")
            if not math.isfinite(v):
                # float() happily parses 'nan'/'inf'/1e400 — as a frame
                # offset NaN would silently make every comparison False
                raise ParseError(f"expected a number, got {tok.text!r}")
        return -v if neg else v

    def _parse_distribution(self):
        if not self.accept_kw("distributed"):
            return None, None
        if self.accept_kw("by"):
            self.expect_op("(")
            ks = [self.expect_ident()]
            while self.accept_op(","):
                ks.append(self.expect_ident())
            self.expect_op(")")
            return "hash", tuple(ks)
        if self.accept_kw("replicated"):
            return "replicated", ()
        if self.accept_kw("randomly"):
            return "random", ()
        raise ParseError("expected BY/REPLICATED/RANDOMLY after DISTRIBUTED")

    def parse_insert(self):
        self.expect_kw("insert")
        self.expect_kw("into")
        table = self.expect_ident()
        columns: list[str] = []
        if self.accept_op("("):
            columns.append(self.expect_ident())
            while self.accept_op(","):
                columns.append(self.expect_ident())
            self.expect_op(")")
        if self.at_kw("select") or self.at_op("("):
            return ast.InsertSelect(table, columns, self.parse_query())
        self.expect_kw("values")
        rows = []
        while True:
            self.expect_op("(")
            row = [self.parse_expr()]
            while self.accept_op(","):
                row.append(self.parse_expr())
            self.expect_op(")")
            rows.append(row)
            if not self.accept_op(","):
                break
        return ast.InsertValues(table, columns, rows)

    def parse_copy(self):
        self.expect_kw("copy")
        table = self.expect_ident()
        direction = self.accept_kw("from", "to")
        if direction is None:
            raise ParseError("expected FROM or TO after COPY <table>")
        if self.cur.kind != "string":
            raise ParseError("COPY path must be a string literal")
        path = self.advance().text
        delim, header = "|", False
        reject_limit, reject_percent, log_errors = None, False, False
        self.accept_kw("with")
        while True:
            if self.accept_kw("delimiter"):
                if self.cur.kind != "string" or len(self.cur.text) != 1:
                    raise ParseError("DELIMITER must be a 1-char string")
                delim = self.advance().text
            elif self.accept_kw("header"):
                header = True
            elif self.accept_kw("log"):
                self.expect_kw("errors")
                log_errors = True
            elif self.accept_kw("segment"):
                # SEGMENT REJECT LIMIT n [ROWS | PERCENT] (gram.y sreh)
                self.expect_kw("reject")
                self.expect_kw("limit")
                reject_limit = self._signed_int()
                if self.accept_kw("percent"):
                    reject_percent = True
                else:
                    self.accept_kw("rows")
            else:
                break
        if direction == "to":
            return ast.CopyTo(table, path, delim, header)
        return ast.CopyFrom(table, path, delim, header,
                            reject_limit, reject_percent, log_errors)

    def parse_update(self) -> ast.Update:
        self.expect_kw("update")
        table = self.expect_ident()
        self.expect_kw("set")
        sets = []
        while True:
            col = self.expect_ident()
            self.expect_op("=")
            sets.append((col, self.parse_expr()))
            if not self.accept_op(","):
                break
        where = self.parse_expr() if self.accept_kw("where") else None
        return ast.Update(table, sets, where)

    # --------------------------------------------------------------- SELECT

    def parse_query(self) -> ast.Node:
        """[WITH ctes] select-core (UNION|INTERSECT|EXCEPT select-core)*
        [ORDER BY] [LIMIT]; set operations own the trailing ORDER BY/LIMIT."""
        if self.at_kw("with"):
            self.advance()
            if self.accept_kw("recursive"):
                raise ParseError("WITH RECURSIVE is not supported yet")
            ctes = []
            while True:
                name = self.expect_ident()
                self.expect_kw("as")
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                ctes.append((name, q))
                if not self.accept_op(","):
                    break
            return ast.WithQuery(ctes, self.parse_query())
        node: ast.Node = self._parse_intersect_chain()
        while self.at_kw("union", "except"):
            op = self.advance().text
            all_ = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            right = self._parse_intersect_chain()
            node = ast.SetOp(op, all_, node, right)
        if isinstance(node, ast.SetOp):
            if self.accept_kw("order"):
                self.expect_kw("by")
                node.order_by = [self.parse_order_item()]
                while self.accept_op(","):
                    node.order_by.append(self.parse_order_item())
            if self.accept_kw("limit"):
                node.limit = int(self.advance().text)
            if self.accept_kw("offset"):
                node.offset = int(self.advance().text)
        else:
            node = self._parse_select_tail(node)
        return node

    def _parse_intersect_chain(self) -> ast.Node:
        # INTERSECT binds tighter than UNION/EXCEPT (SQL precedence)
        node: ast.Node = self._parse_core()
        while self.at_kw("intersect"):
            self.advance()
            all_ = bool(self.accept_kw("all"))
            self.accept_kw("distinct")
            node = ast.SetOp("intersect", all_, node, self._parse_core())
        return node

    def _parse_core(self) -> ast.Node:
        if self.at_op("("):
            self.advance()
            inner = self.parse_query()
            self.expect_op(")")
            return inner
        return self.parse_select(allow_tail=False)

    def _parse_select_tail(self, sel: ast.Select) -> ast.Select:
        if self.accept_kw("order"):
            self.expect_kw("by")
            sel.order_by = [self.parse_order_item()]
            while self.accept_op(","):
                sel.order_by.append(self.parse_order_item())
        if self.accept_kw("limit"):
            sel.limit = int(self.advance().text)
        if self.accept_kw("offset"):
            sel.offset = int(self.advance().text)
        return sel

    def parse_select(self, allow_tail: bool = True) -> ast.Select:
        self.expect_kw("select")
        distinct = bool(self.accept_kw("distinct"))
        self.accept_kw("all")
        items = [self.parse_select_item()]
        while self.accept_op(","):
            items.append(self.parse_select_item())
        sel = ast.Select(items=items, distinct=distinct)
        if self.accept_kw("from"):
            sel.from_refs = [self.parse_table_ref()]
            while self.accept_op(","):
                sel.from_refs.append(self.parse_table_ref())
        if self.accept_kw("where"):
            sel.where = self.parse_expr()
        if self.accept_kw("group"):
            self.expect_kw("by")
            nxt = self.toks[self.i + 1] \
                if self.i + 1 < len(self.toks) else self.cur
            # lookahead: a column literally named rollup/cube/grouping
            # must still parse as a plain GROUP BY key
            kind = self.accept_kw("rollup", "cube") \
                if nxt.kind == "op" and nxt.text == "(" else None
            if kind:
                # ROLLUP(a,b) / CUBE(a,b) — expanded to grouping sets
                self.expect_op("(")
                cols = [self.parse_expr()]
                while self.accept_op(","):
                    cols.append(self.parse_expr())
                self.expect_op(")")
                sel.group_by = list(cols)
                if kind == "rollup":
                    sel.grouping_sets = [cols[:k]
                                         for k in range(len(cols), -1, -1)]
                else:
                    import itertools as _it

                    sel.grouping_sets = [
                        [c for i, c in enumerate(cols) if mask[i]]
                        for mask in _it.product(
                            (True, False), repeat=len(cols))]
            elif self.at_kw("grouping") and nxt.kind == "ident" \
                    and nxt.text == "sets":
                self.advance()
                self.expect_kw("sets")
                self.expect_op("(")
                sets = []
                while True:
                    if self.accept_op("("):
                        g = []
                        if not self.at_op(")"):
                            g.append(self.parse_expr())
                            while self.accept_op(","):
                                g.append(self.parse_expr())
                        self.expect_op(")")
                    else:
                        # bare expression = a one-column grouping set
                        g = [self.parse_expr()]
                    sets.append(g)
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                seen: list = []
                for g in sets:
                    for e in g:
                        if not any(repr(e) == repr(s) for s in seen):
                            seen.append(e)
                sel.group_by = seen
                sel.grouping_sets = sets
            else:
                sel.group_by = [self.parse_expr()]
                while self.accept_op(","):
                    sel.group_by.append(self.parse_expr())
        if self.accept_kw("having"):
            sel.having = self.parse_expr()
        if allow_tail:
            sel = self._parse_select_tail(sel)
        return sel

    def parse_select_item(self) -> ast.SelectItem:
        if self.at_op("*"):
            self.advance()
            return ast.SelectItem(ast.Star())
        # t.* pattern
        if (self.cur.kind == "ident"
                and self.toks[self.i + 1].kind == "op"
                and self.toks[self.i + 1].text == "."
                and self.toks[self.i + 2].kind == "op"
                and self.toks[self.i + 2].text == "*"):
            t = self.advance().text
            self.advance()
            self.advance()
            return ast.SelectItem(ast.Star(table=t))
        e = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        elif self.cur.kind == "ident" and self.cur.text not in _RESERVED:
            alias = self.advance().text
        return ast.SelectItem(e, alias)

    def parse_order_item(self) -> ast.OrderItem:
        e = self.parse_expr()
        asc = True
        if self.accept_kw("desc"):
            asc = False
        else:
            self.accept_kw("asc")
        return ast.OrderItem(e, asc)

    # ----------------------------------------------------------- table refs

    def parse_table_ref(self) -> ast.TableRefNode:
        left = self.parse_table_primary()
        while True:
            if self.accept_kw("cross"):
                self.expect_kw("join")
                right = self.parse_table_primary()
                left = ast.JoinRef("cross", left, right, None)
                continue
            kind = None
            if self.at_kw("inner", "join"):
                self.accept_kw("inner")
                kind = "inner"
            elif self.at_kw("left", "right", "full"):
                kind = self.advance().text
                self.accept_kw("outer")
            else:
                return left
            self.expect_kw("join")
            right = self.parse_table_primary()
            self.expect_kw("on")
            on = self.parse_expr()
            left = ast.JoinRef(kind, left, right, on)

    def parse_table_primary(self) -> ast.TableRefNode:
        if self.accept_op("("):
            # a derived table holds a full QUERY expression: plain
            # SELECT, WITH, or a set-op chain whose operands may
            # themselves be parenthesized ("(sel) intersect (sel)" —
            # the q38-class shape). The lookahead alone cannot separate
            # that from a parenthesized JOIN whose first element is a
            # derived table ("((select ...) a join b on ...)"), so try
            # the query parse and BACKTRACK to the join-ref grammar
            # unless it consumed exactly up to the closing paren.
            if self.at_kw("select", "with") \
                    or (self.at_op("(")
                        and self.toks[self.i + 1].kind == "ident"
                        and self.toks[self.i + 1].text
                        in ("select", "with")):
                save = self.i
                try:
                    sub = self.parse_query()
                    done = self.at_op(")")
                except ParseError:
                    done = False
                if done:
                    self.advance()
                    self.accept_kw("as")
                    alias = self.expect_ident()
                    return ast.DerivedTable(sub, alias)
                self.i = save
            ref = self.parse_table_ref()
            self.expect_op(")")
            return ref
        name = self.expect_ident()
        if self.at_op("("):
            # set-returning function in FROM: name(args) [AS] alias
            self.advance()
            args: list[ast.ExprNode] = []
            if not self.accept_op(")"):
                args.append(self.parse_expr())
                while self.accept_op(","):
                    args.append(self.parse_expr())
                self.expect_op(")")
            return ast.FuncTable(name, args, self._parse_alias())
        return ast.TableName(name, self._parse_alias())

    def _parse_alias(self):
        if self.accept_kw("as"):
            return self.expect_ident()
        if self.cur.kind == "ident" and self.cur.text not in _RESERVED:
            return self.advance().text
        return None

    # ---------------------------------------------------------- expressions

    def parse_expr(self) -> ast.ExprNode:
        return self.parse_or()

    def parse_or(self) -> ast.ExprNode:
        e = self.parse_and()
        while self.accept_kw("or"):
            e = ast.BinOp("or", e, self.parse_and())
        return e

    def parse_and(self) -> ast.ExprNode:
        e = self.parse_not()
        while self.accept_kw("and"):
            e = ast.BinOp("and", e, self.parse_not())
        return e

    def parse_not(self) -> ast.ExprNode:
        if self.accept_kw("not"):
            return ast.UnaryOp("not", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> ast.ExprNode:
        if self.at_kw("exists"):
            self.advance()
            self.expect_op("(")
            sub = self.parse_select()
            self.expect_op(")")
            return ast.Exists(sub)
        e = self.parse_additive()
        negated = bool(self.accept_kw("not"))
        if self.accept_kw("between"):
            low = self.parse_additive()
            self.expect_kw("and")
            high = self.parse_additive()
            return ast.Between(e, low, high, negated)
        if self.accept_kw("in"):
            self.expect_op("(")
            if self.at_kw("select"):
                sub = self.parse_select()
                self.expect_op(")")
                return ast.InSubquery(e, sub, negated)
            items = [self.parse_expr()]
            while self.accept_op(","):
                items.append(self.parse_expr())
            self.expect_op(")")
            return ast.InList(e, items, negated)
        if self.accept_kw("like"):
            pat = self.advance()
            if pat.kind != "string":
                raise ParseError("LIKE pattern must be a string literal")
            return ast.Like(e, pat.text, negated)
        if self.accept_kw("is"):
            neg = bool(self.accept_kw("not"))
            self.expect_kw("null")
            return ast.IsNull(e, neg)
        if negated:
            raise ParseError("expected BETWEEN/IN/LIKE after NOT")
        op = self.accept_op("=", "<>", "!=", "<", "<=", ">", ">=")
        if op:
            if op == "!=":
                op = "<>"
            rhs = self.parse_additive()
            return ast.BinOp(op, e, rhs)
        return e

    def parse_additive(self) -> ast.ExprNode:
        e = self.parse_multiplicative()
        while True:
            op = self.accept_op("+", "-", "||")
            if not op:
                return e
            e = ast.BinOp(op, e, self.parse_multiplicative())

    def parse_multiplicative(self) -> ast.ExprNode:
        e = self.parse_unary()
        while True:
            op = self.accept_op("*", "/", "%")
            if not op:
                return e
            e = ast.BinOp(op, e, self.parse_unary())

    def parse_unary(self) -> ast.ExprNode:
        op = self.accept_op("-", "+")
        if op:
            return ast.UnaryOp(op, self.parse_unary())
        return self.parse_primary()

    def parse_primary(self) -> ast.ExprNode:
        t = self.cur
        if t.kind == "number":
            self.advance()
            return ast.NumberLit(t.text)
        if t.kind == "string":
            self.advance()
            return ast.StringLit(t.text)
        if self.at_op("("):
            self.advance()
            if self.at_kw("select"):
                sub = self.parse_select()
                self.expect_op(")")
                return ast.ScalarSubquery(sub)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "ident":
            return self.parse_ident_expr()
        raise ParseError(f"unexpected token {t.text!r} (pos {t.pos})")

    def parse_ident_expr(self) -> ast.ExprNode:
        word = self.cur.text
        if word == "date" and self.toks[self.i + 1].kind == "string":
            self.advance()
            return ast.DateLit(self.advance().text)
        if word == "interval" and self.toks[self.i + 1].kind == "string":
            n, unit = self._parse_interval_literal()
            if unit not in ("year", "month", "day"):
                raise ParseError(f"unsupported interval unit {unit!r}")
            return ast.IntervalLit(n, unit)
        if word == "case":
            return self.parse_case()
        if word == "cast":
            self.advance()
            self.expect_op("(")
            e = self.parse_expr()
            self.expect_kw("as")
            tname = self.expect_ident()
            scale = None
            if self.accept_op("("):
                self.advance()
                if self.accept_op(","):
                    scale = int(self.advance().text)
                self.expect_op(")")
            self.expect_op(")")
            return ast.CastExpr(e, tname, scale)
        if word == "extract":
            self.advance()
            self.expect_op("(")
            part = self.expect_ident()
            self.expect_kw("from")
            e = self.parse_expr()
            self.expect_op(")")
            return ast.ExtractExpr(part, e)
        if word == "substring":
            self.advance()
            self.expect_op("(")
            e = self.parse_expr()
            if self.accept_kw("from"):
                start = self.parse_expr()
                length = self.parse_expr() if self.accept_kw("for") else None
            else:
                self.expect_op(",")
                start = self.parse_expr()
                length = self.parse_expr() if self.accept_op(",") else None
            self.expect_op(")")
            return ast.SubstringExpr(e, start, length)
        if word in ("true", "false"):
            self.advance()
            return ast.BoolLit(word == "true")
        if word == "null":
            self.advance()
            return ast.NullLit()
        if word in _RESERVED:
            raise ParseError(f"unexpected keyword {word.upper()!r} "
                             f"(pos {self.cur.pos})")
        # function call or (qualified) column name
        if (self.toks[self.i + 1].kind == "op"
                and self.toks[self.i + 1].text == "("):
            fname = self.advance().text
            self.advance()  # (
            if self.accept_op("*"):
                self.expect_op(")")
                if self.at_kw("over"):
                    return self._parse_over(fname, [])
                return ast.FuncCall(fname, [], star=True)
            distinct = bool(self.accept_kw("distinct"))
            args: list[ast.ExprNode] = []
            if not self.at_op(")"):
                args.append(self.parse_expr())
                while self.accept_op(","):
                    args.append(self.parse_expr())
            self.expect_op(")")
            if self.at_kw("over"):
                return self._parse_over(fname, args)
            return ast.FuncCall(fname, args, distinct=distinct)
        parts = [self.advance().text]
        while self.at_op(".") and self.toks[self.i + 1].kind == "ident":
            self.advance()
            parts.append(self.advance().text)
        return ast.Name(tuple(parts))

    def _parse_over(self, fname: str, args) -> ast.WindowExpr:
        self.expect_kw("over")
        self.expect_op("(")
        partition: list[ast.ExprNode] = []
        order: list[ast.OrderItem] = []
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition.append(self.parse_expr())
            while self.accept_op(","):
                partition.append(self.parse_expr())
        if self.accept_kw("order"):
            self.expect_kw("by")
            order.append(self.parse_order_item())
            while self.accept_op(","):
                order.append(self.parse_order_item())
        frame = None
        kind = self.accept_kw("rows", "range")
        if kind:
            if self.accept_kw("between"):
                lo = self._parse_frame_bound(kind)
                self.expect_kw("and")
                hi = self._parse_frame_bound(kind)
            else:
                lo, hi = self._parse_frame_bound(kind), ("current", 0)
            frame = (kind, lo, hi)
        self.expect_op(")")
        return ast.WindowExpr(fname, args, partition, order, frame)

    def _parse_frame_bound(self, kind: str):
        """UNBOUNDED PRECEDING|FOLLOWING | <n> PRECEDING|FOLLOWING |
        CURRENT ROW -> ('unbounded'|'offset'|'current', signed rows)"""
        if self.accept_kw("unbounded"):
            d = self.accept_kw("preceding", "following")
            if not d:
                raise ParseError("UNBOUNDED needs PRECEDING or FOLLOWING")
            return ("unbounded", -1 if d == "preceding" else 1)
        if self.accept_kw("current"):
            self.expect_kw("row")
            return ("current", 0)
        if self.at_kw("interval") and self.toks[self.i + 1].kind == "string":
            if kind != "range":
                # PG rejects intervals in ROWS mode — silently reading
                # one as a row count would answer a different question
                raise ParseError("interval frame offsets need RANGE mode")
            # INTERVAL 'n' DAY on a date ORDER BY key: days are the
            # key's integer domain, so the offset is just n.
            # MONTH/YEAR are calendar distances — they ride as a
            # ("months", n) marker and the executor shifts each row's
            # civil date in-program (timestamp.c interval_pl semantics:
            # month shift, day-of-month clamped).
            n, unit = self._parse_interval_literal()
            if unit in ("month", "year"):
                n = ("months", n * (12 if unit == "year" else 1))
            elif unit != "day":
                raise ParseError(
                    "RANGE frame intervals support DAY, MONTH and YEAR")
        else:
            n = self._signed_number()
        months = isinstance(n, tuple)
        nv = n[1] if months else n
        if nv < 0:
            # PG: "frame starting offset must not be negative" — a
            # negative n would silently flip PRECEDING into FOLLOWING
            raise ParseError("frame offset must not be negative")
        d = self.accept_kw("preceding", "following")
        if not d:
            raise ParseError("frame offset needs PRECEDING or FOLLOWING")
        signed = -nv if d == "preceding" else nv
        return ("offset", ("months", signed) if months else signed)

    def parse_case(self) -> ast.CaseExpr:
        self.expect_kw("case")
        whens: list[tuple[ast.ExprNode, ast.ExprNode]] = []
        while self.accept_kw("when"):
            c = self.parse_expr()
            self.expect_kw("then")
            v = self.parse_expr()
            whens.append((c, v))
        otherwise = self.parse_expr() if self.accept_kw("else") else None
        self.expect_kw("end")
        return ast.CaseExpr(whens, otherwise)


_CLAUSE_KWS = ("from", "where", "group", "having", "order", "limit", "offset",
               "union", "intersect", "except", "as", "and", "or", "not",
               "when", "then", "else", "end", "desc", "asc", "between", "in",
               "like", "is")

# words that can never start a primary expression (bare column name)
_RESERVED = frozenset(_CLAUSE_KWS) | {
    "select", "by", "on", "join", "inner", "left", "right", "full", "cross",
    "distinct", "exists", "create", "drop", "insert", "into", "values",
    "table", "distributed", "with",
}
