"""SQL lexer — hand-rolled, no dependencies (no sqlglot in the image).

The reference uses flex (src/backend/parser/scan.l). Token kinds: IDENT,
NUMBER, STRING, OP, punctuation; keywords are uppercased IDENTs checked by
the parser (case-insensitive, PG style).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Token:
    kind: str   # 'ident' | 'number' | 'string' | 'op' | 'eof'
    text: str   # idents lowercased; strings unquoted; ops literal
    pos: int


_TWO_CHAR_OPS = ("<=", ">=", "<>", "!=", "||")
_ONE_CHAR_OPS = "+-*/%=<>(),.;"


class LexError(ValueError):
    pass


def tokenize(sql: str) -> list[Token]:
    out: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                raise LexError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if c == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'" and j + 1 < n and sql[j + 1] == "'":
                    buf.append("'")
                    j += 2
                elif sql[j] == "'":
                    break
                else:
                    buf.append(sql[j])
                    j += 1
            if j >= n:
                raise LexError(f"unterminated string at {i}")
            out.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if c == '"':
            j = sql.find('"', i + 1)
            if j < 0:
                raise LexError(f"unterminated quoted identifier at {i}")
            out.append(Token("ident", sql[i + 1:j], i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = False
            while j < n and (sql[j].isdigit() or (sql[j] == "." and not seen_dot)):
                if sql[j] == ".":
                    # "1." followed by non-digit is number then dot (e.g. 1..2)
                    if j + 1 >= n or not sql[j + 1].isdigit():
                        break
                    seen_dot = True
                j += 1
            if j < n and sql[j] in "eE":
                k = j + 1
                if k < n and sql[k] in "+-":
                    k += 1
                if k < n and sql[k].isdigit():
                    while k < n and sql[k].isdigit():
                        k += 1
                    j = k
            out.append(Token("number", sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            out.append(Token("ident", sql[i:j].lower(), i))
            i = j
            continue
        if sql[i:i + 2] in _TWO_CHAR_OPS:
            out.append(Token("op", sql[i:i + 2], i))
            i += 2
            continue
        if c in _ONE_CHAR_OPS:
            out.append(Token("op", c, i))
            i += 1
            continue
        raise LexError(f"unexpected character {c!r} at position {i}")
    out.append(Token("eof", "", n))
    return out
