"""Unbound SQL AST — what the parser produces.

The reference's analog is PG's raw parse tree (src/backend/parser/gram.y,
with Cloudberry additions like DISTRIBUTED BY at gram.y's CREATE TABLE
productions). This AST covers the analytical SQL surface TPC-H/TPC-DS-class
workloads need; the binder (plan/binder.py) resolves names and types.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


class Node:
    pass


# ---------------------------------------------------------------- expressions


class ExprNode(Node):
    pass


@dataclass
class Name(ExprNode):
    parts: tuple[str, ...]  # ("t", "col") or ("col",)

    @property
    def text(self) -> str:
        return ".".join(self.parts)


@dataclass
class Star(ExprNode):
    table: Optional[str] = None  # t.* if set


@dataclass
class NumberLit(ExprNode):
    text: str  # keep literal text; binder decides int vs decimal + scale


@dataclass
class StringLit(ExprNode):
    value: str


@dataclass
class DateLit(ExprNode):
    value: str  # ISO yyyy-mm-dd


@dataclass
class IntervalLit(ExprNode):
    n: int
    unit: str  # 'year' | 'month' | 'day'


@dataclass
class BoolLit(ExprNode):
    value: bool


@dataclass
class NullLit(ExprNode):
    pass


@dataclass
class BinOp(ExprNode):
    op: str
    left: ExprNode
    right: ExprNode


@dataclass
class UnaryOp(ExprNode):
    op: str  # 'not' | '-' | '+'
    operand: ExprNode


@dataclass
class IsNull(ExprNode):
    operand: ExprNode
    negated: bool = False


@dataclass
class Between(ExprNode):
    expr: ExprNode
    low: ExprNode
    high: ExprNode
    negated: bool = False


@dataclass
class InList(ExprNode):
    expr: ExprNode
    items: list[ExprNode]
    negated: bool = False


@dataclass
class Like(ExprNode):
    expr: ExprNode
    pattern: str
    negated: bool = False


@dataclass
class FuncCall(ExprNode):
    name: str
    args: list[ExprNode]
    distinct: bool = False
    star: bool = False  # count(*)


@dataclass
class ExtractExpr(ExprNode):
    part: str  # 'year' | 'month' | 'day'
    operand: ExprNode


@dataclass
class SubstringExpr(ExprNode):
    operand: ExprNode
    start: ExprNode
    length: Optional[ExprNode]


@dataclass
class CaseExpr(ExprNode):
    whens: list[tuple[ExprNode, ExprNode]]
    otherwise: Optional[ExprNode]


@dataclass
class CastExpr(ExprNode):
    operand: ExprNode
    type_name: str
    scale: Optional[int] = None


@dataclass
class WindowExpr(ExprNode):
    func: str
    args: list[ExprNode]
    partition_by: list[ExprNode]
    order_by: list["OrderItem"]
    # frame clause: (kind, lo, hi) where kind is 'rows'|'range' and each
    # bound is ('unbounded'|'offset'|'current', signed row/peer offset);
    # None = the SQL default frame
    frame: Optional[tuple] = None


@dataclass
class ScalarSubquery(ExprNode):
    select: "Select"


@dataclass
class InSubquery(ExprNode):
    expr: ExprNode
    select: "Select"
    negated: bool = False


@dataclass
class Exists(ExprNode):
    select: "Select"
    negated: bool = False


# ---------------------------------------------------------------- table refs


class TableRefNode(Node):
    pass


@dataclass
class TableName(TableRefNode):
    name: str
    alias: Optional[str] = None


@dataclass
class DerivedTable(TableRefNode):
    select: "Select"
    alias: str


@dataclass
class FuncTable(TableRefNode):
    """Set-returning function in FROM (Function Scan analog):
    name(args) [AS] alias."""

    name: str
    args: list[ExprNode]
    alias: Optional[str] = None


@dataclass
class JoinRef(TableRefNode):
    kind: str  # 'inner' | 'left' | 'right' | 'full' | 'cross'
    left: TableRefNode
    right: TableRefNode
    on: Optional[ExprNode]


# ---------------------------------------------------------------- statements


@dataclass
class SelectItem(Node):
    expr: ExprNode
    alias: Optional[str] = None


@dataclass
class OrderItem(Node):
    expr: ExprNode
    ascending: bool = True


@dataclass
class Select(Node):
    items: list[SelectItem]
    from_refs: list[TableRefNode] = field(default_factory=list)
    where: Optional[ExprNode] = None
    group_by: list[ExprNode] = field(default_factory=list)
    having: Optional[ExprNode] = None
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0
    distinct: bool = False
    # GROUPING SETS / ROLLUP / CUBE: list of grouping-key subsets; the
    # binder rewrites to a UNION ALL of per-set aggregations with NULLs
    # for the keys a set omits (nodeAgg.c grouping-sets role)
    grouping_sets: Optional[list] = None


@dataclass
class WithQuery(Node):
    """WITH name AS (query), ... body — non-recursive CTEs; each name is
    bound once and shared across references (ShareInputScan analog)."""
    ctes: list[tuple[str, Node]]   # (name, Select | SetOp | WithQuery)
    query: Node                    # Select | SetOp


@dataclass
class SetOp(Node):
    """UNION/INTERSECT/EXCEPT chain; ORDER BY/LIMIT apply to the whole."""
    op: str                      # 'union' | 'intersect' | 'except'
    all: bool
    left: Node                   # Select or SetOp
    right: Node
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: int = 0


@dataclass
class ColumnDef(Node):
    name: str
    type_name: str
    scale: Optional[int] = None
    not_null: bool = False


@dataclass
class CreateTable(Node):
    name: str
    columns: list[ColumnDef]
    distribution: str = "random"  # 'hash' | 'random' | 'replicated'
    dist_keys: tuple[str, ...] = ()
    if_not_exists: bool = False
    # PARTITION BY clause (gram.y partition grammar analog):
    # ('range', col, start, end, every) | ('list', col) | None
    partition: Optional[tuple] = None


@dataclass
class CreateDirectoryTable(Node):
    """CREATE DIRECTORY TABLE name — files as catalog objects
    (storage/dirtable.py; the dirtable analog)."""

    name: str


@dataclass
class CreateForeignTable(Node):
    """CREATE FOREIGN TABLE name (cols) SERVER srv OPTIONS (k 'v', ...)
    — the FDW surface; servers resolve through storage/fdw.py's
    registry (built-ins: sqlite; register_fdw adds more)."""

    name: str
    columns: list["ColumnDef"]
    server: str
    options: dict


@dataclass
class CreateExternalTable(Node):
    """CREATE EXTERNAL TABLE ... LOCATION('cbfdist://h:p/f' | 'file://p')
    FORMAT 'csv' [DELIMITER 'c'] [SEGMENT REJECT LIMIT ...] — readable
    external tables (access/external, gpfdist URLs)."""

    name: str
    columns: list[ColumnDef]
    url: str
    delimiter: str = "|"
    header: bool = False
    reject_limit: Optional[int] = None
    reject_percent: bool = False
    log_errors: bool = False


@dataclass
class CreateTableAs(Node):
    name: str
    query: Node
    distribution: str = "random"
    dist_keys: tuple[str, ...] = ()
    if_not_exists: bool = False


@dataclass
class CreateSequence(Node):
    name: str
    start: int = 1
    increment: int = 1
    if_not_exists: bool = False


@dataclass
class DropSequence(Node):
    name: str
    if_exists: bool = False


@dataclass
class CreateResourceQueue(Node):
    name: str
    options: dict  # active_statements, max_cost, priority


@dataclass
class DropResourceQueue(Node):
    name: str
    if_exists: bool = False


@dataclass
class DeclareParallelCursor(Node):
    name: str
    query: Node


@dataclass
class CloseCursor(Node):
    name: str


@dataclass
class CreateMatView(Node):
    name: str
    query: Node
    incremental: bool = False


@dataclass
class DropMatView(Node):
    name: str
    if_exists: bool = False


@dataclass
class RefreshMatView(Node):
    name: str


@dataclass
class CreateView(Node):
    name: str
    query: Node  # Select or SetOp


@dataclass
class DropView(Node):
    name: str
    if_exists: bool = False


@dataclass
class DropTable(Node):
    name: str
    if_exists: bool = False


@dataclass
class InsertValues(Node):
    table: str
    columns: list[str]
    rows: list[list[ExprNode]]


@dataclass
class InsertSelect(Node):
    table: str
    columns: list[str]
    query: Node  # Select or SetOp


@dataclass
class Update(Node):
    table: str
    sets: list[tuple[str, ExprNode]]
    where: Optional[ExprNode] = None


@dataclass
class Delete(Node):
    table: str
    where: Optional[ExprNode] = None


@dataclass
class CopyFrom(Node):
    table: str
    path: str
    delimiter: str = "|"
    header: bool = False
    # single-row error handling (cdbsreh.c): tolerate up to this many
    # malformed rows (or percent of rows when reject_percent) instead of
    # aborting the load; rejected rows land in the error log
    reject_limit: Optional[int] = None
    reject_percent: bool = False
    log_errors: bool = False


@dataclass
class CopyTo(Node):
    table: str
    path: str
    delimiter: str = "|"
    header: bool = False


@dataclass
class TxnStmt(Node):
    kind: str  # 'begin' | 'commit' | 'rollback'


@dataclass
class Explain(Node):
    stmt: Select
    analyze: bool = False


@dataclass
class Analyze(Node):
    """ANALYZE <table> — collect column statistics (NDV)."""
    table: str


@dataclass
class Cluster(Node):
    """CLUSTER <table> BY (cols) — z-order rewrite for pruning locality."""
    table: str
    columns: list[str]
