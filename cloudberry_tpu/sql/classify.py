"""Statement classification shared by every read-only gate.

Three consumers ask "can this statement change state?": the Session's
failure-recovery retry (a replayed write double-applies), the hot
standby (must refuse writes), and the MCP query tool (agents get reads
only). One classifier keeps them agreeing — they diverged once already
(nextval: head says SELECT, but sequence allocation happens at plan time
and durably advances the sequence file)."""

from __future__ import annotations

import re

READ_HEADS = frozenset(
    {"select", "with", "values", "explain", "show", "retrieve"})

_STRING_LIT = re.compile(r"'(?:[^']|'')*'")


def strip_string_literals(sql: str) -> str:
    """SQL with quoted literals blanked — so classification never trips
    on keyword-looking or punctuation-looking text inside strings."""
    return _STRING_LIT.sub("''", sql)


def read_only(sql: str) -> bool:
    """True when re-running the statement cannot change engine state."""
    s = sql.lstrip()
    bare = strip_string_literals(s).lower()
    if "nextval" in bare:
        return False  # plan-time sequence allocation is a durable write
    if s.startswith("("):
        return True  # parenthesized set operation — a query by grammar
    head = s.split(None, 1)
    return bool(head) and head[0].lower() in READ_HEADS
