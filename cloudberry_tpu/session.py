"""Session — the QD (query dispatcher) analog.

A Session owns a catalog, a config, and a device mesh; ``sql()`` runs the full
pipeline: parse → bind/plan (motion insertion) → compile → execute. The
reference's equivalent surface is a libpq connection to the coordinator
backend (exec_simple_query, src/backend/tcop/postgres.c:1655); here it is an
in-process Python API (the serving layer comes later).

The session also owns segment data placement: the analog of the reference's
load-time row routing (cdbhash + jump_consistent_hash, cdbhash.c:55-78),
cached per (table, n_segments) the way segment data lives on segment disks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

from cloudberry_tpu.config import Config, get_config


from cloudberry_tpu.sql.classify import read_only as _read_only  # noqa: E402
# (the shared classifier: statements safe to re-execute after a device
# failure — re-running a query cannot change state; replayed DML/DDL/COPY
# or nextval() double-applies)


class SerializationError(RuntimeError):
    """COMMIT lost the single-writer OCC race: another session committed a
    conflicting table version after this transaction's BEGIN snapshot."""


@dataclass
class ShardedTable:
    """Host-side sharded layout: per-column (n_segments, capacity) arrays
    padded to the largest shard, plus true per-segment row counts."""
    columns: dict[str, np.ndarray]
    counts: np.ndarray          # (n_segments,) int64
    capacity: int
    replicated: bool
    version: int


class Session:
    def __init__(self, config: Config | None = None):
        from cloudberry_tpu.catalog.catalog import Catalog

        self.config = config or get_config()
        self.catalog = Catalog()
        # durable storage: register stored tables cold (schema/stats only),
        # then bind the catalog so new tables persist (order matters: the
        # registration itself must not write empty snapshots)
        self.store = None
        if self.config.storage.root:
            from cloudberry_tpu.storage.table_store import TableStore

            self.store = TableStore(self.config.storage.root)
            self.store.rows_per_partition = \
                self.config.storage.rows_per_partition
            self.store.quota_bytes = self.config.storage.quota_bytes
            self.store.verify_checksums = \
                self.config.storage.verify_checksums
            if self.config.storage.encryption_key:
                from cloudberry_tpu.utils.tde import make_cipher

                self.store.cipher = make_cipher(
                    self.config.storage.encryption_key)
            for name in self.store.table_names():
                self.store.register_cold(self.catalog, name)
            self.catalog.store = self.store
            from cloudberry_tpu.plan.matview import load_defs

            load_defs(self)
        # per-query pruned store reads, keyed (table, version, parts, cols)
        self._store_scan_cache: dict = {}
        # guards the scan cache's LRU mutations (hits reorder the dict,
        # and shared-session server mode runs concurrent readers)
        self._store_scan_lock = __import__("threading").Lock()
        self._sync_lock = __import__("threading").Lock()
        self._shard_cache: dict[str, ShardedTable] = {}
        # query_info_collect_hook analog: callables receiving QueryMetrics
        self.metrics_hooks: list = []
        from cloudberry_tpu.exec.resource import (AdmissionGate,
                                                  QueueManager, VmemTracker)

        self._gate = AdmissionGate(self.config.resource.max_concurrency)
        # resource queues + engine-wide vmem red line (resqueue.c /
        # vmem_tracker.c analogs, exec/resource.py)
        self._queues = QueueManager()
        self._vmem = VmemTracker(self.config.resource.total_mem_bytes)
        self._stmt_ids = __import__("itertools").count(1)
        # prepared-statement cache: sql text -> (tables, versions, nseg, run)
        # LRU + lock-guarded (the store-scan cache discipline): hits
        # reorder the dict, and shared-session server mode runs
        # concurrent readers
        self._stmt_cache: dict = {}
        self._stmt_lock = __import__("threading").Lock()
        # shared cache tier (sched/sharedcache.py): the generic-plan,
        # capacity-rung, and join-index caches live in an engine-wide
        # SCOPE — sessions over the same durable store share one (tenant
        # B re-binds tenant A's compiled skeleton with zero recompiles),
        # storeless sessions get a private scope (pre-tier behavior).
        # The _generic_cache/_rung_cache properties below are views into
        # it so existing callers and tests keep working.
        from cloudberry_tpu.sched import sharedcache

        self._cache_scope = sharedcache.scope_for(self)
        # counts-only shard layout (planning fast path; sharded_table
        # materializes the actual arrays for execution)
        self._shard_count_cache: dict = {}
        # spill diagnostics for the LAST statement (None = not tiled)
        self.last_tiled_report = None
        # adaptive-capacity growths this session (expansion-overflow
        # recoveries, exec/executor.py:grow_expansion) — observability for
        # skew tests and EXPLAIN ANALYZE consumers
        self.growth_events = 0
        # statement history + active registry (pg_stat_activity / log
        # collector analog); a server shares ONE across its connection
        # sessions (serve/server.py:_connection_session)
        from cloudberry_tpu.exec.instrument import StatementLog

        self.stmt_log = StatementLog()
        # observability plane (cloudberry_tpu/obs/): the log carries the
        # engine's metrics registry, trace ring, and statement-stats
        # table; the session's ObsConfig sizes/gates them
        self.stmt_log.configure_obs(self.config.obs)
        # admission circuit breaker (lifecycle.py): K consecutive
        # device-loss recoveries trip writes to read-only-degraded; a
        # server shares ONE across its connection sessions, like the gate
        from cloudberry_tpu.lifecycle import CircuitBreaker

        self._breaker = CircuitBreaker(
            self.config.health.breaker_threshold,
            self.config.health.breaker_cooldown_s)
        # mid-statement recovery checkpoints (exec/recovery.py): the
        # tiled executors snapshot carried state every K tiles here, and
        # a device-loss retry resumes from the last snapshot instead of
        # replaying the whole stream; statement-scoped — discarded when
        # the statement finishes
        from cloudberry_tpu.exec.recovery import RecoveryStore

        self._recovery = RecoveryStore(
            self.config.recovery.max_statements,
            max_bytes=self.config.recovery.max_bytes,
            log=self.stmt_log)
        self._session_id = id(self) & 0xFFFF
        # versioned topology (parallel/topology.py): every statement
        # pins the current TopologyEpoch at dispatch; expand/shrink
        # creates a successor epoch (online rebalance + cutover) instead
        # of mutating the mesh in place. A server shares ONE manager
        # across its connection backends, like the breaker and the
        # recovery store.
        from cloudberry_tpu.parallel.topology import TopologyManager

        self._topology = TopologyManager(self)
        # feedback-driven re-optimization (plan/feedback.py): learned
        # per-(table, key-set) sketches folded from live motion stats.
        # The store is scope-anchored (shared across sessions of a store
        # root); the VIEW is stamped on the catalog so cost/memo code
        # that only sees the catalog can consult sketches.
        from cloudberry_tpu.plan import feedback as FB

        fb_store = FB.store_for(self)
        if fb_store is not None:
            self.catalog._feedback = FB.FeedbackView(fb_store, self)
        # planck verifications still owed after a topology adoption
        # (config.topology.verify_replans): the first fresh plans after
        # a cutover run through the gate even when debug.verify_plans
        # is off
        self._verify_next_plans = 0
        # COPY ... LOG ERRORS row rejects, per table (the error-log /
        # gp_read_error_log analog, cdbsreh.c)
        self.copy_errors: dict[str, list] = {}
        # open parallel retrieve cursors (the endpoint registry analog,
        # cdbendpoint.c EndpointTokenHash) — name -> ParallelCursor
        self.parallel_cursors: dict[str, object] = {}

    # shared-tier views (sched/sharedcache.py): one lock/dict pair per
    # cache per SCOPE — shared across every session of a store scope,
    # private otherwise. Kept as properties so the pre-tier call sites
    # (paramplan, tests, degrade_mesh) stay unchanged.
    @property
    def _generic_cache(self) -> dict:
        return self._cache_scope.generic

    @property
    def _generic_lock(self):
        return self._cache_scope.generic_lock

    @property
    def _rung_cache(self) -> dict:
        return self._cache_scope.rung

    @property
    def _rung_lock(self):
        return self._cache_scope.rung_lock

    def retrieve(self, cursor: str, segment: int,
                 limit: int | None = None, token: str | None = None):
        """Drain rows from one endpoint of a PARALLEL RETRIEVE CURSOR
        (the retrieve-mode connection analog, cdbendpointretrieve.c)."""
        from cloudberry_tpu.exec.endpoint import retrieve as _r

        return _r(self, cursor, segment, limit, token)

    def dir_upload(self, table: str, rel: str, data: bytes) -> str:
        """Put a file into a DIRECTORY TABLE (the gpdirtableload role)."""
        from cloudberry_tpu.storage import dirtable as DT

        return DT.upload(self, table, rel, data)

    def dir_read(self, table: str, rel: str) -> bytes:
        """Read one file's content from a DIRECTORY TABLE."""
        from cloudberry_tpu.storage import dirtable as DT

        return DT.read(self, table, rel)

    def dir_remove(self, table: str, rel: str) -> None:
        from cloudberry_tpu.storage import dirtable as DT

        DT.remove(self, table, rel)

    def read_error_log(self, table: str):
        """Rejected rows recorded by COPY ... LOG ERRORS for ``table``
        (the gp_read_error_log() analog): DataFrame of line/errmsg/rawdata."""
        import pandas as pd

        return pd.DataFrame(self.copy_errors.get(table.lower(), []),
                            columns=["line", "errmsg", "rawdata"])

    def sql(self, query: str, _deadline: float | None = None,
            **params: Any):
        """Run one statement with failure recovery (the FTS consumption
        point, fts.c:118): a device/runtime failure probes the devices,
        optionally shrinks the segment mesh to the live count (stateless
        segments — placement re-derives for any n), and re-dispatches.

        ``_deadline`` (monotonic absolute seconds, lifecycle.py): the
        statement's cancellation deadline, checked cooperatively at
        execution seams. ``config.statement_timeout_s`` tightens it;
        the dispatcher/server pass their per-request deadline here so it
        governs EXECUTION, not just queueing. (Underscored so it can
        never shadow a user bind parameter in ``**params``.)"""
        import time as _t

        from cloudberry_tpu import lifecycle
        from cloudberry_tpu.parallel.health import (recoverable,
                                                    run_with_retry)

        h = self.config.health
        log_id = self.stmt_log.begin(query, self._session_id)
        deadline = _deadline
        timeout = self.config.statement_timeout_s
        if timeout:
            t_dl = _t.monotonic() + timeout
            deadline = t_dl if deadline is None else min(deadline, t_dl)
        handle = lifecycle.StatementHandle(log_id, deadline=deadline)
        # statement trace (obs/trace.py): the span tree rides the handle
        # so every thread serving this statement records against it; the
        # sampler (config.obs.trace_sample) bounds tracing under load
        handle.trace = self.stmt_log.start_trace(log_id, query)
        # live progress (obs/progress.py): the tiled executors' tile
        # loops feed it through the same handle channel; meta
        # "progress" and the activity rows read it
        if self.stmt_log.obs_enabled:
            from cloudberry_tpu.obs.progress import Progress

            handle.progress = Progress()
        self.stmt_log.attach(log_id, handle)
        t_begin = _t.monotonic()
        is_read = _read_only(query)
        # device-loss recoveries THIS statement needed — the circuit
        # breaker's consecutive-recovery signal; trial = this write is
        # the half-open probe write and owns the breaker verdict
        recoveries = [0]
        t_first_fail = [0.0]
        trial = False
        # the classifier's last verdict was epoch-motivated: counted in
        # on_retry (a verdict on the FINAL attempt raises instead of
        # retrying and must not inflate the counter)
        epoch_retry = [False]

        def on_retry(e, backoff_s=0.0):
            if epoch_retry[0]:
                epoch_retry[0] = False
                self.stmt_log.bump("topo_epoch_retries")
            recoveries[0] += 1
            if not t_first_fail[0]:
                t_first_fail[0] = _t.monotonic()
            if handle.trace is not None:
                handle.trace.attempt = recoveries[0]
            # recovery observability: the activity row shows the attempt
            # count + planned backoff, and the state flips to
            # 'recovering' so a stalled row reads as a retry in
            # progress, not a hang (the watchdog still enforces the
            # DEADLINE — recovery is liveness, not license)
            self.stmt_log.bump("recoveries")
            self.stmt_log.set_state(log_id, "recovering")
            self.stmt_log.annotate(
                log_id, attempts=recoveries[0],
                backoff_s=round(backoff_s, 4),
                last_error=type(e).__name__)
            if h.probe_on_error:
                self._recover_mesh(e)
            # the retry replans at the CURRENT epoch — re-stamp the
            # handle so a later unrelated failure is not misclassified
            # as another topology race (one flip buys one re-dispatch)
            handle.topology_epoch = self._topology.current.epoch_id

        def epoch_recoverable(e):
            """Device loss as always — PLUS any non-semantic failure of
            a read whose pinned topology epoch was cut over mid-flight
            (parallel/topology.py): the flip between plan and launch can
            surface as a shape/compile error rather than device loss,
            and re-dispatching at the new epoch IS the recovery."""
            from cloudberry_tpu.exec.recovery import TileReplan
            from cloudberry_tpu.parallel.topology import \
                TopologyRaceError

            if isinstance(e, TileReplan):
                return False  # the adaptive-replan loop in sql() owns it
            if recoverable(e) or isinstance(e, TopologyRaceError):
                return True
            if isinstance(e, (lifecycle.StatementError,
                              SerializationError)):
                return False
            ep = getattr(handle, "topology_epoch", None)
            if ep is None or ep == self._topology.current.epoch_id:
                return False
            epoch_retry[0] = True
            return True

        # per-statement compile observability: the delta of the engine-wide
        # compile counter over this statement (exact single-threaded; an
        # upper bound under concurrency) — "zero after warmup" is the
        # generic-plan acceptance contract
        compiles_before = self.stmt_log.counter("compiles")
        # per-statement generic-plan observability, same delta discipline
        # as the compile counter: the statements table aggregates the
        # generic-hit rate per skeleton from these (obs/statements.py)
        generic_before = self.stmt_log.counter("generic_hits")
        head = query.lstrip()[:10].split(None, 1)
        is_txn_control = bool(head) and head[0].lower() in (
            "begin", "commit", "rollback", "abort", "start", "end")
        topo_epoch = None
        try:
            # topology pin (parallel/topology.py): the statement runs to
            # completion against this epoch; a concurrent cutover waits
            # for pinned statements (bounded) before flipping, and the
            # pin is what the drain barrier counts. Pinning also ADOPTS
            # a newer epoch into this session first (a backend that
            # missed a flip, or a cross-process `mgmt expand --online`).
            topo_epoch = self._topology.pin(self)
            handle.topology_epoch = topo_epoch.epoch_id
            with lifecycle.statement_scope(handle):
                if not is_read and not is_txn_control:
                    # read-only-degraded admission: an open breaker
                    # refuses writes (retryable) while reads keep
                    # flowing. Transaction control is EXEMPT: it is
                    # host-side only (never dispatches to devices), and
                    # a session must always be able to ROLLBACK out of
                    # an open transaction on a degraded engine
                    trial = self._breaker.check_write()
                # mid-statement adaptive replan (exec/tiled.py
                # SkewSentinel): reads only — a write's tiled subplan
                # must never restart after host-side mutation. The
                # sentinel checks this flag (and its own per-handle
                # replan budget) before raising TileReplan.
                handle.adaptive_ok = is_read
                from cloudberry_tpu.exec.recovery import TileReplan
                adaptations = 0
                while True:
                    try:
                        if h.retries <= 0 or not is_read:
                            # DML/DDL/COPY are NOT retried: a device
                            # failure striking after the host-side
                            # mutation would re-apply the statement on
                            # retry (re-execution is only safe when
                            # re-running cannot change state — the
                            # reference's FTS likewise lets in-flight
                            # write transactions abort rather than
                            # replay them)
                            out = self._sql_once(query, **params)
                        else:
                            def attempt():
                                # a retried attempt is live again: the
                                # activity row leaves 'recovering' when
                                # execution resumes
                                if recoveries[0]:
                                    self.stmt_log.set_state(
                                        log_id, "running")
                                return self._sql_once(query, **params)

                            out = run_with_retry(
                                attempt,
                                retries=h.retries,
                                backoff_s=h.backoff_s,
                                on_retry=on_retry,
                                max_backoff_s=h.backoff_max_s,
                                budget_s=h.retry_budget_s,
                                recoverable_fn=epoch_recoverable)
                        break
                    except TileReplan as e:
                        # NOT a failure (no probe, no backoff, no
                        # breaker signal): the sentinel already folded
                        # the observed sketch and force-checkpointed the
                        # carried state. Evict the cached statement so
                        # the immediate re-dispatch re-plans against the
                        # fresh sketch, owe the plan verifier a pass on
                        # whatever the re-plan produces, and re-run
                        # under the SAME statement handle — the
                        # replanned executable resumes from the
                        # checkpoint (plan_signature excludes motion
                        # choices by design).
                        adaptations += 1
                        if adaptations > self.config.feedback\
                                .max_replans + 1:
                            raise  # belt over the sentinel's budget
                        with self._stmt_lock:
                            self._stmt_cache.pop(
                                self._stmt_cache_key(query, params),
                                None)
                        self._verify_next_plans = max(
                            getattr(self, "_verify_next_plans", 0), 1)
                        self.stmt_log.bump("adaptive_replans")
                        self.stmt_log.set_state(log_id, "replanning")
                        self.stmt_log.annotate(
                            log_id, adaptive_skew=round(e.ratio, 2),
                            replan_at_tile=e.tiles_done)
        except BaseException as e:
            # BaseException too: a Ctrl-C mid-statement must not leave a
            # phantom "running" entry in the shared active registry
            if trial:
                # the half-open trial write failed (loss, semantic error,
                # cancel — any reason): re-arm the cooldown, never wedge
                self._breaker.trial_failed()
            elif recoveries[0]:
                # recovery was attempted but the statement still failed
                # (retries exhausted): a hard outage counts toward the
                # trip threshold exactly like a recovered flap
                self._breaker.record_recovery()
            if isinstance(e, lifecycle.StatementTimeout):
                self.stmt_log.bump("statement_timeouts")
            elif isinstance(e, lifecycle.StatementCancelled):
                self.stmt_log.bump("statement_cancels")
            else:
                from cloudberry_tpu.exec.executor import \
                    DuplicateBuildKeyError

                if isinstance(e, DuplicateBuildKeyError):
                    # the PK-inference violation surfaced by the join's
                    # runtime duplicate check — a counted, semantic
                    # (never-retried) error class of its own
                    self.stmt_log.bump("duplicate_build_key_errors")
            self.stmt_log.finish(log_id, "error",
                                 error=f"{type(e).__name__}: {e}")
            # flight recorder (obs/flightrec.py): an erroring statement
            # auto-captures its debug bundle — after finish, so the
            # trace is closed and the bundle ships complete spans
            from cloudberry_tpu.obs import flightrec as OF

            OF.maybe_capture(
                self, query, "error", _t.monotonic() - t_begin, handle,
                params=params, error=e, counters={
                    "compiles": self.stmt_log.counter("compiles")
                    - compiles_before,
                    "generic_hits": self.stmt_log.counter("generic_hits")
                    - generic_before,
                    "recoveries": recoveries[0]})
            raise
        finally:
            # statement-scoped checkpoints die with their statement:
            # success consumed them, and a semantic failure must not
            # leak state to whatever reuses the log id space later
            self._recovery.discard(log_id)
            if topo_epoch is not None:
                self._topology.unpin(topo_epoch)
        if trial:
            self._breaker.trial_succeeded()
        if recoveries[0]:
            self._breaker.record_recovery()
            # recovery latency observability: wall clock from the first
            # device-loss failure to the statement completing
            self.stmt_log.bump(
                "recovery_wall_ms",
                int((_t.monotonic() - t_first_fail[0]) * 1000))
        else:
            self._breaker.record_success()
        is_batch = hasattr(out, "num_rows")
        compiles_d = self.stmt_log.counter("compiles") - compiles_before
        generic_d = self.stmt_log.counter("generic_hits") - generic_before
        self.stmt_log.finish(
            log_id, "ok" if is_batch else str(out)[:80],
            rows=out.num_rows() if is_batch else -1,
            compiles=compiles_d, generic_hits=generic_d)
        # flight recorder (obs/flightrec.py): a statement crossing
        # config.obs.slow_ms auto-captures its debug bundle — including
        # the result digest tools/flight_replay.py re-checks offline
        from cloudberry_tpu.obs import flightrec as OF

        OF.maybe_capture(
            self, query, "ok", _t.monotonic() - t_begin, handle,
            params=params, result=out if is_batch else None,
            counters={"compiles": compiles_d, "generic_hits": generic_d,
                      "recoveries": recoveries[0]})
        return out

    def _recover_mesh(self, e: Exception) -> None:
        """Between-retry hook: probe every device; when any are gone,
        re-derive the mesh over the SURVIVORS (probeWalRepUpdateConfig
        analog — except nothing promotes: placement is recomputed). A
        real loss leaves a hole mid-list, so the survivor indices matter,
        not just the count (segment_mesh skips the dead device)."""
        from cloudberry_tpu.parallel.health import probe

        r = probe()
        if self.config.health.degrade and r.live:
            self.degrade_mesh(len(r.live), r.live)
        # failover-as-shrink (parallel/topology.py): the probe result
        # also feeds the persistence detector — the SAME survivor set
        # observed config.topology.promote_after times promotes this
        # per-statement degrade to a formal shrink epoch, and recovery
        # triggers the symmetric expand back. Called OUTSIDE
        # degrade_mesh's sync lock (lock-order discipline).
        self._topology.note_probe(r)

    def degrade_mesh(self, n_devices: int, live_ids=None) -> bool:
        """Shrink the segment mesh to ``n_devices`` (over ``live_ids``
        when given) and invalidate every placement/plan cache. Derived
        placement (jump hash over shared storage) makes this a pure
        recompute — no data movement protocol, the reference's
        gprecoverseg/rebalance role collapses into cache invalidation.

        Versioned (parallel/topology.py): the degrade MINTS a 'degrade'
        TopologyEpoch FIRST, then adopts it (config swap + cache
        clears). Mint-before-swap matters: a statement pinning in the
        window sees the new epoch and adopts the shrunken config — the
        old ordering let a racing pin re-impose the previous epoch's
        config on top of the degrade, yielding mixed-shape plans; and
        the moved epoch token is what lets a statement that raced the
        swap re-dispatch (epoch_recoverable) instead of surfacing a
        shape error."""
        cur = self._topology.current
        n = max(1, min(cur.nseg, n_devices))
        ids = None
        if live_ids is not None:
            l = list(live_ids)
            if len(l) > n:
                # more survivors than segments: the first n suffice,
                # and an unchanged prefix keeps caches valid
                l = l[:n]
            if l != list(range(n)):
                ids = l  # a hole mid-list: the mesh must skip dead ones
        ep = self._topology.note_degrade(n, ids)
        if ep is not None:
            self._topology._adopt(self, ep)
            return True
        # the epoch already reflects this loss (another backend minted
        # it): THIS session may still be on the old config — adopt the
        # current epoch so the retry replans on the survivor mesh
        # instead of re-failing at the dead size every attempt
        cur = self._topology.current
        if cur.nseg == n and (cur.device_ids or None) == \
                (tuple(ids) if ids else None):
            return self._topology._adopt(self, cur)
        return False

    @staticmethod
    def _dispatch_seams(fault_point) -> None:
        """The two seams every dispatch path hits: dispatch_start (not
        retriable) and exec_device_lost (retriable via health.recoverable
        — the virtual mesh cannot lose a real device; this seam can).
        The cancel check AFTER them gates dispatch: an already-expired or
        cancelled statement never launches (the dispatcher's
        deadline-before-dispatch discipline, now for every path)."""
        from cloudberry_tpu.lifecycle import check_cancel

        fault_point("dispatch_start")
        fault_point("exec_device_lost")
        check_cancel()

    @staticmethod
    def _stmt_cache_key(query: str, params: dict) -> str:
        """Statement-cache key: the SQL text PLUS the user-supplied
        ``sql(query, **params)`` arguments — two calls with the same text
        but different params must never share a cached runner (the
        reference's plan cache likewise keys prepared statements on their
        parameter signature)."""
        if not params:
            return query
        return query + "\x00" + repr(sorted(params.items()))

    def _sql_once(self, query: str, **params: Any):
        import time as _t

        from cloudberry_tpu.exec.resource import check_admission
        from cloudberry_tpu.obs import trace as OT
        from cloudberry_tpu.plan.planner import plan_statement
        from cloudberry_tpu.sql.parser import parse_sql
        from cloudberry_tpu.utils.faultinject import fault_point

        self._sync_store()
        self.last_tiled_report = None  # set again by a tiled runner
        ckey = self._stmt_cache_key(query, params)
        cached = self._cached_statement(ckey)
        if cached is not None:
            runner, cost, obs_bytes = cached
            self.stmt_log.bump("stmt_cache_hits")
            self.stmt_log.bump("dispatches")
            # capacity plane (obs/capacity.py): the cached DEVICE-BYTE
            # estimate — one histogram sample, no plan walk on the hot
            # path. Kept separate from the admission cost: a tiled
            # runner admits against the whole per-query budget but its
            # measured working set is the step estimate, and feeding
            # the budget constant here would pin the peak gauge at
            # config forever
            from cloudberry_tpu.obs import capacity as OC

            OC.observe_stmt_bytes(self.stmt_log, obs_bytes)
            self._dispatch_seams(fault_point)
            t_wait = _t.perf_counter()
            with self._gate, self._admitted(cost):
                # the admission wait is the direct path's queue-wait:
                # span from requesting the slot to holding it
                self._obs_wait(t_wait)
                return self._obs_launch(runner)

        from cloudberry_tpu.obs import metrics as OM

        t0 = _t.perf_counter()
        with OT.span("parse"):
            stmt = parse_sql(query)
        t1 = _t.perf_counter()
        OM.observe_stage(self.stmt_log, "parse", t1 - t0)
        # the config this statement PLANS under: a topology cutover
        # swapping it before execute/cache makes the plan's baked
        # capacities stale — the executors below refuse with the
        # retryable TopologyRaceError instead of tracing (or caching) a
        # mixed-shape program (parallel/topology.py)
        cfg_plan = self.config
        with OT.span("plan"):
            result = plan_statement(stmt, self, params)
        OM.observe_stage(self.stmt_log, "plan", _t.perf_counter() - t1)
        if result.is_ddl:
            return result.ddl_result
        # the planck gate (config.debug.verify_plans): every plan the
        # planner or memo emitted is verified against the derived-vs-
        # required property rules RIGHT BEFORE compile — a finding is a
        # refusal, not a silently wrong answer at 8 segments
        self._verify_plan(result.plan, "session")
        # admission control: memory budget check + queue slot + vmem
        # reservation (vmem-tracker / resqueue analogs, exec/resource.py);
        # an over-budget plan falls back to tiled out-of-core execution
        # (the workfile manager / spill analog, exec/tiled.py) first
        from cloudberry_tpu.exec.resource import ResourceError

        try:
            est = check_admission(result.plan, self)
        except ResourceError:
            from cloudberry_tpu.exec.tiled import plan_tiled

            texe = plan_tiled(result.plan, self)
            if texe is None and self.config.planner.enable_memo:
                # the memo's joint order may have put a big relation on
                # a BUILD side (cheap in memory, spill-hostile: tiling
                # streams the probe path only). Re-plan greedy — the
                # fact side stays the stream — and tile that instead;
                # the reference likewise re-plans when a hash join
                # flips to batches (nodeHash.c increase-nbatch)
                # a shallow session clone carries the greedy config so
                # concurrent planners (and the mesh-resize path, which
                # also assigns self.config) never observe the override
                import copy

                clone = copy.copy(self)
                clone.config = self.config.with_overrides(
                    **{"planner.enable_memo": False})
                result2 = plan_statement(stmt, clone, params)
                self._verify_plan(result2.plan, "greedy-replan")
                texe = plan_tiled(result2.plan, clone)
                if texe is not None:
                    # the clone only existed to plan greedy: runs must
                    # report (last_tiled_report) to the REAL session
                    texe.session = self
            if texe is None:
                raise
            from cloudberry_tpu.obs import capacity as OC

            # a cached executable's report predates the pool's current
            # residency — re-stamp before charging the capacity plane
            texe.refresh_bufpool_charge()
            OC.record_tiled(self.stmt_log, texe.report)
            self.stmt_log.bump("dispatches")
            self._dispatch_seams(fault_point)
            t_wait = _t.perf_counter()
            with self._gate, self._admitted(
                    self.config.resource.query_mem_bytes):
                self._obs_wait(t_wait)
                return self._run_cached_tiled(ckey, texe, cfg_plan)
        from cloudberry_tpu.obs import capacity as OC

        # capacity plane: itemized device-byte estimate (intermediates
        # + wire buffers + rung capacities) for every fresh plan
        OC.record_statement(self.stmt_log, result.plan, self, est=est)
        self.stmt_log.bump("dispatches")
        self._dispatch_seams(fault_point)
        t_wait = _t.perf_counter()
        with self._gate, self._admitted(est.peak_bytes) as sid:
            self._obs_wait(t_wait)
            return self._run_with_growth(ckey, query, result.plan, sid,
                                         cfg_plan)

    def _obs_wait(self, t0: float) -> None:
        """Record the admission/queue wait that just ended (span +
        stage histogram) — called immediately after entering the gate."""
        import time as _t

        from cloudberry_tpu.obs import metrics as OM
        from cloudberry_tpu.obs import trace as OT

        dt = _t.perf_counter() - t0
        OT.mark("queue-wait", t0)
        OM.observe_stage(self.stmt_log, "queue_wait", dt)

    def _obs_launch(self, runner):
        """Run a compiled statement runner, recording the launch stage
        (histogram; the precise device span records inside
        run_executable/execute_distributed)."""
        import time as _t

        from cloudberry_tpu.obs import metrics as OM

        t0 = _t.perf_counter()
        out = runner()
        OM.observe_stage(self.stmt_log, "launch", _t.perf_counter() - t0)
        return out

    def _admitted(self, cost: int):
        """Queue slot (bounded active statements, MAX_COST, priority wake
        order) + engine-wide vmem reservation for one statement; yields
        the statement id growth re-reservations key on."""
        import contextlib

        q = self.catalog.resource_queues.get(
            self.config.resource.queue.lower()) \
            or self.catalog.resource_queues["default"]

        @contextlib.contextmanager
        def _cm():
            with self._queues.slot(q, cost, q.priority):
                sid = next(self._stmt_ids)
                self._vmem.reserve(sid, cost)
                try:
                    yield sid
                finally:
                    self._vmem.release(sid)

        return _cm()

    def _run_with_growth(self, ckey: str, query: str, plan,
                         stmt_id: int = 0, cfg_plan=None):
        """Execute; on a detected join-expansion overflow, grow the pair
        buffer (re-checking admission) and retry — adaptive capacity, never
        truncation (exec/executor.py:grow_expansion). Growth that blows the
        per-query budget falls back to tiled execution; growth that would
        cross the ENGINE-WIDE vmem red line terminates this statement (the
        runaway_cleaner.c decision)."""
        from cloudberry_tpu.exec.executor import ExecError, grow_expansion
        from cloudberry_tpu.exec.resource import ResourceError, check_admission

        for _ in range(6):
            try:
                return self._execute_and_cache(ckey, query, plan,
                                               cfg_plan)
            except ExecError as e:
                with self._stmt_lock:  # drop the failed runner
                    self._stmt_cache.pop(ckey, None)
                # allow_fallback: this loop may be retrying a program
                # served from the rung cache, whose check messages can
                # embed node ids from an equivalent, since-collected
                # plan — blanket growth still guarantees progress here
                if not grow_expansion(plan, str(e), allow_fallback=True):
                    raise
                self.growth_events += 1
                from cloudberry_tpu.exec.resource import RunawayError

                try:
                    est = check_admission(plan, self)  # budget-ok growth…
                    self._vmem.grow(stmt_id, est.peak_bytes)  # …red-zone ok
                except RunawayError:
                    raise  # red-zone termination, never a spill case
                except ResourceError:
                    from cloudberry_tpu.exec.tiled import plan_tiled

                    texe = plan_tiled(plan, self)  # …or the plan spills
                    if texe is None:
                        raise
                    return self._run_cached_tiled(ckey, texe, cfg_plan)
        return self._execute_and_cache(ckey, query, plan, cfg_plan)

    def _check_topology_race(self, cfg_plan) -> None:
        """Refuse to execute (or cache) a plan whose epoch moved under
        it: the baked capacities no longer match placement, and the
        compiled program — or worse, a CACHED one serving later
        statements — would mix shard shapes from two epochs. The
        epoch-race retry replans at the new epoch."""
        if cfg_plan is not None and cfg_plan is not self.config:
            from cloudberry_tpu.parallel.topology import TopologyRaceError

            self.stmt_log.bump("topo_plan_races")
            raise TopologyRaceError(
                "topology epoch changed between plan and execute; "
                "the statement re-plans at the new epoch")

    def _run_cached_tiled(self, ckey: str, texe, cfg_plan=None):
        from cloudberry_tpu.exec import executor as X

        self._check_topology_race(cfg_plan)
        names = sorted({s.table_name
                        for s in X.scans_of(texe._whole_plan())})
        if not self._any_external(names):
            report = texe.report
            self._cache_statement(
                ckey, names, texe.run,
                self.config.resource.query_mem_bytes,
                obs_bytes=max(int(report.get("est_step_bytes", 0)),
                              int(report.get("est_finalize_bytes", 0))),
                cfg=cfg_plan)
        out = self._obs_launch(texe.run)
        from cloudberry_tpu.obs import capacity as OC

        OC.record_tile_dispatch(self.stmt_log, texe.report)
        return out

    def _any_external(self, names) -> bool:
        # foreign (FDW) and directory tables count: their rows change
        # outside this engine's versioning, so cached programs would
        # replay stale reads
        def _t(n):
            return self.catalog.tables.get(n)

        return any(getattr(_t(n), "external", None)
                   or getattr(_t(n), "foreign", None)
                   or getattr(_t(n), "directory", None)
                   or getattr(_t(n), "_tablefunc", None)
                   for n in names)

    def _sync_store(self) -> None:
        """Pick up OTHER sessions' committed changes at statement start
        (outside transactions): any table whose store version moved
        re-registers cold; new tables appear, dropped ones vanish. The
        coordinator-catalog analog of the reference's shared catalog —
        manifests ARE the catalog of record."""
        if self.store is None \
                or getattr(self, "_txn_snapshot", None) is not None:
            return
        with self._sync_lock:  # server handler threads share this session
            from cloudberry_tpu.utils.faultinject import fault_point

            fault_point("sync_store")
            # fast path: one epoch read; the per-table walk only runs when
            # SOMETHING changed since this session last looked
            epoch = self.store.epoch()
            if epoch == getattr(self, "_seen_epoch", None):
                return
            self._seen_epoch = epoch
            names = set(self.store.table_names())
            for name in list(self.catalog.tables):
                t = self.catalog.tables[name]
                if t.backing is None:
                    continue
                if name not in names:
                    del self.catalog.tables[name]
                    self.catalog.bump_ddl()
                    continue
                v = self.store.current_version(name)
                if v != getattr(t, "_store_version", None):
                    del self.catalog.tables[name]
                    self.store.register_cold(self.catalog, name)
            for name in sorted(names - set(self.catalog.tables)):
                self.store.register_cold(self.catalog, name)
            # matview definitions are store state too (another session may
            # have created/refreshed one)
            from cloudberry_tpu.plan.matview import load_defs

            self.catalog.matviews = {}
            load_defs(self)

    # ----------------------------------------------------- transactions
    # Single-session transactions over the in-memory catalog: BEGIN
    # snapshots every table's (immutable-once-set) data dict plus deep
    # copies of the mutable string dictionaries and the view registry;
    # ROLLBACK restores and bumps versions so statement caches invalidate.
    # The durable-store analog is TableStore's snapshot manifests (atomic
    # CURRENT commit); this is the session-surface counterpart.

    def txn(self, kind: str) -> str:
        from cloudberry_tpu.columnar.dictionary import StringDictionary
        from cloudberry_tpu.plan.binder import BindError

        snap = getattr(self, "_txn_snapshot", None)
        if kind == "begin":
            if snap is not None:
                raise BindError("already in a transaction")
            import copy

            self._txn_snapshot = {
                "tables": {
                    name: (t, t.data,
                           {c: StringDictionary(d.values)
                            for c, d in t.dicts.items()},
                           t.policy, dict(t.validity), t.cold,
                           copy.deepcopy(t.stats))
                    for name, t in self.catalog.tables.items()},
                "views": dict(self.catalog.views),
                "matviews": dict(self.catalog.matviews),
            }
            if self.store is not None:
                # durable writes defer to COMMIT; ROLLBACK never touches
                # disk. The BEGIN snapshot's versions are the OCC base.
                self.store.begin_txn()
                self._txn_base = dict(self.store.pinned)
            return "BEGIN"
        if snap is None:
            raise BindError(f"{kind.upper()}: no transaction in progress")
        if kind == "commit":
            if self.store is not None:
                # OCC commit (the 2PC-role analog, cdbtm.c:883): first
                # committer wins for REWRITES; append-only writes merge
                # onto the concurrent snapshot instead of aborting (the
                # concurrent-DML capability of the reference's GDD). The
                # store lock makes check-then-publish atomic ACROSS
                # PROCESSES — and because it is the ONLY commit-time lock
                # and conflicts abort rather than wait, no waits-for cycle
                # can form: the no-deadlock argument that replaces the
                # reference's global deadlock detector (gdd/README.md).
                with self.store.lock():
                    # chaos seam inside the commit critical section:
                    # 'sleep' widens the conflict window for race tests,
                    # 'error' exercises in-lock failure cleanup
                    from cloudberry_tpu.utils.faultinject import \
                        fault_point

                    fault_point("occ_commit_window")
                    # cancellation seam: a statement cancelled while
                    # waiting on (or wedged inside) the commit window
                    # aborts cleanly — nothing published, lock released,
                    # RAM state restored (the before-commit-point abort)
                    from cloudberry_tpu import lifecycle

                    try:
                        lifecycle.check_cancel()
                    except lifecycle.StatementError:
                        self.store.abort_txn()
                        self._restore_snapshot(snap)
                        raise
                    base = getattr(self, "_txn_base", {})
                    conflicts = self.store.conflicting_tables(base)
                    if conflicts:
                        self.store.abort_txn()
                        self._restore_snapshot(snap)
                        raise SerializationError(
                            "could not serialize access: table(s) "
                            f"{', '.join(conflicts)} were modified by "
                            "another session after this transaction began")
                    merged = [n for n in list(self.store._txn_dirty)
                              if self.store.txn_append_only(n)
                              and self.store.current_version(n)
                              != base.get(n, 0)]
                    self.store.commit_txn(base)
                # a merged table's RAM copy is missing the other
                # session's rows — drop it so the next statement reloads
                # the merged snapshot from the store
                for name in merged:
                    self.catalog.tables.pop(name, None)
                    self.store.register_cold(self.catalog, name)
                    self.catalog.bump_ddl()
                if getattr(self, "_matviews_dirty", False):
                    # definitions deferred during the transaction flush
                    # only after the data commit succeeded
                    from cloudberry_tpu.plan.matview import _persist_defs

                    self._matviews_dirty = False
                    _persist_defs(self)
            self._txn_snapshot = None
            return "COMMIT"
        # rollback: restore RAM state WITHOUT persisting (the store never
        # saw the transaction's writes); cold tables restore to cold —
        # their placeholder arrays must never overwrite stored data
        if self.store is not None:
            self.store.abort_txn()
        self._restore_snapshot(snap)
        return "ROLLBACK"

    def _restore_snapshot(self, snap) -> None:
        self.catalog.tables = {}
        for name, (t, data, dicts, policy, validity, cold, stats) in \
                snap["tables"].items():
            t.policy = policy
            t._loading = True
            try:
                t.set_data(data, dicts, validity=validity)  # bumps version
            finally:
                t._loading = False
            t.cold = cold
            t.stats = stats  # manifest-derived stats survive (cold tables)
            self.catalog.tables[name] = t
        self.catalog.views = snap["views"]
        self.catalog.matviews = snap.get("matviews", {})
        # rolled-back DML may have advanced view contents/tokens — every
        # view is conservatively stale until refreshed or re-maintained
        from cloudberry_tpu.plan.matview import invalidate_all

        invalidate_all(self)
        self._matviews_dirty = False  # deferred defs die with the rollback
        self.catalog.bump_ddl()
        self._txn_snapshot = None

    # ------------------------------------------------- statement cache
    # The prepared-statement / plan-cache analog: a repeated query string
    # reuses its compiled XLA program as long as every referenced table's
    # data version (and the segment count) is unchanged — shapes are static
    # per version, so reuse is exact, never heuristic.

    def _table_versions(self, names) -> tuple:
        out = []
        for n in names:
            t = self.catalog.table(n)
            out.append((n, getattr(t, "_version", 0),
                        getattr(t, "_stats_version", 0)))
        return tuple(out)

    _STMT_CACHE_MAX = 64

    def _cached_statement(self, ckey: str):
        """(runner, admission cost, obs device-byte estimate) from a
        live cache entry, else None — returned together so the caller
        never re-indexes an entry a concurrent thread may have evicted.
        LRU: a hit moves the entry to the dict's end (under the lock —
        hits MUTATE the dict) so hot prepared statements survive bursts
        of one-off queries."""
        with self._stmt_lock:
            entry = self._stmt_cache.pop(ckey, None)
            if entry is not None:
                self._stmt_cache[ckey] = entry  # LRU touch
        if entry is None:
            return None
        from cloudberry_tpu.exec.udf import registry_version
        from cloudberry_tpu.plan.feedback import feedback_gen

        names, versions, cfg, ddlv, runner, cost, obs_bytes, fbgen = \
            entry
        # ddlv pairs the catalog DDL version with the UDF registry
        # version: re-registering a function must drop plans that baked
        # its OLD results in at bind time. The config IDENTITY check is
        # the config-epoch guard: any with_overrides/degrade_mesh swap
        # (n_segments, pallas, packed wire, ...) replaces the frozen tree
        # wholesale, so `is` catches every knob a program may have baked.
        # fbgen is the feedback-store generation the plan was built
        # against: a MATERIAL sketch fold (plan/feedback.py — new
        # observation or >10% drift, never a steady-state re-fold) bumps
        # it, so learned stats reach even statements the cache would
        # otherwise pin to their first plan forever.
        stale = (cfg is not self.config
                 or ddlv != (self.catalog.ddl_version,
                             registry_version())
                 or fbgen != feedback_gen(self))
        if not stale:
            try:
                stale = self._table_versions(names) != versions
            except KeyError:
                stale = True
        if stale:
            with self._stmt_lock:  # free the compiled program
                self._stmt_cache.pop(ckey, None)
            return None
        return runner, cost, obs_bytes

    def _execute_and_cache(self, ckey: str, query: str, plan,
                           cfg_plan=None):
        from cloudberry_tpu.exec import executor as X

        self._check_topology_race(cfg_plan)
        names = sorted({s.table_name for s in X.scans_of(plan)})
        seg = getattr(plan, "_direct_segment", None)
        runner = None
        if self.config.sched.generic_plans:
            # generic-plan gate (sched/paramplan.py): same-shape
            # statements share one compiled program with literals bound
            # as device inputs — zero recompiles on a skeleton hit
            from cloudberry_tpu.sched import paramplan

            runner = paramplan.generic_runner(self, query, plan)
        if runner is not None:
            pass
        elif seg is not None:
            exe = X.compile_plan(plan, self)
            runner = lambda: X.run_executable(
                exe, X.prepare_inputs(exe, self, segment=seg))
        elif self.config.n_segments > 1:
            from cloudberry_tpu.exec.dist_executor import \
                execute_distributed

            fn = self._rung_executable(query, plan, names)
            runner = lambda: execute_distributed(plan, self, fn)
        else:
            exe = X.compile_plan(plan, self)
            runner = lambda: X.run_executable(
                exe, X.prepare_inputs(exe, self))
        # external tables re-read their source per statement — a cached
        # program would replay the previous read
        if not getattr(plan, "_no_stmt_cache", False) \
                and not self._any_external(names):
            from cloudberry_tpu.exec.resource import estimate_plan_memory

            self._cache_statement(ckey, names, runner,
                                  estimate_plan_memory(plan).peak_bytes,
                                  cfg=cfg_plan)
        return self._obs_launch(runner)

    def _cache_statement(self, ckey: str, names, runner,
                         cost: int = 0, obs_bytes: int | None = None,
                         cfg=None) -> None:
        """``cost`` is the ADMISSION reservation for cache hits;
        ``obs_bytes`` (defaults to cost) is the device-byte estimate the
        capacity plane observes — tiled runners reserve the whole
        budget but measure their step working set. ``cfg`` pins the
        entry to the config the runner's plan was BUILT under (not
        whatever config the session holds at cache time): a topology
        flip between plan and cache must leave an entry the identity
        guard rejects, never one that serves a stale-epoch program."""
        from cloudberry_tpu.exec.udf import registry_version
        from cloudberry_tpu.plan.feedback import feedback_gen

        entry = (
            names, self._table_versions(names),
            cfg if cfg is not None else self.config,
            (self.catalog.ddl_version, registry_version()),
            runner, cost,
            cost if obs_bytes is None else int(obs_bytes),
            feedback_gen(self))
        with self._stmt_lock:
            self._stmt_cache.pop(ckey, None)  # re-insert at the tail
            while len(self._stmt_cache) >= self._STMT_CACHE_MAX:
                # LRU eviction (hits reorder, so the head really is the
                # least recently used) keeps the cache and its pinned
                # XLA programs bounded under literal-inlining workloads
                self._stmt_cache.pop(next(iter(self._stmt_cache)))
            self._stmt_cache[ckey] = entry

    # ----------------------------------------------- capacity-rung cache
    # Redistribute bucket capacities live on a power-of-two rung ladder
    # (plan/distribute.py seeds a rung, skew overflow promotes one —
    # exec/executor.py:grow_expansion). Each rung changes motion buffer
    # SHAPES, hence needs its own compiled SPMD program; this cache keeps
    # every rung's executable for the session so recompiles are bounded
    # by the ladder height per motion shape, and re-promoted statements
    # land on a cached program.

    _RUNG_CACHE_MAX = 32

    def _motion_rung_sig(self, plan) -> tuple:
        from cloudberry_tpu.exec import executor as X
        from cloudberry_tpu.plan import nodes as N

        # joins ride in the signature too: adaptive growth also resizes
        # PJoin.out_capacity (expansion overflow), and a retry must not
        # be served the pre-growth executable
        sig = []
        for n in X.all_nodes(plan):
            if isinstance(n, N.PMotion):
                sig.append((n.kind, n.bucket_cap, n.out_capacity,
                            n.pre_compact, n.host_bucket_cap,
                            n.hier_hosts, n.host_combine))
            elif isinstance(n, N.PJoin):
                sig.append(("join", n.out_capacity))
        return tuple(sig)

    def _rung_executable(self, query: str, plan, names):
        """Compiled distributed program for this plan's motion rungs,
        from the session cache when an equivalent (same statement, same
        table versions, same rung signature) program already exists."""
        from cloudberry_tpu.exec.dist_executor import compile_distributed
        from cloudberry_tpu.exec.udf import registry_version

        # plans that bake per-execution state into the program (folded
        # sequence nextval literals) or read outside the version system
        # (external tables) must compile fresh every time — reusing the
        # executable would replay the baked values
        if getattr(plan, "_no_stmt_cache", False) \
                or self._any_external(names):
            return compile_distributed(plan, self)
        from cloudberry_tpu.sched import sharedcache

        try:
            versions = sharedcache.table_versions(self, names)
        except KeyError:
            return compile_distributed(plan, self)
        # rung programs close over their traced plan, so cross-session
        # reuse demands the plan be a pure function of store content:
        # the scope token pins entries to one catalog generation unless
        # the scope is shared and view-free (sharedcache.rung_scope_token)
        key = (query, self.config.n_segments,
               sharedcache.rung_scope_token(self),
               registry_version(), versions, self._motion_rung_sig(plan))
        from cloudberry_tpu.exec.dist_executor import stat_node_ids

        with self._rung_lock:
            ent = self._rung_cache.pop(key, None)
            if ent is not None:
                self._rung_cache[key] = ent  # LRU touch
        if ent is not None:
            fn, traced = ent
            cur = stat_node_ids(plan)
            if traced != cur \
                    and tuple(map(len, traced)) == tuple(map(len, cur)):
                # the program's telemetry keys embed the TRACED plan's
                # node ids — alias this signature-equal plan's nodes to
                # them so motion stats (and the feedback fold behind
                # them) survive the cache hit
                plan._stat_id_alias = {
                    o: n for ts, cs in zip(traced, cur)
                    for o, n in zip(ts, cs)}
            return fn
        fn = compile_distributed(plan, self)
        with self._rung_lock:
            while len(self._rung_cache) >= self._RUNG_CACHE_MAX:
                self._rung_cache.pop(next(iter(self._rung_cache)))
            self._rung_cache[key] = (fn, stat_node_ids(plan))
        return fn

    def _verify_plan(self, plan, context: str) -> None:
        """config.debug.verify_plans gate (plan/verify.py): verify a
        freshly planned statement and raise PlanVerifyError with
        node-path findings instead of compiling a broken plan."""
        if plan is None:
            return
        owed = getattr(self, "_verify_next_plans", 0)
        if not self.config.debug.verify_plans and owed <= 0:
            return
        if owed > 0:
            # post-cutover replan window (config.topology.verify_replans):
            # approximate decrement — an extra verification under a
            # concurrent race costs wall clock, never correctness
            self._verify_next_plans = owed - 1
        from cloudberry_tpu.plan.verify import check_plan

        check_plan(plan, self, context)

    def explain(self, query: str) -> str:
        from cloudberry_tpu.sql.parser import parse_sql
        from cloudberry_tpu.plan.planner import plan_statement

        self._sync_store()
        stmt = parse_sql(query)
        result = plan_statement(stmt, self, {}, explain_only=True)
        if result.is_ddl:
            return str(result.ddl_result)
        if self.config.n_segments > 1 \
                and getattr(result.plan, "_direct_segment", None) is None:
            # stamp the verifier's DERIVED distribution on every node
            # so the plan text shows sharding explicitly (``dist:``):
            # the bracketed locus is what the distributor STAMPED, the
            # dist: suffix is what the rule table DERIVES — golden
            # diffs pin both, independently. The annotation walk IS a
            # verification, so the debug gate rides it for free.
            from cloudberry_tpu.plan.verify import (PlanVerifyError,
                                                    annotate_derived)

            findings = annotate_derived(result.plan, self)
            if findings and self.config.debug.verify_plans:
                raise PlanVerifyError(findings, "explain")
        else:
            self._verify_plan(result.plan, "explain")
        return result.plan.explain()

    def explain_analyze(self, query: str) -> str:
        """Execute with instrumentation; returns the annotated plan (the
        distributed EXPLAIN ANALYZE analog, explain_gp.c).

        Runs THROUGH the statement pipeline (instrument.run_pipeline):
        lifecycle handle + activity entry, dispatch seams, admission
        gate, and the generic-plan form of the program — the same
        program the serving path runs, with per-node row counts as an
        extra output. Motion nodes annotate with collective launches /
        wire bytes / capacity rung, runtime filters with observed
        jf_rows_in/out, and tiled execution appends its per-tile time
        histogram + checkpoint/resume counters."""
        from cloudberry_tpu.exec.instrument import (
            explain_analyze_text, plan_nodes_in_order, run_pipeline)
        from cloudberry_tpu.plan.planner import plan_statement
        from cloudberry_tpu.sql.parser import parse_sql

        self._sync_store()
        stmt = parse_sql(query)
        result = plan_statement(stmt, self, {})
        if result.is_ddl:
            return str(result.ddl_result)
        self._verify_plan(result.plan, "explain-analyze")
        _, metrics, annotations = run_pipeline(result.plan, self, query)
        counts = {id(n): r for n, (_, _, r) in
                  zip(plan_nodes_in_order(result.plan), metrics.node_rows)
                  if r >= 0}
        return explain_analyze_text(result.plan, counts,
                                    metrics.wall_s, metrics.compile_s,
                                    annotations=annotations,
                                    tiled_report=self.last_tiled_report)

    # ------------------------------------------------------- data placement

    def sharded_table(self, name: str) -> ShardedTable:
        t = self.catalog.table(name)
        t.ensure_loaded()  # distributed placement needs whole arrays
        nseg = self.config.n_segments
        key = f"{name}@{nseg}"
        cached = self._shard_cache.get(key)
        version = getattr(t, "_version", t.stats.row_count)
        if cached is not None and cached.version == version:
            return cached

        # validity masks ride as ordinary "$nn:<col>" bool columns so the
        # distributed input plumbing shards them like any other column
        phys_cols = dict(t.data)
        for cname, vm in t.validity.items():
            phys_cols[f"$nn:{cname}"] = np.asarray(vm, dtype=np.bool_)
        if t.policy.kind == "replicated":
            st = ShardedTable(phys_cols, self.shard_counts(name),
                              max(t.num_rows, 1), True, version)
        else:
            assign = t.shard_assignment(nseg)
            # ONE derivation of per-segment counts (shard_counts) feeds
            # both the planner's capacities and this materialization —
            # reusing this call's assignment so rows hash exactly once
            counts = self.shard_counts(name, _assign=assign)
            cap = max(int(counts.max()) if len(counts) else 0, 1)
            cols = {}
            order = np.argsort(assign, kind="stable") if len(assign) else assign
            starts = np.concatenate([[0], np.cumsum(counts)])
            for cname, arr in phys_cols.items():
                buf = np.zeros((nseg, cap), dtype=arr.dtype)
                sorted_arr = arr[order]
                for s in range(nseg):
                    n = counts[s]
                    buf[s, :n] = sorted_arr[starts[s]:starts[s] + n]
                cols[cname] = buf
            st = ShardedTable(cols, counts, cap, False, version)
        # deliberate lock-free publish: key embeds nseg, entry is
        # version-checked on read, and concurrent writers produce
        # identical values (last-writer-wins is idempotent)
        self._shard_cache[key] = st
        return st

    def shard_counts(self, name: str, _assign=None) -> np.ndarray:
        """Per-segment row counts WITHOUT materializing the (nseg, cap)
        shard arrays — the planner (shard capacities, motion sizing)
        only needs the counts; execution materializes via
        sharded_table, which passes its already-computed row assignment
        through ``_assign`` so the per-row hash runs once. ONE
        derivation either way, so the two always agree."""
        t = self.catalog.table(name)
        t.ensure_loaded()
        nseg = self.config.n_segments
        version = getattr(t, "_version", t.stats.row_count)
        key = (name, nseg)
        hit = self._shard_count_cache.get(key)
        if hit is not None and hit[0] == version:
            return hit[1]
        st = self._shard_cache.get(f"{name}@{nseg}")
        if st is not None and st.version == version:
            counts = st.counts  # a materialized layout already knows
        elif t.policy.kind == "replicated":
            counts = np.full(nseg, t.num_rows, dtype=np.int64)
        else:
            assign = t.shard_assignment(nseg) if _assign is None \
                else _assign
            counts = np.bincount(assign, minlength=nseg).astype(np.int64)\
                if len(assign) else np.zeros(nseg, dtype=np.int64)
        # deliberate lock-free publish: version rides the value and all
        # writers derive identical counts — a race only repeats work
        self._shard_count_cache[key] = (version, counts)
        return counts

    def shard_capacity(self, name: str) -> int:
        return max(int(self.shard_counts(name).max()), 1)
