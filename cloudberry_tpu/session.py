"""Session — the QD (query dispatcher) analog.

A Session owns a catalog, a config, and a device mesh; ``sql()`` runs the full
pipeline: parse → bind/plan (motion insertion) → compile → execute. The
reference's equivalent surface is a libpq connection to the coordinator
backend (exec_simple_query, src/backend/tcop/postgres.c:1655); here it is an
in-process Python API (the serving layer comes later).
"""

from __future__ import annotations

from typing import Any

from cloudberry_tpu.config import Config, get_config


class Session:
    def __init__(self, config: Config | None = None):
        from cloudberry_tpu.catalog.catalog import Catalog

        self.config = config or get_config()
        self.catalog = Catalog()

    def sql(self, query: str, **params: Any):
        from cloudberry_tpu.sql.parser import parse_sql
        from cloudberry_tpu.plan.planner import plan_statement
        from cloudberry_tpu.exec.executor import execute

        stmt = parse_sql(query)
        result = plan_statement(stmt, self, params)
        if result.is_ddl:
            return result.ddl_result
        return execute(result.plan, self)

    def explain(self, query: str) -> str:
        from cloudberry_tpu.sql.parser import parse_sql
        from cloudberry_tpu.plan.planner import plan_statement

        stmt = parse_sql(query)
        result = plan_statement(stmt, self, {})
        return result.plan.explain()
