"""Session — the QD (query dispatcher) analog.

A Session owns a catalog, a config, and a device mesh; ``sql()`` runs the full
pipeline: parse → bind/plan (motion insertion) → compile → execute. The
reference's equivalent surface is a libpq connection to the coordinator
backend (exec_simple_query, src/backend/tcop/postgres.c:1655); here it is an
in-process Python API (the serving layer comes later).

The session also owns segment data placement: the analog of the reference's
load-time row routing (cdbhash + jump_consistent_hash, cdbhash.c:55-78),
cached per (table, n_segments) the way segment data lives on segment disks.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

from cloudberry_tpu.config import Config, get_config


@dataclass
class ShardedTable:
    """Host-side sharded layout: per-column (n_segments, capacity) arrays
    padded to the largest shard, plus true per-segment row counts."""
    columns: dict[str, np.ndarray]
    counts: np.ndarray          # (n_segments,) int64
    capacity: int
    replicated: bool
    version: int


class Session:
    def __init__(self, config: Config | None = None):
        from cloudberry_tpu.catalog.catalog import Catalog

        self.config = config or get_config()
        self.catalog = Catalog()
        self._shard_cache: dict[str, ShardedTable] = {}
        # query_info_collect_hook analog: callables receiving QueryMetrics
        self.metrics_hooks: list = []
        from cloudberry_tpu.exec.resource import AdmissionGate

        self._gate = AdmissionGate(self.config.resource.max_concurrency)

    def sql(self, query: str, **params: Any):
        from cloudberry_tpu.exec.executor import execute
        from cloudberry_tpu.exec.resource import check_admission
        from cloudberry_tpu.plan.planner import plan_statement
        from cloudberry_tpu.sql.parser import parse_sql
        from cloudberry_tpu.utils.faultinject import fault_point

        stmt = parse_sql(query)
        result = plan_statement(stmt, self, params)
        if result.is_ddl:
            return result.ddl_result
        # admission control: memory budget check + statement slot
        # (vmem-tracker / resgroup analog, exec/resource.py)
        check_admission(result.plan, self)
        fault_point("dispatch_start")
        with self._gate:
            return execute(result.plan, self)

    def explain(self, query: str) -> str:
        from cloudberry_tpu.sql.parser import parse_sql
        from cloudberry_tpu.plan.planner import plan_statement

        stmt = parse_sql(query)
        result = plan_statement(stmt, self, {})
        if result.is_ddl:
            return str(result.ddl_result)
        return result.plan.explain()

    def explain_analyze(self, query: str) -> str:
        """Execute with instrumentation; returns the annotated plan (the
        distributed EXPLAIN ANALYZE analog, explain_gp.c)."""
        from cloudberry_tpu.exec.instrument import (
            explain_analyze_text, plan_nodes_in_order, run_instrumented)
        from cloudberry_tpu.plan.planner import plan_statement
        from cloudberry_tpu.sql.parser import parse_sql

        stmt = parse_sql(query)
        result = plan_statement(stmt, self, {})
        if result.is_ddl:
            return str(result.ddl_result)
        _, metrics = run_instrumented(result.plan, self, query)
        counts = {id(n): r for n, (_, _, r) in
                  zip(plan_nodes_in_order(result.plan), metrics.node_rows)
                  if r >= 0}
        return explain_analyze_text(result.plan, counts,
                                    metrics.wall_s, metrics.compile_s)

    # ------------------------------------------------------- data placement

    def sharded_table(self, name: str) -> ShardedTable:
        t = self.catalog.table(name)
        nseg = self.config.n_segments
        key = f"{name}@{nseg}"
        cached = self._shard_cache.get(key)
        version = getattr(t, "_version", t.stats.row_count)
        if cached is not None and cached.version == version:
            return cached

        if t.policy.kind == "replicated":
            st = ShardedTable(dict(t.data),
                              np.full(nseg, t.num_rows, dtype=np.int64),
                              max(t.num_rows, 1), True, version)
        else:
            assign = t.shard_assignment(nseg)
            counts = np.bincount(assign, minlength=nseg).astype(np.int64) \
                if len(assign) else np.zeros(nseg, dtype=np.int64)
            cap = max(int(counts.max()) if len(counts) else 0, 1)
            cols = {}
            order = np.argsort(assign, kind="stable") if len(assign) else assign
            starts = np.concatenate([[0], np.cumsum(counts)])
            for cname, arr in t.data.items():
                buf = np.zeros((nseg, cap), dtype=arr.dtype)
                sorted_arr = arr[order]
                for s in range(nseg):
                    n = counts[s]
                    buf[s, :n] = sorted_arr[starts[s]:starts[s] + n]
                cols[cname] = buf
            st = ShardedTable(cols, counts, cap, False, version)
        self._shard_cache[key] = st
        return st

    def shard_capacity(self, name: str) -> int:
        return self.sharded_table(name).capacity
