"""Seeded plan-IR corruption classes — the planck verifier's fuzz
corpus (the test_lint seeded-bug-fixture discipline applied to the plan
layer).

Each mutation is one TARGETED way a plan invariant can rot: drop a
motion, lie about a hash key, desync a param slot, undercut a capacity
rung, forge a join-index stamp. ``MUTATIONS`` maps a corruption class
to (sql, mutate_fn, expected rule ids); tests/test_planverify.py plans
the statement fresh, applies the corruption, and pins that
plan/verify.py catches it with a node-path finding carrying one of the
expected rules. A mutation returns a human-readable description of what
it broke (and the mutated plan root), or None when the planned shape
does not contain its target pattern — the test treats None as a broken
fixture, not a skip, so the corpus can never silently go stale.

These corruptions are what an incorrect planner CHANGE would produce:
every class was chosen so that, had the verifier not existed, the
mutated plan would compile and return silently wrong rows (or blow up
mid-collective) at 8 segments.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Callable, Optional

from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.plan.sharding import Sharding

# ------------------------------------------------------------- helpers


def _nodes(plan: N.PlanNode):
    # ONE child-enumeration source for the whole engine — a new node
    # class extends all_nodes once and every mutation sees it
    from cloudberry_tpu.exec.executor import all_nodes

    seen: set[int] = set()
    for node in all_nodes(plan):
        if id(node) not in seen:
            seen.add(id(node))
            yield node


def _parents(plan: N.PlanNode) -> dict:
    out = {}
    for node in _nodes(plan):
        for c in node.children():
            out.setdefault(id(c), node)
    return out


def _replace_child(parent: N.PlanNode, old: N.PlanNode,
                   new: N.PlanNode) -> None:
    for attr in ("child", "build", "probe"):
        if getattr(parent, attr, None) is old:
            setattr(parent, attr, new)
            return
    if isinstance(parent, N.PConcat):
        parent.inputs = [new if c is old else c for c in parent.inputs]
        return
    raise AssertionError("old is not a child of parent")


def _splice(plan: N.PlanNode, node: N.PlanNode) -> N.PlanNode:
    """Remove a single-child node from the tree; returns the new root."""
    child = node.children()[0]
    parents = _parents(plan)
    p = parents.get(id(node))
    if p is None:
        return child
    _replace_child(p, node, child)
    return plan


def _first(plan: N.PlanNode, pred) -> Optional[N.PlanNode]:
    for node in _nodes(plan):
        if pred(node):
            return node
    return None


def _motions(plan: N.PlanNode, kind: Optional[str] = None):
    return [m for m in _nodes(plan) if isinstance(m, N.PMotion)
            and (kind is None or m.kind == kind)]


# ----------------------------------------------------------- mutations
#
# Each fn(plan, session) -> (new_root, description) | None.


def drop_motion_under_join(plan, session):
    """Splice a broadcast/redistribute feeding a join: equal keys never
    meet again."""
    parents = _parents(plan)
    for m in _motions(plan):
        p = parents.get(id(m))
        if isinstance(p, N.PJoin) and m.kind in ("broadcast",
                                                 "redistribute"):
            return _splice(plan, m), f"spliced {m.kind} under join"
    return None


def drop_gather_at_root(plan, session):
    """Remove the statement's final gather: the coordinator slot would
    see one shard and call it the result."""
    if isinstance(plan, N.PMotion) and plan.kind == "gather":
        return plan.child, "removed root gather"
    return None


def wrong_hash_keys(plan, session):
    """Point a redistribute at a different column than it claims: rows
    route by one key, consumers assume another."""
    for m in _motions(plan, "redistribute"):
        have = {k.name for k in m.hash_keys if isinstance(k, ex.ColumnRef)}
        for f in m.child.fields:
            if f.name not in have and f.type.np_dtype.itemsize in (4, 8):
                m.hash_keys = [ex.ColumnRef(f.name, f.type)]
                return plan, f"redistribute now hashes {f.name!r}"
    return None


def rung_off_ladder(plan, session):
    """Nudge a bucket capacity off the power-of-two rung ladder."""
    for m in _motions(plan, "redistribute"):
        m.bucket_cap += 3
        m.out_capacity = m.bucket_cap * session.config.n_segments
        return plan, f"bucket_cap now {m.bucket_cap}"
    return None


def rung_below_exact(plan, session):
    """Drop a bucket capacity below the exact skew bound with no
    runtime filter to justify it: the hot key is a guaranteed
    overflow."""
    from cloudberry_tpu.exec.kernels import rung_up
    from cloudberry_tpu.plan.verify import Verifier, _rf_below

    v = Verifier(session, plan)
    for m in _motions(plan, "redistribute"):
        if _rf_below(m) is not None:
            continue
        exact = v.exact_bucket_bound(m.child, m.hash_keys)
        if exact is None or rung_up(max(exact, 8)) <= 8:
            continue
        m.bucket_cap = max(rung_up(max(exact, 8)) // 2, 8)
        m.out_capacity = m.bucket_cap * session.config.n_segments
        return plan, f"bucket_cap {m.bucket_cap} < exact rung"
    return None


def feedback_rung_forged(plan, session):
    """Stamp a redistribute as feedback-seeded and drop its rung below
    anything a live sketch justifies: a poisoned/forged learned seed
    must be a guaranteed overflow finding, not a trusted stamp."""
    for m in _motions(plan, "redistribute"):
        m._feedback_seed = {"demand": 1, "static": m.bucket_cap,
                            "rung": 8, "src": ()}
        m.bucket_cap = 8
        m.out_capacity = m.bucket_cap * session.config.n_segments
        return plan, "forged feedback seed, bucket_cap dropped to 8"
    return None


def gather_capacity_shrink(plan, session):
    """Undersize a gather's receive buffer below rows x nseg."""
    for m in _motions(plan, "gather"):
        m.out_capacity -= 1
        return plan, f"gather out_capacity now {m.out_capacity}"
    return None


def sharding_stamp_lie(plan, session):
    """Stamp a redistribute replicated: downstream consumers would skip
    motions they still need."""
    for m in _motions(plan, "redistribute"):
        m.sharding = Sharding.replicated()
        return plan, "redistribute stamped replicated"
    return None


def param_slot_desync(plan, session):
    """Inject a $params slot with no signature neighbor: the rebind
    vector and the plan disagree about what slot 0..n mean."""
    flt = _first(plan, lambda n: isinstance(n, N.PFilter))
    if flt is None:
        return None

    def sub(e):
        if isinstance(e, ex.Literal) and not isinstance(e.value, bool):
            return ex.Param(7, e.dtype, e.value)
        return None

    new_pred = ex.rewrite(flt.predicate, sub)
    if new_pred is flt.predicate:
        return None
    flt.predicate = new_pred
    return plan, "literal replaced by orphan $params slot 7"


def rf_above_motion(plan, session):
    """Hoist a runtime filter ABOVE the shuffle it prices: the wire
    ships every probe row the filter was inserted to drop."""
    parents = _parents(plan)
    for m in _motions(plan, "redistribute"):
        rf = m.child
        if not isinstance(rf, N.PRuntimeFilter):
            continue
        p = parents.get(id(m))
        if p is None:
            continue
        m.child = rf.child
        rf.child = m
        rf.sharding = m.sharding
        rf.fields = list(m.fields)
        _replace_child(p, m, rf)
        return plan, "runtime filter hoisted above its redistribute"
    return None


def rf_build_forged(plan, session):
    """Point a runtime filter at a COPY of the build: the filter keys
    no longer come from rows the join will see."""
    rf = _first(plan, lambda n: isinstance(n, N.PRuntimeFilter))
    if rf is None:
        return None
    rf.build = copy.copy(rf.build)
    return plan, "runtime filter build reference replaced by a clone"


def agg_final_partials_split(plan, session):
    """Re-route the two-stage agg's merge motion onto a NON-group
    column: each segment merges a random subset of every group's
    partials."""
    for node in _nodes(plan):
        if not (isinstance(node, N.PAgg) and node.mode == "final"
                and node.group_keys):
            continue
        m = node.child
        if not (isinstance(m, N.PMotion) and m.kind == "redistribute"):
            continue
        keys = {e.name for _, e in node.group_keys
                if isinstance(e, ex.ColumnRef)}
        for f in m.fields:
            if f.name not in keys and f.type.np_dtype.itemsize in (4, 8):
                m.hash_keys = [ex.ColumnRef(f.name, f.type)]
                m.sharding = Sharding.hashed(f.name)
                return plan, f"merge motion re-keyed to {f.name!r}"
    return None


def agg_merge_illegal(plan, session):
    """Merge a partial count with max: the final 'count' becomes the
    largest per-segment count instead of the sum."""
    for node in _nodes(plan):
        if not (isinstance(node, N.PAgg) and node.mode == "final"):
            continue
        below = node.child
        while isinstance(below, (N.PMotion, N.PShare)):
            below = below.child
        if not (isinstance(below, N.PAgg) and below.mode == "partial"):
            continue
        pf = {n: c.func for n, c in below.aggs}
        for i, (name, call) in enumerate(node.aggs):
            if isinstance(call.arg, ex.ColumnRef) \
                    and pf.get(call.arg.name) == "count":
                node.aggs[i] = (name, ex.AggCall("max", call.arg))
                return plan, f"final {name!r} now merges count with max"
    return None


def agg_single_not_colocated(plan, session):
    """Drop the group key that made a one-stage agg colocated: equal
    groups now live on several segments and aggregate alone."""
    for node in _nodes(plan):
        if not (isinstance(node, N.PAgg) and node.mode == "single"
                and node.sharding is not None
                and node.sharding.is_partitioned):
            continue
        csh = node.child.sharding
        if csh is None or csh.kind != "hashed":
            continue
        doomed = [n for n, e in node.group_keys
                  if isinstance(e, ex.ColumnRef) and e.name in csh.keys]
        if not doomed:
            continue
        node.group_keys = [(n, e) for n, e in node.group_keys
                           if n not in doomed]
        node.fields = [f for f in node.fields if f.name not in doomed]
        return plan, f"dropped colocating group key(s) {doomed}"
    return None


def window_not_colocated(plan, session):
    """Splice the redistribute under a window: partitions span
    segments and every frame is wrong."""
    for node in _nodes(plan):
        if isinstance(node, N.PWindow) \
                and isinstance(node.child, N.PMotion) \
                and node.child.kind == "redistribute":
            m = node.child
            node.child = m.child
            return plan, "spliced redistribute under window"
    return None


def concat_partitioned_input(plan, session):
    """Splice a gather feeding a set-op append: one input contributes
    a single shard."""
    for node in _nodes(plan):
        if not isinstance(node, N.PConcat):
            continue
        for i, c in enumerate(node.inputs):
            if isinstance(c, N.PMotion) and c.kind == "gather":
                node.inputs[i] = c.child
                return plan, f"spliced gather under append input {i}"
    return None


def topn_merge_key_flip(plan, session):
    """Flip the merge sort's direction above a pre-compacting gather:
    each segment keeps its top k ascending, the coordinator merges
    descending."""
    parents = _parents(plan)
    for m in _motions(plan, "gather"):
        if m.pre_compact <= 0:
            continue
        p = parents.get(id(m))
        if isinstance(p, N.PSort) and p.keys:
            e, asc = p.keys[0]
            p.keys[0] = (e, not asc)
            return plan, "merge sort direction flipped"
    return None


def full_join_dist_degrade(plan, session):
    """Flip an inner join with a replicated build to FULL: unmatched
    build rows would be emitted once per segment."""
    for node in _nodes(plan):
        if isinstance(node, N.PJoin) and node.kind == "inner" \
                and node.build.sharding is not None \
                and node.build.sharding.kind == "replicated" \
                and node.probe.sharding is not None \
                and node.probe.sharding.is_partitioned:
            node.kind = "full"
            return plan, "inner join flipped to full"
    return None


def join_key_arity(plan, session):
    """Drop one probe key: the join compares ragged key tuples."""
    j = _first(plan, lambda n: isinstance(n, N.PJoin)
               and len(n.probe_keys) >= 1)
    if j is None:
        return None
    j.probe_keys = j.probe_keys[:-1]
    return plan, "dropped last probe key"


def mask_dangling(plan, session):
    """Declare a validity mask no node provides: NULLs read as
    values."""
    f = plan.fields[0]
    plan.fields[0] = dataclasses.replace(f, null_mask=("$nn:forged",))
    return plan, f"field {f.name!r} now claims mask '$nn:forged'"


def scan_rows_overflow(plan, session):
    """Claim more rows than the scan's static capacity holds."""
    sc = _first(plan, lambda n: isinstance(n, N.PScan)
                and n.table_name != "$dual")
    if sc is None:
        return None
    sc.num_rows = sc.capacity + 5
    return plan, f"scan num_rows {sc.num_rows} > capacity {sc.capacity}"


def motion_wire_dtype(plan, session):
    """Ship a 2-byte column over the packed wire: no lane exists for
    it (the limb convention bitcasts whole u32 words)."""
    import numpy as np

    class _HalfType:
        np_dtype = np.dtype("int16")

        def __str__(self):
            return "int16"

    for m in _motions(plan):
        if m.fields:
            m.fields[0] = dataclasses.replace(m.fields[0],
                                              type=_HalfType())
            return plan, f"motion column {m.fields[0].name!r} now int16"
    return None


def jix_forged(plan, session):
    """Stamp a join-index spec on a join whose build is NOT the
    fragment the cache would describe."""
    from cloudberry_tpu.exec.joinindex import JoinIndexSpec

    for node in _nodes(plan):
        if isinstance(node, N.PJoin):
            node._jix = JoinIndexSpec("$jix:forged:k:64:table",
                                      "forged", ("k",), 64, "table", 8)
            return plan, "forged join-index stamp"
    return None


def hier_wrong_host_grouping(plan, session):
    """Stamp two-level caps for a host count that does not divide the
    mesh: rows would route to a host lane that does not exist."""
    from cloudberry_tpu.exec.kernels import rung_up

    for m in _motions(plan, "redistribute"):
        m.hier_hosts = 3            # 8-segment corpus: 8 % 3 != 0
        m.host_bucket_cap = rung_up(max(m.bucket_cap, 8))
        return plan, "two-level stamps with hier_hosts=3 on 8 segments"
    return None


def hier_inter_buffer_undersize(plan, session):
    """Undersize the aggregated inter-host block below one segment-pair
    bucket: the DCN exchange cannot hold what the intra hop may legally
    deliver — a guaranteed overflow stamped as a valid plan."""
    for m in _motions(plan, "redistribute"):
        if m.bucket_cap <= 8:
            continue
        m.hier_hosts = 2
        m.host_bucket_cap = 8       # a valid rung, below bucket_cap
        return plan, f"host_bucket_cap 8 < bucket_cap {m.bucket_cap}"
    return None


def hier_combine_forged(plan, session):
    """Forge a host-combine stamp on a join redistribute (child is not
    a partial aggregate): the 'combine' would grouped-aggregate
    arbitrary join rows and silently drop data."""
    from cloudberry_tpu.exec.kernels import rung_up

    for m in _motions(plan, "redistribute"):
        if isinstance(m.child, N.PAgg):
            continue
        m.hier_hosts = 2
        m.host_bucket_cap = rung_up(max(m.bucket_cap, 8))
        m.host_combine = True
        keys = tuple(k.name for k in m.hash_keys
                     if isinstance(k, ex.ColumnRef))
        m.combine_spec = (keys, tuple())
        return plan, "host_combine forged on a join redistribute"
    return None


def expansion_no_capacity(plan, session):
    """Zero an expansion join's pair buffer."""
    j = _first(plan, lambda n: isinstance(n, N.PJoin)
               and not n.unique_build)
    if j is None:
        return None
    j.out_capacity = 0
    return plan, "expansion join out_capacity zeroed"


# ------------------------------------------------------------ registry
#
# name -> (sql, mutate fn, expected rule ids). The SQL is planned on
# the standard TPC-H corpus session (SF0.01 seed 7, 8 segments — the
# golden-plan fixtures' world); expected rules are ANY-of: a corruption
# may trip secondary findings too, but at least one finding must carry
# an expected rule AND anchor at a path containing the mutated node
# class.

_Q_JOIN_GROUP = (
    "select l_orderkey, sum(l_extendedprice) as revenue "
    "from customer, orders, lineitem "
    "where c_custkey = o_custkey and l_orderkey = o_orderkey "
    "and c_mktsegment = 'BUILDING' "
    "group by l_orderkey order by revenue desc limit 10")
_Q_TWO_STAGE = (
    "select l_partkey, sum(l_quantity) as q, count(*) as n "
    "from lineitem group by l_partkey")
_Q_REDIST_JOIN = (
    "select count(*) as n from partsupp, lineitem "
    "where ps_partkey = l_partkey and ps_suppkey = l_suppkey")
_Q_WINDOW = (
    "select l_partkey, sum(l_quantity) over "
    "(partition by l_partkey) as w from lineitem")
_Q_UNION = (
    "select l_orderkey as k from lineitem "
    "union all select o_orderkey as k from orders")
_Q_SCAN = "select l_orderkey, l_quantity from lineitem"
# a LEFT join redistributes both sides with NO runtime filter (outer
# joins are ineligible) and a non-unique build — the expansion-buffer
# and bare-redistribute corruption targets
_Q_LEFT_EXPAND = (
    "select count(*) as n from orders left join lineitem "
    "on o_custkey = l_suppkey")

MUTATIONS: dict[str, tuple[str, Callable, frozenset]] = {
    "drop-motion-under-join": (
        _Q_JOIN_GROUP, drop_motion_under_join,
        frozenset({"join-not-colocated"})),
    "drop-gather-at-root": (
        _Q_SCAN, drop_gather_at_root, frozenset({"root-partitioned"})),
    "wrong-hash-keys": (
        _Q_TWO_STAGE, wrong_hash_keys, frozenset({"dist-mismatch"})),
    "rung-off-ladder": (
        _Q_REDIST_JOIN, rung_off_ladder, frozenset({"motion-rung"})),
    "rung-below-exact": (
        _Q_LEFT_EXPAND, rung_below_exact,
        frozenset({"motion-rung-below-exact"})),
    "feedback-rung-forged": (
        _Q_REDIST_JOIN, feedback_rung_forged,
        frozenset({"motion-rung-feedback-forged",
                   "motion-rung-below-exact"})),
    "gather-capacity-shrink": (
        _Q_SCAN, gather_capacity_shrink, frozenset({"motion-capacity"})),
    "sharding-stamp-lie": (
        _Q_TWO_STAGE, sharding_stamp_lie, frozenset({"dist-mismatch"})),
    "param-slot-desync": (
        _Q_JOIN_GROUP, param_slot_desync,
        frozenset({"param-slot-desync"})),
    "rf-above-motion": (
        _Q_REDIST_JOIN, rf_above_motion, frozenset({"rf-placement"})),
    "rf-build-forged": (
        _Q_REDIST_JOIN, rf_build_forged,
        frozenset({"rf-build-unshared"})),
    "agg-final-partials-split": (
        _Q_TWO_STAGE, agg_final_partials_split,
        frozenset({"agg-final-partials-split"})),
    "agg-merge-illegal": (
        _Q_TWO_STAGE, agg_merge_illegal,
        frozenset({"agg-merge-illegal"})),
    "agg-single-not-colocated": (
        _Q_JOIN_GROUP, agg_single_not_colocated,
        frozenset({"agg-single-not-colocated"})),
    "window-not-colocated": (
        _Q_WINDOW, window_not_colocated,
        frozenset({"window-not-colocated"})),
    "concat-partitioned-input": (
        _Q_UNION, concat_partitioned_input,
        frozenset({"concat-partitioned-input"})),
    "topn-merge-key-flip": (
        _Q_JOIN_GROUP, topn_merge_key_flip,
        frozenset({"topn-merge-sort"})),
    "full-join-dist-degrade": (
        _Q_JOIN_GROUP, full_join_dist_degrade,
        frozenset({"join-full-dist"})),
    "join-key-arity": (
        _Q_REDIST_JOIN, join_key_arity, frozenset({"join-key-arity"})),
    "mask-dangling": (
        _Q_SCAN, mask_dangling, frozenset({"mask-dangling"})),
    "scan-rows-overflow": (
        _Q_SCAN, scan_rows_overflow, frozenset({"scan-rows"})),
    "motion-wire-dtype": (
        _Q_SCAN, motion_wire_dtype, frozenset({"motion-wire-dtype"})),
    "jix-forged": (
        _Q_JOIN_GROUP, jix_forged, frozenset({"jix-illegal"})),
    "expansion-no-capacity": (
        _Q_LEFT_EXPAND, expansion_no_capacity,
        frozenset({"join-out-capacity"})),
    "hier-wrong-host-grouping": (
        _Q_REDIST_JOIN, hier_wrong_host_grouping,
        frozenset({"motion-host-grouping"})),
    "hier-inter-buffer-undersize": (
        _Q_REDIST_JOIN, hier_inter_buffer_undersize,
        frozenset({"motion-host-capacity"})),
    "hier-combine-forged": (
        _Q_REDIST_JOIN, hier_combine_forged,
        frozenset({"motion-host-combine"})),
}
