"""Plan rewrites that run before distribution:

- predicate pushdown through projections (qual pushdown): a filter whose
  columns are simple renames in the projection below moves under it —
  filters reach scans, which unlocks direct dispatch through views and
  shrinks every downstream intermediate;
- column pruning — the targetlist-narrowing the reference's planner does
  (and PAX's column projection exploits, SURVEY §2.5): each node keeps only
  the columns its ancestors actually use. On TPU this directly cuts HBM
  traffic — every pruned column is one less array scanned, gathered through
  joins, permuted by sorts, and shuffled by motions.
"""

from __future__ import annotations

from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N


def prune_plan(plan: N.PlanNode) -> N.PlanNode:
    plan = _pushdown(plan)
    _prune(plan, set(plan.names))
    return plan


def _pushdown(node: N.PlanNode) -> N.PlanNode:
    """Move PFilter under PProject when every referenced column is a plain
    rename (ColumnRef) in the projection."""
    if isinstance(node, N.PShare):
        # shared subtree: rewrite ONCE (every PShare holds the same child);
        # filters above a PShare never push into it — other consumers see
        # the same materialization
        done = getattr(node.child, "_pushdown_done", None)
        if done is None:
            done = _pushdown(node.child)
            node.child._pushdown_done = done
            done._pushdown_done = done
        node.child = done
        return node
    # rewrite children first
    if isinstance(node, N.PFilter):
        node.child = _pushdown(node.child)
        child = node.child
        if isinstance(child, N.PProject):
            renames = {n: e for n, e in child.exprs
                       if isinstance(e, ex.ColumnRef)}
            used = ex.columns_used(node.predicate)
            if used <= set(renames):
                new_pred = _substitute_cols(
                    node.predicate, {n: renames[n] for n in used})
                inner = N.PFilter(child.child, new_pred)
                inner.fields = list(child.child.fields)
                child.child = _pushdown(inner)
                return child
        return node
    for attr in ("child", "build", "probe"):
        c = getattr(node, attr, None)
        if c is not None:
            setattr(node, attr, _pushdown(c))
    if isinstance(node, N.PConcat):
        node.inputs = [_pushdown(c) for c in node.inputs]
    return node


def _substitute_cols(e: ex.Expr, mapping: dict[str, ex.Expr]) -> ex.Expr:
    def fn(n):
        if isinstance(n, ex.ColumnRef):
            return mapping.get(n.name)
        if isinstance(n, ex.IsValid):
            # mask references rewrite with the projection's renames too
            new = []
            for m in n.mask_names:
                t = mapping.get(m)
                if not isinstance(t, ex.ColumnRef):
                    return None
                new.append(t.name)
            return ex.IsValid(tuple(new), n.negate)
        return None

    return ex.rewrite(e, fn)


def _expr_cols(e: ex.Expr) -> set[str]:
    out = ex.columns_used(e)
    for node in ex.walk(e):
        v = getattr(node, "_null_expr", None)
        if v is not None:
            out |= ex.columns_used(v)
        if isinstance(node, ex.SubqueryScalar):
            _prune(node.plan, set(node.plan.names))
    return out


def _with_field_masks(node: N.PlanNode, req: set[str]) -> set[str]:
    """A required field drags its validity mask columns along."""
    out = set(req)
    for f in node.fields:
        if f.name in out:
            out.update(f.masks)
    return out


def _prune(node: N.PlanNode, req: set[str]) -> None:
    if isinstance(node, N.PScan):
        req = _with_field_masks(node, req)
        node.column_map = {phys: out for phys, out in node.column_map.items()
                           if out in req}
        node.mask_map = {phys: out for phys, out in node.mask_map.items()
                         if out in req}
        node.fields = [f for f in node.fields if f.name in req]
        return

    if isinstance(node, N.PShare):
        # consumers may need different column subsets of the shared
        # subplan: keep its full output (materialize-once trade-off)
        if not getattr(node.child, "_share_pruned", False):
            node.child._share_pruned = True
            _prune(node.child, set(node.child.names))
        return

    if isinstance(node, N.PFilter):
        _prune(node.child, req | _expr_cols(node.predicate))
        return

    if isinstance(node, N.PProject):
        req = _with_field_masks(node, req)
        node.exprs = [(n, e) for n, e in node.exprs if n in req]
        node.fields = [f for f in node.fields if f.name in req]
        child_req = set()
        for _, e in node.exprs:
            child_req |= _expr_cols(e)
        _prune(node.child, child_req)
        return

    if isinstance(node, N.PJoin):
        req = _with_field_masks(node, req)
        build_req = set()
        probe_req = set()
        for k in node.build_keys:
            build_req |= _expr_cols(k)
        for k in node.probe_keys:
            probe_req |= _expr_cols(k)
        if node.build_key_valid is not None:
            build_req |= _expr_cols(node.build_key_valid)
        if node.probe_key_valid is not None:
            probe_req |= _expr_cols(node.probe_key_valid)
        if node.residual is not None:
            rcols = _expr_cols(node.residual)
            build_names = set(node.build.names)
            build_req |= rcols & build_names
            probe_req |= rcols - build_names
        node.build_payload = [c for c in node.build_payload
                              if c in req or c in
                              (_expr_cols(node.residual)
                               if node.residual is not None else ())]
        build_req |= set(node.build_payload)
        probe_req |= req - set(node.build_payload) - {node.match_name}
        probe_req &= set(node.probe.names)
        _prune(node.build, build_req)
        _prune(node.probe, probe_req)
        node.fields = [f for f in node.fields
                       if f.name in req or f.name in node.build_payload]
        return

    if isinstance(node, N.PAgg):
        child_req = set()
        for _, e in node.group_keys:
            child_req |= _expr_cols(e)
        for _, c in node.aggs:
            if c.arg is not None:
                child_req |= _expr_cols(c.arg)
        _prune(node.child, child_req)
        return

    if isinstance(node, N.PSort):
        child_req = set(req)
        for e, _ in node.keys:
            child_req |= _expr_cols(e)
        _prune(node.child, child_req)
        return

    if isinstance(node, N.PLimit):
        _prune(node.child, set(req))
        return

    if isinstance(node, N.PMotion):
        child_req = _with_field_masks(node, set(req))
        for e in node.hash_keys:
            child_req |= _expr_cols(e)
        _prune(node.child, child_req)
        node.fields = [f for f in node.fields if f.name in child_req]
        return

    if isinstance(node, N.PWindow):
        child_req = req - {n for n, _, _ in node.calls}
        for e in node.partition_keys:
            child_req |= _expr_cols(e)
        for e, _ in node.order_keys:
            child_req |= _expr_cols(e)
        for _, _, arg in node.calls:
            if arg is not None:
                child_req |= _expr_cols(arg)
        for vexpr in (node.valids or ()):
            if vexpr is not None:
                child_req |= _expr_cols(vexpr)
        _prune(node.child, child_req)
        return

    if isinstance(node, N.PConcat):
        for c in node.inputs:
            _prune(c, set(req))
        return

    # unknown/leaf nodes: nothing to prune
    return
