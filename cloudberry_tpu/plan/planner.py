"""Statement planning + DDL/DML execution.

The dispatch analog of exec_simple_query (src/backend/tcop/postgres.c:1655):
DDL executes directly against the catalog; SELECT goes binder → distribution
pass → executable plan. The distribution pass (plan/distribute.py) is the
cdbllize analog — it inserts Motion nodes per the Sharding algebra.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from cloudberry_tpu import types as T
from cloudberry_tpu.catalog.catalog import DistributionPolicy
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.plan.binder import BindError, Binder
from cloudberry_tpu.sql import ast
from cloudberry_tpu.types import Field, Schema, SqlType


@dataclass
class PlanResult:
    is_ddl: bool = False
    ddl_result: Any = None
    plan: Optional[N.PlanNode] = None


def plan_statement(stmt: ast.Node, session, params: dict,
                   explain_only: bool = False) -> PlanResult:
    catalog = session.catalog
    # new statement: function tables it materializes while binding are
    # pinned against transient-pool eviction until the next statement
    from cloudberry_tpu.exec import tablefunc as _tf

    _tf.begin_statement(catalog)
    _refresh_referenced_externals(session, stmt)

    if isinstance(stmt, ast.CreateTable):
        if stmt.name.lower() in catalog.views:
            raise BindError(f"{stmt.name!r} already exists as a view")
        fields = []
        for c in stmt.columns:
            t = T.SQL_TYPE_MAP.get(c.type_name)
            if t is None:
                raise BindError(f"unknown type {c.type_name!r}")
            if t.base == T.DType.DECIMAL and c.scale is not None:
                t = T.DECIMAL(c.scale)
            fields.append(Field(c.name, t, nullable=not c.not_null))
        policy = {
            "hash": DistributionPolicy.hashed(*stmt.dist_keys),
            "replicated": DistributionPolicy.replicated(),
            "random": DistributionPolicy.random(),
        }[stmt.distribution]
        catalog.create_table(stmt.name, Schema(tuple(fields)), policy,
                             if_not_exists=stmt.if_not_exists,
                             partition_spec=stmt.partition)
        return PlanResult(is_ddl=True, ddl_result=f"CREATE TABLE {stmt.name}")

    if isinstance(stmt, ast.CreateExternalTable):
        if stmt.name.lower() in catalog.views:
            raise BindError(f"{stmt.name!r} already exists as a view")
        fields = []
        for c in stmt.columns:
            t = T.SQL_TYPE_MAP.get(c.type_name)
            if t is None:
                raise BindError(f"unknown type {c.type_name!r}")
            if t.base == T.DType.DECIMAL and c.scale is not None:
                t = T.DECIMAL(c.scale)
            fields.append(Field(c.name, t, nullable=not c.not_null))
        # external data is never stored: the catalog entry is ephemeral
        # and every statement re-reads the LOCATION (external.c behavior)
        tab = catalog.create_table(stmt.name, Schema(tuple(fields)),
                                   DistributionPolicy.random(),
                                   durable=False)
        tab.external = {"url": stmt.url, "delimiter": stmt.delimiter,
                        "header": stmt.header,
                        "reject_limit": stmt.reject_limit,
                        "reject_percent": stmt.reject_percent,
                        "log_errors": stmt.log_errors}
        return PlanResult(is_ddl=True,
                          ddl_result=f"CREATE EXTERNAL TABLE {stmt.name}")

    if isinstance(stmt, ast.CreateDirectoryTable):
        from cloudberry_tpu.storage import dirtable as DT

        if stmt.name.lower() in catalog.views:
            raise BindError(f"{stmt.name!r} already exists as a view")
        try:
            DT.create(session, stmt.name)
        except DT.DirTableError as e:
            raise BindError(str(e))
        return PlanResult(is_ddl=True,
                          ddl_result=f"CREATE DIRECTORY TABLE {stmt.name}")

    if isinstance(stmt, ast.CreateForeignTable):
        from cloudberry_tpu.storage.fdw import known_servers

        if stmt.name.lower() in catalog.views:
            raise BindError(f"{stmt.name!r} already exists as a view")
        if stmt.server.lower() not in known_servers():
            raise BindError(
                f"unknown foreign server {stmt.server!r} "
                f"(known: {', '.join(known_servers())}); register one "
                "with cloudberry_tpu.storage.fdw.register_fdw")
        fields = []
        for c in stmt.columns:
            ftype = T.SQL_TYPE_MAP.get(c.type_name)
            if ftype is None:
                raise BindError(f"unknown type {c.type_name!r}")
            if ftype.base == T.DType.DECIMAL and c.scale is not None:
                ftype = T.DECIMAL(c.scale)
            fields.append(Field(c.name, ftype, nullable=not c.not_null))
        # like external tables: ephemeral catalog entry, re-read per
        # referencing statement — the foreign server owns the data
        tab = catalog.create_table(stmt.name, Schema(tuple(fields)),
                                   DistributionPolicy.random(),
                                   durable=False)
        tab.foreign = {"server": stmt.server.lower(),
                       "options": dict(stmt.options)}
        return PlanResult(is_ddl=True,
                          ddl_result=f"CREATE FOREIGN TABLE {stmt.name}")

    if isinstance(stmt, ast.CreateTableAs):
        return PlanResult(is_ddl=True, ddl_result=_ctas(session, stmt))

    if isinstance(stmt, ast.CreateSequence):
        try:
            catalog.create_sequence(stmt.name, stmt.start, stmt.increment,
                                    if_not_exists=stmt.if_not_exists)
        except ValueError as e:
            raise BindError(str(e))
        return PlanResult(is_ddl=True,
                          ddl_result=f"CREATE SEQUENCE {stmt.name}")

    if isinstance(stmt, ast.DropSequence):
        try:
            catalog.drop_sequence(stmt.name, if_exists=stmt.if_exists)
        except KeyError as e:
            raise BindError(str(e.args[0]))
        return PlanResult(is_ddl=True,
                          ddl_result=f"DROP SEQUENCE {stmt.name}")

    if isinstance(stmt, ast.CreateResourceQueue):
        from cloudberry_tpu.exec.resource import _PRIORITY, ResourceQueue

        name = stmt.name.lower()
        if name in catalog.resource_queues:
            raise BindError(f"resource queue {name!r} already exists")
        known = {"active_statements", "max_cost", "priority"}
        bad = set(stmt.options) - known
        if bad:
            raise BindError(f"unknown resource queue option(s) "
                            f"{sorted(bad)}; valid: {sorted(known)}")
        prio = str(stmt.options.get("priority", "medium")).lower()
        if prio not in _PRIORITY:
            raise BindError(f"unknown priority {prio!r}")
        catalog.resource_queues[name] = ResourceQueue(
            name,
            active_statements=int(stmt.options.get("active_statements", 0)),
            max_cost=int(stmt.options.get("max_cost", 0)),
            priority=prio)
        return PlanResult(is_ddl=True,
                          ddl_result=f"CREATE RESOURCE QUEUE {stmt.name}")

    if isinstance(stmt, ast.DropResourceQueue):
        name = stmt.name.lower()
        if name == "default":
            raise BindError("cannot drop the default resource queue")
        if name not in catalog.resource_queues:
            if stmt.if_exists:
                return PlanResult(is_ddl=True,
                                  ddl_result="DROP RESOURCE QUEUE")
            raise BindError(f"unknown resource queue {name!r}")
        del catalog.resource_queues[name]
        return PlanResult(is_ddl=True,
                          ddl_result=f"DROP RESOURCE QUEUE {stmt.name}")

    if isinstance(stmt, ast.DeclareParallelCursor):
        from cloudberry_tpu.exec import endpoint as EP

        try:
            return PlanResult(is_ddl=True,
                              ddl_result=EP.declare(session, stmt.name,
                                                    stmt.query))
        except EP.CursorError as e:
            raise BindError(str(e))

    if isinstance(stmt, ast.CloseCursor):
        from cloudberry_tpu.exec import endpoint as EP

        try:
            return PlanResult(is_ddl=True,
                              ddl_result=EP.close_cursor(session,
                                                         stmt.name))
        except EP.CursorError as e:
            raise BindError(str(e))

    if isinstance(stmt, ast.CreateMatView):
        from cloudberry_tpu.plan import matview as MV

        try:
            return PlanResult(is_ddl=True,
                              ddl_result=MV.create_matview(session, stmt))
        except MV.MatViewError as e:
            raise BindError(str(e))

    if isinstance(stmt, ast.DropMatView):
        from cloudberry_tpu.plan import matview as MV

        try:
            return PlanResult(is_ddl=True, ddl_result=MV.drop_matview(
                session, stmt.name, stmt.if_exists))
        except MV.MatViewError as e:
            raise BindError(str(e))

    if isinstance(stmt, ast.RefreshMatView):
        from cloudberry_tpu.plan import matview as MV

        try:
            return PlanResult(is_ddl=True, ddl_result=MV.refresh_matview(
                session, stmt.name))
        except MV.MatViewError as e:
            raise BindError(str(e))

    if isinstance(stmt, ast.CreateView):
        if stmt.name.lower() in catalog.tables:
            raise BindError(f"{stmt.name!r} already exists as a table")
        if stmt.name.lower() in catalog.views:
            raise BindError(f"view {stmt.name!r} already exists "
                            "(no OR REPLACE yet)")
        catalog.views[stmt.name.lower()] = stmt.query
        catalog.bump_ddl()
        return PlanResult(is_ddl=True, ddl_result=f"CREATE VIEW {stmt.name}")

    if isinstance(stmt, ast.DropView):
        if stmt.name.lower() not in catalog.views:
            if stmt.if_exists:
                return PlanResult(is_ddl=True, ddl_result="DROP VIEW")
            raise BindError(f"unknown view {stmt.name!r}")
        del catalog.views[stmt.name.lower()]
        catalog.bump_ddl()
        return PlanResult(is_ddl=True, ddl_result=f"DROP VIEW {stmt.name}")

    if isinstance(stmt, ast.DropTable):
        deps = [n for n, d in catalog.matviews.items()
                if getattr(d, "base_table", None) == stmt.name.lower()]
        if deps:
            raise BindError(
                f"cannot drop table {stmt.name!r}: materialized view(s) "
                f"{', '.join(sorted(deps))} depend on it")
        if stmt.name.lower() in catalog.matviews:
            raise BindError(
                f"{stmt.name!r} is a materialized view — use DROP "
                "MATERIALIZED VIEW")
        catalog.drop_table(stmt.name, if_exists=stmt.if_exists)
        return PlanResult(is_ddl=True, ddl_result=f"DROP TABLE {stmt.name}")

    if isinstance(stmt, ast.InsertValues):
        _reject_matview_dml(catalog, stmt.table)
        res = _insert_values(catalog, stmt)
        _maintain(session, stmt.table, appended=len(stmt.rows))
        return PlanResult(is_ddl=True, ddl_result=res)

    if isinstance(stmt, ast.Explain):
        inner = stmt.stmt
        if isinstance(inner, ast.Select) and not inner.from_refs:
            # plain EXPLAIN has no side effects: fold sequence calls to a
            # placeholder WITHOUT allocating (PostgreSQL semantics)
            inner = _fold_sequence_calls(catalog, inner, allocate=False)
        aqumv_from = None
        if isinstance(inner, ast.Select) \
                and session.config.planner.enable_aqumv:
            # EXPLAIN must show the plan that would EXECUTE — including
            # the matview rewrite
            from cloudberry_tpu.plan import matview as MV

            inner, aqumv_from = MV.aqumv_rewrite(session, inner)
        binder = Binder(catalog, session.config)
        plan = binder.bind_query(inner)
        plan = _optimize(plan, session)
        if aqumv_from is not None:
            plan._aqumv = aqumv_from
        return PlanResult(is_ddl=True, ddl_result=plan.explain())

    if isinstance(stmt, (ast.Select, ast.SetOp, ast.WithQuery)):
        folded = False
        if isinstance(stmt, ast.Select) and not stmt.from_refs:
            # FROM-less sequence calls evaluate host-side at the QD — the
            # coordinator owns the number line (sequence.c '?' protocol).
            # Session.explain() plans without executing, so it must not
            # consume values (allocate=False placeholder fold).
            stmt2 = _fold_sequence_calls(catalog, stmt,
                                         allocate=not explain_only)
            folded = stmt2 is not stmt
            stmt = stmt2
        aqumv_from = None
        if isinstance(stmt, ast.Select) \
                and session.config.planner.enable_aqumv:
            from cloudberry_tpu.plan import matview as MV

            stmt, aqumv_from = MV.aqumv_rewrite(session, stmt)
        binder = Binder(catalog, session.config)
        plan = binder.bind_query(stmt)
        plan = _optimize(plan, session)
        if folded:
            # replaying a cached program would replay the SAME value —
            # sequence statements must re-plan every execution
            plan._no_stmt_cache = True
        if aqumv_from is not None:
            plan._aqumv = aqumv_from
            # view freshness is checked at PLAN time; a cached program
            # would replay a possibly-stale view after base-table DML
            plan._no_stmt_cache = True
        return PlanResult(plan=plan)

    if isinstance(stmt, ast.Analyze):
        t = catalog.table(stmt.table)
        ndv = t.analyze()
        return PlanResult(is_ddl=True,
                          ddl_result=f"ANALYZE {stmt.table} "
                                     f"({len(ndv)} columns)")

    if isinstance(stmt, ast.Cluster):
        return PlanResult(is_ddl=True, ddl_result=_cluster(session, stmt))

    if isinstance(stmt, ast.TxnStmt):
        return PlanResult(is_ddl=True,
                          ddl_result=session.txn(stmt.kind))

    if isinstance(stmt, ast.CopyFrom):
        _reject_matview_dml(catalog, stmt.table)
        res = _copy_from(session, stmt)
        _maintain(session, stmt.table, appended=int(res.split()[1]))
        return PlanResult(is_ddl=True, ddl_result=res)

    if isinstance(stmt, ast.CopyTo):
        t = catalog.tables.get(stmt.table.lower())
        if t is not None and getattr(t, "external", None):
            # CopyTo names its table as a plain string, invisible to the
            # TableName walker — refresh explicitly so the export sees
            # the source's current contents
            refresh_external_table(session, t)
        return PlanResult(is_ddl=True, ddl_result=_copy_to(session, stmt))

    if isinstance(stmt, ast.Delete):
        _reject_matview_dml(catalog, stmt.table)
        res, delta = _delete(session, stmt)
        _maintain(session, stmt.table, appended=None, delta=delta)
        return PlanResult(is_ddl=True, ddl_result=res)

    if isinstance(stmt, ast.Update):
        _reject_matview_dml(catalog, stmt.table)
        res, delta = _update(session, stmt)
        _maintain(session, stmt.table, appended=None, delta=delta)
        return PlanResult(is_ddl=True, ddl_result=res)

    if isinstance(stmt, ast.InsertSelect):
        _reject_matview_dml(catalog, stmt.table)
        res = _insert_select(session, stmt)
        _maintain(session, stmt.table, appended=int(res.split()[1]))
        return PlanResult(is_ddl=True, ddl_result=res)

    raise BindError(f"unsupported statement {type(stmt).__name__}")


def _reject_matview_dml(catalog, name: str) -> None:
    """Materialized views change only through REFRESH / maintenance, and
    readable external tables only through their LOCATION — direct DML
    would desynchronize both (the reference rejects it the same way)."""
    if name.lower() in catalog.matviews:
        raise BindError(
            f"cannot change materialized view {name!r} (use REFRESH "
            "MATERIALIZED VIEW)")
    t = catalog.tables.get(name.lower())
    if t is not None and getattr(t, "external", None):
        raise BindError(
            f"cannot change readable external table {name!r}")


def _stmt_table_names(node, catalog) -> set:
    """Every table name referenced anywhere in a statement AST (joins,
    subqueries, CTE bodies), with view definitions expanded."""
    names: set = set()

    def walk(x):
        if isinstance(x, ast.TableName):
            nm = x.name.lower()
            if nm not in names:
                names.add(nm)
                v = catalog.views.get(nm)
                if v is not None:
                    walk(v)
            return
        if isinstance(x, ast.Node):
            for val in vars(x).items():
                walk(val[1])
            return
        if isinstance(x, (list, tuple)):
            for item in x:
                walk(item)

    walk(node)
    return names


def _refresh_referenced_externals(session, stmt) -> None:
    """Re-read an external/foreign table's source only when THIS statement
    references it — an unreachable source must not fail unrelated
    queries, and unrelated statements pay no fetch."""
    cat = session.catalog
    ext = {n for n, t in cat.tables.items()
           if getattr(t, "external", None) or getattr(t, "foreign", None)
           or getattr(t, "directory", None)}
    if not ext:
        return
    for name in _stmt_table_names(stmt, cat) & ext:
        t = cat.tables[name]
        if getattr(t, "foreign", None):
            from cloudberry_tpu.storage.fdw import fetch_foreign

            fetch_foreign(session, t)
        elif getattr(t, "directory", None):
            from cloudberry_tpu.storage import dirtable as DT

            DT.refresh(session, t)
        else:
            refresh_external_table(session, t)


def _cluster(session, stmt: ast.Cluster) -> str:
    """CLUSTER t BY (cols): rewrite the table in z-order of the named
    columns (zorder_clustering.cc role). The snapshot writer chunks rows
    into micro-partition files in row order, so after the reorder each
    file's manifest min/max is a tight bounding box — predicates on any
    clustered column prune most files. A one-shot rewrite, like
    PostgreSQL's CLUSTER: later appends are not re-ordered."""
    import numpy as np

    from cloudberry_tpu.utils.zorder import zorder_key

    t = session.catalog.table(stmt.table)
    if getattr(t, "external", None):
        raise BindError("cannot CLUSTER an external table")
    t.ensure_loaded()
    cols = []
    for c in stmt.columns:
        name = c.lower()
        arr = t.data.get(name)
        if arr is None or name not in t.schema:
            raise BindError(f"CLUSTER: unknown column {c!r}")
        # schema type, not array dtype: string columns store int32
        # dictionary CODES, whose order is insertion order, not collation
        if t.schema.field(name).type.base == T.DType.STRING:
            raise BindError(f"CLUSTER: column {c!r} is a string "
                            "(dictionary codes order by insertion, "
                            "not value — not supported)")
        cols.append(arr)
    if t.num_rows == 0:
        return f"CLUSTER {stmt.table} (0 rows)"
    order = np.argsort(zorder_key(cols), kind="stable")
    data = {c: a[order] for c, a in t.data.items()}
    validity = {c: np.asarray(v)[order] for c, v in t.validity.items()}
    t.set_data(data, t.dicts, validity=validity)
    return f"CLUSTER {stmt.table} ({t.num_rows} rows)"


def _maintain(session, table_name: str, appended, delta=None) -> None:
    """Post-DML materialized-view maintenance (the IMMV trigger analog):
    appends merge incrementally; UPDATE/DELETE merge their captured
    (subtract, add) delta frames when the DML path could capture them,
    else force refresh/staleness. Also the autostats trigger point
    (autostats.c:283 — the reference likewise hooks ANALYZE off DML
    completion)."""
    _maybe_autostats(session, table_name)
    if not session.catalog.matviews:
        return
    from cloudberry_tpu.plan import matview as MV

    if appended is not None:
        MV.maintain_on_append(session, table_name, appended)
    elif delta is not None:
        MV.maintain_on_dml(session, table_name, delta[0], delta[1])
    else:
        MV.maintain_full(session, table_name)


def _ivm_frames(session, table_name: str, table, mask,
                new_data=None, new_dicts=None):
    """Decoded delta frames of the DML-affected rows for incremental
    views: (sub, add), or None when no incremental view watches the
    table (the frames then never materialize). ``mask`` selects the
    affected rows in the PRE-DML arrays; ``new_data`` (UPDATE) holds
    the post-DML arrays the add-side reads."""
    from cloudberry_tpu.plan import matview as MV

    need = MV.delta_columns(session, table_name)
    if need is None:
        return None
    import pandas as pd

    def frame(data, dicts):
        out = {}
        for c in need:
            arr = np.asarray(data[c])[mask]
            d = dicts.get(c)
            if d is not None:
                arr = np.asarray(d.values, dtype=object)[arr]
            out[c] = arr
        return pd.DataFrame(out)

    sub = frame(table.data, table.dicts)
    add = None if new_data is None else frame(new_data, new_dicts)
    return (sub, add)


def _maybe_autostats(session, table_name: str) -> None:
    """Auto-ANALYZE after DML (gp_autostats_mode): "on_no_stats" analyzes
    the first time a never-analyzed table is written; "on_change" when the
    row count drifted past autostats_threshold since the last ANALYZE.
    Cold tables are skipped — auto-analyzing would pull the whole table
    into RAM for a statement that never needed it."""
    mode = session.config.planner.autostats
    if mode == "none":
        return
    t = session.catalog.tables.get(table_name.lower())
    if t is None or t.cold or getattr(t, "external", None):
        return
    ar = t.stats.analyzed_rows
    if ar < 0:
        t.analyze()
        return
    if mode == "on_change":
        thresh = session.config.planner.autostats_threshold
        if abs(int(t.num_rows) - ar) > max(1.0, ar * thresh):
            t.analyze()


def _run_internal(session, query: ast.Node):
    """Plan + execute a synthetic query (DML rewrite machinery) — under
    the same admission control and statement slot as user queries."""
    from cloudberry_tpu.exec.executor import execute
    from cloudberry_tpu.exec.resource import check_admission

    binder = Binder(session.catalog, session.config)
    plan = _optimize(binder.bind_query(query), session)
    check_admission(plan, session)
    with session._gate:
        return execute(plan, session)


def _copy_from(session, stmt: ast.CopyFrom) -> str:
    """Delimited-file ingest (the COPY / gpfdist load path): numeric and
    decimal columns parse through the native C++ codec
    (cloudberry_tpu.native), strings/dates through the host splitter."""
    from cloudberry_tpu.utils.faultinject import fault_point

    fault_point("copy_from")
    from cloudberry_tpu import native

    table = session.catalog.table(stmt.table)
    table.ensure_loaded()
    with open(stmt.path, "rb") as fh:
        buf = fh.read()
    if stmt.header:
        nl = buf.find(b"\n")
        buf = buf[nl + 1:] if nl >= 0 else b""
    d = stmt.delimiter
    db = d.encode()
    if stmt.reject_limit is not None:
        return _copy_from_sreh(session, table, stmt, buf, db)
    # NULLs in the file (\N, or an empty field for non-string columns) need
    # per-row masks: take the host text path. The conservative byte probe
    # keeps the native fast path for files that can't contain NULLs.
    if (b"\\N" in buf or db + db in buf or buf.startswith(db)
            or b"\n" + db in buf or db + b"\n" in buf or buf.endswith(db)):
        return _copy_from_text(table, buf, db)
    fields = table.schema.fields
    text_cols: dict[int, list] = {}
    need_text = [i for i, f in enumerate(fields)
                 if f.dtype in (T.DType.STRING, T.DType.DATE,
                                T.DType.BOOL, T.DType.FLOAT64)]
    if need_text:
        db = d.encode()
        rows = [ln.split(db) for ln in buf.splitlines() if ln]
        for i in need_text:
            try:
                text_cols[i] = [r[i].decode() for r in rows]
            except IndexError:
                raise BindError(
                    f"COPY: a line has fewer than {i + 1} columns")
    parsed: dict[str, np.ndarray] = {}
    n_rows = None
    for i, f in enumerate(fields):
        if f.dtype in (T.DType.INT32, T.DType.INT64):
            arr = native.parse_int64_column(buf, i, d).astype(f.type.np_dtype)
        elif f.dtype == T.DType.DECIMAL:
            # already int64 fixed-point at the field's scale (physical form)
            arr = native.parse_decimal_column(buf, i, f.type.scale, d)
        else:  # FLOAT/BOOL/STRING/DATE through the shared text parser
            arr = _parse_text_column(text_cols[i], f, table)
        if n_rows is None:
            n_rows = len(arr)
        elif len(arr) != n_rows:
            raise BindError(
                f"COPY: column {f.name!r} parsed {len(arr)} rows, "
                f"expected {n_rows} (malformed file?)")
        old = table.data.get(f.name)
        parsed[f.name] = arr if old is None or len(old) == 0 \
            else np.concatenate([old, arr])
    # the file itself carries no NULLs on this path, but appended rows must
    # EXTEND any existing validity masks, not erase them
    new_valid = {c: np.concatenate([v, np.ones(n_rows or 0, dtype=np.bool_)])
                 for c, v in table.validity.items()}
    table.set_data(parsed, table.dicts, validity=new_valid,
                   appended=n_rows or 0)
    return f"COPY {n_rows or 0}"


def _parse_text_column(vals, f, table) -> np.ndarray:
    """One COPY column from text values — shared by the native fast path
    (float/bool/string/date columns) and the NULL-bearing text path."""
    from cloudberry_tpu.columnar.batch import encode_column

    try:
        if f.dtype in (T.DType.INT32, T.DType.INT64):
            return np.asarray([int(v) for v in vals]) \
                .astype(f.type.np_dtype)
        if f.dtype == T.DType.DECIMAL:
            return np.asarray([_exact_decimal(v, f.type.scale)
                               for v in vals], dtype=np.int64)
        if f.dtype == T.DType.FLOAT64:
            return np.asarray([float(v) for v in vals])
        if f.dtype == T.DType.BOOL:
            out = []
            for v in vals:
                lv = str(v).lower()
                if lv in ("t", "true", "1"):
                    out.append(True)
                elif lv in ("f", "false", "0"):
                    out.append(False)
                else:
                    raise BindError(
                        f"COPY: malformed boolean {v!r} in column "
                        f"{f.name!r}")
            return np.asarray(out)
        return encode_column(np.asarray(vals, dtype=object), f, table.dicts)
    except ValueError as e2:
        raise BindError(
            f"COPY: malformed value in column {f.name!r}: {e2}")


def _sreh_convert(tok_b: bytes, f):
    """One field of one row → physical value or None (NULL); raises
    ValueError on a malformed token (the per-row reject decision)."""
    from cloudberry_tpu.types import date_to_days

    tok = tok_b.decode()
    if tok_b == b"\\N" or (tok == "" and f.dtype != T.DType.STRING):
        if not f.nullable:
            raise ValueError(f"null value in NOT NULL column {f.name!r}")
        return None
    if f.dtype in (T.DType.INT32, T.DType.INT64):
        v = int(tok)
        bits = 31 if f.dtype == T.DType.INT32 else 63
        if not -(1 << bits) <= v < (1 << bits):
            raise ValueError(f"value {tok} out of range for {f.name!r}")
        return v
    if f.dtype == T.DType.DECIMAL:
        v = _exact_decimal(tok, f.type.scale)
        if not -(1 << 63) <= v < (1 << 63):
            raise ValueError(f"value {tok} out of range for {f.name!r}")
        return v
    if f.dtype == T.DType.FLOAT64:
        return float(tok)
    if f.dtype == T.DType.BOOL:
        lv = tok.lower()
        if lv in ("t", "true", "1"):
            return True
        if lv in ("f", "false", "0"):
            return False
        raise ValueError(f"malformed boolean {tok!r}")
    if f.dtype == T.DType.DATE:
        return date_to_days(tok)
    return tok  # STRING


def _copy_from_sreh(session, table, stmt: ast.CopyFrom, buf: bytes,
                    db: bytes) -> str:
    """COPY with single-row error handling (cdbsreh.c): malformed rows are
    rejected (and logged with LOG ERRORS) instead of aborting, until the
    SEGMENT REJECT LIMIT trips — then the whole load aborts with nothing
    appended (validation precedes the single set_data)."""
    from cloudberry_tpu.columnar.batch import encode_column

    fields = table.schema.fields
    good: list[list] = []
    errors: list[dict] = []
    lines = [ln for ln in buf.splitlines() if ln]
    limit = stmt.reject_limit

    def tripped() -> bool:
        if stmt.reject_percent:
            return len(errors) * 100 > limit * max(len(lines), 1)
        # cdbsreh.c aborts when the reject count REACHES the limit
        return len(errors) >= limit

    for lineno, ln in enumerate(lines, start=1 + int(stmt.header)):
        toks = ln.split(db)
        if len(toks) != len(fields):
            errors.append({"line": lineno,
                           "errmsg": f"expected {len(fields)} columns, "
                                     f"got {len(toks)}",
                           "rawdata": ln.decode(errors="replace")})
            continue
        try:
            good.append([_sreh_convert(t, f)
                         for t, f in zip(toks, fields)])
        except (ValueError, BindError, OverflowError) as e:
            errors.append({"line": lineno, "errmsg": str(e),
                           "rawdata": ln.decode(errors="replace")})
    if not stmt.reject_percent and tripped():
        raise BindError(
            f"COPY: segment reject limit {limit} reached "
            f"({len(errors)} rejected rows); load aborted")
    if stmt.reject_percent and tripped():
        raise BindError(
            f"COPY: segment reject limit {limit} PERCENT exceeded "
            f"({len(errors)}/{len(lines)} rejected); load aborted")

    n_rows = len(good)
    parsed, new_valid = {}, {}
    for i, f in enumerate(fields):
        vals = [r[i] for r in good]
        isnull = np.asarray([v is None for v in vals], dtype=np.bool_)
        if f.dtype == T.DType.STRING:
            arr = encode_column(
                np.asarray([v if v is not None else "" for v in vals],
                           dtype=object), f, table.dicts)
        else:
            arr = np.asarray([0 if v is None else v for v in vals]) \
                .astype(f.type.np_dtype) if vals else \
                np.zeros(0, dtype=f.type.np_dtype)
        old = table.data.get(f.name)
        n_old = len(old) if old is not None else 0
        parsed[f.name] = arr if n_old == 0 else np.concatenate([old, arr])
        old_v = table.validity.get(f.name)
        if isnull.any() or old_v is not None:
            if old_v is None:
                old_v = np.ones(n_old, dtype=np.bool_)
            new_valid[f.name] = np.concatenate([old_v, ~isnull]) \
                if n_old else ~isnull
    table.set_data(parsed, table.dicts, validity=new_valid,
                   appended=n_rows)
    if stmt.log_errors and errors:
        session.copy_errors.setdefault(table.name, []).extend(errors)
    if errors:
        return f"COPY {n_rows} (rejected {len(errors)} rows)"
    return f"COPY {n_rows}"


def refresh_external_table(session, t) -> None:
    """(Re)load an external table from its LOCATION — called at statement
    start, so every query sees the source's current contents (external
    scans in the reference read the URL per query, url_curl.c). cbfdist
    URLs fetch one stripe per segment IN PARALLEL (the gpfdist scatter
    protocol); file:// reads locally."""
    from urllib.parse import urlparse

    spec = t.external
    parsed = urlparse(spec["url"])
    if parsed.scheme == "file":
        try:
            with open(parsed.netloc + parsed.path, "rb") as fh:
                buf = fh.read()
        except OSError as e:
            raise BindError(
                f"external table {t.name!r}: cannot read source: {e}")
    elif parsed.scheme == "cbfdist":
        import urllib.request
        from concurrent.futures import ThreadPoolExecutor

        n = max(session.config.n_segments, 1)

        def fetch(i: int) -> bytes:
            u = (f"http://{parsed.netloc}{parsed.path}"
                 f"?segment={i}&nseg={n}")
            with urllib.request.urlopen(u, timeout=30) as r:
                return r.read()

        try:
            with ThreadPoolExecutor(max_workers=min(n, 8)) as ex:
                buf = b"".join(ex.map(fetch, range(n)))
        except Exception as e:
            raise BindError(
                f"external table {t.name!r}: cbfdist fetch failed: {e}")
    else:
        raise BindError(
            f"external table {t.name!r}: unsupported URL scheme "
            f"{parsed.scheme!r} (use cbfdist:// or file://)")
    if spec["header"]:
        nl = buf.find(b"\n")
        buf = buf[nl + 1:] if nl >= 0 else b""
    # replace semantics: the table IS the file's current contents
    t._loading = True
    try:
        t.set_data({f.name: np.zeros(0, dtype=f.type.np_dtype)
                    for f in t.schema.fields}, t.dicts, validity={})
    finally:
        t._loading = False
    db = spec["delimiter"].encode()
    if spec["reject_limit"] is not None:
        from types import SimpleNamespace

        # the error log reflects the CURRENT read, not an accumulation
        # over every statement's re-read
        session.copy_errors.pop(t.name, None)
        opts = SimpleNamespace(reject_limit=spec["reject_limit"],
                               reject_percent=spec["reject_percent"],
                               log_errors=spec["log_errors"], header=False)
        _copy_from_sreh(session, t, opts, buf, db)
    else:
        _copy_from_text(t, buf, db)


def _copy_from_text(table, buf: bytes, db: bytes) -> str:
    """COPY FROM host text path with NULL support: \\N is NULL everywhere;
    an empty field is NULL for non-string columns (empty string is a value
    for strings, matching PostgreSQL text-format COPY)."""
    fields = table.schema.fields
    rows = [ln.split(db) for ln in buf.splitlines() if ln]
    n_rows = len(rows)
    parsed = {}
    new_valid = {}
    for i, f in enumerate(fields):
        try:
            toks = [r[i] for r in rows]
        except IndexError:
            raise BindError(f"COPY: a line has fewer than {i + 1} columns")
        if f.dtype == T.DType.STRING:
            isnull = np.asarray([t == b"\\N" for t in toks], dtype=np.bool_)
        else:
            isnull = np.asarray([t in (b"", b"\\N") for t in toks],
                                dtype=np.bool_)
        if isnull.any() and not f.nullable:
            raise BindError(f"COPY: NULL in NOT NULL column {f.name!r}")
        vals = [_NULL_FILL[f.dtype] if m else t.decode()
                for t, m in zip(toks, isnull)]
        arr = _parse_text_column(vals, f, table)
        old = table.data.get(f.name)
        n_old = len(old) if old is not None else 0
        parsed[f.name] = arr if n_old == 0 else np.concatenate([old, arr])
        old_v = table.validity.get(f.name)
        if isnull.any() or old_v is not None:
            if old_v is None:
                old_v = np.ones(n_old, dtype=np.bool_)
            new_valid[f.name] = np.concatenate([old_v, ~isnull]) \
                if n_old else ~isnull
    table.set_data(parsed, table.dicts, validity=new_valid,
                   appended=n_rows)
    return f"COPY {n_rows}"


def _copy_to(session, stmt: ast.CopyTo) -> str:
    """Delimited-file unload (COPY TO / writable-external analog).
    Decimals format from their raw int64 fixed-point (never through float,
    which would round past 2^53); values containing the delimiter or a
    newline are rejected rather than silently corrupting the file."""
    from cloudberry_tpu.types import days_to_date

    table = session.catalog.table(stmt.table)
    table.ensure_loaded()
    n = table.num_rows
    d = stmt.delimiter
    cols = []
    for f in table.schema.fields:
        arr = table.data[f.name]
        if f.dtype == T.DType.DECIMAL:
            cols.append([_fmt_decimal(int(v), f.type.scale) for v in arr])
        elif f.dtype == T.DType.DATE:
            cols.append([str(days_to_date(int(v))) for v in arr])
        elif f.dtype == T.DType.STRING:
            values = table.dicts[f.name].values if f.name in table.dicts \
                else []
            out = []
            for code in arr:
                v = values[code]
                if d in v or "\n" in v:
                    raise BindError(
                        f"COPY TO: value in column {f.name!r} contains the "
                        "delimiter or a newline; choose another DELIMITER")
                out.append(v)
            cols.append(out)
        elif f.dtype == T.DType.FLOAT64:
            cols.append([repr(float(v)) for v in arr])
        else:
            cols.append([str(v) for v in arr])
    for idx, f in enumerate(table.schema.fields):
        vm = table.validity.get(f.name)
        if vm is not None:
            col = cols[idx]
            for i in np.nonzero(~np.asarray(vm))[0]:
                col[i] = "\\N"
    with open(stmt.path, "w") as fh:
        if stmt.header:
            fh.write(d.join(table.schema.names) + "\n")
        for i in range(n):
            fh.write(d.join(c[i] for c in cols) + "\n")
    return f"COPY {n}"


def _fmt_decimal(raw: int, scale: int) -> str:
    if scale == 0:
        return str(raw)
    sign = "-" if raw < 0 else ""
    raw = abs(raw)
    return f"{sign}{raw // 10 ** scale}.{raw % 10 ** scale:0{scale}d}"


def _eval_aligned(session, table_name: str, items: list):
    """Run ``SELECT items FROM table`` (no WHERE — every row, exactly once)
    and return (columns, validity, dicts) ALIGNED to the table's canonical
    host row order.

    This is the DML read path: only the expressions DML actually needs flow
    through the executor (and, distributed, through the gather motion) —
    never the whole table. Distributed results arrive segment-major (the
    shard layout order), so they scatter back through the same stable
    placement permutation ``sharded_table`` used; canonical row order is
    therefore STABLE under DML in every mode."""
    q = ast.Select(items=items, from_refs=[ast.TableName(table_name)])
    batch = _run_internal(session, q)
    sel = np.asarray(batch.sel)
    cols = {f.name: np.asarray(batch.columns[f.name])[sel]
            for f in batch.schema.fields}
    valid = {n: np.asarray(v).astype(np.bool_)[sel]
             for n, v in batch.validity.items()}
    t = session.catalog.table(table_name)
    n = t.num_rows
    for name, arr in cols.items():
        if len(arr) != n:
            raise BindError(
                f"DML row evaluation returned {len(arr)} rows for "
                f"{table_name!r} ({n} rows) — internal error")
    nseg = session.config.n_segments
    if nseg > 1 and t.policy.kind != "replicated" and n:
        assign = t.shard_assignment(nseg)
        order = np.argsort(assign, kind="stable")
        cols = {name: _unpermute(arr, order) for name, arr in cols.items()}
        valid = {name: _unpermute(arr, order)
                 for name, arr in valid.items()}
    return cols, valid, dict(batch.dicts)


def _unpermute(arr: np.ndarray, order: np.ndarray) -> np.ndarray:
    out = np.empty_like(arr)
    out[order] = arr
    return out


def _delete(session, stmt: ast.Delete) -> tuple:
    """DELETE = keep the complement (delete-and-rewrite over immutable
    columns — the visimap-style store path lives in storage/table_store).
    Only the PREDICATE flows through the executor (nodeSplitUpdate.c's
    discipline of shipping decisions, not payloads): survivors are sliced
    from the canonical host arrays, so peak extra memory is one bool column
    plus the survivor arrays — independent of column count."""
    from cloudberry_tpu.utils.faultinject import fault_point

    fault_point("dml_delete")
    table = session.catalog.table(stmt.table)
    table.ensure_loaded()
    before = table.num_rows
    if stmt.where is None:
        delta = _ivm_frames(session, stmt.table, table,
                            np.ones(before, dtype=bool))
        table.set_data({f.name: np.zeros(0, dtype=f.type.np_dtype)
                        for f in table.schema.fields}, table.dicts)
        return f"DELETE {before}", delta
    # DELETE removes rows where the predicate is TRUE; a NULL predicate
    # KEEPS the row (3VL) — so keep NOT pred OR pred IS NULL
    keep_expr = ast.BinOp("or", ast.UnaryOp("not", stmt.where),
                          ast.IsNull(stmt.where, False))
    cols, _, _ = _eval_aligned(session, stmt.table,
                               [ast.SelectItem(keep_expr, "keep")])
    keep = cols["keep"].astype(np.bool_)
    # capture the deleted rows' key/arg columns BEFORE the rewrite:
    # incremental views subtract exactly this contribution
    delta = _ivm_frames(session, stmt.table, table, ~keep)
    new_data = {f.name: table.data[f.name][keep]
                for f in table.schema.fields}
    new_valid = {c: np.asarray(v)[keep]
                 for c, v in table.validity.items()}
    table.set_data(new_data, table.dicts, validity=new_valid)
    return f"DELETE {before - int(keep.sum())}", delta


_TYPE_NAME = {T.DType.BOOL: ("boolean", None), T.DType.INT32: ("integer", None),
              T.DType.INT64: ("bigint", None),
              T.DType.FLOAT64: ("double", None),
              T.DType.DATE: ("date", None), T.DType.STRING: ("text", None)}


def _update(session, stmt: ast.Update) -> tuple:
    """UPDATE col = CASE WHEN pred THEN expr ELSE col END — but ONLY the
    SET columns (plus the predicate) flow through the executor; untouched
    columns pass to set_data as the SAME host arrays, copy-free (the
    nodeSplitUpdate.c role: ship the changed values, not the table). The
    result re-shards lazily if a distribution key changed (version bump
    invalidates the shard cache)."""
    from cloudberry_tpu.utils.faultinject import fault_point

    fault_point("dml_update")
    table = session.catalog.table(stmt.table)
    table.ensure_loaded()
    set_cols = {c for c, _ in stmt.sets}
    unknown = set_cols - set(table.schema.names)
    if unknown:
        raise BindError(f"UPDATE of unknown column(s) {sorted(unknown)}")
    items = []
    sets = dict(stmt.sets)
    set_fields = [f for f in table.schema.fields if f.name in set_cols]
    for f in set_fields:
        src: ast.ExprNode = ast.Name((f.name,))
        expr = sets[f.name]
        if stmt.where is not None:
            val = ast.CaseExpr([(stmt.where, expr)], src)
        elif f.dtype == T.DType.STRING:
            # CASE wrapper even without WHERE: the string-CASE binder is
            # what assigns dictionary codes to string literals
            val = ast.CaseExpr([(ast.BoolLit(True), expr)], src)
        else:
            val = expr
        if f.dtype == T.DType.DECIMAL:
            val = ast.CastExpr(val, "decimal", f.type.scale)
        elif f.dtype != T.DType.STRING:
            tname, _ = _TYPE_NAME[f.dtype]
            val = ast.CastExpr(val, tname)
        items.append(ast.SelectItem(val, f.name))
    if stmt.where is not None:
        items.append(ast.SelectItem(stmt.where, "$updated"))
    cols, valid, qdicts = _eval_aligned(session, stmt.table, items)
    n = table.num_rows
    if stmt.where is not None:
        upd = cols["$updated"].astype(np.bool_)
        if "$updated" in valid:  # NULL predicate updates nothing (3VL)
            upd &= valid["$updated"]
        n_upd = int(upd.sum())
    else:
        n_upd = n
    new_data = dict(table.data)  # untouched columns: same arrays, no copy
    new_valid = dict(table.validity)
    dicts = dict(table.dicts)
    for f in set_fields:
        # the query may have produced codes in a NEW dictionary (string
        # CASE/literal): adopt it — old codes stay valid only because it
        # extends the old one, which _bind_string_case guarantees
        if f.dtype == T.DType.STRING and f.name in qdicts:
            dicts[f.name] = qdicts[f.name]
        new_data[f.name] = cols[f.name].astype(f.type.np_dtype)
        vm = valid.get(f.name)
        if vm is not None:
            new_valid[f.name] = vm
        else:
            new_valid.pop(f.name, None)  # column is now fully valid
    # incremental views: subtract the affected rows' OLD contribution,
    # add their NEW one — captured before set_data swaps the arrays
    mask = upd if stmt.where is not None else np.ones(n, dtype=bool)
    delta = _ivm_frames(session, stmt.table, table, mask,
                        new_data=new_data, new_dicts=dicts)
    table.set_data(new_data, dicts, validity=new_valid)
    return f"UPDATE {n_upd}", delta


def _ctas(session, stmt: ast.CreateTableAs) -> str:
    """CREATE TABLE AS: materialize the query, derive the schema from its
    output fields, place per the DISTRIBUTED clause."""
    if stmt.name.lower() in session.catalog.views:
        raise BindError(f"{stmt.name!r} already exists as a view")
    if stmt.name.lower() in session.catalog.tables:
        if stmt.if_not_exists:
            return f"CREATE TABLE {stmt.name} (exists, skipped)"
        raise BindError(f"table {stmt.name!r} already exists")
    batch = _run_internal(session, stmt.query)
    policy = {
        "hash": DistributionPolicy.hashed(*stmt.dist_keys),
        "replicated": DistributionPolicy.replicated(),
        "random": DistributionPolicy.random(),
    }[stmt.distribution]
    if stmt.distribution == "hash":
        missing = set(stmt.dist_keys) - set(batch.schema.names)
        if missing:
            raise BindError(f"distribution key(s) {sorted(missing)} not in "
                            "the query output")
    t = session.catalog.create_table(stmt.name, batch.schema, policy)
    sel = np.asarray(batch.sel)
    data, validity = {}, {}
    for f in batch.schema.fields:
        data[f.name] = np.asarray(batch.columns[f.name])[sel] \
            .astype(f.type.np_dtype)
        vm = batch.validity.get(f.name)
        if vm is not None:
            validity[f.name] = np.asarray(vm).astype(np.bool_)[sel]
    t.set_data(data, dict(batch.dicts), validity=validity)
    return f"SELECT {int(sel.sum())}"


def _physical_convert(arr: np.ndarray, qf, f, qdicts, table) -> np.ndarray:
    """Query-output physical column → target table physical column. Same
    dtype (and, for decimals, same scale; for strings, the same dictionary)
    copies raw physical values — digit-exact for decimals, where a decode
    round-trip through float would lose precision past 2^53. Everything
    else funnels through the shared decode/encode pair."""
    from cloudberry_tpu.columnar.batch import decode_column, encode_column

    if qf.dtype == f.dtype:
        if f.dtype == T.DType.DECIMAL:
            d = f.type.scale - qf.type.scale
            if d == 0:
                return arr.astype(np.int64)
            if d > 0:
                a = arr.astype(np.int64)
                limit = (2 ** 63 - 1) // 10 ** d
                if len(a) and int(np.abs(a).max()) > limit:
                    raise BindError(
                        f"INSERT: value out of range for column "
                        f"{f.name!r} (DECIMAL scale {f.type.scale})")
                return a * np.int64(10 ** d)
            # downscale: round half away from zero, matching numeric
            div = np.int64(10 ** (-d))
            a = arr.astype(np.int64)
            lo = np.iinfo(np.int64).min
            if len(a) and bool((a == lo).any()):
                # |int64.min| overflows np.abs — route those lanes
                # through exact Python ints
                out = np.empty(len(a), dtype=np.int64)
                dv = int(div)
                for i, v in enumerate(a):
                    av, neg = abs(int(v)), int(v) < 0
                    qq, rr = divmod(av, dv)
                    qq += 2 * rr >= dv
                    out[i] = -qq if neg else qq
                return out
            q, r = np.divmod(np.abs(a), div)
            q = q + (2 * r >= div)
            return np.where(arr < 0, -q, q)
        if f.dtype == T.DType.STRING:
            qd = qdicts.get(qf.name)
            td = table.dicts.get(f.name)
            if qd is not None and qd is td:
                return arr.astype(f.type.np_dtype)
        else:
            return arr.astype(f.type.np_dtype)
    vals = decode_column(np.asarray(arr), qf, qdicts)
    return encode_column(np.asarray(vals), f, table.dicts)


def _insert_select(session, stmt: ast.InsertSelect) -> str:
    """INSERT ... SELECT appends the query's PHYSICAL columns directly —
    no pandas round-trip: dictionary codes translate only when the query
    produced a different dictionary, decimals at the target scale copy raw
    int64 (exact), and validity masks carry over as-is."""
    from cloudberry_tpu.utils.faultinject import fault_point

    fault_point("dml_insert_select")
    table = session.catalog.table(stmt.table)
    cols = stmt.columns or table.schema.names
    if list(cols) != list(table.schema.names):
        raise BindError("INSERT ... SELECT must target all columns in "
                        "schema order (no defaults yet)")
    table.ensure_loaded()
    batch = _run_internal(session, stmt.query)
    if len(batch.schema.fields) != len(table.schema.fields):
        raise BindError(
            f"INSERT arity mismatch: query returns "
            f"{len(batch.schema.fields)} columns, table has "
            f"{len(table.schema.fields)}")
    sel = np.asarray(batch.sel)
    new_rows = int(sel.sum())
    new_data = {}
    new_valid = {}
    for f, qf in zip(table.schema.fields, batch.schema.fields):
        arr = np.asarray(batch.columns[qf.name])[sel]
        vm = batch.validity.get(qf.name)
        isna = ~np.asarray(vm).astype(np.bool_)[sel] if vm is not None \
            else np.zeros(new_rows, dtype=np.bool_)
        if isna.any():
            if not f.nullable:
                raise BindError(
                    f"INSERT: NULL in NOT NULL column {f.name!r}")
            if f.dtype == T.DType.STRING:
                # NULL lanes may hold out-of-dictionary codes (e.g. -1
                # from CASE NULL branches): clamp before any translation
                arr = np.where(isna, 0, arr)
        arr = _physical_convert(arr, qf, f, batch.dicts, table)
        old = table.data.get(f.name)
        n_old = len(old) if old is not None else 0
        new_data[f.name] = arr if n_old == 0 \
            else np.concatenate([old, arr])
        old_v = table.validity.get(f.name)
        if isna.any() or old_v is not None:
            if old_v is None:
                old_v = np.ones(n_old, dtype=np.bool_)
            new_valid[f.name] = np.concatenate([old_v, ~isna]) \
                if n_old else ~isna
    table.set_data(new_data, table.dicts, validity=new_valid,
                   appended=new_rows)
    return f"INSERT {new_rows}"


def _optimize(plan: N.PlanNode, session) -> N.PlanNode:
    from cloudberry_tpu.plan.prune import prune_plan
    from cloudberry_tpu.plan.scanprune import apply_storage_scans

    plan = prune_plan(plan)
    apply_storage_scans(plan, session)
    from cloudberry_tpu.plan.cost import annotate_pack_bits

    annotate_pack_bits(plan, session.catalog)
    from cloudberry_tpu.plan.pointlookup import optimize_point_lookups

    if session.config.n_segments > 1 \
            and session.config.planner.enable_direct_dispatch:
        from cloudberry_tpu.plan.distribute import (apply_direct_dispatch,
                                                    direct_dispatch_segment)

        seg = direct_dispatch_segment(plan, session)
        if seg is not None:
            plan = apply_direct_dispatch(plan, session, seg)
            # routed to ONE shard: the sorted sidecar then narrows the
            # scan to the matching rows (index/block-directory analog)
            optimize_point_lookups(plan, session)
            _annotate_join_index(plan, session)
            return plan
    plan = _distribute(plan, session)
    if session.config.n_segments <= 1:
        optimize_point_lookups(plan, session)
    _annotate_join_index(plan, session)
    return plan


def _annotate_join_index(plan: N.PlanNode, session) -> None:
    """Stamp eligible joins with their sorted-build cache spec
    (exec/joinindex.py) — runs LAST so the specs see final capacities,
    motions, and the direct-dispatch rewrite."""
    from cloudberry_tpu.exec.joinindex import annotate_join_index

    annotate_join_index(plan, session)


def _distribute(plan: N.PlanNode, session) -> N.PlanNode:
    if session.config.n_segments > 1:
        from cloudberry_tpu.plan.distribute import distribute_plan

        return distribute_plan(plan, session)
    return plan


_NULL = object()   # sentinel for a NULL literal in VALUES

_NULL_FILL = {T.DType.BOOL: False, T.DType.INT32: "0", T.DType.INT64: "0",
              T.DType.FLOAT64: "0", T.DType.DECIMAL: "0",
              T.DType.DATE: "1970-01-01", T.DType.STRING: ""}


def _insert_values(catalog, stmt: ast.InsertValues) -> str:
    from cloudberry_tpu.columnar.batch import encode_column

    table = catalog.table(stmt.table)
    table.ensure_loaded()  # appends need the existing rows in RAM
    cols = stmt.columns or table.schema.names
    if set(cols) != set(table.schema.names):
        raise BindError("INSERT must target all columns (no defaults yet)")
    by_col: dict[str, list] = {c: [] for c in cols}
    for row in stmt.rows:
        if len(row) != len(cols):
            raise BindError("INSERT row arity mismatch")
        for c, v in zip(cols, row):
            sv = _eval_sequence_call(catalog, v)
            by_col[c].append(str(sv) if sv is not None
                             else _literal_value(v))
    new_data = {}
    new_valid = {}
    for f in table.schema.fields:
        raw = by_col[f.name]
        isnull = np.asarray([v is _NULL for v in raw], dtype=np.bool_)
        if isnull.any():
            if not f.nullable:
                raise BindError(
                    f"INSERT: NULL in NOT NULL column {f.name!r}")
            raw = [_NULL_FILL[f.dtype] if v is _NULL else v for v in raw]
        try:
            if f.dtype == T.DType.DECIMAL:
                # exact fixed-point from the literal TEXT — a float
                # round-trip loses precision beyond 2^53
                arr = np.asarray(
                    [_exact_decimal(v, f.type.scale) for v in raw],
                    dtype=np.int64)
            elif f.dtype in (T.DType.INT32, T.DType.INT64):
                arr = np.asarray([_int_literal(v) for v in raw]) \
                    .astype(f.type.np_dtype)
            elif f.dtype == T.DType.FLOAT64:
                arr = np.asarray([float(v) for v in raw])
            else:
                arr = encode_column(np.asarray(raw), f, table.dicts)
        except (ValueError, TypeError, OverflowError) as e2:
            raise BindError(
                f"INSERT: bad literal for column {f.name!r}: {e2}")
        old = table.data.get(f.name)
        n_old = len(old) if old is not None else 0
        new_data[f.name] = arr if n_old == 0 \
            else np.concatenate([old, arr])
        old_v = table.validity.get(f.name)
        if isnull.any() or old_v is not None:
            if old_v is None:
                old_v = np.ones(n_old, dtype=np.bool_)
            new_valid[f.name] = np.concatenate([old_v, ~isnull]) \
                if n_old else ~isnull
    table.set_data(new_data, table.dicts, validity=new_valid,
                   appended=len(stmt.rows))
    return f"INSERT {len(stmt.rows)}"


def _exact_decimal(v, scale: int) -> int:
    """Literal text/int → int64 fixed-point, digit-exact."""
    text = str(v)
    neg = text.startswith("-")
    if neg:
        text = text[1:]
    if "e" in text.lower():
        raise BindError("scientific notation not supported for DECIMAL "
                        "literals (write the digits out)")
    whole, _, frac = text.partition(".")
    frac_digits = frac + "0" * (scale + 1)
    kept, next_digit = frac_digits[:scale], frac_digits[scale]
    out = int(whole or "0") * 10 ** scale + (int(kept) if kept else 0)
    if next_digit >= "5":
        out += 1  # round half up, matching PostgreSQL numeric
    return -out if neg else out


def _int_literal(v) -> int:
    """Literal → int: digit-exact for plain integers (no float round-trip:
    2^53-adjacent bigints must survive), half-away-from-zero rounding for
    fractional text, float only for exponent forms."""
    text = str(v)
    try:
        return int(text)
    except ValueError:
        pass
    if "e" in text.lower():
        import math

        x = float(text)
        return int(math.floor(x + 0.5)) if x >= 0 else \
            int(math.ceil(x - 0.5))
    return _exact_decimal(text, 0)  # digit-exact, rounds half up


_SEQ_FUNCS = ("nextval", "currval", "setval")


def _signed_int_lit(e: ast.ExprNode):
    """Integer from a NumberLit or a negated NumberLit, else None."""
    if isinstance(e, ast.NumberLit):
        try:
            return int(e.text)
        except ValueError:
            return None
    if isinstance(e, ast.UnaryOp) and e.op == "-":
        v = _signed_int_lit(e.operand)
        return -v if v is not None else None
    return None


def _eval_sequence_call(catalog, e: ast.ExprNode):
    """Evaluate nextval/currval/setval('name'[, n]) host-side, or None if
    ``e`` is not a sequence call. Allocation goes through the durable
    store's locked number line when one is bound (catalog.seq_* )."""
    if not (isinstance(e, ast.FuncCall) and e.name in _SEQ_FUNCS):
        return None
    if not e.args or not isinstance(e.args[0], ast.StringLit):
        raise BindError(f"{e.name}() takes a quoted sequence name")
    name = e.args[0].value
    try:
        if e.name == "nextval":
            return catalog.seq_nextval(name)
        if e.name == "currval":
            return catalog.seq_currval(name)
        val = _signed_int_lit(e.args[1]) if len(e.args) == 2 else None
        if val is None:
            raise BindError("setval('name', value) takes an integer value")
        return catalog.seq_setval(name, val)
    except KeyError as k:
        raise BindError(str(k.args[0]))
    except ValueError as v:
        raise BindError(str(v))


def _fold_sequence_calls(catalog, sel: ast.Select,
                         allocate: bool = True) -> ast.Select:
    """Replace sequence calls in a FROM-less select list with the values
    they evaluate to (each call evaluated exactly once, left to right).
    ``allocate=False`` (plain EXPLAIN): a zero placeholder binds the same
    plan shape with NO state change — EXPLAIN never consumes values."""
    if not any(isinstance(i.expr, ast.FuncCall)
               and i.expr.name in _SEQ_FUNCS for i in sel.items):
        return sel
    items = []
    for i, item in enumerate(sel.items):
        if not allocate and isinstance(item.expr, ast.FuncCall) \
                and item.expr.name in _SEQ_FUNCS:
            alias = item.alias or item.expr.name
            items.append(ast.SelectItem(ast.NumberLit("0"), alias))
            continue
        v = _eval_sequence_call(catalog, item.expr)
        if v is None:
            items.append(item)
        else:
            alias = item.alias or item.expr.name
            items.append(ast.SelectItem(ast.NumberLit(str(v)), alias))
    return ast.Select(items=items, from_refs=sel.from_refs,
                      where=sel.where, group_by=sel.group_by,
                      having=sel.having, order_by=sel.order_by,
                      limit=sel.limit, offset=sel.offset,
                      distinct=sel.distinct)


def _literal_value(e: ast.ExprNode):
    if isinstance(e, ast.NumberLit):
        # keep numeric literal TEXT so decimal targets stay digit-exact
        return e.text
    if isinstance(e, ast.StringLit):
        return e.value
    if isinstance(e, ast.DateLit):
        return e.value
    if isinstance(e, ast.BoolLit):
        return e.value
    if isinstance(e, ast.NullLit):
        return _NULL
    if isinstance(e, ast.UnaryOp) and e.op == "-":
        inner = _literal_value(e.operand)
        return f"-{inner}" if isinstance(inner, str) else -inner
    raise BindError("INSERT VALUES must be literals")
