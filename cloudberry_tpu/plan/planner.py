"""Statement planning + DDL/DML execution.

The dispatch analog of exec_simple_query (src/backend/tcop/postgres.c:1655):
DDL executes directly against the catalog; SELECT goes binder → distribution
pass → executable plan. The distribution pass (plan/distribute.py) is the
cdbllize analog — it inserts Motion nodes per the Sharding algebra.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from cloudberry_tpu import types as T
from cloudberry_tpu.catalog.catalog import DistributionPolicy
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.plan.binder import BindError, Binder
from cloudberry_tpu.sql import ast
from cloudberry_tpu.types import Field, Schema, SqlType


@dataclass
class PlanResult:
    is_ddl: bool = False
    ddl_result: Any = None
    plan: Optional[N.PlanNode] = None


def plan_statement(stmt: ast.Node, session, params: dict) -> PlanResult:
    catalog = session.catalog

    if isinstance(stmt, ast.CreateTable):
        fields = []
        for c in stmt.columns:
            t = T.SQL_TYPE_MAP.get(c.type_name)
            if t is None:
                raise BindError(f"unknown type {c.type_name!r}")
            if t.base == T.DType.DECIMAL and c.scale is not None:
                t = T.DECIMAL(c.scale)
            fields.append(Field(c.name, t, nullable=not c.not_null))
        policy = {
            "hash": DistributionPolicy.hashed(*stmt.dist_keys),
            "replicated": DistributionPolicy.replicated(),
            "random": DistributionPolicy.random(),
        }[stmt.distribution]
        catalog.create_table(stmt.name, Schema(tuple(fields)), policy,
                             if_not_exists=stmt.if_not_exists)
        return PlanResult(is_ddl=True, ddl_result=f"CREATE TABLE {stmt.name}")

    if isinstance(stmt, ast.DropTable):
        catalog.drop_table(stmt.name, if_exists=stmt.if_exists)
        return PlanResult(is_ddl=True, ddl_result=f"DROP TABLE {stmt.name}")

    if isinstance(stmt, ast.InsertValues):
        return PlanResult(is_ddl=True,
                          ddl_result=_insert_values(catalog, stmt))

    if isinstance(stmt, ast.Explain):
        binder = Binder(catalog)
        plan = binder.bind_query(stmt.stmt)
        plan = _optimize(plan, session)
        return PlanResult(is_ddl=True, ddl_result=plan.explain())

    if isinstance(stmt, (ast.Select, ast.SetOp)):
        binder = Binder(catalog)
        plan = binder.bind_query(stmt)
        plan = _optimize(plan, session)
        return PlanResult(plan=plan)

    raise BindError(f"unsupported statement {type(stmt).__name__}")


def _optimize(plan: N.PlanNode, session) -> N.PlanNode:
    from cloudberry_tpu.plan.prune import prune_plan

    plan = prune_plan(plan)
    return _distribute(plan, session)


def _distribute(plan: N.PlanNode, session) -> N.PlanNode:
    if session.config.n_segments > 1:
        from cloudberry_tpu.plan.distribute import distribute_plan

        return distribute_plan(plan, session)
    return plan


def _insert_values(catalog, stmt: ast.InsertValues) -> str:
    from cloudberry_tpu.columnar.batch import encode_column

    table = catalog.table(stmt.table)
    cols = stmt.columns or table.schema.names
    if set(cols) != set(table.schema.names):
        raise BindError("INSERT must target all columns (no defaults yet)")
    by_col: dict[str, list] = {c: [] for c in cols}
    for row in stmt.rows:
        if len(row) != len(cols):
            raise BindError("INSERT row arity mismatch")
        for c, v in zip(cols, row):
            by_col[c].append(_literal_value(v))
    new_data = {}
    for f in table.schema.fields:
        vals = np.asarray(by_col[f.name])
        arr = encode_column(vals, f, table.dicts)
        old = table.data.get(f.name)
        new_data[f.name] = arr if old is None or len(old) == 0 \
            else np.concatenate([old, arr])
    table.set_data(new_data, table.dicts)
    return f"INSERT {len(stmt.rows)}"


def _literal_value(e: ast.ExprNode):
    if isinstance(e, ast.NumberLit):
        return float(e.text) if "." in e.text or "e" in e.text.lower() \
            else int(e.text)
    if isinstance(e, ast.StringLit):
        return e.value
    if isinstance(e, ast.DateLit):
        return e.value
    if isinstance(e, ast.BoolLit):
        return e.value
    if isinstance(e, ast.UnaryOp) and e.op == "-":
        return -_literal_value(e.operand)
    raise BindError("INSERT VALUES must be literals")
