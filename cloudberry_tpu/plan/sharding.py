"""Sharding — the CdbPathLocus analog (cdbpathlocus.h:41-68).

Every plan node carries one; the distribution pass uses it exactly the way
cdbpath_motion_for_join (cdbpath.c:1346) uses loci: decide whether an op can
run where its inputs are, or needs a Motion.

Mapping from the reference's locus taxonomy:
- Hashed(keys)      ← CdbLocusType_Hashed (rows hash-distributed on keys)
- Replicated        ← CdbLocusType_SegmentGeneral/Replicated (full copy per segment)
- Singleton         ← CdbLocusType_Entry/SingleQE (one place: the coordinator slot)
- General           ← CdbLocusType_General (constant/computed anywhere, e.g. 1-row)
- Strewn            ← CdbLocusType_Strewn (partitioned, no known key)
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Sharding:
    kind: str                      # 'hashed' | 'replicated' | 'singleton' | 'general' | 'strewn'
    keys: tuple[str, ...] = ()     # output column names, for 'hashed'

    def __str__(self):
        if self.kind == "hashed":
            return f"hashed({', '.join(self.keys)})"
        return self.kind

    @property
    def is_partitioned(self) -> bool:
        return self.kind in ("hashed", "strewn")

    @staticmethod
    def hashed(*keys: str) -> "Sharding":
        return Sharding("hashed", tuple(keys))

    @staticmethod
    def replicated() -> "Sharding":
        return Sharding("replicated")

    @staticmethod
    def singleton() -> "Sharding":
        return Sharding("singleton")

    @staticmethod
    def general() -> "Sharding":
        return Sharding("general")

    @staticmethod
    def strewn() -> "Sharding":
        return Sharding("strewn")


def hashed_compatible(s: Sharding, required_keys: list[str]) -> bool:
    """True if rows already colocated for grouping/joining on required_keys:
    the sharding keys must be a SUBSET of the required keys (then equal
    required-tuples hash to the same segment)."""
    return s.kind == "hashed" and len(s.keys) > 0 and set(s.keys) <= set(required_keys)
