"""Distribution pass — the cdbllize/cdbpath analog.

Walks the bound plan bottom-up, assigns a Sharding to every node (the
CdbPathLocus discipline, cdbpathlocus.h:41-68) and inserts PMotion nodes
exactly where the reference's planner inserts Motions:

- joins: colocated if both sides hash-partitioned on corresponding join keys
  (cdbpath_motion_for_join, cdbpath.c:1346); else broadcast the small side
  (BROADCAST motion) or redistribute (HASH motion) — here lowered to
  all_gather / all_to_all over the mesh;
- grouped aggregation: one-stage when child is partitioned on a subset of
  the group keys, else two-stage partial→redistribute→final
  (cdbgroupingpaths.c multi-stage agg), with avg split into sum+count;
- global aggregation: partial per segment → gather → final merge;
- sort/limit and the query result: gathered to a singleton (GATHER motion,
  the QD top slice).

Segment placement (load time, host) and Motion routing (device) both use
jump_consistent_hash over the same column hash — colocation depends on it.
"""

from __future__ import annotations

import math
from typing import Optional

from cloudberry_tpu.exec.kernels import rung_up
from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.plan.sharding import Sharding
from cloudberry_tpu.types import DType, FLOAT64, INT64


def direct_dispatch_segment(plan: N.PlanNode, session):
    """The cdbtargeteddispatch.c analog: if every partitioned scan is
    filtered by equality literals covering its FULL distribution key set and
    all scans route to the same segment, the statement can run on that one
    segment with no collectives at all. Returns the segment id or None."""
    import numpy as np

    from cloudberry_tpu.utils import hashing

    nseg = session.config.n_segments
    segs: set[int] = set()

    def conjuncts(e: ex.Expr):
        if isinstance(e, ex.BinOp) and e.op == "and":
            yield from conjuncts(e.left)
            yield from conjuncts(e.right)
        else:
            yield e

    def visit(node: N.PlanNode, preds: tuple) -> bool:
        if isinstance(node, N.PFilter):
            return visit(node.child, preds + (node.predicate,))
        if isinstance(node, N.PScan):
            if node.table_name == "$dual":
                return True
            table = session.catalog.table(node.table_name)
            if table.policy.kind == "replicated":
                return True
            if table.policy.kind != "hashed":
                return False
            eq: dict[str, ex.Literal] = {}
            for p in preds:
                for c in conjuncts(p):
                    if isinstance(c, ex.BinOp) and c.op == "=":
                        l, r = c.left, c.right
                        if isinstance(r, ex.ColumnRef) and \
                                isinstance(l, ex.Literal):
                            l, r = r, l
                        if isinstance(l, ex.ColumnRef) and \
                                isinstance(r, ex.Literal):
                            eq[l.name] = r
            try:
                key_names = [node.column_map[k] for k in table.policy.keys]
            except KeyError:
                return False
            if not all(k in eq for k in key_names):
                return False
            cols = []
            for k, phys in zip(key_names, table.policy.keys):
                dt = table.schema.field(phys).type.np_dtype
                cols.append(np.asarray([eq[k].value], dtype=dt))
            h = hashing.hash_columns_np(cols)
            segs.add(int(hashing.jump_consistent_hash_np(h, nseg)[0]))
            return True
        return all(visit(c, ()) for c in node.children())

    if not visit(plan, ()):
        return None
    for e in _all_exprs(plan):
        for sub in ex.walk(e):
            if isinstance(sub, ex.SubqueryScalar):
                return None  # subquery plans may scan other segments
    if len(segs) != 1:
        return None
    return next(iter(segs))


def _all_exprs(plan: N.PlanNode):
    yield from _node_exprs(plan)
    for c in plan.children():
        yield from _all_exprs(c)


def apply_direct_dispatch(plan: N.PlanNode, session, seg: int) -> N.PlanNode:
    """Rewrite scans for single-shard execution (capacities become the
    shard's) and tag the plan; the executor feeds segment ``seg``'s arrays."""
    def rewrite(node: N.PlanNode):
        if isinstance(node, N.PScan) and node.table_name != "$dual":
            table = session.catalog.table(node.table_name)
            if table.policy.kind != "replicated":
                st = session.sharded_table(node.table_name)
                node.capacity = st.capacity
                node.num_rows = int(st.counts[seg])
        for c in node.children():
            rewrite(c)

    rewrite(plan)
    plan._direct_segment = seg
    return plan


def broadcast_struct_rows(thr: int) -> int:
    """Structural ceiling on a replicated build buffer (rows × nseg) for
    memo-chosen broadcasts: the memo may broadcast ABOVE the greedy
    threshold when it is globally cheaper, but a misestimate must never
    allocate an unbounded replicated buffer."""
    return max(thr, 65536) * 16


def distribute_plan(plan: N.PlanNode, session) -> N.PlanNode:
    if session.config.planner.enable_memo:
        from cloudberry_tpu.plan.memo import annotate_distribution

        annotate_distribution(plan, session)
    d = Distributor(session)
    plan, cap = d.walk(plan)
    if plan.sharding.is_partitioned:
        plan, cap = d.gather(plan, cap)
    return plan


class Distributor:
    def __init__(self, session):
        self.session = session
        self.nseg = session.config.n_segments
        self.cfg = session.config

    # -------------------------------------------------------------- walking

    def walk(self, node: N.PlanNode) -> tuple[N.PlanNode, int]:
        self._walk_subqueries(node)
        if isinstance(node, N.PScan):
            return self._scan(node)
        if isinstance(node, N.PFilter):
            child, cap = self.walk(node.child)
            node.child = child
            node.sharding = child.sharding
            return node, cap
        if isinstance(node, N.PProject):
            child, cap = self.walk(node.child)
            node.child = child
            node.sharding = _project_sharding(child.sharding, node.exprs)
            return node, cap
        if isinstance(node, N.PJoin):
            return self._join(node)
        if isinstance(node, N.PAgg):
            return self._agg(node)
        if isinstance(node, N.PSort):
            child, cap = self.walk(node.child)
            if child.sharding.is_partitioned:
                child, cap = self.gather(child, cap)
            node.child = child
            node.sharding = child.sharding
            return node, cap
        if isinstance(node, N.PLimit):
            k = node.limit + node.offset
            if isinstance(node.child, N.PSort) and 0 < k <= (1 << 20):
                self._walk_subqueries(node.child)  # sort keys' subqueries
                # top-N pushdown (the merge-sorted-receive analog,
                # execMotionSortedReceiver): each segment sorts and keeps its
                # own top k, compacts to k rows, THEN gathers — the
                # coordinator merges k·nseg rows instead of whole shards
                srt = node.child
                inner, icap = self.walk(srt.child)
                if inner.sharding.is_partitioned and k < icap:
                    local_sort = N.PSort(inner, list(srt.keys))
                    local_sort.fields = list(inner.fields)
                    local_sort.sharding = inner.sharding
                    local_top = N.PLimit(local_sort, k)
                    local_top.fields = list(inner.fields)
                    local_top.sharding = inner.sharding
                    m, _ = self.gather(local_top, k)
                    m.pre_compact = k
                    srt.child = m
                    srt.sharding = m.sharding
                    node.sharding = m.sharding
                    return node, m.out_capacity
                # fall through: finish as a plain gathered sort+limit
                if inner.sharding.is_partitioned:
                    inner, icap = self.gather(inner, icap)
                srt.child = inner
                srt.sharding = inner.sharding
                node.sharding = inner.sharding
                return node, icap
            child, cap = self.walk(node.child)
            if child.sharding.is_partitioned:
                child, cap = self.gather(child, cap)
            node.child = child
            node.sharding = child.sharding
            return node, cap
        if isinstance(node, N.PWindow):
            child, cap = self.walk(node.child)
            if child.sharding.is_partitioned:
                names = [e.name for e in node.partition_keys
                         if isinstance(e, ex.ColumnRef)]
                ok_coloc = (child.sharding.kind == "hashed"
                            and child.sharding.keys
                            and set(child.sharding.keys) <= set(names))
                if not ok_coloc:
                    if node.partition_keys and                             len(names) == len(node.partition_keys):
                        child, cap = self.redistribute(
                            child, cap, list(node.partition_keys))
                    else:
                        child, cap = self.gather(child, cap)
            node.child = child
            node.sharding = child.sharding
            return node, cap
        if isinstance(node, N.PShare):
            # distribute the shared subplan ONCE; every reference sees the
            # same (possibly motion-wrapped) result — consumers add their
            # own motions above if they need a different distribution
            cached = getattr(node.child, "_dist_out", None)
            if cached is None:
                child, cap = self.walk(node.child)
                cached = (child, cap)
                node.child._dist_out = cached
                child._dist_out = cached
            child, cap = cached
            node.child = child
            node.sharding = child.sharding
            return node, cap
        if isinstance(node, N.PConcat):
            total = 0
            new_inputs = []
            for c in node.inputs:
                cc, cap = self.walk(c)
                if cc.sharding.is_partitioned:
                    cc, cap = self.gather(cc, cap)
                new_inputs.append(cc)
                total += cap
            node.inputs = new_inputs
            node.sharding = Sharding.singleton()
            return node, total
        raise ValueError(f"distribute: unhandled node {type(node).__name__}")

    def _walk_subqueries(self, node: N.PlanNode) -> None:
        """Uncorrelated scalar subqueries ride inside expressions (InitPlan
        analog): distribute each one and make its one-row result available
        on every segment (gather → replicated compute)."""
        for e in _node_exprs(node):
            for sub in ex.walk(e):
                if isinstance(sub, ex.SubqueryScalar) \
                        and not getattr(sub, "_distributed", False):
                    plan, cap = self.walk(sub.plan)
                    if plan.sharding.is_partitioned:
                        plan, cap = self.gather(plan, cap)
                    sub.plan = plan
                    sub._distributed = True

    def _scan(self, node: N.PScan) -> tuple[N.PlanNode, int]:
        if node.table_name == "$dual":
            node.sharding = Sharding.general()
            return node, 1
        table = self.session.catalog.table(node.table_name)
        policy = table.policy
        if policy.kind == "replicated":
            node.sharding = Sharding.replicated()
            return node, node.capacity
        shard_cap = self.session.shard_capacity(node.table_name)
        node.capacity = shard_cap
        node.num_rows = -2  # per-segment count provided at runtime
        if policy.kind == "hashed" and all(k in node.column_map
                                           for k in policy.keys):
            keys = tuple(node.column_map[k] for k in policy.keys)
            node.sharding = Sharding.hashed(*keys)
        elif policy.kind == "hashed":
            # distribution keys pruned out of the scan: rows are still
            # hash-placed, but the planner can no longer NAME the keys
            node.sharding = Sharding.strewn()
        else:
            node.sharding = Sharding.strewn()
        return node, shard_cap

    # --------------------------------------------------------------- motion

    def gather(self, child: N.PlanNode, cap: int) -> tuple[N.PlanNode, int]:
        m = N.PMotion(child, "gather")
        m.fields = list(child.fields)
        m.sharding = Sharding.singleton()
        m.out_capacity = cap * self.nseg
        return m, m.out_capacity

    def broadcast(self, child: N.PlanNode, cap: int) -> tuple[N.PlanNode, int]:
        m = N.PMotion(child, "broadcast")
        m.fields = list(child.fields)
        m.sharding = Sharding.replicated()
        m.out_capacity = cap * self.nseg
        return m, m.out_capacity

    def redistribute(self, child: N.PlanNode, cap: int,
                     keys: list[ex.Expr],
                     est_rows: float | None = None,
                     est_under_exact: bool = False
                     ) -> tuple[N.PlanNode, int]:
        m = N.PMotion(child, "redistribute", hash_keys=list(keys))
        m.fields = list(child.fields)
        key_names = tuple(k.name for k in keys
                          if isinstance(k, ex.ColumnRef))
        m.sharding = (Sharding.hashed(*key_names)
                      if len(key_names) == len(keys) else Sharding.strewn())
        # skew-proof sizing: when the redistributed subtree is a (filtered)
        # base-table scan with column keys, compute the TRUE per-(source,
        # destination) row counts host-side — an exact upper bound that
        # absorbs ANY key skew (the planner-level answer to the reference's
        # skew handling; filters only shrink it further)
        exact = self._exact_bucket_cap(child, keys)
        factor = self.cfg.interconnect.capacity_factor
        if exact is not None:
            # the exact bound is authoritative: it absorbs ANY key skew,
            # and a runtime filter below only removes rows — never grows a
            # bucket past it. Estimates must not undercut it (a skewed hot
            # key would trip the overflow check the exact count prevents).
            # Rounded up to its capacity rung (kernels.rung_up) so equal-
            # shaped motions share compiled executables.
            m.bucket_cap = rung_up(max(exact, 8))
            if est_rows is not None and est_under_exact:
                # a DIGEST runtime filter shrank the input: the exact
                # bound (computed on the UNFILTERED scan) stays the
                # CEILING — it absorbs any skew — but the survivor
                # estimate may seed a LOWER rung: fewer padded wire
                # bytes, and an under-estimate (bloom false positives,
                # skewed survivors) is a detected overflow that promotes
                # back up the ladder (grow_expansion), never past the
                # ceiling it started from and never a wrong result
                est_bucket = rung_up(max(int(math.ceil(
                    min(est_rows, cap) / self.nseg * factor)), 64))
                m.bucket_cap = min(m.bucket_cap, est_bucket)
            m.out_capacity = m.bucket_cap * self.nseg
            self._stamp_hier(m, child, keys)
            return m, m.out_capacity
        # capacity-based flow control (the ic_udpifc.c:3018 analog): each
        # destination bucket holds factor × fair share; overflow is a
        # detected runtime error that promotes the motion one capacity
        # rung and retries (exec/executor.py:grow_expansion) — never a
        # silent drop. The seed rung comes from the planner estimate, so
        # padded bytes track expected volume, and skew climbs a BOUNDED
        # power-of-two ladder instead of forcing worst-case buffers.
        m.bucket_cap = max(int(math.ceil(cap / self.nseg * factor)), 8)
        if est_rows is not None:
            # a runtime filter shrank the input: size buckets as if the
            # worst source segment held min(cap, est) surviving rows —
            # robust to source skew (all survivors on one shard) while
            # still shrinking when the filter is selective; overflow stays
            # a detected error pointing at capacity_factor
            est_bucket = max(int(math.ceil(
                min(est_rows, cap) / self.nseg * factor)), 64)
            m.bucket_cap = min(m.bucket_cap, est_bucket)
        m.bucket_cap = rung_up(m.bucket_cap)
        # feedback-driven seed (plan/feedback.py): when a prior execution
        # OBSERVED this (table, key-set) shuffle under the same validity
        # tokens, the observed per-destination demand replaces the static
        # estimate — a learned rung, not a guess. Both directions pay:
        # seeding BELOW the static rung cuts padded wire bytes
        # (rung_downgrades), seeding ABOVE it skips the grow-and-retry
        # recompile the static seed would have hit (rung_upgrades). The
        # ladder discipline is untouched — the exact path above never gets
        # here, and an overflow against a stale-generalized sketch still
        # promotes and retries. planck re-derives the justified bound
        # from the live sketch (verify.py motion-rung-feedback-forged).
        self._feedback_seed(m, child, keys)
        m.out_capacity = m.bucket_cap * self.nseg
        self._stamp_hier(m, child, keys)
        return m, m.out_capacity

    def _feedback_seed(self, m: N.PMotion, child: N.PlanNode,
                       keys) -> None:
        from cloudberry_tpu.plan import feedback as FB

        store = FB.store_for(self.session)
        if store is None:
            return
        src = FB.resolve_sources(child, keys)
        if src is None:
            return
        sk = store.lookup(self.session, "redist", src)
        if sk is None or sk.demand_max <= 0:
            return
        headroom = self.cfg.feedback.headroom
        seeded = rung_up(max(int(sk.demand_max * headroom), 8))
        if seeded == m.bucket_cap:
            return
        log = getattr(self.session, "stmt_log", None)
        if log is not None:
            log.bump("feedback_seeded")
            log.bump("rung_downgrades" if seeded < m.bucket_cap
                     else "rung_upgrades")
        m._feedback_seed = {"demand": sk.demand_max, "static": m.bucket_cap,
                            "rung": seeded, "src": src}
        m.bucket_cap = seeded

    # ------------------------------------------------- two-level stamping

    def _hier_topo(self):
        """The session's two-level topology (None = flat), derived once
        per Distributor walk. Epoch-aware: the derivation reads the live
        device list + survivor restriction, both of which an epoch flip
        changes — and a replan is exactly when this runs again."""
        if not hasattr(self, "_hier_topo_cache"):
            from cloudberry_tpu.parallel.transport import hier_topology

            self._hier_topo_cache = hier_topology(
                self.cfg, self.nseg,
                getattr(self.session, "_live_device_ids", None))
        return self._hier_topo_cache

    def _stamp_hier(self, m: N.PMotion, child: N.PlanNode, keys) -> None:
        """Stamp the two-level caps on a redistribute when the topology
        gate selects the hierarchical transport: host_bucket_cap sizes
        the aggregated inter-host (DCN) block per (source host ->
        destination host) pair — the exact host-granularity bound when
        the subtree is a base scan, else the host's combined fair share
        — and hier_hosts pins the grouping the caps assume. Flat
        sessions (n_hosts == 1) never reach here: single-host plans are
        byte-identical to pre-two-level plans by construction."""
        topo = self._hier_topo()
        if topo is None:
            return
        if self.cfg.interconnect.hierarchical == "auto" \
                and m.bucket_cap * _wire_row_bytes(m) \
                < self.cfg.interconnect.hier_min_block_bytes:
            return      # blocks too small to amortize the extra launches
        if m.out_capacity >= 1 << 31:
            return      # route words address slots in u32 (transport)
        n_hosts = topo.n_hosts
        S = self.nseg // n_hosts
        exact = self._exact_host_cap(child, keys, n_hosts)
        if exact is not None:
            m.host_bucket_cap = rung_up(max(exact, 8))
        else:
            # a host's S segments' per-destination shares combined; an
            # under-estimate is a detected overflow that promotes the
            # host rung and retries (executor.grow_expansion), never a
            # wrong result — same ladder discipline as bucket_cap
            m.host_bucket_cap = rung_up(max(S * m.bucket_cap, 8))
        m.hier_hosts = n_hosts

    def _exact_host_cap(self, child: N.PlanNode, keys,
                        n_hosts: int) -> Optional[int]:
        """Exact max rows any (source host, destination host) pair
        exchanges — the host-granularity analog of _exact_bucket_cap
        (contiguous uniform grouping: host = segment // S)."""
        import numpy as np

        from cloudberry_tpu.utils import hashing

        node = child
        while isinstance(node, (N.PFilter, N.PRuntimeFilter)):
            node = node.child
        if not isinstance(node, N.PScan) or node.table_name == "$dual":
            return None
        try:
            t = self.session.catalog.table(node.table_name)
        except KeyError:
            return None
        if t.policy.kind == "replicated":
            return None
        rev = {out: phys for phys, out in node.column_map.items()}
        phys = []
        for k in keys:
            p = rev.get(k.name) if isinstance(k, ex.ColumnRef) else None
            if p is None:
                return None
            phys.append(p)
        t.ensure_loaded()
        if t.num_rows == 0:
            return None
        cache = getattr(self.session, "_bucket_cap_cache", None)
        if cache is None:
            cache = self.session._bucket_cap_cache = {}
        key = ("host", node.table_name, getattr(t, "_version", 0),
               tuple(phys), self.nseg, n_hosts)
        hit = cache.get(key)
        if hit is not None:
            return hit
        S = self.nseg // n_hosts
        cols = [np.asarray(t.data[p]) for p in phys]
        dst = hashing.jump_consistent_hash_np(
            hashing.hash_columns_np(cols), self.nseg) // S
        src = t.shard_assignment(self.nseg)
        if src is None:
            return None
        counts = np.bincount(
            (src.astype(np.int64) // S) * n_hosts + dst,
            minlength=n_hosts * n_hosts)
        out = int(counts.max())
        if len(cache) >= 64:
            cache.pop(next(iter(cache)))
        cache[key] = out
        return out

    def _exact_bucket_cap(self, child: N.PlanNode, keys) -> Optional[int]:
        """Exact max rows any (source, destination) bucket can receive,
        from the base table's actual key values — None when the subtree
        isn't a plain (possibly filtered/runtime-filtered) scan."""
        import numpy as np

        from cloudberry_tpu.utils import hashing

        node = child
        while isinstance(node, (N.PFilter, N.PRuntimeFilter)):
            node = node.child
        if not isinstance(node, N.PScan) or node.table_name == "$dual":
            return None
        try:
            t = self.session.catalog.table(node.table_name)
        except KeyError:
            return None
        if t.policy.kind == "replicated":
            return None
        rev = {out: phys for phys, out in node.column_map.items()}
        phys = []
        for k in keys:
            p = rev.get(k.name) if isinstance(k, ex.ColumnRef) else None
            if p is None:
                return None
            phys.append(p)
        t.ensure_loaded()  # distributed scans materialize anyway
        if t.num_rows == 0:
            return None
        cache = getattr(self.session, "_bucket_cap_cache", None)
        if cache is None:
            cache = self.session._bucket_cap_cache = {}
        key = (node.table_name, getattr(t, "_version", 0),
               tuple(phys), self.nseg)
        hit = cache.get(key)
        if hit is not None:
            return hit
        cols = [np.asarray(t.data[p]) for p in phys]
        dst = hashing.jump_consistent_hash_np(
            hashing.hash_columns_np(cols), self.nseg)
        src = t.shard_assignment(self.nseg)
        if src is None:
            return None
        counts = np.bincount(src.astype(np.int64) * self.nseg + dst,
                             minlength=self.nseg * self.nseg)
        out = int(counts.max())
        if len(cache) >= 64:
            cache.pop(next(iter(cache)))
        cache[key] = out
        return out

    def _maybe_runtime_filter(self, node: N.PJoin, build_src: N.PlanNode,
                              probe: N.PlanNode, est_build_rows: float,
                              est_semi_rows: float | None,
                              est_probe_rows: float | None = None
                              ) -> tuple[N.PlanNode, float | None, bool]:
        """Wrap the probe in a pre-motion runtime filter when profitable;
        returns (probe', TOTAL surviving-row estimate for bucket sizing —
        computed pre-walk by the caller so shard-mutated scans can't skew
        it, allow-undercut-of-exact-bound flag). Small builds get the
        EXACT filter (all-gathered keys); bigger builds get the bloom +
        min/max DIGEST when its estimated wire savings beat the digest
        broadcast cost (config.join_filter)."""
        if node.kind not in ("inner", "semi") or est_semi_rows is None:
            return probe, None, False

        def wrap(mode: str, bits: int = 0) -> N.PlanNode:
            rf = N.PRuntimeFilter(probe, build_src,
                                  list(node.build_keys),
                                  list(node.probe_keys),
                                  pack_bits=node.pack_bits, mode=mode,
                                  bloom_bits=bits,
                                  bloom_k=self.cfg.join_filter.bloom_k)
            rf.fields = list(probe.fields)
            rf.sharding = probe.sharding
            return rf

        thresh = self.cfg.planner.runtime_filter_threshold
        if thresh > 0 and est_build_rows <= thresh:
            rf = wrap("exact")
            rf._est_in = est_probe_rows
            rf._est_out = max(est_semi_rows, 1.0)
            return rf, max(est_semi_rows, 1.0), False
        if est_probe_rows is None:
            return probe, None, False
        ok, est, bits = digest_decision(est_build_rows, est_probe_rows,
                                        est_semi_rows, probe.fields,
                                        len(node.build_keys), self.cfg,
                                        self.nseg)
        if not ok:
            return probe, None, False
        rf = wrap("digest", bits)
        rf._est_in = est_probe_rows
        rf._est_out = max(est, 1.0)
        return rf, max(est, 1.0), True

    # ----------------------------------------------------------------- join

    def _join(self, node: N.PJoin) -> tuple[N.PlanNode, int]:
        from cloudberry_tpu.plan.cost import estimate_rows, semi_estimate

        # estimate BEFORE the walk mutates scan capacities to shard sizes
        # (both the build size and the runtime filter's survivor count)
        est_build_rows = estimate_rows(node.build, self.session.catalog)
        est_probe_rows = estimate_rows(node.probe, self.session.catalog)
        est_semi_rows = semi_estimate(node.build, node.probe,
                                      node.build_keys, node.probe_keys,
                                      self.session.catalog) \
            if node.kind in ("inner", "semi") else None
        build, bcap = self.walk(node.build)
        probe, pcap = self.walk(node.probe)
        bsh, psh = build.sharding, probe.sharding

        if node.kind == "full":
            # FULL join emits unmatched rows from BOTH sides exactly once:
            # broadcast/replicated inputs would duplicate them per segment,
            # so require key colocation or gather both sides
            if not (bsh.is_partitioned and psh.is_partitioned
                    and _join_colocated(node, bsh, psh)):
                if bsh.is_partitioned:
                    build, bcap = self.gather(build, bcap)
                if psh.is_partitioned:
                    probe, pcap = self.gather(probe, pcap)
                node.build = build
                node.probe = probe
                node.sharding = Sharding.singleton()
                return node, _join_out_cap(node, bcap, pcap, self.nseg)
            node.build = build
            node.probe = probe
            node.sharding = psh
            return node, _join_out_cap(node, bcap, pcap, self.nseg)

        b_part = bsh.is_partitioned
        p_part = psh.is_partitioned

        if b_part and p_part and not _join_colocated(node, bsh, psh):
            # statistics-estimated build size (cost.py) decides, but the
            # STATIC broadcast buffer is bcap·nseg rows regardless of actual
            # data — cap it structurally so a misestimate can never allocate
            # an unbounded replicated buffer
            thr = self.cfg.planner.broadcast_threshold
            bsub = _hashed_key_positions(bsh, node.build_keys)
            psub = _hashed_key_positions(psh, node.probe_keys)
            # the memo explorer (plan/memo.py) may have stamped the
            # globally cheapest strategy; honor it after re-checking its
            # preconditions (the plan may have drifted since), else fall
            # back to the greedy per-node rules
            choice = getattr(node, "_dist_choice", None)
            if choice == "broadcast" and not (
                    thr > 0
                    and bcap * self.nseg <= broadcast_struct_rows(thr)):
                choice = None
            if choice == "redist_probe" and bsub is None:
                choice = None
            if choice == "redist_build" and psub is None:
                choice = None
            if choice in (None, "colocate"):
                if est_build_rows <= thr \
                        and bcap * self.nseg <= max(thr, 1) * 16:
                    choice = "broadcast"
                elif bsub is not None:
                    choice = "redist_probe"
                elif psub is not None:
                    choice = "redist_build"
                else:
                    choice = "redist_both"
            if choice == "broadcast":
                build, bcap = self.broadcast(build, bcap)
            elif choice == "redist_probe":
                probe, est, under = self._maybe_runtime_filter(
                    node, build, probe, est_build_rows, est_semi_rows,
                    est_probe_rows)
                probe, pcap = self.redistribute(
                    probe, pcap, [node.probe_keys[i] for i in bsub],
                    est_rows=est, est_under_exact=under)
            elif choice == "redist_build":
                build, bcap = self.redistribute(
                    build, bcap, [node.build_keys[i] for i in psub])
            else:  # redist_both
                build_src = build
                build, bcap = self.redistribute(build, bcap,
                                                list(node.build_keys))
                probe, est, under = self._maybe_runtime_filter(
                    node, build_src, probe, est_build_rows,
                    est_semi_rows, est_probe_rows)
                probe, pcap = self.redistribute(probe, pcap,
                                                list(node.probe_keys),
                                                est_rows=est,
                                                est_under_exact=under)
        elif b_part and not p_part:
            if node.kind in ("inner", "semi"):
                # probe replicated/singleton, build partitioned: each segment
                # joins its build shard against the full probe; a probe row
                # is selected only on the segment owning its build partner,
                # so results are partitioned — by the BUILD side's actual
                # distribution, translated onto the equal-valued probe keys.
                node.build = build
                node.probe = probe
                bsub = _hashed_key_positions(bsh, node.build_keys)
                if bsub is not None:
                    names = [node.probe_keys[i].name for i in bsub
                             if isinstance(node.probe_keys[i], ex.ColumnRef)]
                    node.sharding = (Sharding.hashed(*names)
                                     if len(names) == len(bsub)
                                     else Sharding.strewn())
                else:
                    node.sharding = Sharding.strewn()
                return node, _join_out_cap(node, bcap, pcap, self.nseg)
            # left/anti joins select probe rows that match NOWHERE — every
            # segment must see the whole build side to decide that
            build, bcap = self.broadcast(build, bcap)

        node.build = build
        node.probe = probe
        node.sharding = probe.sharding if p_part else (
            Sharding.strewn() if build.sharding.is_partitioned
            else probe.sharding)
        return node, _join_out_cap(node, bcap, pcap, self.nseg)

    # ------------------------------------------------------------------ agg


    def _agg(self, node: N.PAgg) -> tuple[N.PlanNode, int]:
        child, cap = self.walk(node.child)
        node.child = child
        csh = child.sharding

        if not csh.is_partitioned:
            node.sharding = csh
            node.capacity = min(node.capacity, max(cap, 1))
            return node, node.capacity

        if node.group_keys:
            key_src = {e.name for _, e in node.group_keys
                       if isinstance(e, ex.ColumnRef)}
            if csh.kind == "hashed" and set(csh.keys) <= key_src and csh.keys:
                # colocated grouping: one stage, stays partitioned
                node.sharding = _rename_sharding(csh, node.group_keys)
                node.capacity = min(node.capacity, cap)
                return node, node.capacity
            return self._two_stage_group_agg(node, child, cap)
        return self._two_stage_global_agg(node, child, cap)

    def _two_stage_group_agg(self, node: N.PAgg, child: N.PlanNode,
                             cap: int) -> tuple[N.PlanNode, int]:
        partial_aggs, final_aggs, finalize = _split_aggs(node.aggs)
        partial = N.PAgg(child, node.group_keys, partial_aggs,
                         capacity=min(node.capacity, cap), mode="partial")
        partial.fields = [N.PlanField(n, e.dtype, _f_dict(child, e))
                          for n, e in node.group_keys] + \
                         [N.PlanField(n, c.dtype, None)
                          for n, c in partial_aggs]
        partial.sharding = child.sharding

        gst = self.cfg.planner.gather_single_threshold
        if 0 < node.capacity <= gst:
            # GATHER_SINGLE (plannodes.h:1638 analog): partials are small
            # — gather them to one segment for the final merge. Immune to
            # hash-space skew across destinations (a redistribute's
            # per-bucket variance can overflow when many distinct keys
            # land on one segment), and a cheaper collective besides.
            motion, mcap = self.gather(partial, partial.capacity)
            final_sharding = Sharding.singleton()
        else:
            key_refs = [_field_ref(partial, n) for n, _ in node.group_keys]
            motion, mcap = self.redistribute(partial, partial.capacity,
                                             key_refs)
            if motion.hier_hosts:
                spec = host_combine_spec(motion, partial, final_aggs)
                if spec is not None:
                    # host-local combine between the hops: DCN carries
                    # one partial per (host, group). The combined rows
                    # ship from one segment per host, which can see up
                    # to S segments' worth of distinct groups — grow
                    # the pair rung to that ceiling so the combine can
                    # never manufacture an overflow the uncombined
                    # motion would not have had.
                    S = self.nseg // motion.hier_hosts
                    motion.host_combine = True
                    motion.combine_spec = spec
                    motion.bucket_cap = rung_up(S * motion.bucket_cap)
                    motion.out_capacity = motion.bucket_cap * self.nseg
                    mcap = motion.out_capacity
            final_sharding = _rename_sharding(
                Sharding.hashed(*(k.name for k in key_refs
                                  if isinstance(k, ex.ColumnRef))),
                [(n, _field_ref(motion, n)) for n, _ in node.group_keys])

        final_keys = [(n, _field_ref(motion, n)) for n, _ in node.group_keys]
        final = N.PAgg(motion, final_keys, final_aggs,
                       capacity=min(node.capacity, mcap), mode="final")
        final.fields = [N.PlanField(n, e.dtype, _f_dict(motion, e))
                        for n, e in final_keys] + \
                       [N.PlanField(n, c.dtype, None) for n, c in final_aggs]
        final.sharding = final_sharding

        out = _finalize_project(final, node, finalize)
        out.sharding = final.sharding
        return out, final.capacity

    def _two_stage_global_agg(self, node: N.PAgg, child: N.PlanNode,
                              cap: int) -> tuple[N.PlanNode, int]:
        partial_aggs, final_aggs, finalize = _split_aggs(node.aggs)
        partial = N.PAgg(child, [], partial_aggs, capacity=1, mode="partial")
        partial.fields = [N.PlanField(n, c.dtype, None)
                          for n, c in partial_aggs]
        partial.sharding = child.sharding

        motion, mcap = self.gather(partial, 1)

        final = N.PAgg(motion, [], final_aggs, capacity=1, mode="final")
        final.fields = [N.PlanField(n, c.dtype, None) for n, c in final_aggs]
        final.sharding = Sharding.singleton()

        out = _finalize_project(final, node, finalize)
        out.sharding = final.sharding
        return out, 1


def digest_survivors(est_build: float, est_probe: float, est_semi: float,
                     bits: int, k: int) -> float:
    """Probe rows expected to SURVIVE a digest runtime filter: the true
    partners plus bloom false positives at the estimated load factor
    (fpr ≈ (1 - e^{-k·n/m})^k) — the costing currency shared by the
    distributor's eligibility rule and the memo's motion pricing."""
    import math as _m

    m = max(bits, 64)
    kk = max(k, 1)
    fpr = (1.0 - _m.exp(-kk * max(est_build, 1.0) / m)) ** kk
    return min(est_probe,
               est_semi + fpr * max(est_probe - est_semi, 0.0))


def digest_decision(est_build: float, est_probe: float, est_semi: float,
                    probe_fields, n_keys: int, cfg,
                    nseg: int) -> tuple[bool, float, int]:
    """(eligible, survivor estimate, bloom bits) — THE digest eligibility
    rule: fires only above the exact filter's threshold, and only when the
    estimated wire savings beat the digest broadcast cost. One copy shared
    by the distributor's filter insertion (_maybe_runtime_filter) and the
    memo's motion pricing (digest_filter_frac), so the two can't drift."""
    from cloudberry_tpu.exec.kernels import bloom_bits_pow2

    jf = cfg.join_filter
    est_probe = max(est_probe, 1.0)
    if not jf.enabled:
        return False, est_probe, 0
    thresh = cfg.planner.runtime_filter_threshold
    if thresh > 0 and est_build <= thresh:
        return False, est_probe, 0  # exact-filter territory
    bits = bloom_bits_pow2(jf.bloom_bits)
    est = digest_survivors(est_build, est_probe, est_semi, bits,
                           jf.bloom_k)
    row_bytes = max(sum(f.type.np_dtype.itemsize
                        for f in probe_fields), 1)
    saved = (est_probe - est) * row_bytes * (nseg - 1) / max(nseg, 1)
    digest_bytes = (bits // 8 + 32 * n_keys) * nseg
    return saved > digest_bytes, est, bits


def digest_filter_frac(node: N.PJoin, catalog, cfg, nseg: int) -> float:
    """Fraction of probe rows expected on the wire after the pre-motion
    runtime filter a probe redistribute would get, 1.0 when none fires.
    DIGEST mode only — the exact filter (small builds) is deliberately
    unmodeled so existing plan choices stay put; the digest covers the
    big-build shuffles where semijoin reduction decides the motion."""
    from cloudberry_tpu.plan.cost import estimate_rows, semi_estimate

    if not cfg.join_filter.enabled or node.kind not in ("inner", "semi"):
        return 1.0
    est_b = estimate_rows(node.build, catalog)
    est_p = max(estimate_rows(node.probe, catalog), 1.0)
    est_semi = semi_estimate(node.build, node.probe, node.build_keys,
                             node.probe_keys, catalog)
    ok, est, _ = digest_decision(est_b, est_p, est_semi,
                                 node.probe.fields,
                                 len(node.build_keys), cfg, nseg)
    if not ok:
        return 1.0
    # feedback (plan/feedback.py): a prior execution COUNTED this
    # filter's survivors — price the shuffle at the observed fraction
    # instead of the bloom model's. Learned, so stamp provenance for
    # EXPLAIN / the flight recorder.
    fb = getattr(catalog, "_feedback", None)
    if fb is not None:
        obs = fb.jf_frac(node)
        if obs is not None:
            node._jf_frac_src = "feedback"
            return max(obs, 1e-6)
    return max(est / est_p, 1e-6)


def _join_out_cap(node: N.PJoin, bcap: int, pcap: int,
                  nseg: int = 1) -> int:
    """Per-segment output capacity; expansion joins get resized to the
    post-motion per-segment inputs, floored by the NDV-based PAIR estimate
    the binder memoized (bcap+pcap is no bound for many-to-many fanout —
    a detected overflow grows the buffer and retries, executor.py:
    grow_expansion)."""
    est = getattr(node, "_est_pairs", None)
    floor = int(2 * est / max(nseg, 1)) + 8 if est is not None else 0
    if node.residual is not None:
        # semi/anti residual: pairs expand internally, output rides probe
        node.out_capacity = max(bcap + pcap, floor)
        return pcap
    if not node.unique_build:
        node.out_capacity = max(bcap + pcap, floor)
        return node.out_capacity
    return pcap


def _wire_row_bytes(m: N.PMotion) -> int:
    """Bytes one row costs on the motion's packed wire (fallback: raw
    itemsize sum) — the auto-gate's block-size currency."""
    import numpy as np

    from cloudberry_tpu.exec import kernels as K

    dtypes = {f.name: f.type.np_dtype for f in m.fields}
    try:
        return K.wire_layout(dtypes).row_bytes()
    except NotImplementedError:
        return sum(np.dtype(d).itemsize for d in dtypes.values()) + 1


def host_combine_spec(m: N.PMotion, partial: N.PAgg,
                      final_aggs) -> Optional[tuple]:
    """Combine-eligibility for a two-stage agg's merge motion (the
    planner stamp the verifier's motion-host-combine rule checks).

    Eligible only when every merge is ORDER-INSENSITIVE-EXACT — integer
    sums (count partials are int64; DECIMAL rides int64 cents), min,
    max — so host-combined partials merge to bit-identical finals no
    matter how the combine regrouped them. A float sum partial (f64
    rounding depends on add order) or a masked (nullable) key keeps the
    motion combine-free. Returns (group key names, ((column, merge
    func), ...)) or None."""
    import numpy as np

    if m.kind != "redistribute" or not partial.group_keys:
        return None
    by_name = {f.name: f for f in m.fields}
    for f in m.fields:
        if f.masks:
            return None         # NULL semantics need the mask columns
    merges = []
    for name, call in final_aggs:
        f = by_name.get(name)
        if f is None or call.func not in ("sum", "min", "max"):
            return None
        if call.func == "sum" and not (
                np.issubdtype(f.type.np_dtype, np.integer)
                or f.type.np_dtype == np.bool_):
            return None         # float sums are add-order-sensitive
        merges.append((name, call.func))
    keys = tuple(n for n, _ in partial.group_keys)
    if not all(k in by_name for k in keys):
        return None
    return (keys, tuple(merges))


# ---------------------------------------------------------------- agg split


def _split_aggs(aggs):
    """(partial_aggs, final_merge_aggs, finalize_exprs) — how each aggregate
    decomposes across the motion boundary (the reference's combine
    functions / multi-stage Aggref splitting)."""
    partial: list[tuple[str, ex.AggCall]] = []
    final: list[tuple[str, ex.AggCall]] = []
    finalize: dict[str, tuple[str, str]] = {}  # out name -> ('avg', s, c)
    for name, call in aggs:
        if call.func in ("sum", "min", "max"):
            partial.append((name, call))
            merge = "sum" if call.func == "sum" else call.func
            final.append((name, ex.AggCall(
                merge, ex.ColumnRef(name, call.dtype))))
        elif call.func == "count":
            partial.append((name, call))
            final.append((name, ex.AggCall(
                "sum", ex.ColumnRef(name, INT64))))
        elif call.func == "avg":
            s, c = f"{name}$s", f"{name}$c"
            assert call.arg is not None
            partial.append((s, ex.AggCall("sum", call.arg)))
            partial.append((c, ex.AggCall("count", call.arg)))
            final.append((s, ex.AggCall(
                "sum", ex.ColumnRef(s, call.arg.dtype))))
            final.append((c, ex.AggCall("sum", ex.ColumnRef(c, INT64))))
            finalize[name] = (s, c)
        else:
            raise ValueError(f"cannot distribute aggregate {call.func}")
    return partial, final, finalize


def _finalize_project(final: N.PAgg, node: N.PAgg, finalize) -> N.PlanNode:
    """Restore the original agg output schema (avg = sum/count)."""
    if not finalize:
        final_names = {f.name for f in final.fields}
        assert {f.name for f in node.fields} <= final_names
        proj_exprs = [(f.name, _field_ref(final, f.name))
                      for f in node.fields]
    else:
        proj_exprs = []
        for f in node.fields:
            if f.name in finalize:
                s, c = finalize[f.name]
                sf = _field_ref(final, s)
                cf = _field_ref(final, c)
                proj_exprs.append((f.name, ex.BinOp(
                    "/", ex.Cast(sf, FLOAT64), ex.Cast(cf, FLOAT64),
                    FLOAT64)))
            else:
                proj_exprs.append((f.name, _field_ref(final, f.name)))
    proj = N.PProject(final, proj_exprs)
    proj.fields = list(node.fields)
    return proj


# ------------------------------------------------------------------ helpers


def _node_exprs(node: N.PlanNode):
    if isinstance(node, N.PFilter):
        yield node.predicate
    elif isinstance(node, N.PProject):
        for _, e in node.exprs:
            yield e
    elif isinstance(node, N.PAgg):
        for _, e in node.group_keys:
            yield e
        for _, c in node.aggs:
            if c.arg is not None:
                yield c.arg
    elif isinstance(node, N.PSort):
        for e, _ in node.keys:
            yield e
    elif isinstance(node, N.PJoin):
        yield from node.build_keys
        yield from node.probe_keys
        if node.residual is not None:
            yield node.residual
    elif isinstance(node, N.PWindow):
        yield from node.partition_keys
        for e, _ in node.order_keys:
            yield e
        for _, _, arg in node.calls:
            if arg is not None:
                yield arg
        for vexpr in (node.valids or ()):
            if vexpr is not None:
                yield vexpr
    elif isinstance(node, N.PRuntimeFilter):
        yield from node.build_keys
        yield from node.probe_keys
    elif isinstance(node, N.PMotion):
        yield from node.hash_keys


def _field_ref(plan: N.PlanNode, name: str) -> ex.ColumnRef:
    f = plan.field(name)
    c = ex.ColumnRef(f.name, f.type)
    if f.sdict is not None:
        object.__setattr__(c, "_sdict", f.sdict)
    return c


def _f_dict(plan: N.PlanNode, e: ex.Expr):
    if isinstance(e, ex.ColumnRef):
        try:
            return plan.field(e.name).sdict
        except KeyError:
            return None
    return None


def _project_sharding(child_sh: Sharding, exprs) -> Sharding:
    if child_sh.kind != "hashed":
        return child_sh
    renames = {}
    for out_name, e in exprs:
        if isinstance(e, ex.ColumnRef) and e.name not in renames:
            renames[e.name] = out_name
    if all(k in renames for k in child_sh.keys):
        return Sharding.hashed(*(renames[k] for k in child_sh.keys))
    return Sharding.strewn()


def _rename_sharding(csh: Sharding, group_keys) -> Sharding:
    """Child sharding keys (source col names) → agg output key names."""
    if csh.kind != "hashed":
        return csh
    src_to_out = {}
    for out_name, e in group_keys:
        if isinstance(e, ex.ColumnRef) and e.name not in src_to_out:
            src_to_out[e.name] = out_name
    if all(k in src_to_out for k in csh.keys):
        return Sharding.hashed(*(src_to_out[k] for k in csh.keys))
    return Sharding.strewn()


def _hashed_key_positions(sh: Sharding, keys: list[ex.Expr]
                          ) -> Optional[list[int]]:
    """If ``sh`` is hashed exactly on an ordered subset of ``keys`` (by
    column name), return those key positions; else None."""
    if sh.kind != "hashed" or not sh.keys:
        return None
    names = [k.name if isinstance(k, ex.ColumnRef) else None for k in keys]
    pos = []
    for k in sh.keys:
        if k not in names:
            return None
        pos.append(names.index(k))
    return pos


def _join_colocated(node: N.PJoin, bsh: Sharding, psh: Sharding) -> bool:
    """Both sides hash-partitioned on CORRESPONDING join key positions, in
    the same order — equal key tuples then land on the same segment."""
    bpos = _hashed_key_positions(bsh, node.build_keys)
    if bpos is None:
        return False
    ppos = _hashed_key_positions(psh, node.probe_keys)
    if ppos is None:
        return False
    return bpos == ppos
