"""Cascades-lite distribution exploration — the ORCA (gporca) role,
scoped to the decision that dominates MPP cost: where the motions go.

The reference ships two optimizers: the MPP-ified Postgres planner
(greedy locus rules, cdbpath.c:1346 cdbpath_motion_for_join) and ORCA, a
Cascades engine (src/backend/gporca) that explores alternative plans in
a memo and costs them. This module is the memo idea translated to this
planner's world:

- groups        = join-tree subtrees (scans / filters / projections /
                  joins — the grammar Distributor._join decides over);
- physical
  property      = the subtree's output Sharding (the CdbPathLocus
                  analog; ORCA's CDistributionSpec);
- alternatives  = per join: colocate / broadcast-build / redistribute-
                  probe / redistribute-build / redistribute-both —
                  exactly the moves cdbpath_motion_for_join knows, but
                  COSTED AND COMPARED over the whole tree instead of
                  decided greedily per node;
- cost          = bytes over the interconnect (rows moved × row width),
                  the dominant term on the reference's UDP fabric and on
                  TPU ICI alike;
- required
  property      = the parent context: GROUP BY keys above the join tree
                  add the final-redistribute cost each output property
                  implies, so a locally cheap choice that forces an
                  expensive re-shuffle later LOSES — System R's
                  "interesting orders" insight applied to hash
                  distribution (ORCA: derived vs required distribution
                  specs).

The winning alternative is stamped on each join (``_dist_choice``);
``Distributor._join`` honors the stamp — re-checking its preconditions,
falling back to the greedy rules wherever the memo abstained or the
plan drifted — so the memo can only redirect motions the distributor
already knows how to place.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.plan.distribute import (_hashed_key_positions,
                                            _join_colocated,
                                            _node_exprs,
                                            _project_sharding,
                                            broadcast_struct_rows)
from cloudberry_tpu.plan.sharding import Sharding


@dataclass(frozen=True)
class Alt:
    """One costed alternative for a subtree: total motion bytes below,
    the output sharding it yields, and the per-join choices that
    produce it."""

    cost: float
    sharding: Sharding
    choices: tuple  # ((PJoin, choice-str), ...)


def _width(node: N.PlanNode) -> int:
    return max(sum(f.type.np_dtype.itemsize for f in node.fields), 1)


def _keep_best(alts: dict, alt: Alt) -> None:
    k = str(alt.sharding)
    cur = alts.get(k)
    if cur is None or alt.cost < cur.cost:
        alts[k] = alt


def _redist_sharding(keys) -> Sharding:
    """Mirror Distributor.redistribute's output locus."""
    names = tuple(k.name for k in keys if isinstance(k, ex.ColumnRef))
    return Sharding.hashed(*names) if len(names) == len(keys) \
        else Sharding.strewn()


def explore(node: N.PlanNode, catalog, nseg: int,
            thr: int, gst: int = 0) -> Optional[dict]:
    """Alternative set {sharding-key: Alt} for a join-tree subtree; None
    when the subtree leaves the grammar (set-ops, windows, shares,
    subquery scalars in scope) — the greedy rules then stand alone.
    Single-mode aggregations ARE in the grammar (aggregated derived
    tables, the q65-class multi-block shape); ``gst`` is the
    gather_single_threshold the distributor's two-stage arm applies, so
    explored output shardings match what it actually produces."""
    if isinstance(node, N.PScan):
        return {str(sh): Alt(0.0, sh, ())
                for sh in (_scan_sharding(node, catalog),)}
    if isinstance(node, N.PFilter):
        return explore(node.child, catalog, nseg, thr, gst)
    if isinstance(node, N.PProject):
        sub = explore(node.child, catalog, nseg, thr, gst)
        if sub is None:
            return None
        out: dict = {}
        for a in sub.values():
            _keep_best(out, Alt(a.cost,
                                _project_sharding(a.sharding, node.exprs),
                                a.choices))
        return out
    if isinstance(node, N.PJoin):
        return _explore_join(node, catalog, nseg, thr, gst)
    if isinstance(node, N.PAgg) and node.mode == "single":
        # mirror Distributor._agg's arms — colocated grouping is free
        # and keeps the (renamed) child sharding (_agg_extra prices the
        # move, 0 when colocated); anything else pays the partial rows'
        # move and lands where the distributor will actually put it:
        # singleton under the GATHER_SINGLE threshold, hashed-on-keys
        # above it
        sub = explore(node.child, catalog, nseg, thr, gst)
        if sub is None:
            return None
        from cloudberry_tpu.plan.distribute import _rename_sharding

        out = {}
        for a in sub.values():
            sh = a.sharding
            if not sh.is_partitioned:
                _keep_best(out, Alt(a.cost, sh, a.choices))
                continue
            extra = _agg_extra(node, sh, catalog, nseg)
            if node.group_keys and extra == 0.0:
                _keep_best(out, Alt(
                    a.cost, _rename_sharding(sh, node.group_keys),
                    a.choices))
                continue
            if node.group_keys and not (0 < node.capacity <= gst):
                out_sh = Sharding.hashed(
                    *(n for n, _ in node.group_keys))
            else:
                out_sh = Sharding.singleton()
            _keep_best(out, Alt(a.cost + extra, out_sh, a.choices))
        return out
    return None


def _scan_sharding(node: N.PScan, catalog) -> Sharding:
    """Mirror Distributor._scan's locus assignment."""
    if node.table_name == "$dual":
        return Sharding.general()
    try:
        table = catalog.table(node.table_name)
    except KeyError:
        return Sharding.strewn()
    pol = table.policy
    if pol.kind == "replicated":
        return Sharding.replicated()
    if pol.kind == "hashed" and all(k in node.column_map
                                    for k in pol.keys):
        return Sharding.hashed(*(node.column_map[k] for k in pol.keys))
    return Sharding.strewn()


def _hot_frac(plan: N.PlanNode, keys, catalog) -> float:
    """Estimated fraction of rows holding the HOTTEST redistribute-key
    value, read off the equi-depth histogram: a value spanning k of N
    buckets holds ≈ k/N of the rows (the pg_statistic MCV-list role).
    A compound key is at most as skewed as its least-skewed column."""
    from cloudberry_tpu.plan.cost import _col_source

    frac = 1.0
    seen = False
    for k in keys:
        if not isinstance(k, ex.ColumnRef):
            continue
        src = _col_source(plan, k.name)
        if src is None:
            continue
        try:
            hist = catalog.table(src[0]).stats.hist.get(src[1])
        except KeyError:
            continue
        if not hist or len(hist) < 3:
            continue
        run = best = 1
        for a, b in zip(hist, hist[1:]):
            run = run + 1 if a == b else 1
            best = max(best, run)
        frac = min(frac, (best - 1) / (len(hist) - 1))
        seen = True
    out = frac if seen else 0.0
    # feedback (plan/feedback.py): when a prior execution of this
    # (table, key-set) shuffle ALARMED on observed skew, the measured
    # hottest-destination fraction overrides an optimistic histogram —
    # this is what re-ranks join order / motion choice on the second
    # execution of a mis-estimated hot-key probe. Sub-alarm
    # observations leave the histogram estimate in charge.
    fb = getattr(catalog, "_feedback", None)
    if fb is not None:
        obs = fb.hot_frac(plan, keys)
        if obs is not None and obs > out:
            return obs
    return out


def _redist_cost(est: float, width: int, frac: float, nseg: int) -> float:
    """Bytes cost of a redistribute, skew-aware: when the hottest key
    exceeds its fair 1/nseg share, one destination serializes the motion
    AND the downstream compute — scale by how far it overshoots (the
    cdbpath.c skew-sensitive motion costing role). This is what steers
    the memo toward broadcast for hot-key probes."""
    base = est * width * (nseg - 1) / max(nseg, 1)
    if frac * nseg > 1.0:
        base *= frac * nseg
    return base


def _explore_join(node: N.PJoin, catalog, nseg: int,
                  thr: int, gst: int = 0) -> Optional[dict]:
    from cloudberry_tpu.plan.cost import estimate_rows

    if node.kind == "full":
        return None  # forced shape (coloc or gather-both); greedy path
    balts = explore(node.build, catalog, nseg, thr, gst)
    palts = explore(node.probe, catalog, nseg, thr, gst)
    if balts is None or palts is None:
        return None
    est_b = estimate_rows(node.build, catalog)
    est_p = estimate_rows(node.probe, catalog)
    wb, wp = _width(node.build), _width(node.probe)
    fcache: dict = {}

    def hot(side, keys):
        # skew is a property of the ACTUAL redistribute-key subset: min
        # over more columns can only understate a subset's hot fraction
        ck = (id(side), tuple(k.name if isinstance(k, ex.ColumnRef)
                              else "?" for k in keys))
        if ck not in fcache:
            fcache[ck] = _hot_frac(side, keys, catalog)
        return fcache[ck]
    out: dict = {}
    for ba in balts.values():
        for pa in palts.values():
            base = ba.cost + pa.cost
            ch = ba.choices + pa.choices
            bsh, psh = ba.sharding, pa.sharding
            b_part, p_part = bsh.is_partitioned, psh.is_partitioned
            if not (b_part and p_part):
                # forced arms of Distributor._join: no choice to stamp
                if b_part and not p_part:
                    if node.kind in ("inner", "semi"):
                        bsub = _hashed_key_positions(bsh, node.build_keys)
                        if bsub is not None:
                            names = [node.probe_keys[i].name
                                     for i in bsub
                                     if isinstance(node.probe_keys[i],
                                                   ex.ColumnRef)]
                            sh = (Sharding.hashed(*names)
                                  if len(names) == len(bsub)
                                  else Sharding.strewn())
                        else:
                            sh = Sharding.strewn()
                        _keep_best(out, Alt(base, sh, ch))
                    else:
                        # left/anti: broadcast the partitioned build
                        _keep_best(out, Alt(
                            base + est_b * wb * (nseg - 1), psh, ch))
                else:
                    _keep_best(out, Alt(base, psh, ch))
                continue
            if _join_colocated(node, bsh, psh):
                _keep_best(out, Alt(base, psh,
                                    ch + ((node, "colocate"),)))
                continue
            # thr == 0 is the explicit "never broadcast" switch — the
            # memo honors it like the greedy rule does
            if thr > 0 and est_b * nseg <= broadcast_struct_rows(thr):
                _keep_best(out, Alt(
                    base + est_b * wb * (nseg - 1), psh,
                    ch + ((node, "broadcast"),)))
            bsub = _hashed_key_positions(bsh, node.build_keys)
            psub = _hashed_key_positions(psh, node.probe_keys)
            # semijoin reduction: a probe redistribute ships only the rows
            # a pre-motion DIGEST runtime filter would pass (stamped by
            # annotate_distribution via distribute.digest_filter_frac) —
            # the same currency the distributor uses when it inserts the
            # filter, so a big-build join whose probe shrinks 10x on the
            # wire wins redist_probe over broadcast on its real bytes
            jfrac = getattr(node, "_jf_frac", 1.0)
            if bsub is not None:
                keys = [node.probe_keys[i] for i in bsub]
                _keep_best(out, Alt(
                    base + _redist_cost(est_p * jfrac, wp,
                                        hot(node.probe, keys), nseg),
                    _redist_sharding(keys),
                    ch + ((node, "redist_probe"),)))
            if psub is not None:
                bkeys = [node.build_keys[i] for i in psub]
                _keep_best(out, Alt(
                    base + _redist_cost(est_b, wb,
                                        hot(node.build, bkeys), nseg),
                    psh, ch + ((node, "redist_build"),)))
            _keep_best(out, Alt(
                base + _redist_cost(est_b, wb,
                                    hot(node.build, node.build_keys),
                                    nseg)
                + _redist_cost(est_p * jfrac, wp,
                               hot(node.probe, node.probe_keys), nseg),
                _redist_sharding(node.probe_keys),
                ch + ((node, "redist_both"),)))
    return out or None


# --------------------------------------------------------------------------
# Joint join-order + motion search — the CJoinOrderDPv2 / CMemo marriage
# (reference: src/backend/gporca/libgpopt/src/xforms/CJoinOrderDPv2.cpp,
# libgpopt/src/search/CMemo.cpp). ORCA's core MPP insight: the cheapest
# join ORDER depends on the motion strategy and vice versa — a cheaper
# order under broadcast is not the cheapest order under redistribute — so
# both must be explored in ONE dynamic program. State: per connected
# relation subset, the Pareto set of (output sharding -> cheapest cost,
# build recipe). The binder calls this BEFORE building the join tree
# (plan/binder.py _join_tree); the plain intermediate-rows DP remains the
# fallback when the search abstains or blows its iteration budget.

# cost weights: motion bytes ride the interconnect (slower than local
# HBM traffic), build sides pay for structure construction, every motion
# op pays a fixed launch cost (collective + receiver re-sort — this is
# what keeps the search from trading one broadcast of a small dim for
# two redistributes of small intermediates), and a non-unique build side
# pays the pair-expansion materialization _make_join would set up — the
# relative weights steer order AND motion together, the same currency
# memo exploration uses.
MOTION_WEIGHT = 4.0
BUILD_WEIGHT = 0.5
MOTION_FIXED_BYTES = 1 << 20
# a redistribute costs more PER BYTE than a broadcast: it bucketizes,
# all-to-alls and re-compacts (three passes + a receiver-side resort)
# where broadcast is one all-gather of contiguous rows. Both constants
# grid-searched against dp+greedy on the 8-device mesh at SF0.1
# (geomean 1.26x over q2/3/5/7/8/9/10/18/21; q8 alone 7.5x).
REDIST_WEIGHT = 2.0
JOINT_MAX_RELS = 10
JOINT_ITER_BUDGET = 400_000
JOINT_KEEP_ALTS = 6


def _pair_sel(keys_a, keys_b, atom_a, atom_b, catalog,
              est_a, est_b) -> float:
    """Composite equi-join selectivity for ALL edges between one atom
    pair: 1/max(ndv_left-tuple, ndv_right-tuple) with the tuple NDV the
    product of column NDVs capped by the side's rows — the cost._keys_ndv
    discipline. Treating a composite key as independent edges would
    square its selectivity (q9's (l_partkey, l_suppkey) = partsupp key)
    and make that intermediate look near-free."""
    from cloudberry_tpu.plan.cost import _expr_ndv

    def tup_ndv(keys, atom, est):
        prod = 1.0
        known = False
        for k in keys:
            nd = _expr_ndv(atom, k, catalog)
            if nd is not None:
                known = True
                prod *= nd
        return min(prod, max(est, 1.0)) if known else None

    nd_a = tup_ndv(keys_a, atom_a, est_a)
    nd_b = tup_ndv(keys_b, atom_b, est_b)
    denom = max(nd_a or 1.0, nd_b or 1.0,
                1.0 if (nd_a or nd_b) else max(est_a, est_b, 1.0))
    return 1.0 / max(denom, 1.0)


def _hot_frac_cols(col_atom: dict, keys, catalog) -> float:
    """_hot_frac over pre-resolved column->atom-plan ownership (search
    subsets have no plan node to walk)."""
    frac = 1.0
    seen = False
    for k in keys:
        if not isinstance(k, ex.ColumnRef) or k.name not in col_atom:
            continue
        f = _hot_frac(col_atom[k.name], [k], catalog)
        if f > 0.0:
            frac = min(frac, f)
            seen = True
    return frac if seen else 0.0


def _join_strategies(bsh: Sharding, psh: Sharding, bkeys, pkeys,
                     est_b, est_p, wb, wp, hotb, hotp, nseg, thr):
    """Yield (motion_cost, n_motions, output Sharding, choice|None) for
    one build/probe orientation — the cdbpath_motion_for_join menu,
    shared currency with _explore_join (inner joins only: the DP never
    builds outer joins; those pre-join into atoms)."""
    b_part, p_part = bsh.is_partitioned, psh.is_partitioned
    if not (b_part and p_part):
        if b_part and not p_part:
            bsub = _hashed_key_positions(bsh, bkeys)
            if bsub is not None:
                names = [pkeys[i].name for i in bsub
                         if isinstance(pkeys[i], ex.ColumnRef)]
                sh = (Sharding.hashed(*names) if len(names) == len(bsub)
                      else Sharding.strewn())
            else:
                sh = Sharding.strewn()
            yield (0.0, 0, sh, None)
        else:
            yield (0.0, 0, psh, None)
        return
    bpos = _hashed_key_positions(bsh, bkeys)
    ppos = _hashed_key_positions(psh, pkeys)
    if bpos is not None and bpos == ppos:
        yield (0.0, 0, psh, "colocate")
        return
    if thr > 0 and est_b * nseg <= broadcast_struct_rows(thr):
        yield (est_b * wb * (nseg - 1), 1, psh, "broadcast")
    if bpos is not None:
        keys = [pkeys[i] for i in bpos]
        yield (REDIST_WEIGHT * _redist_cost(est_p, wp, hotp(keys), nseg),
               1, _redist_sharding(keys), "redist_probe")
    if ppos is not None:
        bk = [bkeys[i] for i in ppos]
        yield (REDIST_WEIGHT * _redist_cost(est_b, wb, hotb(bk), nseg),
               1, psh, "redist_build")
    yield (REDIST_WEIGHT * (_redist_cost(est_b, wb, hotb(bkeys), nseg)
           + _redist_cost(est_p, wp, hotp(pkeys), nseg)), 2,
           _redist_sharding(pkeys), "redist_both")


def joint_search(atoms, edges, nseg: int, thr: int, catalog,
                 groupby_names: frozenset, make_join, is_unique=None,
                 gst: int = 0):
    """One DP over join order AND motion strategy.

    atoms: [(plan, width)] per base relation (any bound subtree);
    edges: [(ia, ib, key_a, key_b)] pre-bound equi-join edges;
    groupby_names: bound GROUP BY column names above this region (the
    required-property context — a final sharding matching them saves
    the regroup motion);
    make_join(kind, build, probe, bkeys, pkeys) -> PJoin (the binder's
    node factory, so built trees carry capacities/masks/uniqueness
    exactly like hand-ordered ones);
    is_unique(atom_idx, keys) -> bool: PK-side proof for an atom — a
    build side without it pays the pair-expansion materialization
    (and composite builds always do), which both prices the executor's
    real expansion cost and breaks colocate-orientation ties toward
    the unique build the sorted-build lookup wants.

    Returns the built PJoin tree with ``_dist_choice`` stamps, or None
    (abstain: too many relations, no edges, or budget blown)."""
    n = len(atoms)
    if n < 3 or n > JOINT_MAX_RELS or not edges:
        return None

    from cloudberry_tpu.plan.cost import estimate_rows

    est_atom = [max(estimate_rows(p, catalog), 1.0) for p, _ in atoms]
    width = [w for _, w in atoms]
    col_atom: dict = {}
    for (p, _w) in atoms:
        for f in p.fields:
            col_atom.setdefault(f.name, p)
    # selectivity per atom PAIR (composite keys combine — never multiply
    # a multi-edge key's selectivities independently)
    pair_edges: dict[tuple, list] = {}
    for (ia, ib, ka, kb) in edges:
        lo, hi = (ia, ib) if ia < ib else (ib, ia)
        ka2, kb2 = (ka, kb) if ia < ib else (kb, ka)
        pair_edges.setdefault((lo, hi), []).append((ka2, kb2))
    pair_sel = {}
    for (lo, hi), eks in pair_edges.items():
        pair_sel[(lo, hi)] = _pair_sel(
            [k for k, _ in eks], [k for _, k in eks],
            atoms[lo][0], atoms[hi][0], catalog,
            est_atom[lo], est_atom[hi])

    est_cache: dict[int, float] = {}

    def est_of(mask: int) -> float:
        got = est_cache.get(mask)
        if got is None:
            rows = 1.0
            for i in range(n):
                if mask >> i & 1:
                    rows *= est_atom[i]
            for (lo, hi), sel in pair_sel.items():
                if mask >> lo & 1 and mask >> hi & 1:
                    rows *= sel
            got = est_cache[mask] = max(rows, 1.0)
        return got

    wid_cache: dict[int, int] = {}

    def wid_of(mask: int) -> int:
        got = wid_cache.get(mask)
        if got is None:
            got = wid_cache[mask] = max(
                sum(width[i] for i in range(n) if mask >> i & 1), 1)
        return got

    def hot_fn(keys):
        return _hot_frac_cols(col_atom, keys, catalog)

    # alternatives per atom: the motion-exploration grammar where it
    # applies, a conservative strewn property where it abstains
    best: list[Optional[dict]] = [None] * (1 << n)
    atom_alts: list[dict] = []
    for i, (p, _w) in enumerate(atoms):
        alts = explore(p, catalog, nseg, thr, gst)
        if alts is None:
            alts = {"?": Alt(0.0, Sharding.strewn(), ())}
        atom_alts.append(alts)
        best[1 << i] = {k: (a.cost, a.sharding, ("atom", i, k))
                        for k, a in alts.items()}

    budget = JOINT_ITER_BUDGET
    full = (1 << n) - 1
    by_size: dict[int, list[int]] = {}
    for m in range(1, full + 1):
        by_size.setdefault(bin(m).count("1"), []).append(m)
    for size in range(2, n + 1):
        for m in by_size.get(size, ()):
            out: dict = {}
            s = (m - 1) & m
            while s:
                o = m ^ s
                if s > o and best[s] is not None and best[o] is not None:
                    eidx = [e for e, (ia, ib, _ka, _kb) in enumerate(edges)
                            if (s >> ia & 1 and o >> ib & 1)
                            or (o >> ia & 1 and s >> ib & 1)]
                    if eidx:
                        budget = _joint_pairs(
                            m, s, o, eidx, best, out, edges, est_of,
                            wid_of, hot_fn, nseg, thr, budget,
                            is_unique)
                        if budget <= 0:
                            return None
                s = (s - 1) & m
            if out:
                if len(out) > JOINT_KEEP_ALTS:
                    keep = sorted(out.items(),
                                  key=lambda kv: kv[1][0])[:JOINT_KEEP_ALTS]
                    out = dict(keep)
                best[m] = out
    if best[full] is None:
        return None
    # required property: a final sharding already matching the GROUP BY
    # keys saves the regroup motion above this region. The regroup moves
    # PARTIAL rows — at most (groups × nseg), never more than the join
    # output (the _agg_extra discipline): pricing it as the raw output
    # would overvalue groupby-aligned shardings by orders of magnitude.
    from cloudberry_tpu.plan.cost import _expr_ndv

    groups = 1.0
    for nm in groupby_names:
        p = col_atom.get(nm)
        nd = _expr_ndv(p, ex.ColumnRef(nm, None), catalog) \
            if p is not None else None
        groups *= nd if nd else max(est_of(full) ** 0.5, 1.0)
    rows = min(groups * nseg, est_of(full))
    regroup = rows * wid_of(full) * (nseg - 1) / max(nseg, 1) \
        * MOTION_WEIGHT * REDIST_WEIGHT + MOTION_FIXED_BYTES
    winner = None
    for (cost, sh, desc) in best[full].values():
        extra = 0.0
        if groupby_names:
            if not (sh.kind == "hashed" and sh.keys
                    and set(sh.keys) <= groupby_names):
                extra = regroup
        if winner is None or cost + extra < winner[0]:
            winner = (cost + extra, desc)
    return _joint_build(winner[1], atoms, edges, atom_alts, make_join)


def _joint_pairs(m, s, o, eidx, best, out, edges, est_of, wid_of,
                 hot_fn, nseg, thr, budget, is_unique):
    """Inner loop: cross every sharding alternative pair of the two
    halves with both orientations and the motion menu."""
    est_m = est_of(m)
    compute = est_m * wid_of(m)
    for salt in best[s].values():
        for oalt in best[o].values():
            for bmask, balt, pmask, palt in (
                    (s, salt, o, oalt), (o, oalt, s, salt)):
                budget -= 1
                if budget <= 0:
                    return 0
                bkeys, pkeys = [], []
                for e in eidx:
                    ia, ib, ka, kb = edges[e]
                    if bmask >> ia & 1:
                        bkeys.append(ka)
                        pkeys.append(kb)
                    else:
                        bkeys.append(kb)
                        pkeys.append(ka)
                est_b, est_p = est_of(bmask), est_of(pmask)
                wb, wp = wid_of(bmask), wid_of(pmask)
                base = balt[0] + palt[0] + compute \
                    + BUILD_WEIGHT * est_b * wb
                if not ((bmask & (bmask - 1)) == 0
                        and is_unique is not None
                        and is_unique(bmask.bit_length() - 1, bkeys)):
                    # non-unique (or composite) build side: price the
                    # pair-expansion buffer _make_join will allocate
                    base += compute
                for (mcost, nmot, sh, choice) in _join_strategies(
                        balt[1], palt[1], bkeys, pkeys, est_b, est_p,
                        wb, wp, hot_fn, hot_fn, nseg, thr):
                    cost = base + MOTION_WEIGHT * mcost \
                        + nmot * MOTION_FIXED_BYTES
                    k = str(sh)
                    cur = out.get(k)
                    if cur is None or cost < cur[0]:
                        out[k] = (cost, sh,
                                  ("join", balt[2], palt[2], tuple(eidx),
                                   bmask, choice))
    return budget


def _joint_build(desc, atoms, edges, atom_alts, make_join):
    """Materialize the winning recipe bottom-up through the binder's
    node factory, stamping each join's motion choice."""
    if desc[0] == "atom":
        _kind, i, altkey = desc
        # joins INSIDE an atom (derived tables) carry their own choice
        # stamps through the exploration Alt — and must be final too,
        # or the post-bind exploration re-stamps a locally-cheapest
        # choice whose sharding the parent's motions were not priced for
        for jn, choice in atom_alts[i][altkey].choices:
            jn._dist_choice = choice
            jn._joint = True
        return atoms[i][0]
    _kind, bdesc, pdesc, eidx, bmask, choice = desc
    bplan = _joint_build(bdesc, atoms, edges, atom_alts, make_join)
    pplan = _joint_build(pdesc, atoms, edges, atom_alts, make_join)
    bkeys, pkeys = [], []
    for e in eidx:
        ia, ib, ka, kb = edges[e]
        if bmask >> ia & 1:
            bkeys.append(ka)
            pkeys.append(kb)
        else:
            bkeys.append(kb)
            pkeys.append(ka)
    j = make_join("inner", bplan, pplan, bkeys, pkeys)
    if choice is not None:
        j._dist_choice = choice
    # the joint decision is final: the post-bind motion exploration
    # must not re-stamp joins whose order AND motion were chosen
    # together (annotate_distribution skips _joint regions)
    j._joint = True
    return j


def _agg_extra(agg: N.PAgg, sharding: Sharding, catalog,
               nseg: int) -> float:
    """Cost the GROUP BY above the join tree adds for a given output
    property: zero when the grouping can run one-stage colocated
    (Distributor._agg's test), else the partial rows' redistribute."""
    from cloudberry_tpu.plan.cost import estimate_rows

    if not agg.group_keys:
        return 0.0  # global agg gathers one partial row either way
    key_src = {e.name for _, e in agg.group_keys
               if isinstance(e, ex.ColumnRef)}
    if sharding.kind == "hashed" and sharding.keys \
            and set(sharding.keys) <= key_src:
        return 0.0
    est_groups = estimate_rows(agg, catalog)
    rows = min(est_groups * nseg, estimate_rows(agg.child, catalog))
    return rows * _width(agg) * (nseg - 1) / max(nseg, 1)


def _joins_of(node: N.PlanNode):
    """Every join inside the join-tree grammar region rooted here —
    through single-mode aggs, which the grammar now includes: an outer
    region's stamps on sub-agg joins are final and must not be
    re-explored by the visitor."""
    if isinstance(node, (N.PFilter, N.PProject)):
        yield from _joins_of(node.child)
    elif isinstance(node, N.PAgg) and node.mode == "single":
        yield from _joins_of(node.child)
    elif isinstance(node, N.PJoin):
        yield node
        yield from _joins_of(node.build)
        yield from _joins_of(node.probe)


def _through_chain(node: N.PlanNode) -> N.PlanNode:
    while isinstance(node, (N.PFilter, N.PProject)):
        node = node.child
    return node


def annotate_distribution(plan: N.PlanNode, session) -> None:
    """Explore every join-tree region of the bound plan and stamp the
    globally cheapest motion strategy on each join (``_dist_choice``).
    Runs BEFORE the distribution walk (estimates see bind-time
    capacities, exactly like Distributor._join's own estimate calls)."""
    nseg = session.config.n_segments
    if nseg <= 1:
        return
    catalog = session.catalog
    thr = session.config.planner.broadcast_threshold
    gst = session.config.planner.gather_single_threshold
    annotated: set[int] = set()
    seen: set[int] = set()

    # pre-stamp each join's digest-filter survival fraction so the
    # exploration (which deliberately has no config in scope) prices
    # probe redistributes at their POST-FILTER bytes; the joint search
    # (mask-based, no join nodes yet) stays unmodeled by design
    from cloudberry_tpu.exec.executor import all_nodes
    from cloudberry_tpu.plan.distribute import digest_filter_frac

    fb = getattr(catalog, "_feedback", None)
    for nd in all_nodes(plan):
        if isinstance(nd, N.PJoin) and not hasattr(nd, "_jf_frac"):
            try:
                nd._jf_frac = digest_filter_frac(nd, catalog,
                                                 session.config, nseg)
            except Exception:
                nd._jf_frac = 1.0
        if isinstance(nd, N.PJoin) and fb is not None \
                and not hasattr(nd, "_feedback_skew"):
            # provenance for EXPLAIN/flight recorder: this join's probe
            # shuffle has an ALARMED skew sketch, so the exploration
            # below re-ranks with the observed hot fraction
            try:
                if fb.hot_frac(nd.probe, nd.probe_keys) is not None:
                    nd._feedback_skew = True
            except Exception:
                pass

    def region(root: N.PlanNode, agg: Optional[N.PAgg]) -> None:
        alts = explore(root, catalog, nseg, thr, gst)
        if not alts:
            # abstained (out-of-grammar node somewhere inside): leave
            # every join unmarked — the visitor descends and in-grammar
            # subtrees become fresh regions of their own. The mark makes
            # the abstention VISIBLE in EXPLAIN ("memo: abstained"), so
            # golden plans pin which regions fall back to greedy rules.
            root._memo_abstained = True
            return
        for j in _joins_of(root):
            annotated.add(id(j))
        best = None
        for a in alts.values():
            extra = _agg_extra(agg, a.sharding, catalog, nseg) \
                if agg is not None else 0.0
            if best is None or a.cost + extra < best[0]:
                best = (a.cost + extra, a)
        for jn, choice in best[1].choices:
            jn._dist_choice = choice

    def visit(node: N.PlanNode) -> None:
        if id(node) in seen:  # PShare reuse
            return
        seen.add(id(node))
        if isinstance(node, N.PAgg) and node.mode == "single":
            j = _through_chain(node.child)
            if isinstance(j, N.PJoin) and id(j) not in annotated \
                    and not getattr(j, "_joint", False):
                # explore from the agg's child so the Filter/Project
                # chain folds its renames into each alternative's
                # sharding — _agg_extra must see exactly the locus
                # Distributor._agg will test
                region(node.child, node)
        elif isinstance(node, N.PJoin) and id(node) not in annotated \
                and not getattr(node, "_joint", False):
            region(node, None)
        for c in node.children():
            visit(c)
        # uncorrelated scalar subqueries (InitPlan analog) carry their
        # own plans inside expressions; the distributor walks them, so
        # the memo explores them too
        for e in _node_exprs(node):
            for sub in ex.walk(e):
                if isinstance(sub, ex.SubqueryScalar):
                    visit(sub.plan)

    visit(plan)
