"""Cascades-lite distribution exploration — the ORCA (gporca) role,
scoped to the decision that dominates MPP cost: where the motions go.

The reference ships two optimizers: the MPP-ified Postgres planner
(greedy locus rules, cdbpath.c:1346 cdbpath_motion_for_join) and ORCA, a
Cascades engine (src/backend/gporca) that explores alternative plans in
a memo and costs them. This module is the memo idea translated to this
planner's world:

- groups        = join-tree subtrees (scans / filters / projections /
                  joins — the grammar Distributor._join decides over);
- physical
  property      = the subtree's output Sharding (the CdbPathLocus
                  analog; ORCA's CDistributionSpec);
- alternatives  = per join: colocate / broadcast-build / redistribute-
                  probe / redistribute-build / redistribute-both —
                  exactly the moves cdbpath_motion_for_join knows, but
                  COSTED AND COMPARED over the whole tree instead of
                  decided greedily per node;
- cost          = bytes over the interconnect (rows moved × row width),
                  the dominant term on the reference's UDP fabric and on
                  TPU ICI alike;
- required
  property      = the parent context: GROUP BY keys above the join tree
                  add the final-redistribute cost each output property
                  implies, so a locally cheap choice that forces an
                  expensive re-shuffle later LOSES — System R's
                  "interesting orders" insight applied to hash
                  distribution (ORCA: derived vs required distribution
                  specs).

The winning alternative is stamped on each join (``_dist_choice``);
``Distributor._join`` honors the stamp — re-checking its preconditions,
falling back to the greedy rules wherever the memo abstained or the
plan drifted — so the memo can only redirect motions the distributor
already knows how to place.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.plan.distribute import (_hashed_key_positions,
                                            _join_colocated,
                                            _node_exprs,
                                            _project_sharding,
                                            broadcast_struct_rows)
from cloudberry_tpu.plan.sharding import Sharding


@dataclass(frozen=True)
class Alt:
    """One costed alternative for a subtree: total motion bytes below,
    the output sharding it yields, and the per-join choices that
    produce it."""

    cost: float
    sharding: Sharding
    choices: tuple  # ((PJoin, choice-str), ...)


def _width(node: N.PlanNode) -> int:
    return max(sum(f.type.np_dtype.itemsize for f in node.fields), 1)


def _keep_best(alts: dict, alt: Alt) -> None:
    k = str(alt.sharding)
    cur = alts.get(k)
    if cur is None or alt.cost < cur.cost:
        alts[k] = alt


def _redist_sharding(keys) -> Sharding:
    """Mirror Distributor.redistribute's output locus."""
    names = tuple(k.name for k in keys if isinstance(k, ex.ColumnRef))
    return Sharding.hashed(*names) if len(names) == len(keys) \
        else Sharding.strewn()


def explore(node: N.PlanNode, catalog, nseg: int,
            thr: int) -> Optional[dict]:
    """Alternative set {sharding-key: Alt} for a join-tree subtree; None
    when the subtree leaves the grammar (aggs, set-ops, windows, shares,
    subquery scalars in scope) — the greedy rules then stand alone."""
    if isinstance(node, N.PScan):
        return {str(sh): Alt(0.0, sh, ())
                for sh in (_scan_sharding(node, catalog),)}
    if isinstance(node, N.PFilter):
        return explore(node.child, catalog, nseg, thr)
    if isinstance(node, N.PProject):
        sub = explore(node.child, catalog, nseg, thr)
        if sub is None:
            return None
        out: dict = {}
        for a in sub.values():
            _keep_best(out, Alt(a.cost,
                                _project_sharding(a.sharding, node.exprs),
                                a.choices))
        return out
    if isinstance(node, N.PJoin):
        return _explore_join(node, catalog, nseg, thr)
    return None


def _scan_sharding(node: N.PScan, catalog) -> Sharding:
    """Mirror Distributor._scan's locus assignment."""
    if node.table_name == "$dual":
        return Sharding.general()
    try:
        table = catalog.table(node.table_name)
    except KeyError:
        return Sharding.strewn()
    pol = table.policy
    if pol.kind == "replicated":
        return Sharding.replicated()
    if pol.kind == "hashed" and all(k in node.column_map
                                    for k in pol.keys):
        return Sharding.hashed(*(node.column_map[k] for k in pol.keys))
    return Sharding.strewn()


def _hot_frac(plan: N.PlanNode, keys, catalog) -> float:
    """Estimated fraction of rows holding the HOTTEST redistribute-key
    value, read off the equi-depth histogram: a value spanning k of N
    buckets holds ≈ k/N of the rows (the pg_statistic MCV-list role).
    A compound key is at most as skewed as its least-skewed column."""
    from cloudberry_tpu.plan.cost import _col_source

    frac = 1.0
    seen = False
    for k in keys:
        if not isinstance(k, ex.ColumnRef):
            continue
        src = _col_source(plan, k.name)
        if src is None:
            continue
        try:
            hist = catalog.table(src[0]).stats.hist.get(src[1])
        except KeyError:
            continue
        if not hist or len(hist) < 3:
            continue
        run = best = 1
        for a, b in zip(hist, hist[1:]):
            run = run + 1 if a == b else 1
            best = max(best, run)
        frac = min(frac, (best - 1) / (len(hist) - 1))
        seen = True
    return frac if seen else 0.0


def _redist_cost(est: float, width: int, frac: float, nseg: int) -> float:
    """Bytes cost of a redistribute, skew-aware: when the hottest key
    exceeds its fair 1/nseg share, one destination serializes the motion
    AND the downstream compute — scale by how far it overshoots (the
    cdbpath.c skew-sensitive motion costing role). This is what steers
    the memo toward broadcast for hot-key probes."""
    base = est * width * (nseg - 1) / max(nseg, 1)
    if frac * nseg > 1.0:
        base *= frac * nseg
    return base


def _explore_join(node: N.PJoin, catalog, nseg: int,
                  thr: int) -> Optional[dict]:
    from cloudberry_tpu.plan.cost import estimate_rows

    if node.kind == "full":
        return None  # forced shape (coloc or gather-both); greedy path
    balts = explore(node.build, catalog, nseg, thr)
    palts = explore(node.probe, catalog, nseg, thr)
    if balts is None or palts is None:
        return None
    est_b = estimate_rows(node.build, catalog)
    est_p = estimate_rows(node.probe, catalog)
    wb, wp = _width(node.build), _width(node.probe)
    fcache: dict = {}

    def hot(side, keys):
        # skew is a property of the ACTUAL redistribute-key subset: min
        # over more columns can only understate a subset's hot fraction
        ck = (id(side), tuple(k.name if isinstance(k, ex.ColumnRef)
                              else "?" for k in keys))
        if ck not in fcache:
            fcache[ck] = _hot_frac(side, keys, catalog)
        return fcache[ck]
    out: dict = {}
    for ba in balts.values():
        for pa in palts.values():
            base = ba.cost + pa.cost
            ch = ba.choices + pa.choices
            bsh, psh = ba.sharding, pa.sharding
            b_part, p_part = bsh.is_partitioned, psh.is_partitioned
            if not (b_part and p_part):
                # forced arms of Distributor._join: no choice to stamp
                if b_part and not p_part:
                    if node.kind in ("inner", "semi"):
                        bsub = _hashed_key_positions(bsh, node.build_keys)
                        if bsub is not None:
                            names = [node.probe_keys[i].name
                                     for i in bsub
                                     if isinstance(node.probe_keys[i],
                                                   ex.ColumnRef)]
                            sh = (Sharding.hashed(*names)
                                  if len(names) == len(bsub)
                                  else Sharding.strewn())
                        else:
                            sh = Sharding.strewn()
                        _keep_best(out, Alt(base, sh, ch))
                    else:
                        # left/anti: broadcast the partitioned build
                        _keep_best(out, Alt(
                            base + est_b * wb * (nseg - 1), psh, ch))
                else:
                    _keep_best(out, Alt(base, psh, ch))
                continue
            if _join_colocated(node, bsh, psh):
                _keep_best(out, Alt(base, psh,
                                    ch + ((node, "colocate"),)))
                continue
            # thr == 0 is the explicit "never broadcast" switch — the
            # memo honors it like the greedy rule does
            if thr > 0 and est_b * nseg <= broadcast_struct_rows(thr):
                _keep_best(out, Alt(
                    base + est_b * wb * (nseg - 1), psh,
                    ch + ((node, "broadcast"),)))
            bsub = _hashed_key_positions(bsh, node.build_keys)
            psub = _hashed_key_positions(psh, node.probe_keys)
            if bsub is not None:
                keys = [node.probe_keys[i] for i in bsub]
                _keep_best(out, Alt(
                    base + _redist_cost(est_p, wp,
                                        hot(node.probe, keys), nseg),
                    _redist_sharding(keys),
                    ch + ((node, "redist_probe"),)))
            if psub is not None:
                bkeys = [node.build_keys[i] for i in psub]
                _keep_best(out, Alt(
                    base + _redist_cost(est_b, wb,
                                        hot(node.build, bkeys), nseg),
                    psh, ch + ((node, "redist_build"),)))
            _keep_best(out, Alt(
                base + _redist_cost(est_b, wb,
                                    hot(node.build, node.build_keys),
                                    nseg)
                + _redist_cost(est_p, wp,
                               hot(node.probe, node.probe_keys), nseg),
                _redist_sharding(node.probe_keys),
                ch + ((node, "redist_both"),)))
    return out or None


def _agg_extra(agg: N.PAgg, sharding: Sharding, catalog,
               nseg: int) -> float:
    """Cost the GROUP BY above the join tree adds for a given output
    property: zero when the grouping can run one-stage colocated
    (Distributor._agg's test), else the partial rows' redistribute."""
    from cloudberry_tpu.plan.cost import estimate_rows

    if not agg.group_keys:
        return 0.0  # global agg gathers one partial row either way
    key_src = {e.name for _, e in agg.group_keys
               if isinstance(e, ex.ColumnRef)}
    if sharding.kind == "hashed" and sharding.keys \
            and set(sharding.keys) <= key_src:
        return 0.0
    est_groups = estimate_rows(agg, catalog)
    rows = min(est_groups * nseg, estimate_rows(agg.child, catalog))
    return rows * _width(agg) * (nseg - 1) / max(nseg, 1)


def _joins_of(node: N.PlanNode):
    """Every join inside the join-tree grammar region rooted here."""
    if isinstance(node, (N.PFilter, N.PProject)):
        yield from _joins_of(node.child)
    elif isinstance(node, N.PJoin):
        yield node
        yield from _joins_of(node.build)
        yield from _joins_of(node.probe)


def _through_chain(node: N.PlanNode) -> N.PlanNode:
    while isinstance(node, (N.PFilter, N.PProject)):
        node = node.child
    return node


def annotate_distribution(plan: N.PlanNode, session) -> None:
    """Explore every join-tree region of the bound plan and stamp the
    globally cheapest motion strategy on each join (``_dist_choice``).
    Runs BEFORE the distribution walk (estimates see bind-time
    capacities, exactly like Distributor._join's own estimate calls)."""
    nseg = session.config.n_segments
    if nseg <= 1:
        return
    catalog = session.catalog
    thr = session.config.planner.broadcast_threshold
    annotated: set[int] = set()
    seen: set[int] = set()

    def region(root: N.PlanNode, agg: Optional[N.PAgg]) -> None:
        alts = explore(root, catalog, nseg, thr)
        if not alts:
            # abstained (out-of-grammar node somewhere inside): leave
            # every join unmarked — the visitor descends and in-grammar
            # subtrees become fresh regions of their own
            return
        for j in _joins_of(root):
            annotated.add(id(j))
        best = None
        for a in alts.values():
            extra = _agg_extra(agg, a.sharding, catalog, nseg) \
                if agg is not None else 0.0
            if best is None or a.cost + extra < best[0]:
                best = (a.cost + extra, a)
        for jn, choice in best[1].choices:
            jn._dist_choice = choice

    def visit(node: N.PlanNode) -> None:
        if id(node) in seen:  # PShare reuse
            return
        seen.add(id(node))
        if isinstance(node, N.PAgg) and node.mode == "single":
            j = _through_chain(node.child)
            if isinstance(j, N.PJoin) and id(j) not in annotated:
                # explore from the agg's child so the Filter/Project
                # chain folds its renames into each alternative's
                # sharding — _agg_extra must see exactly the locus
                # Distributor._agg will test
                region(node.child, node)
        elif isinstance(node, N.PJoin) and id(node) not in annotated:
            region(node, None)
        for c in node.children():
            visit(c)
        # uncorrelated scalar subqueries (InitPlan analog) carry their
        # own plans inside expressions; the distributor walks them, so
        # the memo explores them too
        for e in _node_exprs(node):
            for sub in ex.walk(e):
                if isinstance(sub, ex.SubqueryScalar):
                    visit(sub.plan)

    visit(plan)
