"""Plan IR — one node family used logically and physically.

The reference has separate Path→Plan layers (src/backend/optimizer,
src/backend/nodes/plannodes.h); here a single tree serves both: the binder
produces it, the distribution pass (plan/distribute.py) rewrites it by
inserting Motion nodes and annotating Sharding (the CdbPathLocus analog,
cdbpathlocus.h:41-68), and the executor lowers it to one jitted function.

Every node carries an output schema: a list of PlanField (unique name, type,
host-side dictionary for strings). Row capacity is static per node — the
XLA shape discipline.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Optional

from cloudberry_tpu.columnar.dictionary import StringDictionary
from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan.sharding import Sharding
from cloudberry_tpu.types import SqlType


@dataclass(frozen=True)
class PlanField:
    name: str
    type: SqlType
    sdict: Optional[StringDictionary] = None  # for STRING columns
    # validity mask column name(s): the column is valid (NOT NULL) where ALL
    # named bool columns are True. A column nullable through several outer
    # joins / a nullable base column carries one name per source.
    null_mask: Optional[str | tuple[str, ...]] = None
    # the column is a NULL literal (a grouping-set branch's omitted-key
    # label): set-op alignment may type it from the OTHER side — a real
    # field so every copy site propagates it by construction
    _is_null_col: bool = False

    @property
    def masks(self) -> tuple[str, ...]:
        if self.null_mask is None:
            return ()
        if isinstance(self.null_mask, str):
            return (self.null_mask,)
        return self.null_mask


def _feedback_suffix(node) -> str:
    """`` feedback: ...`` plan-text tags for estimates learned from live
    telemetry (plan/feedback.py) — absent on purely static plans, so
    golden corpora planned in sketch-free sessions are unchanged."""
    tags = []
    seed = getattr(node, "_feedback_seed", None)
    if seed is not None:
        tags.append(f"rung {seed['rung']} "
                    f"(demand {seed['demand']}, static {seed['static']})")
    ndv = getattr(node, "_feedback_ndv", None)
    if ndv is not None:
        tags.append(f"ndv {ndv[0]}..{ndv[1]}")
    if getattr(node, "_jf_frac_src", None) == "feedback":
        tags.append("jf-frac observed")
    if getattr(node, "_feedback_skew", False):
        tags.append("skew alarmed")
    if not tags:
        return ""
    return "  feedback: " + ", ".join(tags)


@dataclass
class PlanNode:
    fields: list[PlanField] = dc_field(default_factory=list, init=False)
    sharding: Sharding = dc_field(default=None, init=False)  # set by distribute

    @property
    def names(self) -> list[str]:
        return [f.name for f in self.fields]

    def field(self, name: str) -> PlanField:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)

    def children(self) -> list["PlanNode"]:
        return []

    def title(self) -> str:
        return type(self).__name__.removeprefix("P")

    def explain(self, indent: int = 0) -> str:
        lines = []
        seg = getattr(self, "_direct_segment", None)
        if seg is not None and indent == 0:
            lines.append(f"Direct dispatch: segment {seg} "
                         "(point predicate on distribution key)")
        mv = getattr(self, "_aqumv", None)
        if mv is not None and indent == 0:
            lines.append(f"AQUMV: answered from materialized view {mv}")
        # the verifier's DERIVED distribution (plan/verify.py
        # annotate_derived) — printed NEXT TO the stamped locus so plan
        # reviews and golden diffs show sharding explicitly, and a
        # derivation change is a visible diff even when the stamp
        # agrees
        vd = getattr(self, "_vdist", None)
        lines.append(" " * indent + "-> " + self.title()
                     + (f"  [{self.sharding}]" if self.sharding else "")
                     + (f"  dist:{vd}" if vd is not None else "")
                     # memo exploration abstained on this region root —
                     # its joins fell back to the greedy cdbpath rules
                     # (plan/memo.py annotate_distribution); pinned in
                     # plan text so golden tests catch regressions
                     + (" memo: abstained"
                        if getattr(self, "_memo_abstained", False) else "")
                     # learned-vs-guessed provenance (plan/feedback.py):
                     # estimates taken from live-telemetry sketches are
                     # marked so EXPLAIN and the flight recorder show
                     # which numbers the planner LEARNED
                     + _feedback_suffix(self))
        for c in self.children():
            lines.append(c.explain(indent + 3))
        return "\n".join(lines)


@dataclass
class PScan(PlanNode):
    table_name: str
    # physical column name in storage → output (aliased) field name
    column_map: dict[str, str]
    capacity: int          # static array capacity (≥1 even when empty)
    num_rows: int = -1     # actual rows; -1 means == capacity
    # physical column name → output validity-mask field name, for base
    # columns that contain NULLs (storage keys them "$nn:<phys>")
    mask_map: dict[str, str] = dc_field(default_factory=dict)

    def title(self):
        base = f"Scan {self.table_name} [{self.capacity}]"
        pc = getattr(self, "_point_col", None)
        if pc is not None:
            # sorted-sidecar point lookup (plan/pointlookup.py): the
            # scan reads only the matched rows
            base += f" point-lookup({pc})"
        rep = getattr(self, "_prune_report", None)
        if rep is not None:
            kept = len(getattr(self, "_store_parts", ()))
            base += f" parts {kept}/{rep['candidates']}"
            skips = rep["skipped_minmax"] + rep["skipped_bloom"]
            if skips:
                base += (f" (minmax-skip {rep['skipped_minmax']}, "
                         f"bloom-skip {rep['skipped_bloom']})")
            if rep.get("skipped_dynamic"):
                base += f" (partition-selector-skip {rep['skipped_dynamic']})"
        return base


@dataclass
class PFilter(PlanNode):
    child: PlanNode
    predicate: ex.Expr

    def children(self):
        return [self.child]


@dataclass
class PProject(PlanNode):
    child: PlanNode
    exprs: list[tuple[str, ex.Expr]]  # output name -> expr

    def children(self):
        return [self.child]


@dataclass
class PJoin(PlanNode):
    """Join (nodeHashjoin analog). Two execution shapes:
    - unique_build=True: sorted-build lookup, output rides the probe's
      capacity; build uniqueness verified at runtime (dup detection);
    - unique_build=False: many-to-many expansion (one output row per match
      pair) at ``out_capacity`` with overflow detection."""

    kind: str  # 'inner' | 'left' | 'full' | 'semi' | 'anti'
    build: PlanNode
    probe: PlanNode
    build_keys: list[ex.Expr]
    probe_keys: list[ex.Expr]
    # columns of build to carry into output (gathered); probe cols pass through
    build_payload: list[str] = dc_field(default_factory=list)
    # name of the bool match-mask output column (left join null tests)
    match_name: Optional[str] = None
    # FULL joins: validity mask for the probe side (rows synthesized from
    # unmatched build rows have NULL probe columns)
    probe_match_name: Optional[str] = None
    unique_build: bool = True
    out_capacity: int = 0  # expansion joins only
    # semi/anti residual predicate over (probe cols + build cols) — the
    # correlated-EXISTS extra conditions (e.g. Q21's l2.l_suppkey <>
    # l1.l_suppkey); forces pair-expansion evaluation
    residual: Optional[ex.Expr] = None
    # SQL NULL join-key semantics: a NULL key matches nothing. These bool
    # exprs (over build/probe columns) are True where every key is valid;
    # None = keys provably non-null.
    build_key_valid: Optional[ex.Expr] = None
    probe_key_valid: Optional[ex.Expr] = None
    # NOT IN (subquery) null-awareness: if ANY build key is NULL, the anti
    # join yields no rows at all (x NOT IN (..., NULL) is never TRUE)
    null_aware: bool = False
    # packed-key width: 32 when build-side column stats PROVE every
    # in-range pack fits u32 (cost.annotate_pack_bits) — TPU sorts and
    # searches run ~2× faster on 32-bit lanes
    pack_bits: int = 64

    def children(self):
        return [self.build, self.probe]

    def title(self):
        return f"Join {self.kind}"


@dataclass
class PAgg(PlanNode):
    """mode: 'single' | 'partial' | 'final' (multi-stage agg,
    cdbgroupingpaths.c analog)."""

    child: PlanNode
    group_keys: list[tuple[str, ex.Expr]]   # output key name -> expr
    aggs: list[tuple[str, ex.AggCall]]      # output agg name -> call
    capacity: int                            # max groups (static)
    mode: str = "single"

    def children(self):
        return [self.child]

    def title(self):
        kind = "GroupAgg" if self.group_keys else "Agg"
        return f"{kind} {self.mode} [{self.capacity}]"


@dataclass
class PSort(PlanNode):
    child: PlanNode
    keys: list[tuple[ex.Expr, bool]]  # (expr, ascending)

    def children(self):
        return [self.child]


@dataclass
class PLimit(PlanNode):
    child: PlanNode
    limit: int
    offset: int = 0

    def children(self):
        return [self.child]

    def title(self):
        return f"Limit {self.limit}" + (f" offset {self.offset}" if self.offset else "")


@dataclass
class PWindow(PlanNode):
    """Window computation over one (PARTITION BY, ORDER BY) spec; appends
    one output column per call. funcs: row_number | rank | dense_rank |
    ntile | lead | lag | first_value | last_value | sum | count | avg |
    min | max (aggregates are running when ordered — RANGE UNBOUNDED
    PRECEDING TO CURRENT ROW, peers included — else whole-partition;
    positional funcs follow src/backend/executor/nodeWindowAgg.c frame
    rules: first_value = partition head, last_value = current peer-group
    tail under the default frame)."""

    child: PlanNode
    partition_keys: list[ex.Expr]
    order_keys: list[tuple[ex.Expr, bool]]
    calls: list[tuple[str, str, Optional[ex.Expr]]]  # (out, func, arg)
    # per-call argument-validity exprs (parallel to ``calls``; None entry =
    # arg provably non-NULL). count() counts only valid rows; avg divides
    # by the valid count; the pseudo-func 'anyvalid' emits a bool column
    # that is True where the frame holds ≥1 valid arg — the null_mask for
    # nullable sum/min/max/avg outputs (SQL: agg over an all-NULL frame is
    # NULL, src/backend/executor/nodeWindowAgg.c semantics). Positional
    # funcs carry a companion '<func>@mask' pseudo-call instead: its bool
    # output is True where the source row exists in-partition AND (when
    # the arg is nullable) holds a valid value.
    valids: Optional[list] = None
    # per-call static parameters (parallel to ``calls``; None or a dict):
    # lead/lag: {"offset": int, "default": ex.Literal|None}; ntile:
    # {"n": int}. Static by design — XLA traces one program per plan, so
    # data-dependent offsets would force recompiles per row; the reference
    # accepts expressions but constant offsets are the only common case.
    params: Optional[list] = None
    # explicit frame (binder._normalize_frame): None = SQL default;
    # ("whole",) = whole partition; ("rows", lo, hi) = row offsets;
    # ("rangepos", lo, hi) = positional RANGE with only CURRENT ROW /
    # UNBOUNDED bounds (lo: "peer"|"start", hi: "peer"|"end");
    # ("rangeoff", lo, hi, key_nullable) = value-distance offsets over
    # the single numeric ORDER BY key (offsets pre-scaled for DECIMAL
    # keys; key_nullable marks the (validity, masked-value) lowering).
    # None means unbounded on that side. Applies to aggregates and
    # first_value/last_value; positional lead/lag and ranks ignore frames
    # (SQL semantics).
    frame: Optional[tuple] = None

    def children(self):
        return [self.child]

    def title(self):
        return f"Window [{', '.join(f for _, f, _ in self.calls)}]"


@dataclass
class PShare(PlanNode):
    """Materialize-once reference to a shared subplan — the ShareInputScan
    analog (nodeShareInputScan.c:31-45). Every reference to one CTE holds
    the SAME child object; pushdown, pruning, distribution and lowering all
    memoize on that object's identity, so the subplan computes once per
    statement (here: once per XLA program — XLA CSE would usually do this
    anyway, but the memoization guarantees it and keeps plan rewrites from
    mutating the shared subtree twice)."""

    child: PlanNode

    def children(self):
        return [self.child]

    def title(self):
        return "ShareInputScan"


@dataclass
class PConcat(PlanNode):
    """Append inputs (UNION ALL / the setop flow's Append, cdbsetop.c
    analog); output capacity = Σ child capacities."""

    inputs: list[PlanNode]

    def children(self):
        return list(self.inputs)

    def title(self):
        return f"Append x{len(self.inputs)}"


@dataclass
class PRuntimeFilter(PlanNode):
    """Semi-join pushdown before a probe-side motion (nodeRuntimeFilter.c
    analog): drop probe rows whose join key provably has no build partner
    BEFORE the shuffle. The build reference is the SAME object the join
    lowers (memoized, traced once). Two modes:

    - ``exact``: all-gather ONLY the packed u64 build keys — the cheapest
      complete collective — and sorted-membership-test the probes. No
      false positives, so the planner may shrink downstream motion
      buffers on its semi estimate. Preferred for small builds
      (planner.runtime_filter_threshold).
    - ``digest``: build sides too big to ship whole broadcast a COMPACT
      digest instead — per-key u64 min/max plus a fixed-size bloom
      bitmap (config.join_filter) in one tiny all_gather. Bloom false
      positives only let extra rows through; results stay bit-identical
      with the filter on or off, and a survivor overflow just promotes
      the motion one capacity rung (exec/executor.py grow_expansion)."""

    child: PlanNode                  # probe subtree (pre-motion)
    build: PlanNode                  # shared with the join's build input
    build_keys: list[ex.Expr] = dc_field(default_factory=list)
    probe_keys: list[ex.Expr] = dc_field(default_factory=list)
    pack_bits: int = 64              # see PJoin.pack_bits
    mode: str = "exact"              # 'exact' | 'digest'
    bloom_bits: int = 0              # digest bitmap size (power of two)
    bloom_k: int = 3                 # digest hash probes per key

    def children(self):
        return [self.child]          # build is walked under the join

    def title(self):
        if self.mode == "digest":
            return f"RuntimeFilter digest(bloom={self.bloom_bits})"
        return "RuntimeFilter"


@dataclass
class PMotion(PlanNode):
    """The Motion node (nodeMotion.c analog). kind:
    'gather'       — all segments → singleton (GATHER_MOTION)
    'redistribute' — hash on keys (HASH_MOTION → all_to_all)
    'broadcast'    — every row to every segment (BROADCAST → all_gather)
    """

    child: PlanNode
    kind: str
    hash_keys: list[ex.Expr] = dc_field(default_factory=list)
    # set by the distribution pass:
    out_capacity: int = 0   # receive-side array capacity
    bucket_cap: int = 0     # per-destination bucket capacity (redistribute)
    # compact selected rows to this capacity BEFORE the collective (top-N
    # pushdown: gather k·nseg rows instead of whole shards); 0 = off
    pre_compact: int = 0
    # two-level motion stamps (ISSUE 14; redistribute only, stamped when
    # the session's topology gate selects the hierarchical transport):
    # host_bucket_cap is the per-(source host -> destination host) block
    # capacity of the aggregated DCN exchange (a power-of-two rung on
    # the same ladder as bucket_cap; overflow promotes and retries), and
    # hier_hosts pins the host count the caps were derived for — a
    # program compiled at a different host grouping must not reuse them.
    host_bucket_cap: int = 0
    hier_hosts: int = 0
    # host-local combine (pre-aggregable motions): between the two hops,
    # each host merges its segments' agg PARTIALS so DCN carries one
    # partial per (host, group) instead of one per (segment, group).
    # combine_spec = (group key names, ((agg out name, merge func), ...))
    # — stamped ONLY when every merge func is order-insensitive-exact
    # (count/int-sum/min/max), so results stay bit-identical to flat.
    host_combine: bool = False
    combine_spec: Optional[tuple] = None

    def children(self):
        return [self.child]

    def title(self):
        return f"Motion {self.kind}"
