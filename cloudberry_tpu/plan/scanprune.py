"""Storage scan binding: cold-table scans → pruned micro-partition reads.

The planner move PAX makes with sparse filters (contrib/pax_storage
micro_partition_stats.cc) and the executor makes with PartitionSelector
(nodePartitionSelector.c): predicate ranges and equality literals reach the
storage layer BEFORE any column bytes move, so whole files are skipped by
manifest min/max (no IO) and footer bloom filters (footer-only IO), and only
the scan's referenced columns are ever read host-side — then only the
surviving rows transfer to the device.

Runs after predicate pushdown + column pruning (plan/prune.py), so filters
sit directly on scans and column_map is already narrowed.
"""

from __future__ import annotations

from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.types import DType

_RANGE_TYPES = (DType.INT32, DType.INT64, DType.DECIMAL, DType.DATE,
                DType.FLOAT64)


def apply_storage_scans(plan: N.PlanNode, session) -> None:
    """Bind every cold-table scan to its pruned partition list (single-
    segment execution; distributed placement materializes via
    Session.sharded_table instead)."""
    store = getattr(session.catalog, "store", None)
    if store is None or session.config.n_segments > 1:
        return
    _walk(plan, (), session, store)


def _walk(node: N.PlanNode, preds: tuple, session, store) -> None:
    if isinstance(node, N.PFilter):
        # WHERE predicates are where scalar subqueries usually live — their
        # plans' cold scans need binding too
        for sub in ex.walk(node.predicate):
            if isinstance(sub, ex.SubqueryScalar):
                _walk(sub.plan, (), session, store)
        _walk(node.child, preds + (node.predicate,), session, store)
        return
    if isinstance(node, N.PScan):
        if node.table_name == "$dual" or hasattr(node, "_store_parts"):
            return
        t = session.catalog.table(node.table_name)
        if t.cold:
            _bind_scan(node, preds, t, store)
        return
    for e in _exprs_of(node):
        for sub in ex.walk(e):
            if isinstance(sub, ex.SubqueryScalar):
                _walk(sub.plan, (), session, store)
    for c in node.children():
        _walk(c, (), session, store)
    if isinstance(node, N.PJoin):
        # children are bound — partition-selector elimination can now see
        # the probe scan's surviving partition list
        _dynamic_eliminate(node, session, store)


def _exprs_of(node: N.PlanNode):
    from cloudberry_tpu.plan.distribute import _node_exprs

    yield from _node_exprs(node)


def _bind_scan(node: N.PScan, preds: tuple, t, store) -> None:
    rev = {out: phys for phys, out in node.column_map.items()}
    ranges: dict[str, tuple] = {}
    eqs: dict[str, object] = {}
    for p in preds:
        for c in _conjuncts(p):
            got = _simple_cmp(c, rev)
            if got is None:
                continue
            col, op, val = got
            if op == "=":
                eqs[col] = val
                continue
            lo, hi = ranges.get(col, (None, None))
            if op in (">", ">="):
                # strict bounds tighten by 1 on integral literals (exact
                # partition elimination); floats stay conservative
                v = val + 1 if op == ">" and isinstance(val, int) else val
                lo = v if lo is None else max(lo, v)
            else:
                v = val - 1 if op == "<" and isinstance(val, int) else val
                hi = v if hi is None else min(hi, v)
            ranges[col] = (lo, hi)
    parts, report = store.select_partitions(t.name, ranges, eqs)
    rows = sum(p["num_rows"] - len(p["deleted"]) for p in parts)
    node._store_parts = parts
    node._prune_report = report
    node._input_key = f"{node.table_name}#{id(node)}"
    node.capacity = max(rows, 1)
    node.num_rows = rows


def _dynamic_eliminate(join: N.PJoin, session, store) -> None:
    """Join-driven partition elimination (the PartitionSelector /
    Dynamic*Scan analog, nodePartitionSelector.c): for an inner/semi join
    probing a PARTITION BY table on its partition column, run the (small)
    build side host-side FIRST, collect its distinct join-key values, and
    drop probe partitions no value can touch — manifest min/max, then
    footer blooms — before any fact-column IO.

    Only join kinds that discard unmatched probe rows are eligible (a LEFT
    join preserves them, so eliminating probe partitions would drop rows —
    the same restriction the reference's selector has). In this engine's
    plan-time-feeds-the-program model, "executor runtime" for the selector
    is plan time: the build subtree compiles and runs as its own small
    program, exactly like the reference runs the selector subtree before
    the dynamic scan."""
    limit = session.config.storage.partition_selector_max_build
    if limit <= 0 or join.kind not in ("inner", "semi"):
        return
    # probe side: PFilter chains preserve field names; anything else stops
    scan = join.probe
    while isinstance(scan, N.PFilter):
        scan = scan.child
    if not isinstance(scan, N.PScan) or not hasattr(scan, "_store_parts"):
        return
    t = session.catalog.table(scan.table_name)
    spec = t.partition_spec
    if spec is None:
        return
    out_name = scan.column_map.get(spec[1])
    if out_name is None:
        return
    key_i = next((i for i, k in enumerate(join.probe_keys)
                  if isinstance(k, ex.ColumnRef) and k.name == out_name),
                 None)
    if key_i is None:
        return
    from cloudberry_tpu.plan.binder import _plan_capacity

    if _plan_capacity(join.build) > limit:
        return
    values = _eval_build_keys(join.build, join.build_keys[key_i], session)
    if values is None:
        return
    kept, n_dropped = _filter_parts_by_values(
        store, t.name, scan._store_parts, spec[1], values)
    if n_dropped == 0:
        return
    scan._store_parts = kept
    scan._prune_report["skipped_dynamic"] = \
        scan._prune_report.get("skipped_dynamic", 0) + n_dropped
    rows = sum(p["num_rows"] - len(p["deleted"]) for p in kept)
    scan.capacity = max(rows, 1)
    scan.num_rows = rows


def _eval_build_keys(build: N.PlanNode, key_expr: ex.Expr, session):
    """Distinct build-side join-key values, by compiling and running the
    build subtree as its own program (the selector execution)."""
    import numpy as np

    from cloudberry_tpu.exec import executor as X

    proj = N.PProject(build, [("$pskey", key_expr)])
    proj.fields = [N.PlanField("$pskey", key_expr.dtype, None)]
    try:
        exe = X.compile_plan(proj, session)
        cols, sel, checks = exe.fn(X.prepare_inputs(exe, session))
        X.raise_checks(checks)
        vals = np.asarray(cols["$pskey"])[np.asarray(sel)]
    except Exception:
        return None  # elimination is an optimization — never fail the query
    return np.unique(vals)


def _filter_parts_by_values(store, table: str, parts, col: str, values):
    """Partitions a value set can touch: manifest min/max first (no IO),
    then footer bloom membership for any surviving value (shared primitive
    TableStore.bloom_may_match — one footer read per partition)."""
    kept, dropped = [], 0
    for part in parts:
        st = part.get("stats", {}).get(col)
        cand = values
        if st is not None:
            cand = values[(values >= st[0]) & (values <= st[1])]
            if len(cand) == 0:
                dropped += 1
                continue
        # bloom checks read the footer — bound the per-partition work
        if len(cand) <= 64 and not store.bloom_may_match(
                table, part, {col: cand.tolist()}):
            dropped += 1
            continue
        kept.append(part)
    return kept, dropped


def _conjuncts(e: ex.Expr):
    if isinstance(e, ex.BinOp) and e.op == "and":
        yield from _conjuncts(e.left)
        yield from _conjuncts(e.right)
    else:
        yield e


def _simple_cmp(e: ex.Expr, rev: dict):
    """column <op> literal over a range-comparable physical type, in either
    orientation; returns (phys_col, op, value) or None."""
    if not isinstance(e, ex.BinOp) or e.op not in ("=", "<", "<=", ">", ">="):
        return None
    l, r = e.left, e.right
    op = e.op
    if isinstance(r, ex.ColumnRef) and isinstance(l, ex.Literal):
        l, r = r, l
        op = {"=": "=", "<": ">", "<=": ">=", ">": "<", ">=": "<="}[op]
    if not (isinstance(l, ex.ColumnRef) and isinstance(r, ex.Literal)):
        return None
    phys = rev.get(l.name)
    if phys is None or l.dtype.base not in _RANGE_TYPES:
        return None
    if not isinstance(r.value, (int, float)):
        return None
    return phys, op, r.value
