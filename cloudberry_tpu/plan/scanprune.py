"""Storage scan binding: cold-table scans → pruned micro-partition reads.

The planner move PAX makes with sparse filters (contrib/pax_storage
micro_partition_stats.cc) and the executor makes with PartitionSelector
(nodePartitionSelector.c): predicate ranges and equality literals reach the
storage layer BEFORE any column bytes move, so whole files are skipped by
manifest min/max (no IO) and footer bloom filters (footer-only IO), and only
the scan's referenced columns are ever read host-side — then only the
surviving rows transfer to the device.

Runs after predicate pushdown + column pruning (plan/prune.py), so filters
sit directly on scans and column_map is already narrowed.
"""

from __future__ import annotations

from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.types import DType

_RANGE_TYPES = (DType.INT32, DType.INT64, DType.DECIMAL, DType.DATE,
                DType.FLOAT64)


def apply_storage_scans(plan: N.PlanNode, session) -> None:
    """Bind every cold-table scan to its pruned partition list (single-
    segment execution; distributed placement materializes via
    Session.sharded_table instead)."""
    store = getattr(session.catalog, "store", None)
    if store is None or session.config.n_segments > 1:
        return
    _walk(plan, (), session, store)


def _walk(node: N.PlanNode, preds: tuple, session, store) -> None:
    if isinstance(node, N.PFilter):
        # WHERE predicates are where scalar subqueries usually live — their
        # plans' cold scans need binding too
        for sub in ex.walk(node.predicate):
            if isinstance(sub, ex.SubqueryScalar):
                _walk(sub.plan, (), session, store)
        _walk(node.child, preds + (node.predicate,), session, store)
        return
    if isinstance(node, N.PScan):
        if node.table_name == "$dual" or hasattr(node, "_store_parts"):
            return
        t = session.catalog.table(node.table_name)
        if t.cold:
            _bind_scan(node, preds, t, store)
        return
    for e in _exprs_of(node):
        for sub in ex.walk(e):
            if isinstance(sub, ex.SubqueryScalar):
                _walk(sub.plan, (), session, store)
    for c in node.children():
        _walk(c, (), session, store)


def _exprs_of(node: N.PlanNode):
    from cloudberry_tpu.plan.distribute import _node_exprs

    yield from _node_exprs(node)


def _bind_scan(node: N.PScan, preds: tuple, t, store) -> None:
    rev = {out: phys for phys, out in node.column_map.items()}
    ranges: dict[str, tuple] = {}
    eqs: dict[str, object] = {}
    for p in preds:
        for c in _conjuncts(p):
            got = _simple_cmp(c, rev)
            if got is None:
                continue
            col, op, val = got
            if op == "=":
                eqs[col] = val
                continue
            lo, hi = ranges.get(col, (None, None))
            if op in (">", ">="):
                lo = val if lo is None else max(lo, val)
            else:  # < / <=  (strictness ignored — bounds stay conservative)
                hi = val if hi is None else min(hi, val)
            ranges[col] = (lo, hi)
    parts, report = store.select_partitions(t.name, ranges, eqs)
    rows = sum(p["num_rows"] - len(p["deleted"]) for p in parts)
    node._store_parts = parts
    node._prune_report = report
    node._input_key = f"{node.table_name}#{id(node)}"
    node.capacity = max(rows, 1)
    node.num_rows = rows


def _conjuncts(e: ex.Expr):
    if isinstance(e, ex.BinOp) and e.op == "and":
        yield from _conjuncts(e.left)
        yield from _conjuncts(e.right)
    else:
        yield e


def _simple_cmp(e: ex.Expr, rev: dict):
    """column <op> literal over a range-comparable physical type, in either
    orientation; returns (phys_col, op, value) or None."""
    if not isinstance(e, ex.BinOp) or e.op not in ("=", "<", "<=", ">", ">="):
        return None
    l, r = e.left, e.right
    op = e.op
    if isinstance(r, ex.ColumnRef) and isinstance(l, ex.Literal):
        l, r = r, l
        op = {"=": "=", "<": ">", "<=": ">=", ">": "<", ">=": "<="}[op]
    if not (isinstance(l, ex.ColumnRef) and isinstance(r, ex.Literal)):
        return None
    phys = rev.get(l.name)
    if phys is None or l.dtype.base not in _RANGE_TYPES:
        return None
    if not isinstance(r.value, (int, float)):
        return None
    return phys, op, r.value
