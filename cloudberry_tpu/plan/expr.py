"""Bound scalar-expression IR.

The binder turns parsed SQL expressions into this typed IR; the executor
compiles it to jax.numpy ops (exec/expr_compile.py). This is the analog of
PG's ExprState evaluation (src/backend/executor/execExpr.c) — except the
"interpreter" is XLA, so an expression evaluates over a whole column batch in
one fused kernel rather than per tuple.

String predicates never touch device strings: the binder pre-computes a
boolean lookup table over the column's host dictionary and emits
``DictLookup`` (gather by code). Ordering comparisons on strings gather a
host-computed rank table (see columnar/dictionary.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from cloudberry_tpu.types import BOOL, DType, SqlType


class Expr:
    dtype: SqlType

    def children(self) -> tuple["Expr", ...]:
        return ()


@dataclass(frozen=True)
class ColumnRef(Expr):
    name: str
    dtype: SqlType


@dataclass(frozen=True)
class Literal(Expr):
    value: Any
    dtype: SqlType


@dataclass(frozen=True)
class Param(Expr):
    """Runtime-bound scalar literal — the PARAM_EXTERN analog.

    A generic plan (sched/paramplan.py) hoists constant literals out of
    filter/project expressions into numbered parameter slots; the compiled
    program reads slot values from a ``$prm<slot>`` entry that
    ``prepare_inputs``-time binding injects next to the table columns. Same-
    shape statements then share ONE compiled executable with literals fed
    as device inputs instead of baked constants.

    ``value`` keeps the build-time literal: a program traced WITHOUT a
    binding input (e.g. the expansion-growth retry recompiling a rewritten
    plan on the non-generic path) bakes it as a constant — semantically the
    original statement — and re-analysis of a rewritten plan recovers its
    binding vector from it."""
    slot: int
    dtype: SqlType
    value: Any = None

    @property
    def input_name(self) -> str:
        return f"$prm{self.slot}"


@dataclass(frozen=True)
class BinOp(Expr):
    """op ∈ {+,-,*,/,=,<>,<,<=,>,>=,and,or}"""
    op: str
    left: Expr
    right: Expr
    dtype: SqlType

    def children(self):
        return (self.left, self.right)


@dataclass(frozen=True)
class UnaryOp(Expr):
    """op ∈ {not,-}"""
    op: str
    operand: Expr
    dtype: SqlType

    def children(self):
        return (self.operand,)


@dataclass(frozen=True)
class Cast(Expr):
    operand: Expr
    dtype: SqlType

    def children(self):
        return (self.operand,)


@dataclass(frozen=True)
class Func(Expr):
    """Scalar functions: extract_year/extract_month, abs, substring-class
    functions are rewritten to DictLookup by the binder."""
    name: str
    args: tuple[Expr, ...]
    dtype: SqlType

    def children(self):
        return self.args


@dataclass(frozen=True)
class CaseWhen(Expr):
    whens: tuple[tuple[Expr, Expr], ...]
    otherwise: Optional[Expr]
    dtype: SqlType

    def children(self):
        out = []
        for c, v in self.whens:
            out += [c, v]
        if self.otherwise is not None:
            out.append(self.otherwise)
        return tuple(out)


@dataclass(frozen=True, eq=False)
class DictLookup(Expr):
    """Gather host-computed per-code table by a string column's codes.

    table dtype bool → predicate (LIKE/IN/=); int32 → rank/ordering.
    """
    column: Expr
    table: np.ndarray = field(hash=False, compare=False)
    dtype: SqlType = BOOL

    def children(self):
        return (self.column,)


@dataclass(eq=False)
class SubqueryScalar(Expr):
    """Uncorrelated scalar subquery: a full plan whose single-row, single-
    column result is broadcast into the enclosing expression (the InitPlan
    analog). The executor lowers ``plan`` inside the same XLA program;
    the distribution pass walks into it.

    mode "value" broadcasts the single row's value (>1 rows is a runtime
    error; 0 rows yields an arbitrary value that the binder masks NULL
    via a companion mode="exists" validity term — SQL: a scalar subquery
    over zero rows is NULL). mode "exists" broadcasts a bool: did the
    subplan select ≥1 row."""

    plan: object  # N.PlanNode (untyped to avoid the import cycle)
    dtype: "SqlType" = None  # type: ignore[assignment]
    mode: str = "value"


@dataclass(frozen=True)
class IsValid(Expr):
    """True where every named validity column is True (a column is valid /
    IS NOT NULL where the conjunction of its mask columns holds; a column
    nullable through several outer joins carries one mask name per join)."""
    mask_names: tuple[str, ...]
    negate: bool = False
    dtype: SqlType = BOOL

    def __post_init__(self):
        if isinstance(self.mask_names, str):  # tolerate single-name callers
            object.__setattr__(self, "mask_names", (self.mask_names,))


@dataclass(frozen=True)
class AggCall:
    """Aggregate call — lives in Agg plan nodes, not inside scalar exprs.

    func ∈ {sum, count, count_star, min, max, avg, count_distinct}.
    """
    func: str
    arg: Optional[Expr]
    distinct: bool = False
    filter: Optional[Expr] = None

    @property
    def dtype(self) -> SqlType:
        from cloudberry_tpu.types import FLOAT64, INT64

        if self.func in ("count", "count_star", "count_distinct"):
            return INT64
        if self.func == "avg":
            return FLOAT64
        assert self.arg is not None
        return self.arg.dtype


def rewrite(e: Expr, fn) -> Expr:
    """Top-down structural rewrite: ``fn(node)`` returns a replacement or
    None to recurse. THE one place that knows how to rebuild each node —
    substitution passes must use this instead of hand-rolled per-class
    copies (which silently skip newly added node types)."""
    out = fn(e)
    if out is not None:
        return out
    if isinstance(e, BinOp):
        return BinOp(e.op, rewrite(e.left, fn), rewrite(e.right, fn), e.dtype)
    if isinstance(e, UnaryOp):
        return UnaryOp(e.op, rewrite(e.operand, fn), e.dtype)
    if isinstance(e, Cast):
        return Cast(rewrite(e.operand, fn), e.dtype)
    if isinstance(e, Func):
        return Func(e.name, tuple(rewrite(a, fn) for a in e.args), e.dtype)
    if isinstance(e, CaseWhen):
        return CaseWhen(
            tuple((rewrite(c, fn), rewrite(v, fn)) for c, v in e.whens),
            rewrite(e.otherwise, fn) if e.otherwise is not None else None,
            e.dtype)
    if isinstance(e, DictLookup):
        out = DictLookup(rewrite(e.column, fn), e.table, e.dtype)
        d = getattr(e, "_out_dict", None)
        if d is not None:
            object.__setattr__(out, "_out_dict", d)
        return out
    # leaves (ColumnRef, Literal, Param, IsValid, SubqueryScalar) pass
    return e


def walk(e: Expr):
    yield e
    for c in e.children():
        yield from walk(c)


def columns_used(e: Expr) -> set[str]:
    out = set()
    for node in walk(e):
        if isinstance(node, ColumnRef):
            out.add(node.name)
        if isinstance(node, IsValid):
            out.update(node.mask_names)
    return out
