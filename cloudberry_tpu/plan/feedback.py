"""Feedback-driven re-optimization — learned sketches from live telemetry.

Every distributed statement already measures exactly what the planner
guesses at: the motion programs psum per-destination row-demand vectors,
pmax the required bucket rung, and count runtime-filter survivors
(exec/dist_executor.py record_motion_stats). Until now that telemetry
died with the statement, so the second execution of a mis-estimated
query was exactly as bad as the first. This module closes the loop — the
adaptive-scheduling story of "Accelerating Presto with GPUs" and the
data-movement-first costing of "Theseus" (PAPERS.md), mapped onto the
QD/QE split: the dispatcher learns from what the gangs actually shipped.

After every statement, ``fold_plan`` folds the stats pinned on the plan's
motion nodes into per-(table, key-set) ``FeedbackSketch``es held by a
``FeedbackStore`` anchored on the shared cache tier's scope
(sched/sharedcache.py): sessions over one store root share sketches the
way they share compiled programs. Consumers:

- ``plan/distribute.py`` seeds capacity rungs at the observed demand
  rung (exact skew bounds stay the authoritative CEILING — feedback only
  ever replaces the estimate-path seed, and overflow still promotes up
  the ladder, so a stale sketch costs a retry, never a wrong answer);
- ``plan/memo.py``'s hot-fraction read and ``plan/cost.py``'s group-NDV
  estimate consult sketches through ``catalog._feedback``, re-ranking
  join order / motion choice when an observed skew alarm contradicts
  the histogram;
- ``plan/distribute.py digest_filter_frac`` prices probe redistributes
  at the OBSERVED survivor fraction of the runtime filter;
- ``exec/tiled_dist.py`` replans MID-STATEMENT through the PR-6
  checkpoint store when per-tile motion stats cross the skew alarm.

Invalidation is by construction, not by protocol: every sketch carries
the same content-stable tokens the shared cache tier keys on —
``table_key`` (any DML commit or ANALYZE bumps it), the topology epoch
id, and a content-stable config token (segment count + capacity factor
+ filter knobs). A lookup whose tokens no longer match drops the entry.
Store-backed scopes persist sketches to ``_FEEDBACK.json`` beside the
manifests ANALYZE stats live in, so fresh sessions inherit them.

Deliberately NOT learned: sketches key on (table, key-set), not on the
predicate — a filtered query's observations generalize to every query
shuffling the same columns, and the rung ladder absorbs the
mis-generalization (overflow promotes; padding is bounded by the
ceiling). Exact bucket bounds are never replaced, host-pair rungs
derive from the seeded segment rung as before, single-segment plans
have no motions to learn from, and generic (parameterized) plans keep
their compiled shape until a fold materially changes a sketch (the
feedback generation joins the statement-cache guard, not the
generic-plan signature).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field, replace
from typing import Optional

from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.utils.faultinject import fault_point

# sketches retained per store (LRU): a serving workload's hot key-sets
# stay; a scan of one-off ad-hoc shapes cannot grow the store unbounded
_MAX_SKETCHES = 512

# relative change in a folded maximum that counts as MATERIAL — material
# folds bump the store generation, which invalidates cached statements
# planned under the old sketch; steady-state re-executions of the same
# statement reproduce their stats exactly and must NOT churn the cache
_MATERIAL_DELTA = 0.10


@dataclass(frozen=True)
class FeedbackSketch:
    """One (table, key-set)'s observed motion behavior."""

    kind: str                 # "redist" | "jf"
    src: tuple                # ((table, phys_col), ...) sorted
    nseg: int                 # mesh the observation was made on
    demand_max: int = 0       # max observed per-destination bucket demand
    seg_rows_max: int = 0     # max rows any destination received
    rows_total: int = 0       # total rows shipped (post-filter, observed)
    skew_ratio: float = 0.0   # max/mean destination rows
    alarmed: bool = False     # ratio crossed config.obs.skew_ratio
    ndv_est: int = 0          # distinct-group upper bound (merge motions)
    jf_frac: float = 0.0      # runtime-filter survivor fraction ("jf")
    statements: int = 0       # observations folded in
    partial: bool = False     # latest fold came mid-statement (alarm path)

    def hot_frac(self) -> float:
        """Observed hottest-destination row fraction — the learned
        counterpart of memo._hot_frac's histogram estimate."""
        if self.rows_total <= 0:
            return 0.0
        return min(self.seg_rows_max / self.rows_total, 1.0)


def config_token(cfg) -> tuple:
    """Content-stable config component of a sketch's validity: the knobs
    that change what a motion's demand/skew observation MEANS. Unlike
    the shared cache tier's config OBJECT identity, this survives
    process restarts (persisted sketches must be inheritable) and
    ignores irrelevant swaps; any swap that changes these invalidates."""
    return (int(cfg.n_segments),
            round(float(cfg.interconnect.capacity_factor), 6),
            bool(cfg.join_filter.enabled))


def _tokens(session, src) -> Optional[tuple]:
    """Current validity tokens for a source set: per-table content
    tokens + topology epoch + config token. None when any table is
    unknown (sketch can neither fold nor serve)."""
    from cloudberry_tpu.sched import sharedcache as SC

    try:
        tabs = tuple(SC.table_key(session, t)
                     for t in sorted({t for t, _ in src}))
    except KeyError:
        return None
    return (tabs, SC.topology_token(session),
            config_token(session.config))


def resolve_sources(child: N.PlanNode, keys) -> Optional[tuple]:
    """Trace motion hash keys to ((table, phys_col), ...) through the
    child subtree — the sketch's content identity. None when any key
    crosses a computation (those shuffles are deliberately unlearned)."""
    from cloudberry_tpu.plan.cost import _col_source

    out = []
    for k in keys:
        if not isinstance(k, ex.ColumnRef):
            return None
        src = _col_source(child, k.name)
        if src is None:
            return None
        out.append(src)
    if not out:
        return None
    return tuple(sorted(set(out)))


class FeedbackStore:
    """Engine-wide learned-stats store for one cache scope. The lock is
    an innermost leaf (witness rank 4): token derivation, logging, and
    persistence all happen OUTSIDE it — planning paths reach lookups
    while holding cache-tier locks."""

    def __init__(self, path: Optional[str] = None):
        self._lock = threading.Lock()
        self._io_lock = threading.Lock()
        # key -> (tokens, FeedbackSketch); key = (kind, src, nseg)
        self._sketches: dict = {}
        self.gen = 0              # bumped on MATERIAL folds (cache guard)
        self.folds = 0
        self.path = path
        if path is not None:
            self._load()

    # ------------------------------------------------------------- folding

    def fold(self, session, kind: str, src: tuple, nseg: int,
             partial: bool = False, **obs) -> bool:
        """Merge one observation; True when the fold was material (new
        sketch, or a folded maximum moved past the material delta)."""
        toks = _tokens(session, src)
        if toks is None:
            return False
        key = (kind, src, nseg)
        fresh = FeedbackSketch(kind=kind, src=src, nseg=nseg,
                               statements=1, partial=partial, **obs)
        with self._lock:
            ent = self._sketches.pop(key, None)
            if ent is not None and ent[0] == toks:
                merged = _merge(ent[1], fresh, partial)
                material = _material(ent[1], merged)
            else:
                merged = fresh      # stale tokens: start over
                material = True
            self._sketches[key] = (toks, merged)
            while len(self._sketches) > _MAX_SKETCHES:
                self._sketches.pop(next(iter(self._sketches)))
            if material:
                self.gen += 1
            self.folds += 1
        return material

    # ------------------------------------------------------------- lookups

    def lookup(self, session, kind: str, src: tuple,
               nseg: Optional[int] = None) -> Optional[FeedbackSketch]:
        """The live sketch for (kind, src) at the session's current
        segment count — None (and the entry dropped) when any validity
        token moved: DML version bumps, ANALYZE, topology epoch flips,
        and relevant config swaps invalidate by construction."""
        if nseg is None:
            nseg = session.config.n_segments
        key = (kind, src, nseg)
        with self._lock:
            ent = self._sketches.get(key)
        if ent is None:
            return None
        toks = _tokens(session, src)
        if toks != ent[0]:
            with self._lock:
                cur = self._sketches.get(key)
                if cur is ent:      # racing folds keep their fresh entry
                    del self._sketches[key]
            return None
        return ent[1]

    def snapshot(self) -> dict:
        with self._lock:
            n = len(self._sketches)
            alarmed = sum(1 for _, s in self._sketches.values()
                          if s.alarmed)
            return {"sketches": n, "alarmed": alarmed, "gen": self.gen,
                    "folds": self.folds}

    # --------------------------------------------------------- persistence

    def persist(self) -> None:
        """Write-through to ``_FEEDBACK.json`` (atomic replace via the
        iofault primitives — fsynced temp + rename, so a crash never
        leaves torn JSON). Sketch loss is never a correctness problem —
        the loop just re-learns — so IO failures are swallowed here,
        but they are COUNTED (storage_io_errors), not silent."""
        if self.path is None:
            return
        from cloudberry_tpu.lifecycle import StorageIOError
        from cloudberry_tpu.storage import iofault

        with self._lock:
            ents = [{"key": [k[0], [list(p) for p in k[1]], k[2]],
                     "tokens": [list(map(list, t[0])), t[1], list(t[2])],
                     "sketch": _sketch_json(s)}
                    for k, (t, s) in self._sketches.items()]
            body = {"version": 1, "gen": self.gen, "entries": ents}
        try:
            with self._io_lock:
                fault_point("io_feedback_write")
                iofault.atomic_json(self.path, body)
        except StorageIOError:
            pass  # counted by the shim; the learner re-folds

    def _load(self) -> None:
        try:
            with open(self.path) as f:
                body = json.load(f)
        except (OSError, ValueError):
            return
        for ent in body.get("entries", []):
            try:
                kind, src, nseg = ent["key"]
                src = tuple(tuple(p) for p in src)
                toks = ent["tokens"]
                toks = (tuple(tuple(t) for t in toks[0]), toks[1],
                        tuple(toks[2]))
                sk = FeedbackSketch(kind=kind, src=src, nseg=int(nseg),
                                    **ent["sketch"])
                self._sketches[(kind, src, int(nseg))] = (toks, sk)
            except (KeyError, TypeError, ValueError):
                continue        # one bad entry must not poison the rest
        self.gen = int(body.get("gen", 0))


def _sketch_json(s: FeedbackSketch) -> dict:
    return {"demand_max": s.demand_max, "seg_rows_max": s.seg_rows_max,
            "rows_total": s.rows_total, "skew_ratio": s.skew_ratio,
            "alarmed": s.alarmed, "ndv_est": s.ndv_est,
            "jf_frac": s.jf_frac, "statements": s.statements,
            "partial": s.partial}


def _merge(old: FeedbackSketch, new: FeedbackSketch,
           partial: bool) -> FeedbackSketch:
    """Fold maxima (conservative for rung seeding: the largest demand
    ever observed under these tokens is the bound that avoids retries);
    survivor fractions fold toward the LEAST selective observation for
    the same reason. A partial (mid-statement) fold never shrinks what a
    completed statement established."""
    return replace(
        old,
        demand_max=max(old.demand_max, new.demand_max),
        seg_rows_max=max(old.seg_rows_max, new.seg_rows_max),
        rows_total=max(old.rows_total, new.rows_total),
        skew_ratio=max(old.skew_ratio, new.skew_ratio),
        alarmed=old.alarmed or new.alarmed,
        ndv_est=max(old.ndv_est, new.ndv_est),
        jf_frac=max(old.jf_frac, new.jf_frac),
        statements=old.statements + 1,
        partial=partial)


def _material(old: FeedbackSketch, new: FeedbackSketch) -> bool:
    def moved(a, b):
        return abs(b - a) > _MATERIAL_DELTA * max(abs(a), 1.0)

    return (old.alarmed != new.alarmed
            or moved(old.demand_max, new.demand_max)
            or moved(old.rows_total, new.rows_total)
            or moved(old.jf_frac * 1000, new.jf_frac * 1000)
            or moved(old.ndv_est, new.ndv_est))


# ----------------------------------------------------------- scope anchor


_create_lock = threading.Lock()


def store_for(session) -> Optional[FeedbackStore]:
    """The session's feedback store (scope-anchored, created lazily),
    or None when the subsystem is off. Store-backed scopes with
    ``config.feedback.persist`` load/save ``_FEEDBACK.json`` under the
    storage root — the same place ANALYZE stats persist."""
    cfg = getattr(session.config, "feedback", None)
    if cfg is None or not cfg.enabled:
        return None
    from cloudberry_tpu.sched.sharedcache import scope_for

    scope = scope_for(session)
    store = getattr(scope, "feedback", None)
    if store is None:
        with _create_lock:
            store = getattr(scope, "feedback", None)
            if store is None:
                path = None
                if scope.kind == "store" and cfg.persist:
                    path = os.path.join(
                        str(session.config.storage.root),
                        "_FEEDBACK.json")
                store = FeedbackStore(path)
                scope.feedback = store
    return store


class FeedbackView:
    """Session-bound read surface stamped on ``catalog._feedback`` so
    cost/memo code that only sees the catalog can consult sketches (the
    catalog hook). Holds the session weakly — the catalog lives inside
    the session."""

    def __init__(self, store: FeedbackStore, session):
        import weakref

        self.store = store
        self._session = weakref.ref(session)

    def _lookup(self, kind: str, src) -> Optional[FeedbackSketch]:
        session = self._session()
        if session is None or src is None:
            return None
        return self.store.lookup(session, kind, src)

    def hot_frac(self, plan: N.PlanNode, keys) -> Optional[float]:
        """Observed hottest-destination fraction for a shuffle of
        ``keys`` out of ``plan`` — only when the observation ALARMED
        (crossed config.obs.skew_ratio): sub-alarm skew leaves the
        histogram estimate in charge, so plans only re-rank when the
        telemetry contradicts the stats hard enough to matter."""
        sk = self._lookup("redist", resolve_sources(plan, keys))
        if sk is None or not sk.alarmed:
            return None
        return sk.hot_frac()

    def group_ndv(self, agg: N.PAgg) -> Optional[tuple]:
        """(lo, hi) bounds on the distinct-group count of a grouped
        aggregation, from an observed merge motion: every group ships at
        least one and at most nseg partial rows, so the observed partial
        total brackets the true NDV."""
        keys = [e for _, e in agg.group_keys]
        sk = self._lookup("redist", resolve_sources(agg.child, keys))
        if sk is None or sk.ndv_est <= 0:
            return None
        lo = max(sk.ndv_est // max(sk.nseg, 1), 1)
        return (lo, sk.ndv_est)

    def jf_frac(self, node) -> Optional[float]:
        """Observed runtime-filter survivor fraction for a join's probe
        keys — the learned replacement for the bloom-model estimate."""
        sk = self._lookup("jf", resolve_sources(node.probe,
                                                node.probe_keys))
        if sk is None or sk.jf_frac <= 0:
            return None
        return min(sk.jf_frac, 1.0)


# ------------------------------------------------------------ the fold hook


def fold_plan(session, plan: N.PlanNode, partial: bool = False) -> None:
    """Fold every motion/filter observation pinned on ``plan`` (by
    record_motion_stats) into the session's feedback store — called
    after raise_checks passed, at every execution surface. Best-effort
    by contract: learning must never fail a healthy statement."""
    store = store_for(session)
    if store is None:
        return
    if fault_point("feedback_fold"):
        return      # chaos arm: suppress learning
    try:
        material = _fold_plan(session, store, plan, partial)
    except Exception:   # noqa: BLE001 — telemetry, never load-bearing
        return
    log = getattr(session, "stmt_log", None)
    if log is not None:
        log.bump("feedback_folds")
        if material:
            log.bump("feedback_gen_bumps")
    if material:
        store.persist()


def _fold_plan(session, store: FeedbackStore, plan: N.PlanNode,
               partial: bool) -> bool:
    from cloudberry_tpu.exec.executor import all_nodes

    thr = float(session.config.obs.skew_ratio)
    nseg = session.config.n_segments
    material = False
    for node in all_nodes(plan):
        if isinstance(node, N.PMotion) and node.kind == "redistribute":
            rows = getattr(node, "_seg_rows", None)
            if rows is None or rows.shape[0] == 0:
                continue
            src = resolve_sources(node.child, node.hash_keys)
            if src is None:
                continue
            total = int(rows.sum())
            if total <= 0:
                continue
            mx = int(rows.max())
            ratio = mx / (total / rows.shape[0])
            demand = int(getattr(node, "_observed_bucket", 0) or mx)
            below = node.child
            while isinstance(below, (N.PFilter, N.PProject,
                                     N.PRuntimeFilter)):
                below = below.child
            ndv = total if (isinstance(below, N.PAgg)
                            and below.mode == "partial") else 0
            material |= store.fold(
                session, "redist", src, nseg, partial=partial,
                demand_max=demand, seg_rows_max=mx, rows_total=total,
                skew_ratio=float(ratio),
                alarmed=bool(thr > 0 and ratio >= thr), ndv_est=ndv)
        elif isinstance(node, N.PRuntimeFilter):
            pre = getattr(node, "_jf_pre", None)
            post = getattr(node, "_jf_post", None)
            if not pre or post is None:
                continue
            src = resolve_sources(node.child, node.probe_keys)
            if src is None:
                continue
            material |= store.fold(
                session, "jf", src, nseg, partial=partial,
                jf_frac=max(min(post / pre, 1.0), 1e-6))
    return material


def feedback_gen(session) -> int:
    """The store generation — a statement-cache guard component: a
    MATERIAL fold must replan cached statements (that is the whole
    point), while steady-state identical folds must not churn them."""
    store = store_for(session)
    return store.gen if store is not None else 0
