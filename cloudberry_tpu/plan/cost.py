"""Cardinality estimation — the libgpdbcost / clauselist_selectivity analog.

Estimates row counts for bound plan subtrees from table statistics (row
counts, NDV, min/max — catalog.TableStats, filled lazily or by ANALYZE).
Drives the DP join-order search (plan/binder.py) and the distribution
pass's broadcast-vs-redistribute choice (plan/distribute.py) — the two
decisions ORCA spends its cost model on for TPC-H-class plans.

Estimates memoize on the node (attr ``_est_rows``); plans are per-statement
so the memo's lifetime is right by construction.
"""

from __future__ import annotations

from typing import Optional

from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N

DEFAULT_EQ_SEL = 0.1
DEFAULT_RANGE_SEL = 1.0 / 3.0
DEFAULT_SEL = 0.25


def estimate_rows(node: N.PlanNode, catalog) -> float:
    cached = getattr(node, "_est_rows", None)
    if cached is not None:
        return cached
    est = max(_estimate(node, catalog), 0.0)
    node._est_rows = est
    return est


def _estimate(node: N.PlanNode, catalog) -> float:
    if isinstance(node, N.PScan):
        if node.table_name == "$dual":
            return 1.0
        return float(node.num_rows if node.num_rows >= 0 else node.capacity)
    if isinstance(node, N.PFilter):
        return estimate_rows(node.child, catalog) * \
            selectivity(node.predicate, node.child, catalog)
    if isinstance(node, (N.PProject, N.PSort, N.PWindow, N.PShare,
                         N.PMotion)):
        return estimate_rows(node.children()[0], catalog)
    if isinstance(node, N.PLimit):
        return min(estimate_rows(node.child, catalog), float(node.limit))
    if isinstance(node, N.PConcat):
        return sum(estimate_rows(c, catalog) for c in node.inputs)
    if isinstance(node, N.PAgg):
        child = estimate_rows(node.child, catalog)
        if not node.group_keys:
            return 1.0
        prod = 1.0
        for _, e in node.group_keys:
            nd = _expr_ndv(node.child, e, catalog)
            prod *= nd if nd is not None else max(child ** 0.5, 1.0)
            if prod >= child:
                prod = child
                break
        est = min(prod, child)
        # feedback (plan/feedback.py): a prior merge motion over these
        # group keys COUNTED the shipped partials, bracketing the true
        # distinct-group count (every group ships >= 1 and <= nseg
        # partial rows) — clamp the static product into the observed
        # bracket. Refines both failure modes: an over-estimate shrinks
        # the merge rung (fewer padded wire bytes), an under-estimate
        # grows g_cap before the overflow-retry would have.
        fb = getattr(catalog, "_feedback", None)
        if fb is not None:
            bounds = fb.group_ndv(node)
            if bounds is not None:
                lo, hi = bounds
                clamped = min(max(est, float(lo)), float(hi), child)
                if clamped != est:
                    node._feedback_ndv = (lo, hi)
                    est = clamped
        return est
    if isinstance(node, N.PJoin):
        return _estimate_join(node, catalog)
    return 1.0


def _estimate_join(node: N.PJoin, catalog) -> float:
    b = estimate_rows(node.build, catalog)
    p = estimate_rows(node.probe, catalog)
    nd_b = _keys_ndv(node.build, node.build_keys, catalog)
    nd_p = _keys_ndv(node.probe, node.probe_keys, catalog)
    # |B ⋈ P| = |B||P| / max(ndv_B, ndv_P)  (System R equi-join formula)
    denom = max(nd_b or 1.0, nd_p or 1.0,
                1.0 if (nd_b or nd_p) else max(b, p, 1.0))
    inner = b * p / max(denom, 1.0)
    if node.kind == "inner":
        return inner
    if node.kind == "left":
        return max(inner, p)
    if node.kind == "full":
        return max(inner, p) + max(b - inner, 0.0)
    if node.kind == "semi":
        # fraction of probe rows with a partner
        if nd_p:
            return p * min(1.0, (nd_b or b) / nd_p)
        return p * 0.5
    if node.kind == "anti":
        if nd_p:
            return p * (1.0 - min(1.0, (nd_b or b) / nd_p))
        return p * 0.5
    return inner


def semi_estimate(build: N.PlanNode, probe: N.PlanNode, build_keys,
                  probe_keys, catalog) -> float:
    """Rows of ``probe`` surviving a semi filter on the join keys (runtime-
    filter sizing)."""
    j = N.PJoin("semi", build, probe, list(build_keys), list(probe_keys), [])
    return _estimate_join(j, catalog)


def _keys_ndv(plan: N.PlanNode, keys, catalog) -> Optional[float]:
    """Combined NDV of a key tuple (product, capped by subtree rows)."""
    prod = 1.0
    any_known = False
    for k in keys:
        nd = _expr_ndv(plan, k, catalog)
        if nd is not None:
            any_known = True
            prod *= nd
    if not any_known:
        return None
    return min(prod, max(estimate_rows(plan, catalog), 1.0))


def _expr_ndv(plan: N.PlanNode, e: ex.Expr, catalog) -> Optional[int]:
    if not isinstance(e, ex.ColumnRef):
        return None
    src = _col_source(plan, e.name)
    if src is None:
        return None
    table, phys = src
    try:
        return catalog.table(table).ndv(phys)
    except KeyError:
        return None


def _col_source(plan: N.PlanNode, name: str):
    """Trace an output column back to (table, physical column) through
    renames; None when it crosses a computation."""
    if isinstance(plan, N.PScan):
        for phys, out in plan.column_map.items():
            if out == name:
                return (plan.table_name, phys)
        return None
    if isinstance(plan, (N.PFilter, N.PRuntimeFilter, N.PSort, N.PLimit,
                         N.PMotion, N.PWindow, N.PShare)):
        return _col_source(plan.children()[0], name)
    if isinstance(plan, N.PProject):
        for out, e in plan.exprs:
            if out == name:
                if isinstance(e, ex.ColumnRef):
                    return _col_source(plan.child, e.name)
                return None
        return None
    if isinstance(plan, N.PJoin):
        if name in set(plan.probe.names):
            return _col_source(plan.probe, name)
        if name in set(plan.build.names):
            return _col_source(plan.build, name)
        return None
    if isinstance(plan, N.PAgg):
        for out, e in plan.group_keys:
            if out == name and isinstance(e, ex.ColumnRef):
                return _col_source(plan.child, e.name)
        return None
    if isinstance(plan, N.PConcat) and plan.inputs:
        return _col_source(plan.inputs[0], name)
    return None


def annotate_pack_bits(plan: N.PlanNode, catalog) -> None:
    """Prove 32-bit packed join keys from build-side column statistics.

    The kernels pack key tuples into one order-preserving integer using the
    BUILD side's runtime ranges (kernels.pack_with_ranges); probe values
    outside those ranges hit the sentinel. The runtime build range is a
    subset of the build column's table min/max, so if the product of
    stats-proven spans fits 32 bits (minus the sentinel), every in-range
    pack does too — and the sort/search/collective lanes halve. TPC-H keys
    stay 32-bit provable through SF100 (orderkey max 6e9·0.1 < 2^31)."""
    from cloudberry_tpu.types import DType

    # value-space spans only translate to pack-space for types whose
    # sort_key_u64 mapping is affine: integers, dates, scaled decimals,
    # and dictionary codes. FLOATS pack by IEEE bit pattern — a tiny value
    # span can cover ~2^52 bit patterns, so they are never narrowable.
    _AFFINE = (DType.INT32, DType.INT64, DType.DATE, DType.DECIMAL,
               DType.STRING)

    def bits_of(build: N.PlanNode, keys) -> int:
        prod = 1
        for k in keys:
            if not isinstance(k, ex.ColumnRef) \
                    or k.dtype.base not in _AFFINE:
                return 64
            src = _col_source(build, k.name)
            if src is None:
                return 64
            try:
                mm = catalog.table(src[0]).stats.min_max.get(src[1])
            except KeyError:
                return 64
            if mm is None:
                return 64
            # stats store float64 min/max: beyond 2^53 the rounding could
            # understate a span that straddles the 32-bit threshold
            if abs(mm[0]) >= 2 ** 53 or abs(mm[1]) >= 2 ** 53:
                return 64
            span = int(mm[1]) - int(mm[0]) + 1
            if span <= 0:
                return 64
            prod *= span
            if prod > (1 << 32) - 2:
                return 64
        return 32

    def walk(n: N.PlanNode):
        if isinstance(n, (N.PJoin, N.PRuntimeFilter)):
            n.pack_bits = bits_of(n.build, n.build_keys)
        from cloudberry_tpu.plan.distribute import _node_exprs

        for e in _node_exprs(n):
            for sub in ex.walk(e):
                if isinstance(sub, ex.SubqueryScalar):
                    walk(sub.plan)
        for c in n.children():
            walk(c)

    walk(plan)


def selectivity(pred: ex.Expr, child: N.PlanNode, catalog) -> float:
    s = _sel(pred, child, catalog)
    return min(max(s, 1e-6), 1.0)


def _sel(e: ex.Expr, child: N.PlanNode, catalog) -> float:
    if isinstance(e, ex.BinOp):
        if e.op == "and":
            return _sel(e.left, child, catalog) * \
                _sel(e.right, child, catalog)
        if e.op == "or":
            a = _sel(e.left, child, catalog)
            b = _sel(e.right, child, catalog)
            return a + b - a * b
        if e.op in ("=", "<>", "<", "<=", ">", ">="):
            return _cmp_sel(e, child, catalog)
    if isinstance(e, ex.UnaryOp) and e.op == "not":
        return 1.0 - _sel(e.operand, child, catalog)
    if isinstance(e, ex.DictLookup) and e.table.dtype == bool:
        # LIKE/IN over a dictionary: fraction of codes selected (frequency-
        # blind, but exact over the value domain)
        n = len(e.table)
        return float(e.table.sum()) / n if n else DEFAULT_SEL
    if isinstance(e, ex.IsValid):
        return 0.9
    if isinstance(e, ex.Literal):
        return 1.0 if bool(e.value) else 0.0
    return DEFAULT_SEL


def _cmp_sel(e: ex.BinOp, child: N.PlanNode, catalog) -> float:
    l, r = e.left, e.right
    op = e.op
    if isinstance(r, ex.ColumnRef) and isinstance(l, ex.Literal):
        l, r = r, l
        op = {"=": "=", "<>": "<>", "<": ">", "<=": ">=",
              ">": "<", ">=": "<="}[op]
    if not (isinstance(l, ex.ColumnRef) and isinstance(r, ex.Literal)):
        return DEFAULT_RANGE_SEL if op not in ("=", "<>") else DEFAULT_EQ_SEL
    src = _col_source(child, l.name)
    if src is None:
        return DEFAULT_RANGE_SEL if op not in ("=", "<>") else DEFAULT_EQ_SEL
    try:
        t = catalog.table(src[0])
    except KeyError:
        return DEFAULT_SEL
    if op in ("=", "<>"):
        nd = t.ndv(src[1])
        s = 1.0 / nd if nd else DEFAULT_EQ_SEL
        return s if op == "=" else 1.0 - s
    if not isinstance(r.value, (int, float)) or isinstance(r.value, bool):
        return DEFAULT_RANGE_SEL
    hist = t.stats.hist.get(src[1])
    if hist and len(hist) >= 3:
        # equi-depth histogram (ANALYZE output, pg_statistic
        # histogram_bounds role): each bucket holds 1/N of the rows, so
        # P(col <= v) = full buckets below v + linear interpolation
        # inside the containing bucket — skew-proof where uniform
        # [min,max] interpolation is wildly wrong
        frac = _hist_le_frac(hist, float(r.value))
        return frac if op in ("<", "<=") else 1.0 - frac
    mm = t.stats.min_max.get(src[1])
    if mm is None or mm[1] <= mm[0]:
        return DEFAULT_RANGE_SEL
    lo, hi = mm
    frac = (float(r.value) - lo) / (hi - lo)
    frac = min(max(frac, 0.0), 1.0)
    return frac if op in ("<", "<=") else 1.0 - frac


def _hist_le_frac(bounds: list, v: float) -> float:
    """P(col <= v) from equi-depth bounds (N+1 ascending values)."""
    import bisect

    n = len(bounds) - 1
    if v < bounds[0]:
        return 0.0
    if v >= bounds[-1]:
        return 1.0
    i = bisect.bisect_right(bounds, v) - 1  # bucket containing v
    lo, hi = bounds[i], bounds[i + 1]
    inner = (v - lo) / (hi - lo) if hi > lo else 1.0
    return (i + inner) / n
