"""Point-lookup acceleration — the index / AO block-directory analog.

The reference answers `WHERE k = const` point queries through btree
indexes or the append-only block directory
(src/backend/access/appendonly/appendonlyblockdirectory.c): direct
dispatch routes the statement to one segment, and the index narrows the
scan to the few matching blocks. Here direct dispatch already routes to
one shard, but the scan then reads the WHOLE shard. The TPU-native
analog is a host-side sorted-key sidecar: a cached argsort of the
column (built lazily on first point lookup, invalidated by the table
version), searchsorted at PLAN time to the matching row positions, and
the scan re-bound to exactly those rows — the device program then
touches O(matches) rows instead of the shard.

Scope: equality conjuncts against literals, RAM-resident tables above a
size floor, on the single-program paths (one segment, or a
direct-dispatched statement; the multi-segment SPMD program reads whole
shards by construction — its point path IS direct dispatch). Stored
(micro-partition) scans keep their own pruning (plan/scanprune.py:
manifest min/max + blooms play the block-directory role there).

The filter stays in the plan: re-filtering the slice is one fused
mask over O(matches) rows and keeps every other conjunct exact.
"""

from __future__ import annotations

import numpy as np

from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N

MIN_ROWS = 32_768        # below this a full masked scan is already cheap
_INDEX_CACHE_MAX = 8


def optimize_point_lookups(plan: N.PlanNode, session) -> None:
    """Re-bind eligible Filter→Scan patterns to sorted-sidecar row
    slices. Mutates scans in place (capacity, num_rows, _point_rows)."""
    if not getattr(session.config.planner, "enable_point_lookup", True):
        return
    seg = getattr(plan, "_direct_segment", None)
    if session.config.n_segments > 1 and seg is None:
        return

    def visit(node: N.PlanNode) -> None:
        if isinstance(node, N.PFilter):
            scan = node.child
            while isinstance(scan, N.PFilter):
                scan = scan.child
            if isinstance(scan, N.PScan) \
                    and not hasattr(scan, "_store_parts") \
                    and not hasattr(scan, "_point_rows") \
                    and scan.table_name != "$dual":
                _try_bind(node, scan, session, seg)
        for c in node.children():
            visit(c)
        from cloudberry_tpu.plan.distribute import _node_exprs

        for e in _node_exprs(node):
            for sub in ex.walk(e):
                if isinstance(sub, ex.SubqueryScalar):
                    visit(sub.plan)

    visit(plan)


def _eq_conjuncts(pred: ex.Expr):
    """Yield (column name, literal value) for every top-level equality
    conjunct comparing a bare column to a literal."""
    if isinstance(pred, ex.BinOp) and pred.op == "and":
        yield from _eq_conjuncts(pred.left)
        yield from _eq_conjuncts(pred.right)
        return
    if isinstance(pred, ex.BinOp) and pred.op == "=":
        l, r = pred.left, pred.right
        if isinstance(r, ex.ColumnRef) and isinstance(l, ex.Literal):
            l, r = r, l
        if isinstance(l, ex.ColumnRef) and isinstance(r, ex.Literal) \
                and not isinstance(r.value, str):
            yield l.name, r.value


def _try_bind(filt: N.PFilter, scan: N.PScan, session, seg) -> None:
    table = session.catalog.table(scan.table_name)
    if table.policy.kind == "replicated":
        seg_eff = None  # replicated tables read whole on any segment
    else:
        seg_eff = seg
    rows_total = table.num_rows if seg_eff is None else None
    if rows_total is not None and rows_total < MIN_ROWS:
        return
    out_to_phys = {out: phys for phys, out in scan.column_map.items()}
    for cname, value in _eq_conjuncts(filt.predicate):
        phys = out_to_phys.get(cname)
        if phys is None:
            continue
        # NULL rows never satisfy an equality: restrict to the valid
        # rows only when the column carries a mask (the canonical-zero
        # encoding would otherwise alias value 0)
        rows = _lookup(session, scan.table_name, phys, seg_eff, value)
        if rows is None:
            continue
        scan._point_undo = (scan.capacity, scan.num_rows)
        scan._point_rows = rows
        scan._point_col = cname
        scan._input_key = f"$pt{id(scan)}"
        scan.capacity = max(len(rows), 1)
        scan.num_rows = len(rows)
        return


def _lookup(session, tname: str, phys: str, seg, value):
    """Row positions (within the table / the segment's shard) whose
    ``phys`` column equals ``value``, via the cached sorted sidecar;
    None when the column cannot index (shard below the floor, non-1d)."""
    table = session.catalog.table(tname)
    table.ensure_loaded()
    if seg is None:
        col = np.asarray(table.data[phys])
        valid = table.validity.get(phys)
    else:
        st = session.sharded_table(tname)
        nrows = int(st.counts[seg])
        # the shard buffer is zero-padded past its count: padding rows
        # must never match (a k = 0 probe would return phantom rows)
        col = np.asarray(st.columns[phys][seg])[:nrows]
        valid = st.columns.get(f"$nn:{phys}")
        if valid is not None:
            valid = valid[seg][:nrows]
    if col.ndim != 1 or len(col) < MIN_ROWS:
        return None
    version = getattr(table, "_version", 0)
    key = (tname, phys, seg, version)
    cache = session.__dict__.setdefault("_point_index_cache", {})
    hit = cache.get(key)
    if hit is None:
        order = np.argsort(col, kind="stable")
        if len(cache) >= _INDEX_CACHE_MAX:
            cache.pop(next(iter(cache)))
        hit = cache[key] = (order, col[order])
    order, sorted_vals = hit
    try:
        lo = np.searchsorted(sorted_vals, value, side="left")
        hi = np.searchsorted(sorted_vals, value, side="right")
    except TypeError:
        return None
    if (hi - lo) > max(4096, len(col) >> 6):
        # not a POINT: a key-like equality matches O(1) rows; a flag or
        # category column matching a visible fraction of the table is
        # better served by the masked scan (no host gather, and plan
        # shapes stay stable for the golden snapshots)
        return None
    rows = np.sort(order[lo:hi])
    if valid is not None and len(rows):
        rows = rows[np.asarray(valid)[rows]]
    return rows


def unbind_point_lookups(plan: N.PlanNode) -> None:
    """Restore point-bound scans to full scans (the tiled/spill planner
    streams whole tables by table name; a $pt-keyed sliced scan would
    miss its input there)."""
    from cloudberry_tpu.exec.executor import scans_of

    for s in scans_of(plan):
        undo = getattr(s, "_point_undo", None)
        if undo is not None:
            s.capacity, s.num_rows = undo
            for attr in ("_point_rows", "_point_col", "_input_key",
                         "_point_undo"):
                if hasattr(s, attr):
                    delattr(s, attr)
