"""planck — the distributed-plan IR verifier (derived vs required
properties).

The reference's ORCA optimizer never trusts a plan it did not prove:
every Cascades group tracks *required* vs *derived* plan properties
(CDistributionSpec / COrderSpec) and enforcers are inserted until they
match. Our planner stamps those properties by hand — the distribution
pass writes ``node.sharding``, the memo stamps ``_dist_choice``, the
runtime-filter pass wraps probes, the paramplan rewrites literals into
slots — and until this module nothing ever CHECKED them. A wrong
sharding assumption at 8 segments is not a crash; it is a silently
wrong answer (Theseus' "cost of data movement done wrong").

``verify_plan(plan, session)`` walks any physical plan bottom-up and:

1. **derives** each node's distribution (the CdbPathLocus currency,
   plan/sharding.py) and static row bound from a per-node-class rule
   table (``RULES``), mirroring exactly what plan/distribute.py is
   ALLOWED to produce — scan inherits table policy, motions produce
   hashed/replicated/singleton, joins stay where colocation puts them;
2. checks each node's **required** properties against what its
   children derived: joins need colocation or a motion on an edge,
   two-stage aggs need partial-merge compatibility and colocated
   partials, windows need partition-key colocation, set-ops need
   gathered inputs, the root must not stay partitioned;
3. checks the **lowering contracts** that previously lived only in
   reviewers' heads: packed-wire dtype legality (the int64/DECIMAL
   limb convention ships 4/8-byte words — kernels.WIRE_ITEMSIZES),
   capacity-rung discipline (bucket caps sit ON the rung ladder and
   never undercut the exact skew bound unless a runtime filter
   justifies it), ``$params`` slot consistency between the paramplan
   signature and the plan, join-index (``_jix``) annotation legality,
   runtime-filter placement (the digest must sit probe-side of the
   shuffle it prices), validity-mask closure, and recovery-mode
   re-placeability (every checkpointing tiled mode has a declared
   re-placement rule).

Every finding carries a ``file``-style node path (``Limit/Sort/
Join(inner).probe/Motion(redistribute)``), a rule id, and a message —
the same shape graftlint findings have, so the lint CLI, the CI gate
(tools/lint_gate.py --plans) and the seeded plan-mutation fixtures
(tests/test_planverify.py) all speak one currency.

The verifier checks SOUNDNESS, not optimality: a plan that broadcasts
where a redistribute would be cheaper is legal; a plan whose join
inputs are not colocated and have no motion is not.

Run three ways: the golden-corpus gate (tools/golden_plans.py +
tests/test_golden_plans.py verify every TPC-H/TPC-DS plan at 1 and 8
segments), the ``config.debug.verify_plans`` session gate (every plan
the planner or memo emits is verified right before compile), and the
plan-mutation fuzzer (plan/mutate.py seeds ~18 corruption classes and
tests pin that each is caught).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.plan.sharding import Sharding

# ------------------------------------------------------------ findings


@dataclass
class PlanFinding:
    """One verifier diagnostic, anchored at a node path."""

    rule: str
    path: str                 # e.g. "Limit/Sort/Join(inner).probe/Motion"
    message: str

    def render(self) -> str:
        return f"{self.path}: {self.rule}: {self.message}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path,
                "message": self.message}


class PlanVerifyError(RuntimeError):
    """Raised by the ``config.debug.verify_plans`` session gate when a
    plan fails verification; carries the full finding list."""

    def __init__(self, findings: list[PlanFinding], context: str = ""):
        self.findings = findings
        head = f"plan verification failed ({context}): " if context \
            else "plan verification failed: "
        super().__init__(head + "; ".join(f.render() for f in findings))


# ------------------------------------------------- derived properties


@dataclass(frozen=True)
class Props:
    """Derived per-node physical properties — the bottom-up currency.

    ``dist``  — the derived Sharding (None only while deriving a
                local-mode plan, where distribution is vacuous);
    ``rows``  — static per-location row bound (the capacity currency:
                XLA shapes are static, so every node has one).

    Ordering is deliberately NOT part of the lattice: the one ordering
    contract (motions destroy order; the top-N pushdown must re-sort
    above its pre-compacting gather) is checked STRUCTURALLY against
    the exact key lists (_check_topn_merge) — stronger than any
    derived summary of them.
    """

    dist: Optional[Sharding]
    rows: int


@dataclass
class NodeRule:
    """One row of the rule table: how a node class derives its
    properties and what it requires of its children."""

    name: str
    fn: Callable          # fn(v, node, kids: list[Props], path) -> Props
    doc: str = ""


RULES: dict[str, NodeRule] = {}


def rule(*names: str, doc: str = ""):
    """Register the derive/require rule for the named PlanNode
    class(es). Registration is BY NAME so graftlint's planprops pass
    can statically pin the table against plan/nodes.py both ways (no
    unverifiable node class, no orphan rule)."""

    def deco(fn):
        for nm in names:
            RULES[nm] = NodeRule(nm, fn, doc)
        return fn
    return deco


def _label(node: N.PlanNode) -> str:
    nm = type(node).__name__.removeprefix("P")
    if isinstance(node, N.PMotion):
        return f"Motion({node.kind})"
    if isinstance(node, N.PJoin):
        return f"Join({node.kind})"
    if isinstance(node, N.PAgg):
        return f"Agg({node.mode})"
    if isinstance(node, N.PScan):
        return f"Scan({node.table_name})"
    if isinstance(node, N.PRuntimeFilter):
        return f"RuntimeFilter({node.mode})"
    return nm


def _edge_labels(node: N.PlanNode) -> list[str]:
    """Per-child edge names for node paths (build/probe for joins,
    positional for set-ops, empty for single-child chains)."""
    if isinstance(node, N.PJoin):
        return ["build:", "probe:"]
    if isinstance(node, N.PConcat):
        return [f"[{i}]:" for i in range(len(node.inputs))]
    return ["" for _ in node.children()]


# ------------------------------------------------------------ verifier


class Verifier:
    """One verification walk. ``local`` mode (n_segments == 1 or a
    direct-dispatch plan) skips distribution derivation — sharding is
    vacuous there — but keeps every lowering-contract check."""

    def __init__(self, session, plan: N.PlanNode,
                 declared_slots: Optional[list] = None,
                 declared_nrw: Optional[int] = None):
        self.session = session
        self.catalog = session.catalog
        self.nseg = session.config.n_segments
        self.local = (self.nseg <= 1
                      or getattr(plan, "_direct_segment", None) is not None)
        self.declared_slots = declared_slots
        self.declared_nrw = declared_nrw
        self.findings: list[PlanFinding] = []
        self.nodes_checked = 0
        self.rules_hit: set[str] = set()
        self._memo: dict[int, Props] = {}   # PShare / shared-build reuse
        self._parent: dict[int, tuple] = {}  # id -> (parent, edge label)
        self._build_ids: set[int] = set()   # nodes under some join build
        # $params slots seen during the walk: slot -> {(dtype, path)}
        self._params: dict[int, set] = {}
        # $nrw scan row-count slots seen during the walk: key -> [path]
        self._nrw: dict[str, list] = {}

    # ------------------------------------------------------- reporting

    def fail(self, rule_id: str, path: str, msg: str) -> None:
        self.findings.append(PlanFinding(rule_id, path, msg))

    # --------------------------------------------------------- walking

    def verify(self, plan: N.PlanNode) -> list[PlanFinding]:
        self._index(plan, None, "")
        root = self.walk(plan, _label(plan))
        if not self.local and root.dist is not None \
                and root.dist.is_partitioned:
            self.fail("root-partitioned", _label(plan),
                      f"statement root derives {root.dist} — results "
                      "must be gathered (singleton) or replicated "
                      "before they reach the coordinator slot")
        self._check_params(plan)
        self._check_nrw(_label(plan))
        self._check_recovery_modes(_label(plan))
        return self.findings

    def _index(self, node: N.PlanNode, parent, edge: str) -> None:
        """Parent pointers + the set of nodes under join build edges
        (runtime-filter build sharing checks both)."""
        if id(node) in self._parent:
            return
        self._parent[id(node)] = (parent, edge)
        kids = node.children()
        labels = _edge_labels(node)
        for c, lab in zip(kids, labels):
            self._index(c, node, lab)
            if lab == "build:":
                for sub in _subtree(c):
                    self._build_ids.add(id(sub))
        for e in _node_exprs(node):
            for sub in ex.walk(e):
                if isinstance(sub, ex.SubqueryScalar):
                    self._index(sub.plan, node, "$subquery:")

    def walk(self, node: N.PlanNode, path: str) -> Props:
        got = self._memo.get(id(node))
        if got is not None:
            return got
        self.nodes_checked += 1
        nr = RULES.get(type(node).__name__)
        if nr is None:
            self.fail("planprops-unruled", path,
                      f"no planprops rule for node class "
                      f"{type(node).__name__} — add a @rule row in "
                      "plan/verify.py before this node can be verified")
            props = Props(None if self.local else Sharding.strewn(),
                          rows=1)
            self._memo[id(node)] = props
            return props
        self.rules_hit.add(nr.name)
        kids = []
        labels = _edge_labels(node)
        for c, lab in zip(node.children(), labels):
            kids.append(self.walk(c, f"{path}/{lab}{_label(c)}"))
        # uncorrelated scalar subqueries ride inside expressions — each
        # is its own rooted plan and must not stay partitioned (its one
        # row broadcasts into the enclosing expression); $params slots
        # are collected in the same pass (the slot-discipline check
        # runs once at the end, without a second plan walk)
        for e in _node_exprs(node):
            for sub in ex.walk(e):
                if isinstance(sub, ex.Param):
                    self._params.setdefault(sub.slot, set()).add(
                        (sub.dtype, path))
                if isinstance(sub, ex.SubqueryScalar):
                    sp = self.walk(sub.plan,
                                   f"{path}/$subquery:{_label(sub.plan)}")
                    if not self.local and sp.dist is not None \
                            and sp.dist.is_partitioned:
                        self.fail(
                            "root-partitioned",
                            f"{path}/$subquery:{_label(sub.plan)}",
                            f"scalar-subquery plan derives {sp.dist} — "
                            "its single row must be gathered before it "
                            "broadcasts into the enclosing expression")
        props = nr.fn(self, node, kids, path)
        self._check_masks(node, path)
        if not self.local and node.sharding is not None \
                and props.dist is not None \
                and node.sharding != props.dist:
            self.fail("dist-mismatch", path,
                      f"stamped sharding {node.sharding} != derived "
                      f"{props.dist} — the node lies about where its "
                      "rows live")
        self._memo[id(node)] = props
        return props

    # ----------------------------------------------- generic contracts

    def _check_masks(self, node: N.PlanNode, path: str) -> None:
        """Validity-mask closure: every null_mask name a field carries
        must resolve to a BOOL field of the SAME node (or a mask the
        scan's mask_map provides) — a dangling mask would make the
        lowerer read a missing column or, worse, treat NULLs as
        values."""
        provided = {f.name for f in node.fields}
        if isinstance(node, N.PScan):
            provided |= set(node.mask_map.values())
        for f in node.fields:
            for m in f.masks:
                if m not in provided:
                    self.fail("mask-dangling", path,
                              f"field {f.name!r} declares validity mask "
                              f"{m!r} which is not a field of this node")

    def _check_params(self, plan: N.PlanNode) -> None:
        """$params slot discipline: slots dense, dtype-consistent, and
        — when the paramplan signature is in scope — exactly the
        declared vector. A desynced slot binds a literal into the
        wrong predicate. Slots were collected during the main walk."""
        slots = self._params
        if not slots and not self.declared_slots:
            return
        for slot, uses in sorted(slots.items()):
            dts = {dt for dt, _ in uses}
            anyp = next(p for _, p in uses)
            if slot < 0:
                self.fail("param-slot-desync", anyp,
                          f"negative $params slot {slot}")
            if len(dts) > 1:
                self.fail("param-slot-desync", anyp,
                          f"$params slot {slot} used at conflicting "
                          f"dtypes {sorted(str(d) for d in dts)}")
        if self.declared_slots is not None:
            n = len(self.declared_slots)
            for slot, uses in sorted(slots.items()):
                dt, path = next(iter(uses))
                if slot >= n:
                    self.fail("param-slot-desync", path,
                              f"$params slot {slot} outside the "
                              f"paramplan signature ({n} slots)")
                elif self.declared_slots[slot] != dt:
                    self.fail("param-slot-desync", path,
                              f"$params slot {slot} dtype {dt} != "
                              f"signature dtype "
                              f"{self.declared_slots[slot]}")
            # a declared slot with NO site is the same desync from the
            # other side: the binding vector carries a value the plan
            # never reads, and every later slot is suspect
            missing = [i for i in range(n) if i not in slots]
            if missing:
                self.fail("param-slot-desync", _label(plan),
                          f"paramplan signature declares slot(s) "
                          f"{missing} with no $params site in the plan")
        elif slots:
            # no signature in scope: slots must still be dense — a gap
            # means a binding vector entry with no site (or vice versa)
            want = set(range(max(slots) + 1))
            missing = want - set(slots)
            if missing:
                anyp = next(p for _, p in next(iter(slots.values())))
                self.fail("param-slot-desync", anyp,
                          f"$params slots not dense: missing "
                          f"{sorted(missing)} of 0..{max(slots)}")

    def _check_nrw(self, root_path: str) -> None:
        """$nrw (scan row-count) slot discipline for rewritten generic
        plans: every stamped ``_nrows_key`` is unique to ONE scan, the
        indices are dense, and — when the paramplan binding count is
        in scope — exactly as many as the signature declares. A
        desynced $nrw feeds one scan's runtime row count into
        another's padding mask."""
        if not self._nrw and not self.declared_nrw:
            return
        idxs: set[int] = set()
        for key, paths in sorted(self._nrw.items()):
            if len(paths) > 1:
                self.fail("param-slot-desync", paths[1],
                          f"$nrw slot {key!r} stamped on "
                          f"{len(paths)} scans — each scan needs its "
                          "own row-count input")
            if not key.startswith("$nrw"):
                self.fail("param-slot-desync", paths[0],
                          f"malformed scan row-count key {key!r}")
                continue
            try:
                idxs.add(int(key[4:]))
            except ValueError:
                self.fail("param-slot-desync", paths[0],
                          f"malformed scan row-count key {key!r}")
        if idxs:
            missing = set(range(max(idxs) + 1)) - idxs
            if missing:
                self.fail("param-slot-desync", root_path,
                          f"$nrw slots not dense: missing "
                          f"{sorted(missing)} of 0..{max(idxs)}")
        if self.declared_nrw is not None \
                and len(self._nrw) != self.declared_nrw:
            self.fail("param-slot-desync", root_path,
                      f"plan carries {len(self._nrw)} $nrw scan "
                      f"row-count slots; the paramplan signature "
                      f"binds {self.declared_nrw}")

    def _check_recovery_modes(self, path: str) -> None:
        """Recovery-signature stability: every tiled mode that
        checkpoints (exec/tiled.py CHECKPOINT_MODES) must carry a
        declared re-placement rule (exec/recovery.py REPLACEABLE) —
        a checkpointed mode nobody can re-place on a degraded mesh
        would resume into a wrong answer."""
        try:
            from cloudberry_tpu.exec.recovery import REPLACEABLE
            from cloudberry_tpu.exec.tiled import CHECKPOINT_MODES
        except ImportError:  # pragma: no cover - contract modules gone
            return
        for mode in CHECKPOINT_MODES:
            if mode not in REPLACEABLE:
                self.fail("recovery-mode-unreplaceable", path,
                          f"tiled mode {mode!r} checkpoints but has no "
                          "re-placement rule in exec/recovery.py "
                          "REPLACEABLE")
        for mode in REPLACEABLE:
            if mode not in CHECKPOINT_MODES:
                self.fail("recovery-mode-unreplaceable", path,
                          f"recovery declares re-placement for mode "
                          f"{mode!r} which no tiled executor "
                          "checkpoints (stale rule)")

    # ------------------------------------------------- motion helpers

    def exact_bucket_bound(self, child: N.PlanNode,
                           keys) -> Optional[int]:
        """The exact per-(source,destination) bucket bound for a
        redistribute whose subtree is a (filtered) base-table scan —
        the same computation the distributor sized the motion with
        (Distributor._exact_bucket_cap, cached on the session)."""
        from cloudberry_tpu.plan.distribute import Distributor

        try:
            return Distributor(self.session)._exact_bucket_cap(
                child, keys)
        except Exception:
            return None

    def exact_host_bound(self, child: N.PlanNode, keys,
                         n_hosts: int) -> Optional[int]:
        """The exact (source host, destination host) exchange bound for
        a scan-rooted redistribute — the same computation the
        distributor sized host_bucket_cap with (_exact_host_cap)."""
        from cloudberry_tpu.plan.distribute import Distributor

        try:
            return Distributor(self.session)._exact_host_cap(
                child, keys, n_hosts)
        except Exception:
            return None


def _subtree(node: N.PlanNode):
    yield node
    for c in node.children():
        yield from _subtree(c)


def _walk_paths(plan: N.PlanNode):
    """(node, path) for every node including subquery plans — the
    path currency findings anchor to."""
    def rec(node, path, seen):
        if id(node) in seen:
            return
        seen.add(id(node))
        yield node, path
        for c, lab in zip(node.children(), _edge_labels(node)):
            yield from rec(c, f"{path}/{lab}{_label(c)}", seen)
        for e in _node_exprs(node):
            for sub in ex.walk(e):
                if isinstance(sub, ex.SubqueryScalar):
                    yield from rec(sub.plan,
                                   f"{path}/$subquery:{_label(sub.plan)}",
                                   seen)
    yield from rec(plan, _label(plan), set())


# ----------------------------------------------------------- the rules
#
# Each rule mirrors the ONE way plan/distribute.py is allowed to build
# that node class. The imports below are the shared helpers — using the
# distributor's own sharding algebra keeps the two from drifting.

from cloudberry_tpu.plan.distribute import (_hashed_key_positions,  # noqa: E402
                                            _join_colocated,
                                            _node_exprs,
                                            _project_sharding,
                                            _rename_sharding)


@rule("PScan", doc="inherits the table's distribution policy: hashed "
                   "on the (renamed) distribution keys when they "
                   "survive pruning, strewn when they do not, "
                   "replicated for replicated tables, general for "
                   "$dual")
def _r_scan(v: Verifier, node: N.PScan, kids, path) -> Props:
    nk = getattr(node, "_nrows_key", None)
    if nk is not None:
        v._nrw.setdefault(nk, []).append(path)
    if node.capacity < 1:
        v.fail("scan-rows", path,
               f"scan capacity {node.capacity} < 1 (XLA arrays need a "
               "static nonempty shape)")
    if node.num_rows < -2:
        v.fail("scan-rows", path, f"scan num_rows {node.num_rows} is "
               "not a row count / -1 (== capacity) / -2 (runtime "
               "per-segment counts)")
    if node.num_rows > node.capacity:
        v.fail("scan-rows", path,
               f"scan num_rows {node.num_rows} > capacity "
               f"{node.capacity}")
    if node.num_rows == -2 and v.local:
        v.fail("scan-rows", path,
               "num_rows == -2 (runtime per-segment counts) in a "
               "single-segment / direct-dispatch plan — there is no "
               "$nrw input to read")
    if v.local:
        return Props(None, node.capacity)
    if node.table_name == "$dual":
        return Props(Sharding.general(), node.capacity)
    try:
        table = v.catalog.table(node.table_name)
    except KeyError:
        return Props(Sharding.strewn(), node.capacity)
    pol = table.policy
    if pol.kind == "replicated":
        return Props(Sharding.replicated(), node.capacity)
    if pol.kind == "hashed" and all(k in node.column_map
                                    for k in pol.keys):
        return Props(Sharding.hashed(*(node.column_map[k]
                                       for k in pol.keys)),
                     node.capacity)
    return Props(Sharding.strewn(), node.capacity)


@rule("PFilter", doc="preserves the child's distribution; requires a "
                     "BOOL predicate")
def _r_filter(v: Verifier, node: N.PFilter, kids, path) -> Props:
    from cloudberry_tpu.types import BOOL

    pd = getattr(node.predicate, "dtype", None)
    if pd is not None and pd != BOOL:
        v.fail("filter-pred-type", path,
               f"filter predicate has dtype {pd}, not BOOL")
    return Props(kids[0].dist, kids[0].rows)


@rule("PProject", doc="preserves distribution through column renames "
                      "(hashed keys projected away degrade to strewn)")
def _r_project(v: Verifier, node: N.PProject, kids, path) -> Props:
    d = kids[0].dist
    if d is not None:
        d = _project_sharding(d, node.exprs)
    return Props(d, kids[0].rows)


@rule("PShare", doc="the shared subplan computes once; every reference "
                    "sees its distribution")
def _r_share(v: Verifier, node: N.PShare, kids, path) -> Props:
    return kids[0]


@rule("PLimit", doc="preserves distribution; bounds rows at "
                    "limit+offset")
def _r_limit(v: Verifier, node: N.PLimit, kids, path) -> Props:
    if node.limit < 0 or node.offset < 0:
        v.fail("limit-bounds", path,
               f"negative limit/offset ({node.limit}, {node.offset})")
    k = node.limit + node.offset
    rows = min(kids[0].rows, k) if k > 0 else kids[0].rows
    return Props(kids[0].dist, max(rows, 1))


@rule("PSort", doc="preserves distribution; a partitioned sort is "
                   "only legal as the local half of the top-N merge "
                   "pattern (checked structurally at the gather)")
def _r_sort(v: Verifier, node: N.PSort, kids, path) -> Props:
    return Props(kids[0].dist, kids[0].rows)


@rule("PWindow", doc="requires partition-key colocation when the "
                     "child is partitioned (every partition's rows on "
                     "one segment)")
def _r_window(v: Verifier, node: N.PWindow, kids, path) -> Props:
    d = kids[0].dist
    if d is not None and d.is_partitioned:
        names = {e.name for e in node.partition_keys
                 if isinstance(e, ex.ColumnRef)}
        ok = (d.kind == "hashed" and d.keys and set(d.keys) <= names)
        if not ok:
            v.fail("window-not-colocated", path,
                   f"window over {d} child: partition keys "
                   f"{sorted(names) or '(none)'} do not cover the "
                   "child's hash keys — a partition's rows would span "
                   "segments and every frame would be wrong")
    return Props(d, kids[0].rows)


@rule("PConcat", doc="set-op append: every input must be gathered "
                     "(non-partitioned) first; output is singleton")
def _r_concat(v: Verifier, node: N.PConcat, kids, path) -> Props:
    labels = _edge_labels(node)
    for i, kp in enumerate(kids):
        if kp.dist is not None and kp.dist.is_partitioned:
            v.fail("concat-partitioned-input",
                   f"{path}/{labels[i]}{_label(node.inputs[i])}",
                   f"append input {i} derives {kp.dist} — set-op "
                   "inputs are gathered before appending (a "
                   "partitioned input would append one shard only)")
    total = sum(k.rows for k in kids) or 1
    return Props(None if v.local else Sharding.singleton(), total)


@rule("PAgg", doc="single mode requires group-key colocation on a "
                  "partitioned child; final mode requires gathered or "
                  "group-key-hashed partials and partial-merge-"
                  "compatible aggregate pairs")
def _r_agg(v: Verifier, node: N.PAgg, kids, path) -> Props:
    if node.capacity < 1:
        v.fail("agg-capacity", path,
               f"agg capacity {node.capacity} < 1")
    csh = kids[0].dist
    key_src = {e.name for _, e in node.group_keys
               if isinstance(e, ex.ColumnRef)}
    if node.mode == "single":
        if csh is not None and csh.is_partitioned:
            if not (node.group_keys and csh.kind == "hashed"
                    and csh.keys and set(csh.keys) <= key_src):
                v.fail("agg-single-not-colocated", path,
                       f"one-stage agg over {csh} child: group keys "
                       f"{sorted(key_src) or '(none)'} do not cover "
                       "the child's hash keys — equal groups would "
                       "live on several segments and each would "
                       "aggregate alone")
            d = _rename_sharding(csh, node.group_keys) \
                if node.group_keys else csh
        else:
            d = csh
        return Props(d, node.capacity)
    if node.mode == "partial":
        return Props(csh, node.capacity)
    if node.mode != "final":
        v.fail("agg-merge-illegal", path,
               f"unknown agg mode {node.mode!r}")
        return Props(csh, node.capacity)
    # final: all partial rows of one group must be in one place
    if csh is not None and csh.is_partitioned:
        ok = (node.group_keys and csh.kind == "hashed" and csh.keys
              and set(csh.keys) <= key_src)
        if not ok:
            v.fail("agg-final-partials-split", path,
                   f"final agg over {csh} child: partial rows of one "
                   "group are not guaranteed colocated (need a gather "
                   "or a redistribute on the group keys) — merged "
                   "sums would be partial sums")
    _check_merge_pairs(v, node, path)
    if csh is not None and csh.is_partitioned and node.group_keys:
        d = _rename_sharding(csh, node.group_keys)
    else:
        d = csh
    return Props(d, node.capacity)


# the legal (partial, final-merge) aggregate pairs — the _split_aggs
# contract (plan/distribute.py): how each aggregate decomposes across
# the motion boundary. avg never crosses it whole (it splits into
# sum+count and re-divides in a finalize projection).
MERGE_OF = {"sum": "sum", "count": "sum", "min": "min", "max": "max"}


def _check_merge_pairs(v: Verifier, node: N.PAgg, path: str) -> None:
    for name, call in node.aggs:
        if call.func not in set(MERGE_OF.values()):
            v.fail("agg-merge-illegal", path,
                   f"final agg {name!r} merges with {call.func!r} — "
                   f"legal merge functions are "
                   f"{sorted(set(MERGE_OF.values()))}")
        if not isinstance(call.arg, ex.ColumnRef):
            v.fail("agg-merge-illegal", path,
                   f"final agg {name!r} must merge a partial COLUMN, "
                   f"got {type(call.arg).__name__}")
    # the partial stage below (through the motion) must emit columns a
    # legal pair can merge: find it and check func pairing by name
    below = node.child
    while isinstance(below, (N.PMotion, N.PShare)):
        below = below.child
    if not (isinstance(below, N.PAgg) and below.mode == "partial"):
        v.fail("agg-final-no-partial", path,
               f"final agg's input chain reaches "
               f"{type(below).__name__} — two-stage aggregation "
               "merges a PARTIAL stage's output")
        return
    partial_funcs = {n: c.func for n, c in below.aggs}
    for name, call in node.aggs:
        if not isinstance(call.arg, ex.ColumnRef):
            continue
        src = partial_funcs.get(call.arg.name)
        if src is None:
            continue  # group-key column or renamed — arity noise
        want = MERGE_OF.get(src)
        if want is not None and call.func != want:
            v.fail("agg-merge-illegal", path,
                   f"final agg {name!r} merges partial "
                   f"{src!r} with {call.func!r}; the declared merge "
                   f"of {src!r} is {want!r}")


@rule("PJoin", doc="requires colocation (or an already-inserted motion "
                   "on an edge): both-partitioned sides must hash on "
                   "corresponding key positions; left/anti builds must "
                   "be visible everywhere; full joins need colocation "
                   "or two gathered sides")
def _r_join(v: Verifier, node: N.PJoin, kids, path) -> Props:
    bprops, pprops = kids
    if len(node.build_keys) != len(node.probe_keys):
        v.fail("join-key-arity", path,
               f"{len(node.build_keys)} build keys vs "
               f"{len(node.probe_keys)} probe keys")
    if not node.unique_build and node.out_capacity < 1:
        v.fail("join-out-capacity", path,
               "expansion join (unique_build=False) with no "
               "out_capacity — the pair buffer would be empty")
    _check_join_index(v, node, path)
    rows = _join_rows(node, bprops.rows, pprops.rows)
    if v.local:
        return Props(None, rows)
    bsh, psh = bprops.dist, pprops.dist
    b_part, p_part = bsh.is_partitioned, psh.is_partitioned
    if node.kind == "full":
        if b_part and p_part:
            if not _join_colocated(node, bsh, psh):
                v.fail("join-not-colocated", path,
                       f"full join over {bsh} build / {psh} probe "
                       "without key colocation — unmatched rows would "
                       "be missed or duplicated")
            return Props(psh, rows)
        if b_part or p_part:
            v.fail("join-full-dist", path,
                   f"full join with {bsh} build / {psh} probe: a "
                   "replicated or singleton side against a "
                   "partitioned one emits unmatched rows once PER "
                   "SEGMENT — both sides must be gathered or "
                   "colocated")
        return Props(psh, rows)
    if b_part and p_part:
        if not _join_colocated(node, bsh, psh):
            v.fail("join-not-colocated", path,
                   f"join over {bsh} build / {psh} probe: sides are "
                   "not hash-colocated on corresponding join keys and "
                   "no motion was inserted — equal keys would never "
                   "meet")
        return Props(psh, rows)
    if b_part and not p_part:
        if node.kind not in ("inner", "semi"):
            v.fail("join-outer-build-partitioned", path,
                   f"{node.kind} join with partitioned build "
                   f"({bsh}) and {psh} probe: deciding that a probe "
                   "row matches NOWHERE needs the whole build side "
                   "on every segment")
            return Props(psh, rows)
        bsub = _hashed_key_positions(bsh, node.build_keys)
        if bsub is not None:
            names = [node.probe_keys[i].name for i in bsub
                     if isinstance(node.probe_keys[i], ex.ColumnRef)]
            d = (Sharding.hashed(*names) if len(names) == len(bsub)
                 else Sharding.strewn())
        else:
            d = Sharding.strewn()
        return Props(d, rows)
    # remaining arms: build is not partitioned (replicated/singleton/
    # general build beside any probe) — the join runs where the probe
    # lives
    return Props(psh, rows)


def _join_rows(node: N.PJoin, brows: int, prows: int) -> int:
    if node.residual is not None:
        return prows
    if not node.unique_build:
        return max(node.out_capacity, 1)
    return prows


def _check_join_index(v: Verifier, node: N.PJoin, path: str) -> None:
    """Join-index (``_jix``) annotation legality: the stamp must be
    exactly what exec/joinindex.py would derive for this join TODAY —
    a stale or hand-forged spec would feed a cached sort order built
    for a different build fragment."""
    spec = getattr(node, "_jix", None)
    if spec is None:
        return
    from cloudberry_tpu.exec.joinindex import _build_spec

    direct = v.local and v.nseg > 1
    try:
        want = _build_spec(node, v.session, v.nseg, direct)
    except Exception:
        want = None
    if want is None or want.key != spec.key:
        v.fail("jix-illegal", path,
               f"join-index annotation {getattr(spec, 'key', spec)!r} "
               "does not match what exec/joinindex.py derives for "
               f"this join ({getattr(want, 'key', None)!r}) — the "
               "cached sorted-build scaffolding would not describe "
               "this build side")


@rule("PRuntimeFilter", doc="passes the probe through unchanged; must "
                            "sit probe-side of (directly under) the "
                            "redistribute it prices, sharing the "
                            "join's build subtree")
def _r_rfilter(v: Verifier, node: N.PRuntimeFilter, kids, path) -> Props:
    if not node.probe_keys or \
            len(node.build_keys) != len(node.probe_keys):
        v.fail("rf-keys", path,
               f"runtime filter with {len(node.build_keys)} build / "
               f"{len(node.probe_keys)} probe keys")
    if node.mode == "digest":
        bits = node.bloom_bits
        if bits < 64 or bits & (bits - 1):
            v.fail("rf-digest-bits", path,
                   f"digest bloom_bits {bits} is not a power of two "
                   ">= 64 (kernels.bloom word math relies on it)")
    elif node.mode != "exact":
        v.fail("rf-keys", path, f"unknown filter mode {node.mode!r}")
    parent, _ = v._parent.get(id(node), (None, ""))
    if not (isinstance(parent, N.PMotion)
            and parent.kind == "redistribute"):
        v.fail("rf-placement", path,
               "runtime filter is not directly under a redistribute "
               "motion — the digest must drop probe rows BEFORE the "
               "shuffle it prices (above it, the wire already paid)")
    if id(node.build) not in v._build_ids:
        v.fail("rf-build-unshared", path,
               "runtime filter's build reference is not a subtree of "
               "any join's build input — the filter would be built "
               "from rows the join never sees")
    return Props(kids[0].dist, kids[0].rows)


@rule("PMotion", doc="gather derives singleton, broadcast replicated, "
                     "redistribute hashed(keys); bucket capacities sit "
                     "on the rung ladder and never silently undercut "
                     "the exact skew bound; wire dtypes must pack")
def _r_motion(v: Verifier, node: N.PMotion, kids, path) -> Props:
    child = kids[0]
    _check_wire_fields(v, node, path)
    if child.dist is not None and not child.dist.is_partitioned:
        v.fail("motion-child-not-partitioned", path,
               f"motion over a {child.dist} child — the distributor "
               "only moves partitioned rows; this motion would "
               "duplicate or misroute them")
    if node.kind == "gather":
        d = Sharding.singleton()
        need = node.pre_compact if node.pre_compact > 0 else child.rows
        if node.out_capacity < need * v.nseg:
            v.fail("motion-capacity", path,
                   f"gather out_capacity {node.out_capacity} < "
                   f"{need} rows x {v.nseg} segments")
        if node.pre_compact > 0:
            _check_topn_merge(v, node, path)
        return Props(None if v.local else d, max(node.out_capacity, 1))
    if node.kind == "broadcast":
        if node.out_capacity < child.rows * v.nseg:
            v.fail("motion-capacity", path,
                   f"broadcast out_capacity {node.out_capacity} < "
                   f"{child.rows} rows x {v.nseg} segments")
        return Props(None if v.local else Sharding.replicated(),
                     max(node.out_capacity, 1))
    if node.kind != "redistribute":
        v.fail("motion-capacity", path,
               f"unknown motion kind {node.kind!r}")
        return Props(Sharding.strewn(), max(node.out_capacity, 1))
    if not node.hash_keys:
        v.fail("motion-hash-keys", path,
               "redistribute with no hash keys — rows have no "
               "destination function")
    from cloudberry_tpu.exec.kernels import rung_up

    if node.bucket_cap < 8 or rung_up(node.bucket_cap) != node.bucket_cap:
        v.fail("motion-rung", path,
               f"redistribute bucket_cap {node.bucket_cap} is not a "
               "capacity rung (power of two >= 8) — off-ladder shapes "
               "defeat the bounded-recompile discipline and the "
               "grow-and-retry path")
    if node.out_capacity != node.bucket_cap * v.nseg:
        v.fail("motion-capacity", path,
               f"redistribute out_capacity {node.out_capacity} != "
               f"bucket_cap {node.bucket_cap} x {v.nseg} segments")
    exact = v.exact_bucket_bound(node.child, node.hash_keys)
    if exact is not None and node.bucket_cap < rung_up(max(exact, 8)):
        # undercutting the exact skew bound is legal ONLY when a
        # runtime filter below shrank the input (overflow then
        # promotes back up the ladder); without one, a hot key is a
        # guaranteed overflow the exact bound existed to prevent
        if _rf_below(node) is None:
            v.fail("motion-rung-below-exact", path,
                   f"redistribute bucket_cap {node.bucket_cap} < exact "
                   f"skew bound rung {rung_up(max(exact, 8))} with no "
                   "runtime filter below to justify the undercut")
    if getattr(node, "_feedback_seed", None) is not None:
        _check_feedback_seed(v, node, path)
    if node.host_bucket_cap or node.hier_hosts or node.host_combine \
            or node.combine_spec is not None:
        _check_two_level(v, node, path)
    names = tuple(k.name for k in node.hash_keys
                  if isinstance(k, ex.ColumnRef))
    d = Sharding.hashed(*names) if names and \
        len(names) == len(node.hash_keys) else Sharding.strewn()
    return Props(None if v.local else d, max(node.out_capacity, 1))


def _check_feedback_seed(v: Verifier, node: N.PMotion, path: str) -> None:
    """Feedback-seeded rungs (plan/feedback.py, distribute._feedback_seed)
    re-derive their justified bound from the LIVE sketch — the stamp's
    own numbers are never trusted. The sketch's sources are re-resolved
    from the motion's actual child and keys, the sketch must still exist
    under current validity tokens, and the rung must cover the observed
    demand (scaled by the session's headroom when it shrinks the seed,
    never when it would inflate the bound away). A stamp with no live
    sketch behind it is forged — exactly what a feedback-poisoning bug
    or a replayed stale plan would look like."""
    from cloudberry_tpu.exec.kernels import rung_up
    from cloudberry_tpu.plan import feedback as FB

    seed = node._feedback_seed
    store = FB.store_for(v.session)
    src = FB.resolve_sources(node.child, node.hash_keys)
    sk = store.lookup(v.session, "redist", src) \
        if store is not None and src is not None else None
    if sk is None or sk.demand_max <= 0:
        v.fail("motion-rung-feedback-forged", path,
               f"feedback-seeded rung {node.bucket_cap} with no live "
               f"sketch for sources {src!r} — the stamp claims demand "
               f"{seed.get('demand')!r} nothing currently observed "
               "justifies")
        return
    headroom = min(float(v.session.config.feedback.headroom), 1.0)
    bound = rung_up(max(int(sk.demand_max * headroom), 8))
    if node.bucket_cap < bound:
        v.fail("motion-rung-feedback-forged", path,
               f"feedback-seeded bucket_cap {node.bucket_cap} < rung "
               f"{bound} justified by the observed demand "
               f"{sk.demand_max} — an undercut rung is a guaranteed "
               "overflow the sketch existed to prevent")


def _check_two_level(v: Verifier, node: N.PMotion, path: str) -> None:
    """The two-level (hierarchical) motion's capacity rules — ISSUE 14's
    additions to the lowering contracts. Checked whenever ANY two-level
    stamp is present, independent of the live topology: the stamps are
    what the hierarchical transport will trust, so a forged or desynced
    stamp must be a finding even on a session that would run it flat."""
    from cloudberry_tpu.exec.kernels import rung_up

    hh = node.hier_hosts
    hb = node.host_bucket_cap
    if hh < 2 or v.nseg % hh != 0:
        v.fail("motion-host-grouping", path,
               f"two-level stamps with hier_hosts={hh} on a {v.nseg}-"
               "segment plan — the hierarchical exchange requires a "
               "uniform host grouping (hosts >= 2 dividing nseg); a "
               "wrong grouping routes rows to the wrong host lane")
        return
    S = v.nseg // hh
    if hb < 8 or rung_up(hb) != hb:
        v.fail("motion-host-rung", path,
               f"host_bucket_cap {hb} is not a capacity rung (power of "
               "two >= 8) — the DCN block ladder shares the bounded-"
               "recompile discipline of bucket_cap")
    if hb < node.bucket_cap:
        v.fail("motion-host-capacity", path,
               f"host_bucket_cap {hb} < bucket_cap {node.bucket_cap}: "
               "a single segment-pair bucket the intra hop may legally "
               "deliver cannot fit the inter-host block — the "
               "aggregated DCN exchange is undersized by construction")
    elif hb > rung_up(S * S * node.bucket_cap):
        v.fail("motion-host-capacity", path,
               f"host_bucket_cap {hb} exceeds the proven host-pair "
               f"ceiling rung {rung_up(S * S * node.bucket_cap)} "
               f"(S^2 x bucket_cap, S={S}) — pure DCN padding no "
               "demand can fill")
    else:
        exact = v.exact_host_bound(node.child, node.hash_keys, hh)
        if exact is not None and hb < rung_up(max(exact, 8)) \
                and _rf_below(node) is None and not node.host_combine:
            v.fail("motion-host-capacity", path,
                   f"host_bucket_cap {hb} < exact host-pair bound rung "
                   f"{rung_up(max(exact, 8))} with nothing below to "
                   "shrink the input — a guaranteed DCN-block overflow")
    if node.host_combine or node.combine_spec is not None:
        _check_host_combine(v, node, path)


def _check_host_combine(v: Verifier, node: N.PMotion,
                        path: str) -> None:
    """Combine-stamp legality: only a two-stage agg's merge motion may
    carry it, and every merge must be order-insensitive-exact — a
    forged stamp would host-combine rows whose merge is not associative
    - commutative-exact and silently change results."""
    import numpy as np

    spec = node.combine_spec
    if not node.host_combine or spec is None:
        v.fail("motion-host-combine", path,
               "host_combine and combine_spec must be stamped together "
               "(one without the other is a forged/half-applied stamp)")
        return
    child = node.child
    if not (isinstance(child, N.PAgg)
            and getattr(child, "mode", "") == "partial"
            and child.group_keys):
        v.fail("motion-host-combine", path,
               "host_combine stamped on a motion whose child is not a "
               "grouped PARTIAL aggregate — there are no partials to "
               "merge; combining arbitrary rows drops data")
        return
    keys, merges = spec
    want = tuple(n for n, _ in child.group_keys)
    if tuple(keys) != want:
        v.fail("motion-host-combine", path,
               f"combine_spec keys {tuple(keys)} != the partial agg's "
               f"group keys {want}")
    hash_names = {k.name for k in node.hash_keys
                  if isinstance(k, ex.ColumnRef)}
    if hash_names != set(keys):
        v.fail("motion-host-combine", path,
               f"combine groups by {sorted(keys)} but the motion "
               f"hashes {sorted(hash_names)} — combined groups would "
               "not be colocated with their merge destination")
    by_name = {f.name: f for f in node.fields}
    for f in node.fields:
        if f.masks:
            v.fail("motion-host-combine", path,
                   f"host-combine over masked (nullable) column "
                   f"{f.name!r} — NULL grouping semantics need the "
                   "mask columns the combine does not model")
            break
    for name, func in merges:
        f = by_name.get(name)
        if f is None:
            v.fail("motion-host-combine", path,
                   f"combine_spec merges column {name!r} the motion "
                   "does not ship")
            continue
        if func not in ("sum", "min", "max"):
            v.fail("motion-host-combine", path,
                   f"merge func {func!r} for {name!r} is not an exact "
                   "combine (count partials merge as sum)")
        elif func == "sum" and not (
                np.issubdtype(f.type.np_dtype, np.integer)
                or np.dtype(f.type.np_dtype) == np.bool_):
            v.fail("motion-host-combine", path,
                   f"sum-merge of {name!r} ({f.type.np_dtype}) is add-"
                   "order-sensitive — host-combined floats would not "
                   "be bit-identical to the flat merge")


def _rf_below(m: N.PMotion) -> Optional[N.PRuntimeFilter]:
    node = m.child
    while isinstance(node, (N.PFilter, N.PRuntimeFilter)):
        if isinstance(node, N.PRuntimeFilter):
            return node
        node = node.child
    return None


def _check_wire_fields(v: Verifier, node: N.PMotion, path: str) -> None:
    """Packed-wire dtype legality: every column a motion ships must be
    bool (a flag bit) or a 4/8-byte word — the int64/DECIMAL limb
    convention bitcasts whole u32 words (kernels.WIRE_ITEMSIZES); any
    other width has no wire lane and would raise mid-execution."""
    import numpy as np

    from cloudberry_tpu.exec.kernels import WIRE_ITEMSIZES

    for f in node.fields:
        dt = np.dtype(f.type.np_dtype)
        if dt == np.bool_:
            continue
        if dt.itemsize not in WIRE_ITEMSIZES:
            v.fail("motion-wire-dtype", path,
                   f"motion ships column {f.name!r} of dtype {dt} "
                   f"({dt.itemsize} bytes); the packed wire carries "
                   f"bool flags and {WIRE_ITEMSIZES}-byte words only")


def _check_topn_merge(v: Verifier, m: N.PMotion, path: str) -> None:
    """The top-N pushdown contract (merge-sorted-receive analog): a
    pre-compacting gather must sit over PLimit(k)/PSort(keys) and
    UNDER a re-sort on the same keys — each segment keeps its own top
    k, the coordinator merges k*nseg rows; drop either half and the
    global top-N is wrong."""
    lim = m.child
    if not (isinstance(lim, N.PLimit)
            and isinstance(lim.child, N.PSort)
            and lim.limit + lim.offset == m.pre_compact):
        v.fail("topn-merge-sort", path,
               f"pre_compact={m.pre_compact} gather is not over "
               "PLimit(k)/PSort — nothing bounds what each segment "
               "keeps")
        return
    inner_keys = lim.child.keys
    parent, _ = v._parent.get(id(m), (None, ""))
    if not isinstance(parent, N.PSort):
        v.fail("topn-merge-sort", path,
               "pre_compact gather has no merge PSort above it — "
               "k*nseg concatenated shard tops are not a global "
               "order")
        return
    if len(parent.keys) != len(inner_keys) or not all(
            (a is c or a == c) and b == d
            for (a, b), (c, d) in zip(parent.keys, inner_keys)):
        v.fail("topn-merge-sort", path,
               "merge sort above the pre_compact gather orders by "
               "different keys than the per-segment local sort — the "
               "merged top-N would be of the wrong order")


@rule("_AccLeaf", doc="the tiled finalize program's accumulator leaf "
                      "(exec/tiled.py): pooled partial state, one "
                      "place, no children")
def _r_accleaf(v: Verifier, node, kids, path) -> Props:
    cap = getattr(node, "capacity", 0) or 1
    return Props(None if v.local else Sharding.singleton(), cap)


# ---------------------------------------------------------- public API


def verify_plan(plan: N.PlanNode, session,
                declared_slots: Optional[list] = None,
                declared_nrw: Optional[int] = None
                ) -> list[PlanFinding]:
    """Verify one physical plan; returns findings (empty == clean)."""
    return Verifier(session, plan, declared_slots,
                    declared_nrw).verify(plan)


def verify_stats(plan: N.PlanNode, session) -> dict:
    """Verification + counters (the bench.py ``planverify`` record
    currency): nodes checked, rule-table rows hit, findings."""
    v = Verifier(session, plan)
    findings = v.verify(plan)
    return {"nodes": v.nodes_checked,
            "rules_hit": sorted(v.rules_hit),
            "findings": [f.as_dict() for f in findings]}


def check_plan(plan: N.PlanNode, session, context: str = "",
               declared_slots: Optional[list] = None,
               declared_nrw: Optional[int] = None) -> None:
    """The ``config.debug.verify_plans`` gate body: raise
    PlanVerifyError on any finding."""
    findings = verify_plan(plan, session, declared_slots, declared_nrw)
    if findings:
        raise PlanVerifyError(findings, context)


def annotate_derived(plan: N.PlanNode, session) -> list[PlanFinding]:
    """Stamp every node with its DERIVED distribution (``_vdist``) for
    EXPLAIN's ``dist:`` annotation — plan reviews and golden diffs
    then show sharding explicitly instead of implying it. Returns the
    walk's findings so a gated EXPLAIN pays ONE verification."""
    v = Verifier(session, plan)
    findings = v.verify(plan)
    for node, _ in _walk_paths(plan):
        props = v._memo.get(id(node))
        if props is not None and props.dist is not None:
            node._vdist = props.dist
    return findings
