"""Binder: unbound AST → typed plan tree.

The reference's analog is parse analysis + planning
(src/backend/parser/analyze.c + optimizer); this binder does both name/type
resolution and logical planning:

- names resolve to alias-qualified output columns (``alias.col``) so
  self-joins (TPC-H Q21's three lineitem aliases) stay unambiguous;
- decimal scale arithmetic (int64 fixed-point, see types.SqlType);
- string predicates fold into host-side dictionary lookup tables
  (columnar/dictionary.py) at bind time;
- implicit FROM-list joins are assembled from WHERE equi-conjuncts into a
  left-deep tree, dimension side as build — the spirit of
  cdbpath_motion_for_join's colocation reasoning, with cost stats to come;
- aggregates are extracted from select/having/order expressions into a PAgg
  node, outer expressions rewritten over its outputs (the reference's
  TargetEntry/Aggref split).
"""

from __future__ import annotations

import copy
import datetime
import decimal
from dataclasses import dataclass, field as dc_field
from typing import Optional

import numpy as np

from cloudberry_tpu import types as T
from cloudberry_tpu.catalog.catalog import Catalog, Table
from cloudberry_tpu.columnar.dictionary import StringDictionary
from cloudberry_tpu.plan import expr as ex
from cloudberry_tpu.plan import nodes as N
from cloudberry_tpu.sql import ast
from cloudberry_tpu.types import DType, SqlType

AGG_FUNCS = {"sum", "count", "min", "max", "avg", "stddev_samp"}
MAX_DECIMAL_SCALE = 6


class BindError(ValueError):
    pass


@dataclass
class RangeEntry:
    """One FROM item in scope: alias → its plan's output fields."""
    alias: str
    plan: N.PlanNode


@dataclass
class Scope:
    entries: list[RangeEntry] = dc_field(default_factory=list)

    def resolve(self, parts: tuple[str, ...]) -> tuple[RangeEntry, N.PlanField]:
        if len(parts) == 2:
            for e in self.entries:
                if e.alias == parts[0]:
                    for f in e.plan.fields:
                        if f.name == f"{parts[0]}.{parts[1]}":
                            return e, f
            raise BindError(f"unknown column {'.'.join(parts)!r}")
        # exact physical-name match first (generated names like "$agg1" or
        # rewritten qualified names), then unqualified suffix match
        for e in self.entries:
            for f in e.plan.fields:
                if f.name == parts[0]:
                    return e, f
        hits = []
        seen = set()
        for e in self.entries:
            for f in e.plan.fields:
                if f.name.split(".")[-1] == parts[0]:
                    # entries rebound to one merged join plan are one source
                    key = (id(e.plan), f.name)
                    if key not in seen:
                        seen.add(key)
                        hits.append((e, f))
        if not hits:
            raise BindError(f"unknown column {parts[0]!r}")
        if len(hits) > 1:
            raise BindError(f"ambiguous column {parts[0]!r}")
        return hits[0]

    def aliases_of(self, node: ast.ExprNode) -> set[str]:
        """Aliases referenced by an unbound expression (for conjunct
        classification)."""
        out: set[str] = set()

        def walk(n):
            if isinstance(n, ast.Name):
                e, _ = self.resolve(n.parts)
                out.add(e.alias)
            for v in vars(n).values() if isinstance(n, ast.Node) else ():
                if isinstance(v, ast.Node):
                    walk(v)
                elif isinstance(v, (list, tuple)):
                    for x in v:
                        if isinstance(x, ast.Node):
                            walk(x)
                        elif isinstance(x, tuple):
                            for y in x:
                                if isinstance(y, ast.Node):
                                    walk(y)

        walk(node)
        return out


def _unique_sets(plan: N.PlanNode, catalog: Catalog) -> list[frozenset[str]]:
    """Column sets guaranteed unique in a plan's output (PK propagation):
    scans expose unique base columns, joins preserve the PROBE side's
    uniqueness (each probe row matches ≤1 build row), aggs are unique on
    their group keys."""
    cached = getattr(plan, "_unique_sets", None)
    if cached is not None:
        return cached
    out: list[frozenset[str]] = []
    if isinstance(plan, N.PScan) and plan.table_name != "$dual":
        t = catalog.table(plan.table_name)
        for phys, name in plan.column_map.items():
            if t.is_unique(phys):
                out.append(frozenset([name]))
    elif isinstance(plan, (N.PFilter, N.PSort, N.PLimit, N.PMotion,
                           N.PShare)):
        out = _unique_sets(plan.children()[0], catalog)
    elif isinstance(plan, N.PJoin):
        # probe uniqueness survives ONLY when each probe row emits at most
        # one output row: semi/anti always; inner/left with a unique build.
        # Expansion (many-to-many) and full joins duplicate probe rows.
        if plan.kind in ("semi", "anti") or (
                plan.unique_build and plan.kind in ("inner", "left")):
            out = _unique_sets(plan.probe, catalog)
    elif isinstance(plan, N.PAgg):
        if plan.group_keys:
            out = [frozenset(n for n, _ in plan.group_keys)]
    elif isinstance(plan, N.PProject):
        renames = {}
        for name, e in plan.exprs:
            if isinstance(e, ex.ColumnRef):
                renames[e.name] = name
        for s in _unique_sets(plan.child, catalog):
            if all(c in renames for c in s):
                out.append(frozenset(renames[c] for c in s))
    plan._unique_sets = out
    return out


def _build_is_unique(plan: N.PlanNode, keys: list[ex.Expr],
                     catalog: Catalog) -> bool:
    names = {k.name for k in keys if isinstance(k, ex.ColumnRef)}
    if any(s <= names for s in _unique_sets(plan, catalog)):
        return True
    # composite PK on a (possibly filtered) base scan, e.g. partsupp's
    # (ps_partkey, ps_suppkey)
    p = plan
    while isinstance(p, (N.PFilter, N.PSort, N.PLimit, N.PMotion)):
        p = p.children()[0]
    if isinstance(p, N.PScan) and p.table_name != "$dual" and names:
        rev = {v: k for k, v in p.column_map.items()}
        phys = [rev.get(n) for n in names]
        if all(x is not None for x in phys):
            return catalog.table(p.table_name).is_unique_cols(tuple(phys))
    return False


class Binder:
    def __init__(self, catalog: Catalog, config=None):
        self.catalog = catalog
        # session config (None = single-node defaults): the joint
        # join-order search needs n_segments / memo switches at BIND
        # time, because join ORDER is decided here
        self.config = config
        self._counter = 0
        # CTE name -> bound plan; references share the plan via PShare
        self._ctes: dict[str, N.PlanNode] = {}

    def gensym(self, prefix: str) -> str:
        self._counter += 1
        return f"${prefix}{self._counter}"

    # ------------------------------------------------------------ statements

    def bind_query(self, node: ast.Node) -> N.PlanNode:
        if isinstance(node, ast.WithQuery):
            saved = dict(self._ctes)
            try:
                for name, q in node.ctes:
                    # earlier CTEs are visible to later ones (non-recursive)
                    self._ctes[name.lower()] = self.bind_query(q)
                return self.bind_query(node.query)
            finally:
                self._ctes = saved
        if isinstance(node, ast.SetOp):
            return self.bind_setop(node)
        return self.bind_select(node)

    def bind_setop(self, node: ast.SetOp) -> N.PlanNode:
        """UNION/INTERSECT/EXCEPT (the cdbsetop.c flow): align both sides
        to common types/dictionaries, then Append(+distinct) / semi / anti."""
        left = self.bind_query(node.left)
        right = self.bind_query(node.right)
        lvis = _user_fields(left)
        rvis = _user_fields(right)
        if len(lvis) != len(rvis):
            raise BindError(
                f"set operation arity mismatch: {len(lvis)} vs "
                f"{len(rvis)} columns")
        left, right, out_fields = self._align_setop_sides(
            left, right, lvis, rvis)

        if node.op == "union":
            plan: N.PlanNode = N.PConcat([left, right])
            plan.fields = out_fields
            if not node.all:
                plan = self._distinct_on_all(plan)
        elif node.op in ("intersect", "except"):
            kind = "semi" if node.op == "intersect" else "anti"
            if node.all:
                # Bag semantics via occurrence numbering: number duplicate
                # copies 1..n on each side (row_number partitioned on every
                # column), then semi/anti join on (columns…, occurrence) —
                # the i-th left copy survives INTERSECT ALL iff the right
                # has an i-th copy too (min of the counts); EXCEPT ALL is
                # the anti join (max(l_count − r_count, 0) copies). The
                # textbook reduction the reference executes via SetOp's
                # per-group counters (nodeSetOp.c SETOP_HASHED ALL modes).
                lw, locc = self._occurrence_numbered(left)
                rw, rocc = self._occurrence_numbered(right)
                keys_p = [_canonical_ref(f) for f in left.fields] \
                    + [ex.ColumnRef(locc, T.INT64)]
                keys_b = [_canonical_ref(f) for f in right.fields] \
                    + [ex.ColumnRef(rocc, T.INT64)]
                j = N.PJoin(kind, rw, lw, keys_b, keys_p, [],
                            self.gensym("match"))
                j.fields = list(left.fields)
                plan = j
            else:
                # distinct(left) filtered by membership in right; set ops
                # treat NULLs as equal ("not distinct"), so keys are
                # canonical-zero values plus the mask columns — no
                # key-validity exclusion
                probe = self._distinct_on_all(left)
                keys_b = [_canonical_ref(f) for f in right.fields]
                keys_p = [_canonical_ref(f) for f in probe.fields]
                j = N.PJoin(kind, right, probe, keys_b, keys_p, [],
                            self.gensym("match"))
                j.fields = list(probe.fields)
                plan = j
        else:
            raise BindError(f"unknown set operation {node.op!r}")

        if node.order_by:
            keys = []
            out_scope = Scope([RangeEntry("$set", plan)])
            for oi in node.order_by:
                _append_sort_key(keys, self.bind_scalar(oi.expr, out_scope),
                                 oi.ascending)
            srt = N.PSort(plan, keys)
            srt.fields = list(plan.fields)
            plan = srt
        if node.limit is not None or node.offset:
            lim = N.PLimit(plan, node.limit if node.limit is not None
                           else (1 << 62), node.offset)
            lim.fields = list(plan.fields)
            plan = lim
        return plan

    def _occurrence_numbered(self, plan: N.PlanNode):
        """Append a 1..n occurrence column within each duplicate group
        (row_number window partitioned on every column, order immaterial)
        — the multiplicity bookkeeping for INTERSECT/EXCEPT ALL."""
        occ = self.gensym("occ")
        w = N.PWindow(plan, [_canonical_ref(f) for f in plan.fields], [],
                      [(occ, "row_number", None)], [None])
        w.fields = list(plan.fields) + [N.PlanField(occ, T.INT64, None)]
        return w, occ

    def _distinct_on_all(self, plan: N.PlanNode) -> N.PAgg:
        # Nullable columns group by (canonical-zero value, validity mask):
        # mask columns are among plan.fields, so they participate as keys —
        # SQL DISTINCT treats NULLs as equal, which this reproduces exactly.
        agg = N.PAgg(plan,
                     [(f.name, _canonical_ref(f)) for f in plan.fields], [],
                     capacity=_plan_capacity(plan))
        agg.fields = [N.PlanField(f.name, f.type, f.sdict,
                                  null_mask=f.null_mask,
                                  _is_null_col=f._is_null_col)
                      for f in plan.fields]
        return agg

    def _align_setop_sides(self, left: N.PlanNode, right: N.PlanNode,
                           lvis=None, rvis=None):
        """Project both sides to common types under the LEFT side's column
        names; string columns re-code into the left dictionary (extended).
        Only user-visible fields align; hidden validity columns re-emerge
        as SHARED "$vmu<i>" mask columns on both sides."""
        lvis = _user_fields(left) if lvis is None else lvis
        rvis = _user_fields(right) if rvis is None else rvis
        lex, rex, lfields, rfields = [], [], [], []
        changed_l = changed_r = False
        for lf, rf in zip(lvis, rvis):
            le: ex.Expr = _colref(lf)
            re_: ex.Expr = _colref(rf)
            if lf.type.base == DType.STRING or rf.type.base == DType.STRING:
                if lf.type.base != rf.type.base:
                    # a NULL-literal column takes the string side's type:
                    # code 0 under an always-False mask (grouping-set
                    # branches project NULL for omitted keys)
                    if (getattr(rf, "_is_null_col", False)
                            and lf.type.base == DType.STRING):
                        lex.append((lf.name, le))
                        rex.append((lf.name, ex.Literal(0, lf.type)))
                        lfields.append(N.PlanField(lf.name, lf.type,
                                                   lf.sdict))
                        rfields.append(N.PlanField(lf.name, lf.type,
                                                   lf.sdict))
                        changed_r = True
                        continue
                    if (getattr(lf, "_is_null_col", False)
                            and rf.type.base == DType.STRING):
                        lex.append((lf.name, ex.Literal(0, rf.type)))
                        rex.append((lf.name, re_))
                        lfields.append(N.PlanField(lf.name, rf.type,
                                                   rf.sdict))
                        rfields.append(N.PlanField(lf.name, rf.type,
                                                   rf.sdict))
                        changed_l = True
                        continue
                    raise BindError("set operation mixes string and "
                                    "non-string columns")
                ld, rd = lf.sdict, rf.sdict
                if ld is None or rd is None:
                    raise BindError("set operation requires dictionary-"
                                    "encoded string columns")
                if ld is not rd:
                    # fresh output dictionary: left codes stay valid (prefix
                    # copy), right codes translate — binding must NOT mutate
                    # the catalog's dictionary (EXPLAIN would bloat tables)
                    out_d = StringDictionary(ld.values)
                    xlat = np.fromiter((out_d.add(v) for v in rd.values),
                                       dtype=np.int32, count=len(rd))
                    re_ = ex.DictLookup(re_, xlat, T.STRING)
                    object.__setattr__(re_, "_out_dict", out_d)
                    changed_r = True
                    sdict = out_d
                else:
                    sdict = ld
                out_t = lf.type
            else:
                out_t = _common_type([lf.type, rf.type])
                if le.dtype != out_t:
                    le = self._coerce(le, out_t)
                    changed_l = True
                if re_.dtype != out_t:
                    re_ = self._coerce(re_, out_t)
                    changed_r = True
                sdict = None
            lex.append((lf.name, le))
            rex.append((lf.name, re_))
            lfields.append(N.PlanField(lf.name, out_t, sdict))
            rfields.append(N.PlanField(lf.name, out_t, sdict))
        # nullable columns: materialize a SHARED hidden validity column on
        # both sides (same name → PConcat aligns them; set-op joins and
        # DISTINCT then treat NULLs as equal via the mask key)
        n_vis = len(lvis)
        for i, (lf, rf) in enumerate(zip(lvis, rvis)):
            lm, rm = lf.masks, rf.masks
            if not lm and not rm:
                continue
            hidden = f"$vmu{i}"
            true_lit = ex.Literal(True, T.BOOL)
            lex.append((hidden, ex.IsValid(lm) if lm else true_lit))
            rex.append((hidden, ex.IsValid(rm) if rm else true_lit))
            f0 = lfields[i]
            lfields[i] = N.PlanField(f0.name, f0.type, f0.sdict,
                                     null_mask=(hidden,))
            changed_l = changed_r = True
        lfields = lfields + [N.PlanField(n, T.BOOL, None)
                             for n, _ in lex[n_vis:]]
        rfields = [N.PlanField(f.name, f.type, f.sdict, null_mask=f.null_mask)
                   for f in lfields]
        if changed_l or [n for n, _ in lex] != [f.name for f in lvis] \
                or len(lvis) != len(left.fields):
            p = N.PProject(left, lex)
            p.fields = lfields
            left = p
        out_r = N.PProject(right, rex)
        out_r.fields = rfields
        right = out_r
        del changed_r
        return left, right, lfields

    def bind_select(self, sel: ast.Select) -> N.PlanNode:
        if getattr(sel, "grouping_sets", None):
            return self.bind_query(_expand_grouping_sets(sel))
        if any(_contains_grouping(i.expr) for i in sel.items) \
                or (sel.having is not None
                    and _contains_grouping(sel.having)) \
                or any(_contains_grouping(o.expr) for o in sel.order_by):
            sel = _fold_plain_grouping(sel)
        scope = Scope()
        plans: dict[str, N.PlanNode] = {}
        post_join_filters: list[ast.ExprNode] = []

        for ref in sel.from_refs:
            alias, plan = self.bind_table_ref(ref, scope, post_join_filters)
            plans[alias] = plan

        if not plans:
            # FROM-less SELECT (select 1): one-row dummy
            plan = _const_row()
        else:
            all_conjuncts = _split_conjuncts(sel.where) if sel.where else []
            conjuncts = [c for c in all_conjuncts if not _contains_subquery(c)]
            subq_preds = [c for c in all_conjuncts if _contains_subquery(c)]
            edges, per_alias, residual = self._classify(conjuncts, scope)
            for alias, preds in per_alias.items():
                if alias not in plans:
                    # alias buried in an explicit JOIN tree: filter post-join
                    residual.extend(preds)
                    continue
                p = plans[alias]
                old = p
                for pred in preds:
                    p = self._filter(p, self.bind_scalar(pred, scope))
                plans[alias] = p
                # rebind EVERY entry (and plan) that shared the old
                # object: an explicit JOIN's aliases all point at one
                # merged plan, and a stale sibling would make suffix
                # resolution see two distinct sources for one column
                for e in scope.entries:
                    if e.alias == alias or e.plan is old:
                        e.plan = p
                for a2, pv in list(plans.items()):
                    if pv is old:
                        plans[a2] = p
            plan = self._join_tree(plans, edges, scope,
                                   groupby=sel.group_by)
            for pred in residual:
                plan = self._filter(plan, self.bind_scalar(pred, scope))
            for pred in subq_preds:
                plan = self._apply_subquery_pred(pred, plan, scope)
            # every range entry now resolves against the final joined plan —
            # stale pointers would defeat resolve()'s same-source dedupe
            for e in scope.entries:
                if _plan_contains(plan, e.plan):
                    e.plan = plan

        # -------- aggregation
        has_agg = (bool(sel.group_by) or sel.having is not None
                   or any(_has_agg(i.expr) for i in sel.items)
                   or any(_has_agg(o.expr) for o in sel.order_by))

        if has_agg:
            plan, out_scope = self._bind_agg(sel, plan, scope)
        else:
            out_scope = scope
            plan = self._bind_projection(sel, plan, scope)

        # -------- DISTINCT
        if sel.distinct:
            plan = self._distinct_on_all(plan)

        # -------- ORDER BY / LIMIT
        visible = list(plan.fields)  # includes hidden $vm validity columns
        if sel.order_by:
            keys = []
            for oi in sel.order_by:
                bound = self._bind_output_expr(oi.expr, plan, out_scope)
                missing = ex.columns_used(bound) - set(plan.names)
                if missing:
                    # ORDER BY references non-output columns: carry them as a
                    # hidden sort column through the projection, drop after
                    if isinstance(plan, N.PProject):
                        nm = None
                        v = _valid_of(bound)
                        if v is not None:
                            # carry the validity too, or NULL ordering breaks
                            vmname = self.gensym("vm")
                            plan.exprs.append((vmname, v))
                            plan.fields.append(
                                N.PlanField(vmname, T.BOOL, None))
                            nm = (vmname,)
                        name = self.gensym("sort")
                        plan.exprs.append((name, bound))
                        f = N.PlanField(name, bound.dtype, _expr_dict(bound),
                                        null_mask=nm)
                        plan.fields.append(f)
                        bound = _colref(f)
                    else:
                        raise BindError(
                            "ORDER BY expression references columns outside "
                            "the select list")
                _append_sort_key(keys, bound, oi.ascending)
            s = N.PSort(plan, keys)
            s.fields = list(plan.fields)
            plan = s
        if sel.limit is not None or sel.offset:
            limit = sel.limit if sel.limit is not None else (1 << 62)
            l = N.PLimit(plan, limit, sel.offset)
            l.fields = list(plan.fields)
            plan = l
        if len(visible) != len(plan.fields):
            drop = N.PProject(plan, [(f.name, _colref(f)) for f in visible])
            drop.fields = visible
            plan = drop
        return plan

    # ------------------------------------------------------------ FROM refs

    def bind_table_ref(self, ref: ast.TableRefNode, scope: Scope,
                       post_filters: list[ast.ExprNode]) -> tuple[str, N.PlanNode]:
        if isinstance(ref, ast.TableName):
            cte = self._ctes.get(ref.name.lower())
            if cte is not None:
                # CTE reference: every reference shares the SAME bound plan
                # (materialize-once, the ShareInputScan analog)
                share = N.PShare(cte)
                share.fields = list(cte.fields)
                alias = ref.alias or ref.name
                proj = self._requalify(share, alias)
                scope.entries.append(RangeEntry(alias, proj))
                return alias, proj
            view = self.catalog.views.get(ref.name.lower())
            if view is not None:
                # view expansion: re-bind the stored query as a derived
                # table — with the caller's CTEs HIDDEN (a view's references
                # are fixed at creation; PostgreSQL semantics)
                saved = self._ctes
                self._ctes = {}
                try:
                    return self.bind_table_ref(
                        ast.DerivedTable(view, ref.alias or ref.name),
                        scope, post_filters)
                finally:
                    self._ctes = saved
            table = self._lookup_table(ref.name)
            alias = ref.alias or ref.name
            plan = _scan_node(table, alias)
            scope.entries.append(RangeEntry(alias, plan))
            return alias, plan
        if isinstance(ref, ast.DerivedTable):
            sub = self.bind_query(ref.select)
            proj = self._requalify(sub, ref.alias)
            scope.entries.append(RangeEntry(ref.alias, proj))
            return ref.alias, proj
        if isinstance(ref, ast.FuncTable):
            return self._bind_func_table(ref, scope)
        if isinstance(ref, ast.JoinRef):
            return self._bind_join_ref(ref, scope, post_filters)
        raise BindError(f"unsupported FROM item {type(ref).__name__}")

    def _bind_func_table(self, ref: ast.FuncTable,
                         scope: Scope) -> tuple[str, N.PlanNode]:
        """Function Scan (nodeFunctionscan.c role): evaluate host-side at
        bind time — arguments must be constants — and scan the transient
        replicated table exec/tablefunc.py materializes."""
        from cloudberry_tpu.exec import tablefunc

        fn = tablefunc.lookup(ref.name)
        if fn is None:
            raise BindError(
                f"unknown table function {ref.name!r} (known: "
                f"{', '.join(tablefunc.known_functions())}; register "
                "with cloudberry_tpu.exec.tablefunc."
                "register_table_function)")
        vals = []
        for a in ref.args:
            b = self.bind_scalar(a, Scope())
            if _is_null_literal(b):
                vals.append(None)  # functions see NULL as None
                continue
            if not isinstance(b, ex.Literal):
                raise BindError(
                    f"{ref.name}: table function arguments must be "
                    "constants (one XLA program per plan — no per-row "
                    "function scans)")
            v = b.value
            if b.dtype.base == DType.DECIMAL:
                # literals bind in fixed-point; the function sees the
                # numeric VALUE (1.5, never the scaled 15)
                v = v / 10 ** b.dtype.scale
            vals.append(v)
        try:
            tname = tablefunc.materialize(self.catalog, ref.name, fn,
                                          vals)
        except (ValueError, TypeError) as e:
            raise BindError(f"table function {ref.name}: {e}")
        table = self._lookup_table(tname)
        alias = ref.alias or ref.name
        plan = _scan_node(table, alias)
        scope.entries.append(RangeEntry(alias, plan))
        return alias, plan

    def _requalify(self, sub: N.PlanNode, alias: str) -> N.PProject:
        """Re-qualify a subplan's output names under a derived/CTE alias
        (mask column references remap with their fields)."""
        proj = N.PProject(sub, [(f"{alias}.{f.name.split('.')[-1]}",
                                 ex.ColumnRef(f.name, f.type))
                                for f in sub.fields])

        def _remap_mask(nm):
            if nm is None:
                return None
            masks = (nm,) if isinstance(nm, str) else nm
            return tuple(f"{alias}.{m.split('.')[-1]}" for m in masks)

        proj.fields = [N.PlanField(f"{alias}.{f.name.split('.')[-1]}",
                                   f.type, f.sdict,
                                   null_mask=_remap_mask(f.null_mask))
                       for f in sub.fields]
        return proj

    def _bind_join_ref(self, ref: ast.JoinRef, scope: Scope,
                       post_filters: list[ast.ExprNode]) -> tuple[str, N.PlanNode]:
        lalias, lplan = self.bind_table_ref(ref.left, scope, post_filters)
        ralias, rplan = self.bind_table_ref(ref.right, scope, post_filters)
        if ref.kind == "cross":
            raise BindError("CROSS JOIN not supported yet")
        conjs = _split_conjuncts(ref.on)
        lkeys, rkeys, residual = [], [], []
        for c in conjs:
            if isinstance(c, ast.BinOp) and c.op == "=":
                sides = (scope.aliases_of(c.left), scope.aliases_of(c.right))
                lset = {e.alias for e in scope.entries
                        if _plan_contains(lplan, e.plan) or e.alias == lalias}
                if sides[0] <= lset and not (sides[1] & lset):
                    lkeys.append(self.bind_scalar(c.left, scope))
                    rkeys.append(self.bind_scalar(c.right, scope))
                    continue
                if sides[1] <= lset and not (sides[0] & lset):
                    lkeys.append(self.bind_scalar(c.right, scope))
                    rkeys.append(self.bind_scalar(c.left, scope))
                    continue
            residual.append(c)
        if not lkeys:
            raise BindError("JOIN requires at least one equi-condition")
        if ref.kind == "full" and residual:
            raise BindError("FULL JOIN with non-equi ON conditions is not "
                            "supported yet")
        if ref.kind in ("left", "right"):
            # ON-clause extras must filter the NON-preserved side BEFORE the
            # join (post-join filtering would drop preserved rows)
            inner_alias = ralias if ref.kind == "left" else lalias
            inner_plan = rplan if ref.kind == "left" else lplan
            inner_aliases = {e.alias for e in scope.entries
                             if e.plan is inner_plan}
            keep = []
            for c in residual:
                if scope.aliases_of(c) <= inner_aliases:
                    inner_plan = self._filter(
                        inner_plan, self.bind_scalar(c, scope))
                else:
                    keep.append(c)
            if keep:
                raise BindError("OUTER JOIN ON condition referencing the "
                                "preserved side is not supported yet")
            residual = []
            _rebind_scope(scope, inner_alias, inner_plan)
            if ref.kind == "left":
                rplan = inner_plan
            else:
                lplan = inner_plan
        if ref.kind == "inner":
            # build side must be unique on its keys; prefer the smaller side
            l_uniq = _build_is_unique(lplan, lkeys, self.catalog)
            r_uniq = _build_is_unique(rplan, rkeys, self.catalog)
            l_small = _plan_capacity(lplan) <= _plan_capacity(rplan)
            if l_uniq and (not r_uniq or l_small):
                plan = self._make_join("inner", lplan, rplan, lkeys, rkeys)
            else:
                plan = self._make_join("inner", rplan, lplan, rkeys, lkeys)
        elif ref.kind == "left":
            plan = self._make_join("left", rplan, lplan, rkeys, lkeys)
        elif ref.kind == "right":
            plan = self._make_join("left", lplan, rplan, lkeys, rkeys)
        elif ref.kind == "full":
            if _plan_capacity(lplan) <= _plan_capacity(rplan):
                plan = self._make_join("full", lplan, rplan, lkeys, rkeys)
            else:
                plan = self._make_join("full", rplan, lplan, rkeys, lkeys)
        else:
            raise BindError(f"{ref.kind} join not supported yet")
        for c in residual:
            plan = self._filter(plan, self.bind_scalar(c, scope))
        # merge the two range entries into one compound entry set; rebind all
        for e in scope.entries:
            if e.alias in (lalias, ralias) or _plan_contains(plan, e.plan):
                e.plan = plan
        return lalias, plan

    def _lookup_table(self, name: str) -> Table:
        return self.catalog.table(name)

    # --------------------------------------------------------- join assembly

    def _classify(self, conjuncts: list[ast.ExprNode], scope: Scope):
        """Split WHERE conjuncts into join edges / single-rel filters /
        residual (multi-rel non-equi) — the planner's qual distribution."""
        edges = []        # (alias_a, expr_a, alias_b, expr_b)
        per_alias: dict[str, list[ast.ExprNode]] = {}
        residual = []
        for c in conjuncts:
            aliases = scope.aliases_of(c)
            if len(aliases) == 1:
                per_alias.setdefault(next(iter(aliases)), []).append(c)
            elif (len(aliases) == 2 and isinstance(c, ast.BinOp)
                  and c.op == "="):
                la = scope.aliases_of(c.left)
                ra = scope.aliases_of(c.right)
                if len(la) == 1 and len(ra) == 1 and la != ra:
                    edges.append((next(iter(la)), c.left,
                                  next(iter(ra)), c.right))
                else:
                    residual.append(c)
            elif len(aliases) >= 2 and isinstance(c, ast.BinOp) and c.op == "or":
                # Q19 pattern: OR whose every branch repeats the same
                # equi-join condition — hoist the common conjuncts as join
                # edges, keep the full OR as a residual filter.
                for cc in _common_branch_conjuncts(c):
                    if isinstance(cc, ast.BinOp) and cc.op == "=":
                        la = scope.aliases_of(cc.left)
                        ra = scope.aliases_of(cc.right)
                        if len(la) == 1 and len(ra) == 1 and la != ra:
                            edges.append((next(iter(la)), cc.left,
                                          next(iter(ra)), cc.right))
                residual.append(c)
            elif len(aliases) == 0:
                residual.append(c)
            else:
                residual.append(c)
        return edges, per_alias, residual

    def _join_tree(self, plans: dict[str, N.PlanNode], edges, scope: Scope,
                   groupby=()) -> N.PlanNode:
        # group aliases by current plan object (explicit joins may share)
        groups: dict[int, set[str]] = {}
        plan_of: dict[int, N.PlanNode] = {}
        for a, p in plans.items():
            groups.setdefault(id(p), set()).add(a)
            plan_of[id(p)] = p
        # aliases buried inside explicit JOIN trees resolve through scope
        # entries — they belong to the group containing their plan
        for se in scope.entries:
            for gid, p in plan_of.items():
                if p is se.plan or _plan_contains(p, se.plan):
                    groups[gid].add(se.alias)
        # equi-conjuncts between aliases INSIDE one group are plain filters
        # (their join already happened in the explicit JOIN tree) — they
        # must never be dropped as unusable edges
        alias_group = {a: gid for gid, aliases in groups.items()
                       for a in aliases}
        cross = []
        for e in edges:
            ga, gb = alias_group.get(e[0]), alias_group.get(e[2])
            if ga is not None and ga == gb:
                p = plan_of[ga]
                pred = self.bind_scalar(ast.BinOp("=", e[1], e[3]), scope)
                p2 = self._filter(p, pred)
                plan_of[ga] = p2
                for se in scope.entries:
                    if se.alias in groups[ga]:
                        se.plan = p2
                for a2, p_old in list(plans.items()):
                    if a2 in groups[ga]:
                        plans[a2] = p2
            else:
                cross.append(e)
        edges = cross
        if len(plan_of) == 1:
            return next(iter(plan_of.values()))
        gids = list(plan_of)
        joint = self._join_tree_joint(groups, plan_of, gids, edges, scope,
                                      groupby)
        if joint is not None:
            return joint
        if len(gids) <= 10:
            return self._join_tree_dp(groups, plan_of, gids, edges, scope)
        return self._join_tree_greedy(groups, plan_of, edges, scope)

    def _join_tree_joint(self, groups, plan_of, gids, edges, scope: Scope,
                         groupby) -> Optional[N.PlanNode]:
        """Joint join-order + motion search (plan/memo.joint_search — the
        CJoinOrderDPv2/CMemo marriage): only meaningful distributed with
        the memo enabled; the plain DP remains the fallback whenever the
        search abstains."""
        cfg = self.config
        if cfg is None or cfg.n_segments <= 1 \
                or not cfg.planner.enable_memo:
            return None
        from cloudberry_tpu.plan import memo

        idx_of = {g: i for i, g in enumerate(gids)}
        alias_idx = {a: idx_of[gid] for gid, aliases in groups.items()
                     if gid in idx_of for a in aliases}
        atoms = []
        for g in gids:
            p = plan_of[g]
            atoms.append((p, max(sum(f.type.np_dtype.itemsize
                                     for f in p.fields), 1)))
        bedges = []
        for (a, lx, b, rx) in edges:
            ia, ib = alias_idx.get(a), alias_idx.get(b)
            if ia is None or ib is None or ia == ib:
                continue
            bedges.append((ia, ib, self.bind_scalar(lx, scope),
                           self.bind_scalar(rx, scope)))
        gb_names = set()
        for g in groupby or ():
            try:
                bound = self.bind_scalar(g, scope)
            except BindError:
                continue
            if isinstance(bound, ex.ColumnRef):
                gb_names.add(bound.name)
        final = memo.joint_search(
            atoms, bedges, cfg.n_segments,
            cfg.planner.broadcast_threshold, self.catalog,
            frozenset(gb_names), self._make_join,
            is_unique=lambda i, keys: _build_is_unique(
                atoms[i][0], keys, self.catalog),
            gst=cfg.planner.gather_single_threshold)
        if final is None:
            return None
        for e in scope.entries:
            if e.alias in alias_set_of(groups):
                e.plan = final
        return final

    def _join_tree_dp(self, groups, plan_of, gids, edges, scope: Scope
                      ) -> N.PlanNode:
        """Bushy dynamic-programming join-order search over connected
        subsets (the CJoinOrderDP.cpp move): cost = Σ estimated intermediate
        result sizes; per pair, build/probe orientation prefers a provably
        unique (PK) build side, then the smaller estimate."""
        from cloudberry_tpu.plan import cost as C

        cat = self.catalog
        base = [(1 << i, g) for i, g in enumerate(gids)]
        best: dict[int, tuple[float, N.PlanNode, frozenset]] = {}
        for bit, g in base:
            p = plan_of[g]
            best[bit] = (0.0, p, frozenset(groups[g]))
        full = (1 << len(gids)) - 1
        by_size: dict[int, list[int]] = {}
        for m in range(1, full + 1):
            by_size.setdefault(bin(m).count("1"), []).append(m)
        for size in range(2, len(gids) + 1):
            for m in by_size.get(size, ()):
                s = (m - 1) & m
                while s:
                    o = m ^ s
                    if s > o and s in best and o in best:
                        cand = self._dp_join(best[s], best[o], edges,
                                             scope, cat)
                        if cand is not None and (
                                m not in best or cand[0] < best[m][0]):
                            best[m] = cand
                    s = (s - 1) & m
        if full not in best:
            raise BindError("cross join between FROM items not supported "
                            "(no join condition found)")
        final = best[full][1]
        for e in scope.entries:
            if e.alias in alias_set_of(groups):
                e.plan = final
        return final

    def _dp_join(self, left, right, edges, scope: Scope, cat):
        cost_l, pl, al = left
        cost_r, pr, ar = right
        used = [e for e in edges
                if (e[0] in al and e[2] in ar)
                or (e[2] in al and e[0] in ar)]
        if not used:
            return None  # disconnected: no cross joins
        from cloudberry_tpu.plan import cost as C

        lkeys, rkeys = [], []
        for (a, lx, b, rx) in used:
            if a in al:
                lkeys.append(self.bind_scalar(lx, scope))
                rkeys.append(self.bind_scalar(rx, scope))
            else:
                lkeys.append(self.bind_scalar(rx, scope))
                rkeys.append(self.bind_scalar(lx, scope))
        l_uniq = _build_is_unique(pl, lkeys, cat)
        r_uniq = _build_is_unique(pr, rkeys, cat)
        el = C.estimate_rows(pl, cat)
        er = C.estimate_rows(pr, cat)
        if r_uniq and (not l_uniq or er <= el):
            j = self._make_join("inner", pr, pl, rkeys, lkeys)
        elif l_uniq:
            j = self._make_join("inner", pl, pr, lkeys, rkeys)
        elif er <= el:
            j = self._make_join("inner", pr, pl, rkeys, lkeys)
        else:
            j = self._make_join("inner", pl, pr, lkeys, rkeys)
        est = C.estimate_rows(j, cat)
        return (cost_l + cost_r + est, j, al | ar)

    def _join_tree_greedy(self, groups, plan_of, edges, scope: Scope
                          ) -> N.PlanNode:
        # start from the largest capacity group (the fact side)
        order = sorted(plan_of, key=lambda i: _plan_capacity(plan_of[i]),
                       reverse=True)
        joined_aliases = set(groups[order[0]])
        current = plan_of[order[0]]
        remaining = {i for i in order[1:]}
        edges = list(edges)
        while remaining:
            # connectable groups, with bound keys for both orientations
            candidates = []
            for gid in remaining:
                galiases = groups[gid]
                used = [e for e in edges
                        if (e[0] in joined_aliases and e[2] in galiases)
                        or (e[2] in joined_aliases and e[0] in galiases)]
                if not used:
                    continue
                cur_keys, new_keys = [], []
                for (a, lx, b, rx) in used:
                    if a in joined_aliases:
                        cur_keys.append(self.bind_scalar(lx, scope))
                        new_keys.append(self.bind_scalar(rx, scope))
                    else:
                        cur_keys.append(self.bind_scalar(rx, scope))
                        new_keys.append(self.bind_scalar(lx, scope))
                candidates.append((gid, used, cur_keys, new_keys))
            if not candidates:
                raise BindError("cross join between FROM items not supported "
                                "(no join condition found)")
            # Prefer candidates whose build side is provably unique on the
            # join keys (PK side — join_lookup's contract); among those, the
            # smallest build. Non-unique edges (e.g. Q5's c_nationkey =
            # s_nationkey) are deferred until more edges make them unique.
            def rank(c):
                gid, used, cur_keys, new_keys = c
                other = plan_of[gid]
                uniq = _build_is_unique(other, new_keys, self.catalog)
                return (0 if uniq else 1, _plan_capacity(other))

            candidates.sort(key=rank)
            gid, used, cur_keys, new_keys = candidates[0]
            other = plan_of[gid]
            new_unique = _build_is_unique(other, new_keys, self.catalog)
            cur_unique = _build_is_unique(current, cur_keys, self.catalog)
            for e in used:
                edges.remove(e)
            # orientation: prefer a unique build side (lookup join); with
            # neither unique (expansion join) build the smaller side
            new_smaller = _plan_capacity(other) <= _plan_capacity(current)
            if new_unique and (not cur_unique or new_smaller):
                current = self._make_join("inner", other, current,
                                          new_keys, cur_keys)
            elif cur_unique or not new_smaller:
                current = self._make_join("inner", current, other,
                                          cur_keys, new_keys)
            else:
                current = self._make_join("inner", other, current,
                                          new_keys, cur_keys)
            joined_aliases |= groups[gid]
            remaining.discard(gid)
            for e in scope.entries:
                if e.alias in joined_aliases:
                    e.plan = current
        return current

    def _make_join(self, kind: str, build: N.PlanNode, probe: N.PlanNode,
                   build_keys: list[ex.Expr], probe_keys: list[ex.Expr]
                   ) -> N.PJoin:
        # semi/anti only filter the probe side: no build columns in output
        payload = [f.name for f in build.fields] \
            if kind in ("inner", "left", "full") else []
        match_name = self.gensym("match")
        j = N.PJoin(kind, build, probe, build_keys, probe_keys,
                    payload, match_name)
        # semi/anti joins only test membership — build duplicates are fine;
        # inner/left joins with a non-unique build need pair expansion;
        # FULL joins always expand (both-side unmatched regions)
        if kind == "full" or (kind in ("inner", "left")
                              and not _build_is_unique(build, build_keys,
                                                       self.catalog)):
            j.unique_build = False
            # bcap+pcap is NOT an upper bound for many-to-many fanout; take
            # the NDV-based pair estimate with 2× headroom as a floor
            # (overflow stays a detected error, and the session grows the
            # buffer and retries — nodeHash.c's increase-nbatch discipline)
            from cloudberry_tpu.plan.cost import estimate_rows

            est = estimate_rows(j, self.catalog)
            j._est_pairs = est  # distribution/tiling re-derive from this
            j.out_capacity = max(
                _plan_capacity(build) + _plan_capacity(probe),
                int(2 * est) + 8)
        nm = match_name if kind in ("left", "full") else None
        pm = self.gensym("pmatch") if kind == "full" else None
        j.probe_match_name = pm

        def _merge_mask(new_mask, f):
            # a column nullable through BOTH this join and an earlier source
            # simply carries both mask names (validity = their conjunction)
            masks = ((new_mask,) if new_mask else ()) + f.masks
            return masks or None

        j.fields = [
            N.PlanField(f.name, f.type, f.sdict,
                        null_mask=_merge_mask(pm, f))
            for f in probe.fields] + [
            N.PlanField(f.name, f.type, f.sdict,
                        null_mask=_merge_mask(nm, f))
            for f in build.fields if kind in ("inner", "left", "full")]
        # expose the validity masks as (hidden, $-prefixed) columns so
        # downstream projections can carry them to the result surface
        if nm is not None:
            j.fields.append(N.PlanField(nm, T.BOOL, None))
        if pm is not None:
            j.fields.append(N.PlanField(pm, T.BOOL, None))
        _attach_key_validity(j)
        return j

    def _filter(self, child: N.PlanNode, pred: ex.Expr) -> N.PFilter:
        f = N.PFilter(child, pred)
        f.fields = list(child.fields)
        return f

    # ---------------------------------------------------------- aggregation

    def _bind_agg(self, sel: ast.Select, plan: N.PlanNode, scope: Scope
                  ) -> tuple[N.PlanNode, Scope]:
        group_keys: list[tuple[str, ex.Expr]] = []
        key_mask: dict[str, str] = {}   # key output name -> validity key name
        key_name_by_ast: dict[str, str] = {}
        alias_map = {i.alias: i.expr for i in sel.items if i.alias}
        for g in sel.group_by:
            if isinstance(g, ast.Name) and len(g.parts) == 1 \
                    and g.parts[0] in alias_map:
                g = alias_map[g.parts[0]]
            bound = self.bind_scalar(g, scope)
            name = (bound.name if isinstance(bound, ex.ColumnRef)
                    else self.gensym("k"))
            v = _valid_of(bound)
            if v is not None:
                # NULL group keys: group by (canonical-zero value, validity)
                # — all NULLs form ONE group, distinct from any real value
                # (SQL GROUP BY treats NULLs as equal)
                kv = self.gensym("vmk")
                bound = _masked_key(bound, v)
                group_keys.append((name, bound))
                group_keys.append((kv, ex.Cast(v, T.INT32)))
                key_mask[name] = kv
            else:
                group_keys.append((name, bound))
            key_name_by_ast[_ast_key(g)] = name

        aggs: list[tuple[str, ex.AggCall]] = []
        agg_names: dict[str, str] = {}

        def extract(node: ast.ExprNode) -> ast.ExprNode:
            """Replace aggregate calls with references to agg outputs."""
            if isinstance(node, (ast.ScalarSubquery, ast.InSubquery,
                                 ast.Exists)):
                return node
            if isinstance(node, ast.FuncCall) \
                    and node.name == "stddev_samp":
                # sample stddev via the sum/sum-of-squares/count identity:
                # sqrt((Σx² − (Σx)²/n) / (n−1)); n ≤ 1 yields 0 (SQL: NULL)
                if node.distinct:
                    raise BindError(
                        "stddev_samp(DISTINCT ...) is not supported yet")
                if node.star or not node.args:
                    raise BindError("stddev_samp() requires an argument")
                key = _ast_key(node)
                if key not in agg_names:
                    # accumulate Σx and Σx² in FLOAT64: the integer dtypes
                    # of the column would overflow on the square / its sum
                    arg = self._coerce(
                        self.bind_scalar(node.args[0], scope), T.FLOAT64)
                    sq = ex.BinOp("*", arg, arg, T.FLOAT64)
                    names3 = (self.gensym("agg"), self.gensym("agg"),
                              self.gensym("agg"))
                    aggs.append((names3[0], ex.AggCall("sum", arg)))
                    aggs.append((names3[1], ex.AggCall("sum", sq)))
                    aggs.append((names3[2], ex.AggCall("count", arg)))
                    agg_names[key] = names3
                s_, q_, c_ = agg_names[key]
                sn, qn, cn = (ast.Name((s_,)), ast.Name((q_,)),
                              ast.Name((c_,)))
                var = ast.BinOp(
                    "/",
                    ast.BinOp("-", qn,
                              ast.BinOp("/", ast.BinOp("*", sn, sn), cn)),
                    ast.BinOp("-", cn, ast.NumberLit("1")))
                return ast.FuncCall("sqrt", [var])
            if isinstance(node, ast.FuncCall) and node.name in AGG_FUNCS:
                key = _ast_key(node)
                if key not in agg_names:
                    if node.star:
                        call = ex.AggCall("count", None)
                        agg_names[key] = self.gensym("agg")
                        aggs.append((agg_names[key], call))
                    else:
                        arg = self.bind_scalar(node.args[0], scope)
                        func = node.name
                        # DISTINCT is a no-op for min/max; for count it
                        # renames the func; for sum/avg the flag survives
                        # on the AggCall and _plan_dqa splits it (the
                        # TupleSplit-analog rewrite)
                        distinct = node.distinct and func not in ("min",
                                                                  "max")
                        if func == "count" and distinct:
                            func, distinct = "count_distinct", False
                        if func == "avg" and _valid_of(arg) is not None:
                            # avg over a nullable arg: sum(valid)/count(valid)
                            # — NULL when no valid rows (mask rides on the
                            # sum's companion). avg(DISTINCT x) = sum over
                            # the distinct set / count of the distinct set:
                            # both halves carry the flag into the DQA split
                            s = self.gensym("agg")
                            c2 = self.gensym("agg")
                            aggs.append((s, ex.AggCall(
                                "sum", arg, distinct=distinct)))
                            aggs.append((c2, ex.AggCall(
                                "count", arg, distinct=distinct)))
                            agg_names[key] = ("avg2", s, c2)
                        else:
                            agg_names[key] = self.gensym("agg")
                            aggs.append((agg_names[key], ex.AggCall(
                                func, arg, distinct=distinct)))
                entry = agg_names[key]
                if isinstance(entry, tuple) and entry[0] == "avg2":
                    return ast.BinOp("/", ast.Name((entry[1],)),
                                     ast.Name((entry[2],)))
                return ast.Name((entry,))
            if _ast_key(node) in key_name_by_ast:
                return ast.Name((key_name_by_ast[_ast_key(node)],))
            out = node.__class__(**vars(node))
            for fname, v in vars(node).items():
                if isinstance(v, ast.ExprNode):
                    setattr(out, fname, extract(v))
                elif isinstance(v, list):
                    # OrderItem is a Node, not an ExprNode: recurse into
                    # its expr too, or aggregates inside a window's
                    # OVER(ORDER BY sum(x)) never fold to $agg refs
                    setattr(out, fname, [
                        extract(x) if isinstance(x, ast.ExprNode) else
                        ast.OrderItem(extract(x.expr), x.ascending)
                        if isinstance(x, ast.OrderItem) else
                        tuple(extract(y) if isinstance(y, ast.ExprNode) else y
                              for y in x) if isinstance(x, tuple) else x
                        for x in v])
            return out

        rewritten_items = [(i, extract(i.expr)) for i in sel.items]
        rewritten_having = extract(sel.having) if sel.having else None
        rewritten_order = [(extract(o.expr), o.ascending)
                           for o in sel.order_by]

        if any(c.distinct or c.func == "count_distinct" for _, c in aggs):
            agg = self._plan_dqa(plan, group_keys, key_mask, aggs)
        else:
            aggs, agg_masks = self._mask_nullable_aggs(
                aggs, global_agg=not group_keys)
            agg = N.PAgg(plan, group_keys, aggs,
                         capacity=_agg_capacity(plan, group_keys))
            agg.fields = [
                N.PlanField(n, e.dtype, _expr_dict(e),
                            null_mask=((key_mask[n],)
                                       if n in key_mask else None))
                for n, e in group_keys
            ] + [N.PlanField(n, c.dtype, None,
                             null_mask=((agg_masks[n],)
                                        if n in agg_masks else None))
                 for n, c in aggs]
        plan = agg

        agg_scope = Scope([RangeEntry("$agg", agg)])

        if rewritten_having is not None:
            plan = self._filter(plan, self.bind_scalar(rewritten_having,
                                                       agg_scope))

        if any(_has_window(rw) for _, rw in rewritten_items):
            # windows OVER aggregate outputs (the TPC-DS q98 ratio shape:
            # sum(x) * 100 / sum(sum(x)) over (partition by cls)) — the
            # agg rewrite above already folded inner aggregates to $agg
            # column refs, so the standard extraction runs on top of the
            # aggregation plan with the agg scope
            wsel = ast.Select(items=[ast.SelectItem(rw, i.alias)
                                     for i, rw in rewritten_items])
            plan, wsel = self._extract_windows(wsel, plan, agg_scope)
            agg_scope = self._win_scope
            rewritten_items = [(orig, wi.expr)
                               for (orig, _), wi in zip(rewritten_items,
                                                        wsel.items)]

        exprs: list[tuple[str, ex.Expr]] = []
        fields: list[N.PlanField] = []
        taken: set[str] = set()
        for (item, rw) in rewritten_items:
            bound = self.bind_scalar(rw, agg_scope)
            name = item.alias or _default_name(item.expr) or self.gensym("col")
            name = _uniquify(name, taken)
            exprs.append((name, bound))
            fields.append(_field_for(name, bound))
        exprs, fields = _attach_validity_outputs(self, exprs, fields)
        proj = N.PProject(plan, exprs)
        proj.fields = fields
        # stash rewritten order-by for _bind_output_expr
        self._rewritten_order = {id(o.expr): r
                                 for o, (r, _) in zip(sel.order_by,
                                                      rewritten_order)}
        self._agg_scope = agg_scope
        return proj, agg_scope

    def _bind_projection(self, sel: ast.Select, plan: N.PlanNode,
                         scope: Scope) -> N.PlanNode:
        if any(_has_window(i.expr) for i in sel.items):
            plan, sel = self._extract_windows(sel, plan, scope)
            scope = self._win_scope
        exprs: list[tuple[str, ex.Expr]] = []
        fields: list[N.PlanField] = []
        taken: set[str] = set()
        seen_sources: set[str] = set()
        for item in sel.items:
            if isinstance(item.expr, ast.Star):
                for e in scope.entries:
                    if item.expr.table and e.alias != item.expr.table:
                        continue
                    for f in e.plan.fields:
                        if f.name in seen_sources \
                                or f.name.split(".")[-1].startswith("$"):
                            # merged-plan dupes / masks / internal columns
                            continue
                        seen_sources.add(f.name)
                        name = _uniquify(f.name.split(".")[-1], taken)
                        exprs.append((name, _colref(f)))
                        fields.append(N.PlanField(
                            name, f.type, f.sdict,
                            null_mask=f.null_mask))
                continue
            bound = self.bind_scalar(item.expr, scope)
            name = item.alias or _default_name(item.expr) or self.gensym("col")
            name = _uniquify(name, taken)
            exprs.append((name, bound))
            fields.append(_field_for(name, bound))
        # nullable outputs: project their validity masks as hidden columns
        # ("$vm..."), so NULLs render correctly at the result surface
        exprs, fields = _attach_validity_outputs(self, exprs, fields)
        proj = N.PProject(plan, exprs)
        proj.fields = fields
        self._rewritten_order = {}
        self._agg_scope = None
        return proj

    WINDOW_FUNCS = {"row_number", "rank", "dense_rank", "sum", "count",
                    "avg", "min", "max", "ntile", "lead", "lag",
                    "first_value", "last_value"}
    # positional window funcs read another row of the partition; their
    # NULL story is per-row (source row missing or invalid), not
    # frame-aggregate, so they get '<func>@mask' companion calls
    POSITIONAL_WINDOW_FUNCS = {"lead", "lag", "first_value", "last_value"}

    def _extract_windows(self, sel: ast.Select, plan: N.PlanNode,
                         scope: Scope):
        """Pull WindowExpr nodes out of the select list into PWindow nodes
        (one per distinct OVER spec), rewriting items to reference the new
        columns (the WindowAgg planning step)."""
        specs: dict[str, tuple] = {}

        def replace(node):
            if isinstance(node, ast.WindowExpr):
                if node.func not in self.WINDOW_FUNCS:
                    raise BindError(f"unknown window function {node.func!r}")
                frame = _normalize_frame(node.frame)
                key = _ast_key(ast.Select(
                    items=[], group_by=list(node.partition_by),
                    order_by=list(node.order_by))) + f"|{frame}"
                if key not in specs:
                    specs[key] = (node.partition_by, node.order_by, [],
                                  frame)
                name = self.gensym("win")
                specs[key][2].append((name, node.func, list(node.args)))
                return ast.Name((name,))
            if not isinstance(node, ast.Node) or isinstance(
                    node, (ast.ScalarSubquery, ast.InSubquery, ast.Exists)):
                return node
            out = node.__class__(**vars(node))
            for k, v in vars(node).items():
                if isinstance(v, ast.ExprNode):
                    setattr(out, k, replace(v))
                elif isinstance(v, list):
                    setattr(out, k, [
                        replace(x) if isinstance(x, ast.ExprNode) else
                        tuple(replace(y) if isinstance(y, ast.ExprNode)
                              else y for y in x) if isinstance(x, tuple)
                        else x for x in v])
            return out

        new_items = [ast.SelectItem(replace(i.expr), i.alias)
                     for i in sel.items]
        for part_asts, order_asts, calls, frame in specs.values():
            pk = []
            for a in part_asts:
                bound = self.bind_scalar(a, scope)
                v = _valid_of(bound)
                if v is not None:
                    # NULL partition keys form ONE partition, distinct from
                    # any real value: (canonical-zero value, validity) pair
                    # — same discipline as GROUP BY (_masked_key)
                    pk.append(_masked_key(bound, v))
                    pk.append(ex.Cast(v, T.INT32))
                else:
                    pk.append(bound)
            okeys = []
            for o in order_asts:
                bound = self.bind_scalar(o.expr, scope)
                v = _valid_of(bound)
                if v is not None:
                    # NULLs order as largest (same rule as PSort keys)
                    okeys.append((ex.Cast(ex.UnaryOp("not", v, T.BOOL),
                                          T.INT32), o.ascending))
                    okeys.append((_masked_key(bound, v), o.ascending))
                else:
                    okeys.append((bound, o.ascending))
            if frame is not None and frame[0] == "rangeoff":
                frame = _check_rangeoff(frame, order_asts, okeys)
            bound_calls = []
            call_valids = []
            call_params = []
            new_fields = []
            mask_by_valid: dict[str, str] = {}
            # a ROWS/RANGE-offset frame that can exclude the current row
            # can be EMPTY: aggregates over it are NULL, so their
            # outputs need masks even over non-null arguments. A
            # ("months", n) calendar offset unwraps to its signed month
            # count for this test (shifting by +n months excludes the
            # current row exactly when n > 0).
            def _off_sign(o):
                return o[1] if isinstance(o, tuple) else o

            frame_may_empty = (frame is not None
                               and frame[0] in ("rows", "rangeoff")
                               and ((frame[1] is not None
                                     and _off_sign(frame[1]) > 0)
                                    or (frame[2] is not None
                                        and _off_sign(frame[2]) < 0)))
            for name, func, arg_asts in calls:
                params = None
                if func == "ntile":
                    if len(arg_asts) != 1:
                        raise BindError("ntile(n) takes exactly one "
                                        "argument")
                    nb = self.bind_scalar(arg_asts[0], scope)
                    if not isinstance(nb, ex.Literal) \
                            or not isinstance(nb.value, int) \
                            or isinstance(nb.value, bool) or nb.value <= 0:
                        raise BindError("ntile(n): n must be a positive "
                                        "integer constant")
                    params = {"n": int(nb.value)}
                    arg = None
                elif func in ("lead", "lag"):
                    if not 1 <= len(arg_asts) <= 3:
                        raise BindError(
                            f"{func}(value [, offset [, default]])")
                    arg = self.bind_scalar(arg_asts[0], scope)
                    off = 1
                    if len(arg_asts) >= 2:
                        ob = self.bind_scalar(arg_asts[1], scope)
                        if not isinstance(ob, ex.Literal) \
                                or not isinstance(ob.value, int) \
                                or isinstance(ob.value, bool) \
                                or ob.value < 0:
                            raise BindError(
                                f"{func}: offset must be a non-negative "
                                "integer constant")
                        off = int(ob.value)
                    dflt = None
                    if len(arg_asts) == 3:
                        db = self.bind_scalar(arg_asts[2], scope)
                        # an explicit NULL default IS the no-default case
                        # (out-of-range -> NULL via the '@mask' companion)
                        if _is_null_literal(db):
                            db = None
                        elif not isinstance(db, ex.Literal):
                            raise BindError(
                                f"{func}: default must be a constant")
                        elif _expr_dict(arg) is not None:
                            if db.dtype.base != DType.STRING \
                                    or not isinstance(db.value, str):
                                raise BindError(
                                    f"{func}: default for a string "
                                    "argument must be a string")
                            # encode into the argument's dictionary
                            # (append-only: existing codes unchanged)
                            db = ex.Literal(
                                _expr_dict(arg).add(db.value), T.STRING)
                        elif db.dtype.base != arg.dtype.base:
                            db = ex.Cast(db, arg.dtype)
                        dflt = db
                    params = {"offset": off, "default": dflt}
                elif func in ("first_value", "last_value") \
                        and len(arg_asts) != 1:
                    raise BindError(f"{func}(value) takes exactly one "
                                    "argument")
                else:
                    arg = self.bind_scalar(arg_asts[0], scope) \
                        if arg_asts else None
                valid = _valid_of(arg) if arg is not None else None
                if valid is not None:
                    # NULL args never contribute: sum/avg zero-fill the
                    # value (the executor additionally restricts sums to
                    # valid lanes and divides avg by the valid count);
                    # min/max exclude invalid lanes executor-side by
                    # worst-rank substitution — a value-space identity
                    # fill would be unsound for strings, whose sort order
                    # is collation rank, not code order
                    if func in ("sum", "avg"):
                        z = 0.0 if arg.dtype.base == DType.FLOAT64 else 0
                        arg = ex.CaseWhen(((valid, arg),),
                                          ex.Literal(z, arg.dtype), arg.dtype)
                if func in ("row_number", "rank", "dense_rank", "count",
                            "ntile"):
                    t = T.INT64
                elif func == "avg":
                    t = T.FLOAT64
                else:
                    assert arg is not None, f"{func}() needs an argument"
                    t = arg.dtype
                sd = _expr_dict(arg) if func in (
                    "min", "max", "lead", "lag", "first_value",
                    "last_value") and arg is not None else None
                bound_calls.append((name, func, arg))
                call_valids.append(valid)
                call_params.append(params)
                if func in self.POSITIONAL_WINDOW_FUNCS and (
                        valid is not None
                        or (func in ("lead", "lag")
                            and params["default"] is None)
                        or (func in ("first_value", "last_value")
                            and frame_may_empty)):
                    # per-row null mask: the source row may fall outside
                    # the partition (lead/lag without a default) or hold
                    # an invalid value — both positional facts only the
                    # executor can see, so a '<func>@mask' pseudo-call
                    # computes the bool mask alongside the value
                    mname = self.gensym("vmw")
                    bound_calls.append((mname, func + "@mask", None))
                    call_valids.append(valid)
                    call_params.append(params)
                    new_fields.append(N.PlanField(mname, T.BOOL, None))
                    new_fields.append(
                        N.PlanField(name, t, sd, null_mask=(mname,)))
                elif (valid is not None or frame_may_empty) \
                        and func in ("sum", "min", "max", "avg"):
                    # agg over an all-NULL frame is NULL — materialize the
                    # frame's any-valid as this output's hidden null mask
                    # (one mask per distinct validity expr, shared by every
                    # call over the same argument)
                    vkey = repr(valid)
                    mname = mask_by_valid.get(vkey)
                    if mname is None:
                        mname = mask_by_valid[vkey] = self.gensym("vmw")
                        bound_calls.append((mname, "anyvalid", None))
                        call_valids.append(valid)
                        call_params.append(None)
                        new_fields.append(N.PlanField(mname, T.BOOL, None))
                    new_fields.append(
                        N.PlanField(name, t, sd, null_mask=(mname,)))
                else:
                    new_fields.append(N.PlanField(name, t, sd))
            w = N.PWindow(plan, pk, okeys, bound_calls, call_valids,
                          call_params, frame)
            w.fields = list(plan.fields) + new_fields
            plan = w
        # window outputs resolve by exact generated name; rebind existing
        # entries onto the window plan so resolve()'s dedupe sees one source
        for e in scope.entries:
            if _plan_contains(plan, e.plan):
                e.plan = plan
        scope = Scope(list(scope.entries) + [RangeEntry("$win", plan)])
        sel2 = ast.Select(items=new_items, from_refs=sel.from_refs,
                          order_by=sel.order_by, limit=sel.limit,
                          offset=sel.offset, distinct=sel.distinct)
        self._win_scope = scope
        return plan, sel2

    def _bind_output_expr(self, e: ast.ExprNode, plan: N.PlanNode,
                          scope: Scope) -> ex.Expr:
        """Bind an ORDER BY expr: select aliases/outputs first, then scope."""
        if isinstance(e, ast.Name) and len(e.parts) == 1:
            for f in plan.fields:
                if f.name == e.parts[0]:
                    return _colref(f)  # keeps dictionary + null mask
        rw = getattr(self, "_rewritten_order", {}).get(id(e))
        if rw is not None and self._agg_scope is not None:
            try:
                return self.bind_scalar(rw, self._agg_scope)
            except BindError:
                pass
        out_scope = Scope([RangeEntry("$out",
                                      _fields_only_plan(plan.fields))])
        try:
            return self.bind_scalar(e, out_scope)
        except BindError:
            return self.bind_scalar(e, scope)

    # ----------------------------------------------------------- expressions

    def bind_scalar(self, node: ast.ExprNode, scope: Scope) -> ex.Expr:
        b = lambda n: self.bind_scalar(n, scope)

        if isinstance(node, ast.Name):
            _, f = scope.resolve(node.parts)
            return _colref(f)

        if isinstance(node, ast.NumberLit):
            return _bind_number(node.text)

        if isinstance(node, ast.StringLit):
            # bare string literal: binds to a code only in comparison context;
            # keep as python-string literal for the comparison rewriter
            return ex.Literal(node.value, T.STRING)

        if isinstance(node, ast.BoolLit):
            return ex.Literal(node.value, T.BOOL)

        if isinstance(node, ast.DateLit):
            return ex.Literal(T.date_to_days(node.value), T.DATE)

        if isinstance(node, ast.IntervalLit):
            raise BindError("interval literal only valid in date arithmetic")

        if isinstance(node, ast.NullLit):
            return _null_literal(T.INT64)

        if isinstance(node, ast.UnaryOp):
            if node.op == "not":
                return self._not_expr(b(node.operand))
            operand = b(node.operand)
            if node.op == "+":
                return operand
            if isinstance(operand, ex.Literal):
                out: ex.Expr = ex.Literal(-operand.value, operand.dtype)
            else:
                out = ex.UnaryOp("-", operand, operand.dtype)
            return _set_valid(out, _valid_of(operand))

        if isinstance(node, ast.BinOp):
            return self._bind_binop(node, scope)

        if isinstance(node, ast.Between):
            lo = ast.BinOp(">=", node.expr, node.low)
            hi = ast.BinOp("<=", node.expr, node.high)
            both = ast.BinOp("and", lo, hi)
            out = self.bind_scalar(both, scope)
            if node.negated:
                return self._not_expr(out)
            return out

        if isinstance(node, ast.InList):
            e = b(node.expr)
            if e.dtype.base == DType.STRING and all(
                    isinstance(it, ast.StringLit) for it in node.items):
                sdict = _require_dict(e)
                values = {it.value for it in node.items}
                table = sdict.predicate_table(lambda v: v in values)
                out: ex.Expr = ex.DictLookup(e, table)
                v = _valid_of(e)
                if v is not None:
                    out = _set_valid(ex.BinOp("and", out, v, T.BOOL), v)
            else:
                cmps = [self._bind_binop(ast.BinOp("=", node.expr, it), scope)
                        for it in node.items]
                out = cmps[0]
                for c in cmps[1:]:
                    out = self._logic("or", out, c)
            if node.negated:
                return self._not_expr(out)
            return out

        if isinstance(node, ast.Like):
            e = b(node.expr)
            sdict = _require_dict(e)
            out = ex.DictLookup(e, sdict.like_table(node.pattern))
            v = _valid_of(e)
            if v is not None:
                out = _set_valid(ex.BinOp("and", out, v, T.BOOL), v)
            if node.negated:
                return self._not_expr(out)
            return out

        if isinstance(node, ast.IsNull):
            e = b(node.operand)
            v = _valid_of(e)
            if v is None:
                # provably non-null: IS NULL is constant false
                return ex.Literal(bool(node.negated), T.BOOL)
            # v itself is never NULL, so no is-true wrapping needed
            return v if node.negated else ex.UnaryOp("not", v, T.BOOL)

        if isinstance(node, ast.CaseExpr):
            whens = [(b(c), b(v)) for c, v in node.whens]
            otherwise = b(node.otherwise) if node.otherwise else None
            return self._bind_case(whens, otherwise)

        if isinstance(node, ast.ExtractExpr):
            e = b(node.operand)
            if e.dtype.base != DType.DATE:
                raise BindError("EXTRACT requires a date operand")
            return _set_valid(ex.Func(f"extract_{node.part}", (e,), T.INT32),
                              _valid_of(e))

        if isinstance(node, ast.CastExpr):
            e = b(node.operand)
            t = T.SQL_TYPE_MAP.get(node.type_name)
            if t is None:
                raise BindError(f"unknown type {node.type_name!r}")
            if t.base == DType.DECIMAL and node.scale is not None:
                t = T.DECIMAL(node.scale)
            if _is_null_literal(e):
                return _null_literal(t)
            return _set_valid(ex.Cast(e, t), _valid_of(e))

        if isinstance(node, ast.SubstringExpr):
            return self._bind_substring(node, scope)

        if isinstance(node, ast.ScalarSubquery):
            return self._bind_uncorrelated_scalar(node)

        if isinstance(node, ast.FuncCall):
            if node.name == "coalesce":
                return self._bind_coalesce(node, scope)
            if node.name == "sqrt":
                arg = self._coerce(b(node.args[0]), T.FLOAT64)
                return _set_valid(ex.Func("sqrt", (arg,), T.FLOAT64),
                                  _valid_of(arg))
            if node.name in AGG_FUNCS:
                raise BindError(f"aggregate {node.name}() not allowed here")
            from cloudberry_tpu.exec import udf as U

            u = U.lookup(node.name)
            if u is not None:
                return self._bind_udf(u, node, scope)
            raise BindError(
                f"unknown function {node.name!r} (register scalar "
                "functions with cloudberry_tpu.exec.udf."
                "register_function)")

        raise BindError(f"unsupported expression {type(node).__name__}")

    def _not_expr(self, e: ex.Expr) -> ex.Expr:
        """NOT under 3VL, is-true normalized: NOT x is TRUE iff x is valid
        and false; NULL stays NULL (excluded by filters)."""
        v = _valid_of(e)
        out: ex.Expr = ex.UnaryOp("not", e, T.BOOL)
        if v is not None:
            out = ex.BinOp("and", out, v, T.BOOL)
        return _set_valid(out, v)

    def _logic(self, op: str, l: ex.Expr, r: ex.Expr) -> ex.Expr:
        """AND/OR under Kleene 3VL over is-true normalized operands: the
        plain BinOp value is already the correct is-TRUE; validity records
        when the 3VL result is non-NULL (e.g. FALSE AND NULL is known)."""
        out: ex.Expr = ex.BinOp(op, l, r, T.BOOL)
        vl, vr = _valid_of(l), _valid_of(r)
        if vl is None and vr is None:
            return out
        both = _and_valid(vl, vr) or ex.Literal(True, T.BOOL)
        if op == "and":
            def known_false(x, vx):
                nx = ex.UnaryOp("not", x, T.BOOL)
                return nx if vx is None else ex.BinOp("and", vx, nx, T.BOOL)

            valid = ex.BinOp(
                "or", ex.BinOp("or", both, known_false(l, vl), T.BOOL),
                known_false(r, vr), T.BOOL)
        else:
            # OR known if both sides known, or either is TRUE (is-true
            # normalized values already imply validity)
            valid = ex.BinOp("or", ex.BinOp("or", both, l, T.BOOL), r,
                             T.BOOL)
        return _set_valid(out, valid)

    def _bind_case(self, whens, otherwise) -> ex.Expr:
        """CASE under 3VL: NULL conditions fall through (automatic with
        is-true normalized conditions); a missing ELSE is an implicit NULL;
        result validity mirrors the CASE over branch validities."""
        result_exprs = [v for _, v in whens] + (
            [otherwise] if otherwise is not None else [])
        non_null = [e for e in result_exprs if not _is_null_literal(e)]
        if any(e.dtype.base == DType.STRING for e in non_null):
            out = self._bind_string_case(whens, otherwise, non_null)
        else:
            rtype = _common_type([e.dtype for e in non_null]) if non_null \
                else T.INT64
            cw = tuple(
                (c, _null_literal(rtype) if _is_null_literal(v)
                 else self._coerce(v, rtype)) for c, v in whens)
            other = None if otherwise is None else (
                _null_literal(rtype) if _is_null_literal(otherwise)
                else self._coerce(otherwise, rtype))
            out = ex.CaseWhen(cw, other, rtype)
        branch_vs = [_valid_of(v) for _, v in out.whens]
        vo = _valid_of(out.otherwise) if out.otherwise is not None else None
        if out.otherwise is not None and not getattr(
                out, "_implicit_null_else", False) \
                and vo is None and all(v is None for v in branch_vs):
            return out  # no branch can produce NULL
        true_lit = ex.Literal(True, T.BOOL)
        vwhens = tuple((c, v if v is not None else true_lit)
                       for (c, _), v in zip(out.whens, branch_vs))
        if out.otherwise is None or getattr(out, "_implicit_null_else",
                                            False):
            votherwise: ex.Expr = ex.Literal(False, T.BOOL)
        else:
            votherwise = vo if vo is not None else true_lit
        return _set_valid(out, ex.CaseWhen(vwhens, votherwise, T.BOOL))

    def _bind_string_case(self, whens, otherwise, result_exprs) -> ex.Expr:
        """CASE yielding strings: literal results get codes in an output
        dictionary; non-literal results must share ONE dictionary, which the
        output dictionary extends (so their codes pass through unchanged —
        the UPDATE col = CASE WHEN … THEN 'lit' ELSE col END shape)."""
        col_dicts = {id(_expr_dict(e)): _expr_dict(e)
                     for e in result_exprs
                     if not isinstance(e, ex.Literal)
                     and _expr_dict(e) is not None}
        if any(not isinstance(e, ex.Literal) and _expr_dict(e) is None
               for e in result_exprs):
            raise BindError("string CASE branch has no dictionary")
        if len(col_dicts) > 1:
            raise BindError("string CASE mixing columns from different "
                            "dictionaries is not supported yet")
        base = next(iter(col_dicts.values()), None)
        out_dict = StringDictionary(base.values if base else ())

        def enc(e):
            if _is_null_literal(e):
                lit = ex.Literal(-1, T.STRING)  # code -1: masked at render
                object.__setattr__(lit, "_is_null_lit", True)
                object.__setattr__(lit, "_null_expr",
                                   ex.Literal(False, T.BOOL))
                return lit
            if isinstance(e, ex.Literal):
                return ex.Literal(out_dict.add(e.value), T.STRING)
            return e  # column codes valid: out_dict extends its dictionary

        whens = tuple((c, enc(v)) for c, v in whens)
        implicit_null = otherwise is None
        otherwise_e = enc(otherwise) if otherwise is not None else \
            ex.Literal(-1, T.STRING)
        out = ex.CaseWhen(whens, otherwise_e, T.STRING)
        object.__setattr__(out, "_out_dict", out_dict)
        if implicit_null:
            object.__setattr__(out, "_implicit_null_else", True)
        return out

    def _mask_nullable_aggs(self, aggs, global_agg: bool):
        """Make aggregates NULL-correct:
        - count(x) over a nullable x counts only valid rows (sum of 0/1);
        - sum/min/max over a nullable x aggregate identity-filled values and
          gain a hidden companion counting valid rows — zero valid rows
          means the SQL result is NULL (the companion is the output's mask);
        - with no GROUP BY, sum/min/max/avg over an EMPTY input are NULL,
          so they gain a row-count companion even for non-null args.
        Only standard funcs come out, so the distributed partial/final agg
        split (plan/distribute.py) needs no NULL knowledge at all."""
        out: list[tuple[str, ex.AggCall]] = []
        masks: dict[str, str] = {}
        one = ex.Literal(1, T.INT64)
        zero = ex.Literal(0, T.INT64)
        for name, call in aggs:
            v = _valid_of(call.arg) if call.arg is not None else None
            if call.func == "count" and call.arg is not None \
                    and v is not None:
                out.append((name, ex.AggCall(
                    "sum", ex.CaseWhen(((v, one),), zero, T.INT64))))
                continue
            if call.func in ("sum", "min", "max") \
                    and (v is not None or global_agg):
                arg = call.arg
                if v is not None:
                    if call.func == "sum":
                        ident = 0.0 if arg.dtype.base == DType.FLOAT64 else 0
                    else:
                        ident = _dtype_extreme(arg.dtype,
                                               want_max=(call.func == "min"))
                    arg = ex.CaseWhen(((v, arg),),
                                      ex.Literal(ident, arg.dtype), arg.dtype)
                out.append((name, ex.AggCall(call.func, arg)))
                comp = self.gensym("vma")
                if v is not None:
                    out.append((comp, ex.AggCall(
                        "sum", ex.CaseWhen(((v, one),), zero, T.INT64))))
                else:
                    out.append((comp, ex.AggCall("count", None)))
                masks[name] = comp
                continue
            if call.func == "avg" and global_agg and v is None:
                comp = self.gensym("vma")
                out.append((name, call))
                out.append((comp, ex.AggCall("count", None)))
                masks[name] = comp
                continue
            out.append((name, call))
        return out, masks

    def _plan_dqa(self, plan, group_keys, key_mask, aggs):
        """Distinct-qualified aggregates — the TupleSplit / multi-DQA
        analog (reference: src/backend/executor/nodeTupleSplit.c:1-281
        tuple routing, src/backend/cdb/cdbgroupingpaths.c 2/3-stage DQA
        plans). The reference replicates every input tuple once per DQA
        and routes each copy through its own distinct-ification; the
        one-XLA-program redesign instead plans one aggregation subplan
        per distinct ARGUMENT class — inner distinct-on-(group keys,
        arg), then the outer aggregate over the deduplicated rows —
        plus one subplan for the plain aggregates, all over a
        materialize-once shared input (PShare), and zips the
        per-subplan results with 1:1 unique-build joins on the
        canonicalized group keys. Every subplan emits exactly one row
        per group (and global aggregates exactly one row total), so the
        zip is loss-free; NULL group keys join exactly because keys
        ride as (canonical value, validity) pairs — the discipline
        GROUP BY itself uses. A nullable DQA argument becomes a
        (canonical value, validity) inner key pair; the outer aggregate
        then NULL-masks through the standard _mask_nullable_aggs path
        (count skips the NULL group, sum/avg identity-fill it)."""
        def _is_dqa(c: ex.AggCall) -> bool:
            return c.distinct or c.func == "count_distinct"

        plain = [(n, c) for n, c in aggs if not _is_dqa(c)]
        classes: dict[str, list] = {}
        for n, c in aggs:
            if _is_dqa(c):
                if c.arg is None:
                    raise BindError("DISTINCT aggregate requires an "
                                    "argument")
                classes.setdefault(repr(c.arg), []).append((n, c))
        nsub = len(classes) + (1 if plain else 0)

        def _src() -> N.PlanNode:
            if nsub == 1:
                return plan
            sh = N.PShare(plan)  # scan once, feed every subplan
            sh.fields = list(plan.fields)
            return sh

        def _key_fields(keys) -> list:
            return [N.PlanField(n, e.dtype, _expr_dict(e),
                                null_mask=((key_mask[n],)
                                           if n in key_mask else None))
                    for n, e in keys]

        subs: list[N.PlanNode] = []
        if plain:
            p_aggs, p_masks = self._mask_nullable_aggs(
                plain, global_agg=not group_keys)
            src = _src()
            sub = N.PAgg(src, list(group_keys), p_aggs,
                         capacity=_agg_capacity(src, group_keys))
            sub.fields = _key_fields(group_keys) + [
                N.PlanField(n, c.dtype, None,
                            null_mask=((p_masks[n],)
                                       if n in p_masks else None))
                for n, c in p_aggs]
            subs.append(sub)
        for members in classes.values():
            arg = members[0][1].arg
            src = _src()
            aname = self.gensym("darg")
            inner_keys = list(group_keys)
            mask_of: dict[str, tuple] = {}
            v = _valid_of(arg)
            if v is None:
                inner_keys.append((aname, arg))
            else:
                avname = self.gensym("vmk")
                inner_keys.append((aname, _masked_key(arg, v)))
                inner_keys.append((avname, ex.Cast(v, T.INT32)))
                mask_of[aname] = (avname,)
            inner = N.PAgg(src, inner_keys, [],
                           capacity=_agg_capacity(src, inner_keys))
            inner.fields = [N.PlanField(n, e.dtype, _expr_dict(e),
                                        null_mask=mask_of.get(n))
                            for n, e in inner_keys]
            new_group = [(n, _colref(inner.field(n)))
                         for n, _ in group_keys]
            out_aggs = []
            for name, c in members:
                of = "count" if c.func == "count_distinct" else c.func
                out_aggs.append((name, ex.AggCall(
                    of, _colref(inner.field(aname)))))
            out_aggs, o_masks = self._mask_nullable_aggs(
                out_aggs, global_agg=not group_keys)
            outer = N.PAgg(inner, new_group, out_aggs,
                           capacity=_agg_capacity(inner, new_group))
            outer.fields = _key_fields(new_group) + [
                N.PlanField(n, c.dtype, None,
                            null_mask=((o_masks[n],)
                                       if n in o_masks else None))
                for n, c in out_aggs]
            subs.append(outer)

        if len(subs) == 1:
            return subs[0]
        key_names = [n for n, _ in group_keys]
        if not group_keys:
            # global aggregates: each subplan emits exactly ONE row —
            # zip them on a projected constant key
            key_names = ["$dqaone"]
            zipped = []
            for sub in subs:
                pr = N.PProject(sub, [(f.name,
                                       ex.ColumnRef(f.name, f.type))
                                      for f in sub.fields]
                                + [("$dqaone", ex.Literal(1, T.INT64))])
                pr.fields = list(sub.fields) + [
                    N.PlanField("$dqaone", T.INT64, None)]
                zipped.append(pr)
            subs = zipped
        acc = subs[0]
        for nxt in subs[1:]:
            bkeys = [ex.ColumnRef(n, nxt.field(n).type)
                     for n in key_names]
            pkeys = [ex.ColumnRef(n, acc.field(n).type)
                     for n in key_names]
            payload = [f.name for f in nxt.fields
                       if f.name not in key_names]
            j = N.PJoin("inner", nxt, acc, bkeys, pkeys, payload, None,
                        unique_build=True)
            j.fields = list(acc.fields) + [f for f in nxt.fields
                                           if f.name not in key_names]
            acc = j
        return acc

    # -------------------------------------------------- subquery predicates
    # The cdbsubselect.c analog: EXISTS/IN/scalar subqueries in WHERE become
    # semi/anti/inner joins against a (possibly grouped) subplan.

    def _apply_subquery_pred(self, pred: ast.ExprNode, plan: N.PlanNode,
                             scope: Scope) -> N.PlanNode:
        negated = False
        node = pred
        if isinstance(node, ast.UnaryOp) and node.op == "not":
            negated = True
            node = node.operand
        if isinstance(node, ast.Exists):
            return self._apply_exists(node.select, plan, scope,
                                      negated or node.negated)
        if isinstance(node, ast.InSubquery):
            return self._apply_in_subquery(node, plan, scope,
                                           negated != node.negated)
        if isinstance(node, ast.BinOp) and node.op in (
                "=", "<>", "<", "<=", ">", ">="):
            out = self._apply_scalar_comparison(node, plan, scope, negated)
            if out is not None:
                return out
        # fallback: bind as a plain filter (uncorrelated scalar subqueries
        # inside arbitrary expressions)
        return self._filter(plan, self.bind_scalar(pred, scope))

    def _bind_uncorrelated_scalar(self, node: ast.ScalarSubquery) -> ex.Expr:
        sub = Binder(self.catalog, self.config)
        sub._counter = self._counter + 1000
        sub._ctes = self._ctes
        plan = sub.bind_select(node.select)
        ufs = _user_fields(plan)  # hidden $vm mask outputs don't count
        if len(ufs) != 1:
            raise BindError("scalar subquery must return one column")
        f = ufs[0]
        one_row = _one_row_guaranteed(node.select)
        if not f.masks and one_row:
            e = ex.SubqueryScalar(plan, f.type)
            if f.sdict is not None:
                object.__setattr__(e, "_sdict", f.sdict)
            return e
        # nullable scalar: the value and its validity terms are separate
        # scalar subqueries over ONE shared subplan (PShare → computed
        # once); validity then composes like any other expression's.
        # Validity terms: presence (0 rows → NULL, unless the subquery is
        # an ungrouped aggregate, which always yields exactly one row) AND
        # the value's own mask (the single row's value may be NULL).
        share_v = N.PShare(plan)
        share_v.fields = list(plan.fields)
        vproj = N.PProject(share_v, [(f.name, ex.ColumnRef(f.name, f.type))])
        vproj.fields = [N.PlanField(f.name, f.type, f.sdict)]
        e = ex.SubqueryScalar(vproj, f.type)
        if f.sdict is not None:
            object.__setattr__(e, "_sdict", f.sdict)
        vterms = []
        if not one_row:
            share_p = N.PShare(plan)
            share_p.fields = list(plan.fields)
            vterms.append(ex.SubqueryScalar(share_p, T.BOOL, "exists"))
        if f.masks:
            share_m = N.PShare(plan)
            share_m.fields = list(plan.fields)
            mname = self.gensym("sqv")
            mproj = N.PProject(share_m, [(mname, ex.IsValid(f.masks))])
            mproj.fields = [N.PlanField(mname, T.BOOL, None)]
            vterms.append(ex.SubqueryScalar(mproj, T.BOOL))
        return _set_valid(e, _and_valid(*vterms))

    def _scratch_inner_scope(self, sub: ast.Select) -> Scope:
        inner = Scope()
        sb = Binder(self.catalog, self.config)
        sb._counter = self._counter + 2000
        sb._ctes = self._ctes
        dump: list = []
        for ref in sub.from_refs:
            sb.bind_table_ref(ref, inner, dump)
        return inner

    def _split_correlation(self, sub: ast.Select, outer: Scope):
        """Partition the subquery's WHERE into (corr_pairs, inner_conjs,
        residual_conjs): corr_pairs are inner=outer equi conditions,
        residuals reference both sides non-equi."""
        inner = self._scratch_inner_scope(sub)

        def owner(e: ast.ExprNode) -> str:
            owners = set()

            def walk(n):
                if isinstance(n, ast.Select):
                    return  # nested subquery: resolved when it is bound
                if isinstance(n, ast.Name):
                    try:
                        inner.resolve(n.parts)
                        owners.add("inner")
                        return
                    except BindError:
                        pass
                    outer.resolve(n.parts)  # raises if unknown anywhere
                    owners.add("outer")
                for v in vars(n).values() if isinstance(n, ast.Node) else ():
                    if isinstance(v, ast.Node):
                        walk(v)
                    elif isinstance(v, (list, tuple)):
                        for x in v:
                            if isinstance(x, ast.Node):
                                walk(x)
                            elif isinstance(x, tuple):
                                for y in x:
                                    if isinstance(y, ast.Node):
                                        walk(y)

            walk(e)
            if not owners:
                return "none"
            if owners == {"inner"}:
                return "inner"
            if owners == {"outer"}:
                return "outer"
            return "mixed"

        corr_pairs: list[tuple[ast.ExprNode, ast.ExprNode]] = []  # (outer, inner)
        inner_conjs: list[ast.ExprNode] = []
        residual: list[ast.ExprNode] = []
        for c in _split_conjuncts(sub.where):
            o = owner(c)
            if o in ("inner", "none"):
                inner_conjs.append(c)
            elif o == "outer":
                residual.append(c)
            elif isinstance(c, ast.BinOp) and c.op == "=" \
                    and owner(c.left) in ("inner", "outer") \
                    and owner(c.right) in ("inner", "outer") \
                    and owner(c.left) != owner(c.right):
                if owner(c.left) == "outer":
                    corr_pairs.append((c.left, c.right))
                else:
                    corr_pairs.append((c.right, c.left))
            else:
                residual.append(c)
        return inner, corr_pairs, inner_conjs, residual

    def _mangle_inner(self, nodes_: list[ast.ExprNode], inner: Scope):
        """Collect inner column references in ``nodes_`` → (select items
        materializing them, rewrite fn replacing them with mangled names)."""
        tag = self.gensym("sq").strip("$")
        mapping: dict[str, str] = {}   # inner physical name -> mangled
        items: list[ast.SelectItem] = []

        def mangle_of(parts) -> Optional[str]:
            try:
                _, f = inner.resolve(parts)
            except BindError:
                return None
            if f.name not in mapping:
                m = f"${tag}_{len(mapping)}"
                mapping[f.name] = m
                items.append(ast.SelectItem(ast.Name(parts), m))
            return mapping[f.name]

        def rewrite(n):
            if isinstance(n, ast.Name):
                m = mangle_of(n.parts)
                return ast.Name((m,)) if m is not None else n
            if not isinstance(n, ast.Node):
                return n
            out = n.__class__(**vars(n))
            for k, v in vars(n).items():
                if isinstance(v, ast.Node):
                    setattr(out, k, rewrite(v))
                elif isinstance(v, list):
                    setattr(out, k, [
                        rewrite(x) if isinstance(x, ast.Node) else
                        tuple(rewrite(y) for y in x) if isinstance(x, tuple)
                        else x for x in v])
            return out

        rewritten = [rewrite(n) for n in nodes_]
        return items, rewritten

    def _corr_items(self, corr) -> list[ast.SelectItem]:
        tag = self.gensym("ck").strip("$")
        return [ast.SelectItem(iexpr, f"${tag}_{i}")
                for i, (_, iexpr) in enumerate(corr)]

    def _apply_exists(self, sub: ast.Select, plan: N.PlanNode, scope: Scope,
                      negated: bool) -> N.PlanNode:
        inner, corr, inner_conjs, residual = self._split_correlation(sub, scope)
        if not corr:
            raise BindError("uncorrelated EXISTS not supported yet")
        corr_items = self._corr_items(corr)
        res_items, res_rw = self._mangle_inner(residual, inner)
        items = corr_items + res_items
        sub2 = ast.Select(items=items, from_refs=sub.from_refs,
                          where=_and_all(inner_conjs))
        subplan = self.bind_select(sub2)
        probe_keys = [self.bind_scalar(o, scope) for o, _ in corr]
        build_keys = [self.bind_scalar(ast.Name((it.alias,)),
                                       Scope([RangeEntry("$sq", subplan)]))
                      for it in corr_items]
        kind = "anti" if negated else "semi"
        j = N.PJoin(kind, subplan, plan, build_keys, probe_keys, [],
                    self.gensym("match"))
        j.fields = list(plan.fields)
        _attach_key_validity(j)
        if res_rw:
            # residual references outer names + mangled subplan names
            combined = Scope(list(scope.entries)
                             + [RangeEntry("$sq", subplan)])
            j.residual = self.bind_scalar(_and_all(res_rw), combined)
            j.build_payload = [f.name for f in subplan.fields]
            # pair buffer: equi-match PAIRS expand internally before the
            # residual filters them — size from the inner-join estimate
            # with headroom, not just bcap+pcap (see _make_join)
            from cloudberry_tpu.plan.cost import estimate_rows

            pairs = N.PJoin("inner", subplan, plan,
                            list(build_keys), list(probe_keys), [])
            est = estimate_rows(pairs, self.catalog)
            j._est_pairs = est  # distribution/tiling re-derive from this
            j.out_capacity = max(
                _plan_capacity(subplan) + _plan_capacity(plan),
                int(2 * est) + 8)
        return j

    def _apply_in_subquery(self, node: ast.InSubquery, plan: N.PlanNode,
                           scope: Scope, negated: bool) -> N.PlanNode:
        sub = node.select
        inner, corr, inner_conjs, residual = self._split_correlation(sub, scope)
        if residual:
            raise BindError("IN subquery with non-equi correlation "
                            "not supported yet")
        if len(sub.items) != 1:
            raise BindError("IN subquery must return one column")
        del inner
        key_alias = self.gensym("inkey").strip("$")
        items = [ast.SelectItem(sub.items[0].expr, f"${key_alias}")]
        corr_items = self._corr_items(corr)
        items += corr_items
        # keep the subquery's own grouping if it has one (Q18 pattern:
        # IN (select o_orderkey ... group by o_orderkey having ...))
        sub2 = ast.Select(items=items, from_refs=sub.from_refs,
                          where=_and_all(inner_conjs),
                          group_by=sub.group_by, having=sub.having)
        subplan = self.bind_select(sub2)
        sq_scope = Scope([RangeEntry("$sq", subplan)])
        build_keys = [self.bind_scalar(ast.Name((f"${key_alias}",)), sq_scope)]
        probe_keys = [self.bind_scalar(node.expr, scope)]
        for (o, _), it in zip(corr, corr_items):
            probe_keys.append(self.bind_scalar(o, scope))
            build_keys.append(self.bind_scalar(ast.Name((it.alias,)), sq_scope))
        kind = "anti" if negated else "semi"
        j = N.PJoin(kind, subplan, plan, build_keys, probe_keys, [],
                    self.gensym("match"))
        j.fields = list(plan.fields)
        _attach_key_validity(j)
        # x NOT IN (subquery): if the subquery yields ANY NULL key, the
        # predicate is never TRUE — null-aware anti join
        j.null_aware = negated
        return j

    def _apply_scalar_comparison(self, node: ast.BinOp, plan: N.PlanNode,
                                 scope: Scope, negated: bool
                                 ) -> Optional[N.PlanNode]:
        """lhs op (select agg(...) from ... where corr) → decorrelate into a
        grouped subplan + lookup join + filter. Returns None if the pattern
        doesn't apply (caller falls back to expression binding)."""
        lhs, rhs, op = node.left, node.right, node.op
        if isinstance(lhs, ast.ScalarSubquery) and not isinstance(
                rhs, ast.ScalarSubquery):
            lhs, rhs = rhs, lhs
            op = _flip_op(op)
        if not isinstance(rhs, ast.ScalarSubquery) or _contains_subquery(lhs):
            return None
        sub = rhs.select
        if len(sub.items) != 1 or not _has_agg(sub.items[0].expr):
            return None
        inner, corr, inner_conjs, residual = self._split_correlation(sub, scope)
        if residual:
            return None
        if not corr:
            return None  # uncorrelated → expression path handles it
        del inner
        corr_items = self._corr_items(corr)
        val_name = self.gensym("sval").strip("$")
        items = [ast.SelectItem(sub.items[0].expr, f"${val_name}")]
        sub2 = ast.Select(items=corr_items + items, from_refs=sub.from_refs,
                          where=_and_all(inner_conjs),
                          group_by=[it.expr for it in corr_items])
        subplan = self.bind_select(sub2)
        sq_scope = Scope([RangeEntry("$sq", subplan)])
        build_keys = [self.bind_scalar(ast.Name((it.alias,)), sq_scope)
                      for it in corr_items]
        probe_keys = [self.bind_scalar(o, scope) for o, _ in corr]
        j = N.PJoin("inner", subplan, plan, build_keys, probe_keys,
                    [f.name for f in subplan.fields], self.gensym("match"))
        j.fields = list(plan.fields) + [
            N.PlanField(f.name, f.type, f.sdict) for f in subplan.fields]
        _attach_key_validity(j)
        cmp_scope = Scope(list(scope.entries) + [RangeEntry("$sq", j)])
        cmp = self._bind_comparison(
            op, self.bind_scalar(lhs, scope),
            self.bind_scalar(ast.Name((f"${val_name}",)), cmp_scope))
        if negated:
            cmp = ex.UnaryOp("not", cmp, T.BOOL)
        out = self._filter(j, cmp)
        out.fields = list(plan.fields)  # drop subplan columns from output
        return out

    def _bind_udf(self, u, node: ast.FuncCall, scope: Scope) -> ex.Expr:
        """Scalar UDF (exec/udf.py — the PL-function seam) in one of the
        three compilable shapes: bind-time constant folding, dictionary
        rewrite over one string column (the LIKE machinery), or a
        jax-traced function compiled into the program. Strict NULL
        semantics: NULL in → NULL out; a function returning None over a
        dictionary value NULLs exactly the rows holding that value."""
        from cloudberry_tpu.exec import udf as U

        if node.star or len(node.args) != len(u.arg_types):
            raise BindError(f"{u.name}() takes {len(u.arg_types)} "
                            f"argument(s), got {len(node.args)}")
        bound = []
        for a, at in zip(node.args, u.arg_types):
            b = self.bind_scalar(a, scope)
            if _is_null_literal(b):
                bound.append(b)
                continue
            if at.base == DType.STRING:
                if b.dtype.base != DType.STRING:
                    raise BindError(
                        f"{u.name}: expected a string argument, got "
                        f"{b.dtype.base.name}")
            elif b.dtype != at:
                b = self._coerce(b, at)
            bound.append(b)
        if any(_is_null_literal(b) for b in bound):
            # strict: a constant NULL argument folds to NULL
            return _null_literal(u.ret if u.ret.base != DType.STRING
                                 else T.INT64)
        all_const = all(isinstance(b, ex.Literal) for b in bound)
        if u.volatility == "immutable" and all_const:
            vals = [U.py_value(b.value, b.dtype) for b in bound]
            try:
                rv = u.fn(*vals)
            except Exception as e:  # surface the function's own error
                raise BindError(f"{u.name}: {type(e).__name__}: {e}")
            if rv is None:
                return _null_literal(u.ret if u.ret.base != DType.STRING
                                     else T.INT64)
            ev = U.encode_result(rv, u.ret)
            if u.ret.base == DType.STRING:
                # folded string constant: code 0 in a one-entry output
                # dictionary (the substring-fold convention) — a bare
                # python-str literal only works in comparison context
                d = StringDictionary((ev,))
                lit = ex.Literal(0, T.STRING)
                object.__setattr__(lit, "_out_dict", d)
                return lit
            return ex.Literal(ev, u.ret)
        colargs = [(i, b) for i, b in enumerate(bound)
                   if not isinstance(b, ex.Literal)]
        if u.volatility == "immutable" and not u.jit \
                and len(colargs) == 1 \
                and colargs[0][1].dtype.base == DType.STRING \
                and _expr_dict(colargs[0][1]) is not None:
            return self._bind_udf_dict(u, bound, colargs[0])
        if u.jit:
            if any(b.dtype.base == DType.STRING for b in bound):
                raise BindError(
                    f"{u.name}: jit UDFs take numeric arguments "
                    "(string columns are dictionary codes on device — "
                    "use the non-jit dictionary rewrite)")
            out = ex.Func("udf:" + u.name, tuple(bound), u.ret)
            return _set_valid(out,
                              _and_valid(*[_valid_of(b) for b in bound]))
        raise BindError(
            f"{u.name}: this call shape does not compile — supported: "
            "constant arguments (bind-time fold), one dictionary-encoded "
            "string column + constants (dictionary rewrite), or "
            "register_function(..., jit=True) with jax-traceable numeric "
            "code")

    def _bind_udf_dict(self, u, bound, colarg) -> ex.Expr:
        """Dictionary rewrite: run the function host-side once per
        dictionary VALUE, compile the per-row work to a table gather."""
        import numpy as np

        from cloudberry_tpu.exec import udf as U

        i0, col = colarg
        d = _expr_dict(col)
        vals = [U.py_value(b.value, b.dtype)
                if isinstance(b, ex.Literal) else None for b in bound]
        results = []
        for v in d.values:
            args2 = list(vals)
            args2[i0] = v
            try:
                results.append(u.fn(*args2))
            except Exception as e:
                raise BindError(f"{u.name}({v!r}): "
                                f"{type(e).__name__}: {e}")
        has_null = any(r is None for r in results)
        if u.ret.base == DType.STRING:
            out_dict = StringDictionary()
            codes = [(-1 if r is None
                      else out_dict.add(U.encode_result(r, u.ret)))
                     for r in results]
            out: ex.Expr = ex.DictLookup(col, np.asarray(codes,
                                                         dtype=np.int32),
                                         T.STRING)
            # _out_dict: the dictionary governing the RESULT codes (the
            # substring-machinery convention _expr_dict reads)
            object.__setattr__(out, "_out_dict", out_dict)
        else:
            zero = (False if u.ret.base == DType.BOOL else 0)
            table = np.asarray(
                [zero if r is None else U.encode_result(r, u.ret)
                 for r in results], dtype=u.ret.np_dtype)
            out = ex.DictLookup(col, table, u.ret)
        valid = _valid_of(col)
        if has_null:
            nl = ex.DictLookup(col, np.asarray(
                [r is not None for r in results], dtype=bool), T.BOOL)
            valid = _and_valid(valid, nl) or nl
        return _set_valid(out, valid)

    def _bind_coalesce(self, node: ast.FuncCall, scope: Scope) -> ex.Expr:
        """COALESCE: first non-NULL value wins; result is NULL only when
        every operand is. Operands without validity are never null, so
        anything after the first such operand is dead."""
        if not node.args:
            raise BindError("coalesce() requires at least one argument")
        bound = [self.bind_scalar(a, scope) for a in node.args]
        non_null = [b for b in bound if not _is_null_literal(b)]
        if not non_null:
            return _null_literal(T.INT64)
        rtype = _common_type([b.dtype for b in non_null])
        out_dict = None
        if any(b.dtype.base == DType.STRING for b in non_null):
            if not all(b.dtype.base == DType.STRING for b in non_null):
                raise BindError("coalesce mixes string and non-string "
                                "operands")
            rtype = T.STRING
            # reconcile dictionaries: codes re-based onto one output dict
            base = next((_expr_dict(b) for b in non_null
                         if _expr_dict(b) is not None), None)
            out_dict = StringDictionary(base.values if base else ())
            rebased = []
            for b in bound:
                if _is_null_literal(b):
                    b2: ex.Expr = _null_literal(T.STRING)
                elif isinstance(b, ex.Literal) and isinstance(b.value, str):
                    b2 = ex.Literal(out_dict.add(b.value), T.STRING)
                else:
                    d = _expr_dict(b)
                    if d is None:
                        raise BindError("string coalesce operand has no "
                                        "dictionary")
                    if d.values == out_dict.values[:len(d)]:
                        b2 = b  # prefix-compatible: codes already valid
                    else:
                        xlat = np.fromiter((out_dict.add(v)
                                            for v in d.values),
                                           dtype=np.int32, count=len(d))
                        b2 = ex.DictLookup(b, xlat, T.STRING)
                        _set_valid(b2, _valid_of(b))
                rebased.append(b2)
            coerced = rebased
        else:
            coerced = [
                _null_literal(rtype) if _is_null_literal(b)
                else (self._coerce(b, rtype) if b.dtype != rtype else b)
                for b in bound]

        out = None
        all_masked = True
        vexprs = []
        for b in reversed(coerced):
            v = _valid_of(b)
            if v is None:
                all_masked = False
                out = b  # never-null operand: later fallbacks are dead
                continue
            vexprs.append(v)
            out = b if out is None else \
                ex.CaseWhen(((v, b),), out, rtype)
        if all_masked and vexprs:
            # result is NULL only when EVERY operand is: validity = OR of
            # the operand validities, carried for the output surface
            valid = vexprs[0]
            for v in vexprs[1:]:
                valid = ex.BinOp("or", valid, v, T.BOOL)
            out2 = ex.CaseWhen(tuple(), out, rtype) if isinstance(
                out, (ex.ColumnRef, ex.Literal)) else out
            _set_valid(out2, valid)
            out = out2
        if out_dict is not None:
            out3 = out if not isinstance(out, (ex.ColumnRef, ex.Literal)) \
                else _set_valid(ex.CaseWhen(tuple(), out, rtype),
                                _valid_of(out))
            object.__setattr__(out3, "_out_dict", out_dict)
            out = out3
        return out

    def _bind_substring(self, node: ast.SubstringExpr, scope: Scope) -> ex.Expr:
        e = self.bind_scalar(node.operand, scope)
        sdict = _require_dict(e)
        if not (isinstance(node.start, ast.NumberLit)
                and (node.length is None
                     or isinstance(node.length, ast.NumberLit))):
            raise BindError("SUBSTRING bounds must be literals")
        start = int(node.start.text)
        length = int(node.length.text) if node.length else None
        out_dict = StringDictionary()
        table = np.empty(len(sdict), dtype=np.int32)
        for code, v in enumerate(sdict.values):
            sub = v[start - 1:] if length is None else v[start - 1:start - 1 + length]
            table[code] = out_dict.add(sub)
        col = ex.DictLookup(e, table, T.STRING)
        object.__setattr__(col, "_out_dict", out_dict)
        return _set_valid(col, _valid_of(e))

    def _bind_binop(self, node: ast.BinOp, scope: Scope) -> ex.Expr:
        op = node.op
        if op in ("and", "or"):
            return self._logic(op, self.bind_scalar(node.left, scope),
                               self.bind_scalar(node.right, scope))

        # date ± interval folding (literal side only, TPC-H style)
        if op in ("+", "-"):
            folded = self._fold_date_interval(node, scope)
            if folded is not None:
                return folded

        left = self.bind_scalar(node.left, scope)
        right = self.bind_scalar(node.right, scope)

        if op in ("=", "<>", "<", "<=", ">", ">="):
            if _is_null_literal(left) or _is_null_literal(right):
                return _null_bool()  # cmp with NULL is NULL (never TRUE)
            v = _and_valid(_valid_of(left), _valid_of(right))
            out = self._bind_comparison(op, left, right)
            if v is not None:
                out = ex.BinOp("and", out, v, T.BOOL)  # is-true normalize
            return _set_valid(out, v)

        # arithmetic — strict: NULL in, NULL out
        v = _and_valid(_valid_of(left), _valid_of(right))
        return _set_valid(self._bind_arith(op, left, right), v)

    def _bind_arith(self, op: str, left: ex.Expr, right: ex.Expr) -> ex.Expr:
        lt, rt = left.dtype, right.dtype
        if lt.base == DType.DATE or rt.base == DType.DATE:
            if op == "-" and lt.base == DType.DATE and rt.base == DType.DATE:
                return ex.BinOp("-", left, right, T.INT32)
            if lt.base == DType.DATE and rt.base in (DType.INT32, DType.INT64):
                return ex.BinOp(op, left, self._coerce(right, T.INT32), T.DATE)
            raise BindError("unsupported date arithmetic")
        if op == "/":
            lf = self._coerce(left, T.FLOAT64)
            rf = self._coerce(right, T.FLOAT64)
            return ex.BinOp("/", lf, rf, T.FLOAT64)
        if DType.FLOAT64 in (lt.base, rt.base):
            return ex.BinOp(op, self._coerce(left, T.FLOAT64),
                            self._coerce(right, T.FLOAT64), T.FLOAT64)
        if DType.DECIMAL in (lt.base, rt.base):
            if op == "*":
                l = self._as_decimal(left)
                r = self._as_decimal(right)
                scale = l.dtype.scale + r.dtype.scale
                out = ex.BinOp("*", l, r, T.DECIMAL(scale))
                if scale > MAX_DECIMAL_SCALE:
                    out = ex.Func(
                        "scale_down",
                        (out, ex.Literal(scale - MAX_DECIMAL_SCALE, T.INT32)),
                        T.DECIMAL(MAX_DECIMAL_SCALE))
                return out
            # + / -: align scales
            l = self._as_decimal(left)
            r = self._as_decimal(right)
            scale = max(l.dtype.scale, r.dtype.scale)
            return ex.BinOp(op, self._coerce(l, T.DECIMAL(scale)),
                            self._coerce(r, T.DECIMAL(scale)),
                            T.DECIMAL(scale))
        # pure integer
        rtype = T.INT64 if DType.INT64 in (lt.base, rt.base) else T.INT32
        return ex.BinOp(op, self._coerce(left, rtype),
                        self._coerce(right, rtype), rtype)

    def _bind_comparison(self, op: str, left: ex.Expr, right: ex.Expr) -> ex.Expr:
        lt, rt = left.dtype, right.dtype
        # string comparisons fold through the dictionary
        if lt.base == DType.STRING or rt.base == DType.STRING:
            if lt.base != DType.STRING:
                left, right = right, left
                op = _flip_op(op)
                lt, rt = left.dtype, right.dtype
            if isinstance(right, ex.Literal) and rt.base == DType.STRING:
                sdict = _require_dict(left)
                lit = right.value
                if op == "=":
                    code = sdict.code_of(lit)
                    return ex.BinOp("=", left,
                                    ex.Literal(code, T.STRING), T.BOOL)
                if op == "<>":
                    code = sdict.code_of(lit)
                    return ex.BinOp("<>", left,
                                    ex.Literal(code, T.STRING), T.BOOL)
                table = sdict.predicate_table(
                    lambda v: _str_cmp(op, v, lit))
                return ex.DictLookup(left, table)
            if rt.base == DType.STRING:
                ldict, rdict = _expr_dict(left), _expr_dict(right)
                if ldict is None or rdict is None:
                    raise BindError("string comparison requires "
                                    "dictionary-encoded operands")
                if ldict is rdict:
                    if op in ("=", "<>"):
                        return ex.BinOp(op, left, right, T.BOOL)
                    r = ldict.rank_table()
                    return ex.BinOp(op, ex.DictLookup(left, r, T.INT32),
                                    ex.DictLookup(right, r, T.INT32), T.BOOL)
                if op in ("=", "<>"):
                    # translate right codes into left's dictionary; absent → -1
                    # (never equals a valid left code, and -1==-1 cannot arise
                    # because left codes are always ≥ 0 for selected rows)
                    xlat = np.fromiter(
                        (ldict.code_of(v) for v in rdict.values),
                        dtype=np.int32, count=len(rdict))
                    rx = ex.DictLookup(right, xlat, T.STRING)
                    eq = ex.BinOp("=", left, rx, T.BOOL)
                    if op == "=":
                        return eq
                    return ex.UnaryOp("not", eq, T.BOOL)
                # ordering across dictionaries: rank both against the union
                union = sorted(set(ldict.values) | set(rdict.values))
                pos = {v: i for i, v in enumerate(union)}
                lr = np.fromiter((pos[v] for v in ldict.values),
                                 dtype=np.int32, count=len(ldict))
                rr = np.fromiter((pos[v] for v in rdict.values),
                                 dtype=np.int32, count=len(rdict))
                return ex.BinOp(op, ex.DictLookup(left, lr, T.INT32),
                                ex.DictLookup(right, rr, T.INT32), T.BOOL)
            raise BindError("string comparison requires a literal or column")
        if lt.base == DType.FLOAT64 or rt.base == DType.FLOAT64:
            return ex.BinOp(op, self._coerce(left, T.FLOAT64),
                            self._coerce(right, T.FLOAT64), T.BOOL)
        if lt.base == DType.DECIMAL or rt.base == DType.DECIMAL:
            l = self._as_decimal(left)
            r = self._as_decimal(right)
            scale = max(l.dtype.scale, r.dtype.scale)
            return ex.BinOp(op, self._coerce(l, T.DECIMAL(scale)),
                            self._coerce(r, T.DECIMAL(scale)), T.BOOL)
        return ex.BinOp(op, left, right, T.BOOL)

    def _fold_date_interval(self, node: ast.BinOp, scope: Scope
                            ) -> Optional[ex.Expr]:
        if not isinstance(node.right, ast.IntervalLit):
            return None
        base = self.bind_scalar(node.left, scope)
        iv = node.right
        sign = 1 if node.op == "+" else -1
        if isinstance(base, ex.Literal) and base.dtype.base == DType.DATE:
            d = T.days_to_date(base.value)
            d2 = _shift_date(d, sign * iv.n, iv.unit)
            return ex.Literal(T.date_to_days(d2), T.DATE)
        if iv.unit == "day":
            return ex.BinOp("+" if sign > 0 else "-", base,
                            ex.Literal(iv.n, T.INT32), T.DATE)
        raise BindError("year/month interval arithmetic requires a literal date")

    def _as_decimal(self, e: ex.Expr) -> ex.Expr:
        if e.dtype.base == DType.DECIMAL:
            return e
        if e.dtype.base in (DType.INT32, DType.INT64):
            if isinstance(e, ex.Literal):
                return _literal_cast(e, T.DECIMAL(0))
            return ex.Cast(e, T.DECIMAL(0))
        if isinstance(e, ex.Literal) and e.dtype.base == DType.FLOAT64:
            # float literal in decimal context: give it a scale from its text
            return ex.Cast(e, T.DECIMAL(2))
        raise BindError(f"cannot treat {e.dtype} as decimal")

    def _coerce(self, e: ex.Expr, t: SqlType) -> ex.Expr:
        if e.dtype == t:
            return e
        out = _literal_cast(e, t) if isinstance(e, ex.Literal) else ex.Cast(e, t)
        _set_valid(out, _valid_of(e))  # casts are validity-preserving
        if _is_null_literal(e):
            object.__setattr__(out, "_is_null_lit", True)
        return out


# ------------------------------------------------------------------ helpers


def _colref(f: N.PlanField) -> ex.ColumnRef:
    """ColumnRef carrying the field's dictionary (string ops need it) and
    its validity (NULL) mask."""
    c = ex.ColumnRef(f.name, f.type)
    if f.sdict is not None:
        object.__setattr__(c, "_sdict", f.sdict)
    if f.null_mask is not None:
        object.__setattr__(c, "_null_expr", ex.IsValid(f.masks))
    return c


# ----------------------------------------------- validity (NULL) propagation
# The binder tracks, for every bound expression, a bool "validity" expression
# (True = value present, False = SQL NULL) via the ``_null_expr`` attribute;
# None means provably non-null. Boolean expressions are kept "is-TRUE
# normalized": their compiled VALUE is the three-valued-logic is-TRUE (NULL
# evaluates as False), which makes WHERE/join/HAVING filtering correct with
# no executor knowledge of 3VL; the validity expr rides alongside for IS
# NULL, COALESCE, and NULL rendering. At plan boundaries (projections, agg
# outputs, scans) validity is materialized as hidden bool columns and
# recorded in PlanField.null_mask — ordinary columns that flow through
# motions/joins like any other. The reference gets all of this from
# per-datum null flags in every Datum slot; here it is compiled structure.


def _valid_of(e: ex.Expr):
    """Validity expr of a bound expression (None = never NULL)."""
    return getattr(e, "_null_expr", None)


def _set_valid(e: ex.Expr, v) -> ex.Expr:
    if v is not None:
        object.__setattr__(e, "_null_expr", v)
    return e


def _and_valid(*vs):
    out = None
    for v in vs:
        if v is None:
            continue
        out = v if out is None else ex.BinOp("and", out, v, T.BOOL)
    return out


def _field_for(name: str, bound: ex.Expr) -> N.PlanField:
    """Projection output field; NULL-literal columns carry a marker so
    set-op alignment can type them from the OTHER side (grouping-set
    branches project NULL for omitted string keys)."""
    return N.PlanField(name, bound.dtype, _expr_dict(bound),
                       _is_null_col=_is_null_literal(bound))


def _is_null_literal(e: ex.Expr) -> bool:
    return bool(getattr(e, "_is_null_lit", False))


def _null_literal(t: SqlType) -> ex.Expr:
    """A typed NULL: zero value + always-False validity."""
    z = 0.0 if t.base == DType.FLOAT64 else \
        (False if t.base == DType.BOOL else 0)
    lit = ex.Literal(z, t)
    object.__setattr__(lit, "_is_null_lit", True)
    object.__setattr__(lit, "_null_expr", ex.Literal(False, T.BOOL))
    return lit


def _null_bool() -> ex.Expr:
    """The NULL boolean, is-TRUE normalized: value False, validity False."""
    return _null_literal(T.BOOL)


_HIDDEN_PREFIXES = ("$vm", "$nn:", "$match", "$pmatch")


def _is_hidden_name(name: str) -> bool:
    return name.split(".")[-1].startswith(_HIDDEN_PREFIXES)


def _user_fields(plan: N.PlanNode) -> list[N.PlanField]:
    return [f for f in plan.fields if not _is_hidden_name(f.name)]


def _canonical_ref(f: N.PlanField) -> ex.Expr:
    """Reference a field with NULL lanes canonicalized to zero — safe as a
    grouping/set-op key where the validity mask rides as its own column.
    Deliberately carries NO validity (the mask column is the key's partner)."""
    c = ex.ColumnRef(f.name, f.type)
    if f.sdict is not None:
        object.__setattr__(c, "_sdict", f.sdict)
    if not f.masks:
        return c
    z = 0.0 if f.type.base == DType.FLOAT64 else \
        (False if f.type.base == DType.BOOL else 0)
    out = ex.CaseWhen(((ex.IsValid(f.masks), c),),
                      ex.Literal(z, f.type), f.type)
    if f.sdict is not None:
        object.__setattr__(out, "_out_dict", f.sdict)
    return out


def _attach_key_validity(j: N.PJoin) -> None:
    """SQL equi-join NULL semantics: a NULL key matches nothing. The
    executor ANDs these into the build/probe selection for matching."""
    j.build_key_valid = _and_valid(*[_valid_of(k) for k in j.build_keys])
    j.probe_key_valid = _and_valid(*[_valid_of(k) for k in j.probe_keys])


def _dtype_extreme(t: SqlType, want_max: bool):
    if t.base == DType.FLOAT64:
        return float("inf") if want_max else float("-inf")
    bits = 31 if t.np_dtype == np.int32 else 63
    return (1 << bits) - 1 if want_max else -(1 << bits)


def _scan_node(table: Table, alias: str) -> N.PScan:
    cmap = {f.name: f"{alias}.{f.name}" for f in table.schema.fields}
    validity = getattr(table, "validity", {})
    # mask output names keep the "<alias>.$..." shape so the hidden-column
    # convention (last dotted component starts with "$") holds
    mask_map = {f.name: f"{alias}.$nn:{f.name}"
                for f in table.schema.fields if f.name in validity}
    scan = N.PScan(table.name, cmap, capacity=max(table.num_rows, 1),
                   num_rows=table.num_rows, mask_map=mask_map)
    scan.fields = [
        N.PlanField(f"{alias}.{f.name}", f.type, table.dicts.get(f.name),
                    null_mask=((mask_map[f.name],)
                               if f.name in mask_map else None))
        for f in table.schema.fields
    ] + [N.PlanField(m, T.BOOL, None) for m in mask_map.values()]
    return scan


def _fields_only_plan(fields: list[N.PlanField]) -> N.PlanNode:
    p = N.PlanNode()
    p.fields = [N.PlanField(f.name, f.type, f.sdict, null_mask=f.null_mask)
                for f in fields]
    return p


def _append_sort_key(keys: list, bound: ex.Expr, ascending: bool) -> None:
    """ORDER BY with SQL NULL ordering: NULLs sort as larger than every
    value (NULLS LAST when ascending, FIRST when descending) — an is-null
    flag becomes the preceding sort key with the same direction."""
    v = _valid_of(bound)
    if v is not None:
        keys.append((ex.Cast(ex.UnaryOp("not", v, T.BOOL), T.INT32),
                     ascending))
    keys.append((bound, ascending))


def _const_row() -> N.PlanNode:
    p = N.PScan("$dual", {}, capacity=1)
    p.fields = []
    return p


def _rebind_scope(scope: Scope, alias: str, plan: N.PlanNode) -> None:
    for e in scope.entries:
        if e.alias == alias:
            e.plan = plan


def alias_set_of(groups) -> set:
    out: set = set()
    for aliases in groups.values():
        out |= aliases
    return out


def _plan_contains(root: N.PlanNode, target: N.PlanNode) -> bool:
    if root is target:
        return True
    return any(_plan_contains(c, target) for c in root.children())


def _plan_capacity(p: N.PlanNode) -> int:
    if isinstance(p, N.PScan):
        return p.capacity
    if isinstance(p, (N.PAgg,)):
        return p.capacity
    if isinstance(p, N.PConcat):
        return sum(_plan_capacity(c) for c in p.inputs)
    if isinstance(p, N.PWindow):
        return _plan_capacity(p.child)
    if isinstance(p, N.PMotion):
        return p.out_capacity or _plan_capacity(p.child)
    kids = p.children()
    if not kids:
        return 1
    if isinstance(p, N.PJoin):
        if not p.unique_build:
            return p.out_capacity
        return _plan_capacity(p.probe)
    return max(_plan_capacity(c) for c in kids)


def _agg_capacity(child: N.PlanNode, group_keys) -> int:
    if not group_keys:
        return 1
    # product of dictionary sizes when ALL keys are low-cardinality strings
    prod = 1
    for _, e in group_keys:
        d = _expr_dict(e)
        if d is None or len(d) > 10_000:
            prod = None
            break
        prod *= max(len(d), 1)
    cap = _plan_capacity(child)
    if prod is not None:
        return min(max(prod, 8), cap)
    return cap


def _contains_subquery(node: ast.Node) -> bool:
    if isinstance(node, (ast.ScalarSubquery, ast.InSubquery, ast.Exists)):
        return True
    for v in vars(node).values() if isinstance(node, ast.Node) else ():
        if isinstance(v, ast.Node) and not isinstance(v, ast.Select):
            if _contains_subquery(v):
                return True
        elif isinstance(v, (list, tuple)):
            for x in v:
                if isinstance(x, ast.Node) and not isinstance(x, ast.Select) \
                        and _contains_subquery(x):
                    return True
                if isinstance(x, tuple) and any(
                        isinstance(y, ast.Node)
                        and not isinstance(y, ast.Select)
                        and _contains_subquery(y) for y in x):
                    return True
    return False


def _and_all(conjs: list[ast.ExprNode]):
    if not conjs:
        return None
    out = conjs[0]
    for c in conjs[1:]:
        out = ast.BinOp("and", out, c)
    return out


def _or_branches(e: ast.ExprNode) -> list[ast.ExprNode]:
    if isinstance(e, ast.BinOp) and e.op == "or":
        return _or_branches(e.left) + _or_branches(e.right)
    return [e]


def _common_branch_conjuncts(or_expr: ast.ExprNode) -> list[ast.ExprNode]:
    """Conjuncts present (structurally) in EVERY branch of an OR."""
    branches = _or_branches(or_expr)
    sets = []
    for b in branches:
        sets.append({_ast_key(c): c for c in _split_conjuncts(b)})
    common_keys = set(sets[0])
    for s in sets[1:]:
        common_keys &= set(s)
    return [sets[0][k] for k in common_keys]


def _split_conjuncts(e: Optional[ast.ExprNode]) -> list[ast.ExprNode]:
    if e is None:
        return []
    if isinstance(e, ast.BinOp) and e.op == "and":
        return _split_conjuncts(e.left) + _split_conjuncts(e.right)
    return [e]


def _has_window(node: ast.ExprNode) -> bool:
    if isinstance(node, ast.WindowExpr):
        return True
    for v in vars(node).values() if isinstance(node, ast.Node) else ():
        if isinstance(v, ast.ExprNode) and _has_window(v):
            return True
        if isinstance(v, (list, tuple)):
            for x in v:
                if isinstance(x, ast.ExprNode) and _has_window(x):
                    return True
    return False


def _same_key(a, b) -> bool:
    # qualified and bare references to one column are the same key
    # (group by rollup(t.region) with a bare 'region' item — binding
    # would have rejected an ambiguous bare name anyway)
    if repr(a) == repr(b):
        return True
    if isinstance(a, ast.Name) and isinstance(b, ast.Name):
        return a.parts[-1] == b.parts[-1] \
            and (len(a.parts) == 1 or len(b.parts) == 1)
    return False


def _rewrite_ast(e, leaf):
    """Generic expression rewriter: leaf(e) returns a replacement node
    (possibly e itself, stopping descent) or None to recurse into
    children. Subqueries are opaque — their grouping context is their
    own. Shared by the grouping-sets expansion and the plain-GROUP-BY
    grouping() fold so the child dispatch cannot diverge."""
    r = leaf(e)
    if r is not None:
        return r
    if not isinstance(e, ast.Node) or isinstance(
            e, (ast.ScalarSubquery, ast.InSubquery, ast.Exists)):
        return e
    out = e.__class__(**vars(e))
    for k, v in vars(e).items():
        if isinstance(v, ast.ExprNode):
            setattr(out, k, _rewrite_ast(v, leaf))
        elif isinstance(v, list):
            # tuples inside lists = CaseExpr.whens pairs
            setattr(out, k, [
                _rewrite_ast(x, leaf) if isinstance(x, ast.ExprNode)
                else ast.OrderItem(_rewrite_ast(x.expr, leaf),
                                   x.ascending)
                if isinstance(x, ast.OrderItem)
                else tuple(_rewrite_ast(y, leaf)
                           if isinstance(y, ast.ExprNode) else y
                           for y in x)
                if isinstance(x, tuple) else x
                for x in v])
    return out


def _grouping_key_set(sel: ast.Select) -> list:
    """The query's grouping expressions: GROUP BY keys plus their
    select-alias resolutions (GROUP BY r where r aliases region makes
    region a grouping expression too — the alias path _bind_agg takes)."""
    alias_map = {i.alias: i.expr for i in sel.items if i.alias}
    keys = list(sel.group_by)
    for k in sel.group_by:
        if isinstance(k, ast.Name) and len(k.parts) == 1 \
                and k.parts[0] in alias_map:
            keys.append(alias_map[k.parts[0]])
    return keys


def _check_grouping_args(call, keys):
    for a in call.args:
        if not any(_same_key(a, k) for k in keys):
            raise BindError("arguments to grouping() must be grouping "
                            "expressions of the query")


def _contains_grouping(e) -> bool:
    if isinstance(e, ast.FuncCall) and e.name == "grouping":
        return True
    if not isinstance(e, ast.Node) or isinstance(
            e, (ast.ScalarSubquery, ast.InSubquery, ast.Exists)):
        return False
    for v in vars(e).values():
        if isinstance(v, ast.ExprNode) and _contains_grouping(v):
            return True
        if isinstance(v, (list, tuple)):
            for x in v:
                if isinstance(x, ast.ExprNode) and _contains_grouping(x):
                    return True
                if isinstance(x, ast.OrderItem) \
                        and _contains_grouping(x.expr):
                    return True
                if isinstance(x, tuple) and any(
                        isinstance(y, ast.ExprNode)
                        and _contains_grouping(y) for y in x):
                    return True
    return False


def _fold_plain_grouping(sel: ast.Select) -> ast.Select:
    """grouping() outside GROUPING SETS: in a plain GROUP BY query every
    reported key is grouped, so each call folds to the constant 0 after
    validating its arguments are grouping expressions (PG: "arguments to
    GROUPING must be grouping expressions of the associated query
    level", parse_agg.c check_ungrouped_columns role)."""
    keys = _grouping_key_set(sel)

    def leaf(e):
        if isinstance(e, ast.FuncCall) and e.name == "grouping":
            _check_grouping_args(e, keys)
            return ast.NumberLit("0")
        return None

    def repl(e):
        return _rewrite_ast(e, leaf)

    out = copy.copy(sel)  # keeps post-init attrs (e.g. _sql_text)
    out.items = [ast.SelectItem(repl(i.expr), i.alias) for i in sel.items]
    if sel.having is not None:
        out.having = repl(sel.having)
    out.order_by = []
    for o in sel.order_by:
        folded = repl(o.expr)
        if _contains_grouping(o.expr) and isinstance(folded, ast.NumberLit):
            # a constant key cannot affect the order — and a bare number
            # would re-parse as a positional column reference
            continue
        out.order_by.append(ast.OrderItem(folded, o.ascending))
    return out


def _const_num(e) -> Optional[float]:
    """Constant-fold the arithmetic a folded grouping() call produces
    (number literals, +/-/*); None = not a constant."""
    if isinstance(e, ast.NumberLit):
        try:
            return float(e.text)
        except ValueError:
            return None
    if isinstance(e, ast.UnaryOp) and e.op == "-":
        v = _const_num(e.operand)
        return -v if v is not None else None
    if isinstance(e, ast.BinOp) and e.op in ("+", "-", "*"):
        l, r = _const_num(e.left), _const_num(e.right)
        if l is None or r is None:
            return None
        return l + r if e.op == "+" else l - r if e.op == "-" else l * r
    return None


def _windows_of(sel: ast.Select) -> list:
    out = []

    def walk(e):
        if isinstance(e, ast.WindowExpr):
            out.append(e)
            return
        if not isinstance(e, ast.Node) or isinstance(
                e, (ast.ScalarSubquery, ast.InSubquery, ast.Exists)):
            return
        for v in vars(e).values():
            if isinstance(v, ast.ExprNode):
                walk(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if isinstance(x, ast.ExprNode):
                        walk(x)
                    elif isinstance(x, ast.OrderItem):
                        walk(x.expr)
                    elif isinstance(x, tuple):
                        for y in x:
                            if isinstance(y, ast.ExprNode):
                                walk(y)

    for i in sel.items:
        walk(i.expr)
    return out


def _check_branch_windows(branches: list) -> None:
    """Windows inside a grouping-sets query execute per UNION-ALL branch;
    that is sound only when the PARTITION BY pins every branch's rows to
    their own partitions — i.e. the constant-folded partition keys (the
    grouping() bitmasks this rewrite produced) take pairwise-distinct
    values across branches. Anything else would silently rank over one
    branch where SQL ranks over the combined result (nodeWindowAgg runs
    over nodeAgg's full grouping-sets output), so reject it loudly."""
    sels = [b for b in branches if isinstance(b, ast.Select)]
    wins = [_windows_of(b) for b in sels]
    if len(wins) <= 1 or not wins[0]:
        return
    for i in range(len(wins[0])):
        sigs = [tuple(_const_num(pk) for pk in bw[i].partition_by)
                for bw in wins]
        for a in range(len(sigs)):
            for b in range(a + 1, len(sigs)):
                if not any(x is not None and y is not None and x != y
                           for x, y in zip(sigs[a], sigs[b])):
                    raise BindError(
                        "window function partitions may span grouping "
                        "sets; PARTITION BY needs a grouping() "
                        "expression that distinguishes every set "
                        "(e.g. the full grouping(k1, ..., kn) bitmask)")


def _expand_grouping_sets(sel: ast.Select) -> ast.Node:
    """GROUPING SETS / ROLLUP / CUBE → UNION ALL of per-set aggregations
    (the nodeAgg.c grouping-sets role translated to plan algebra): each
    set aggregates with its own GROUP BY, keys a set omits project as
    NULL (the set-op column alignment coerces them to the key's type),
    and ORDER BY/LIMIT apply to the whole union. Re-aggregating the base
    per set matches the reference's multi-phase grouping-sets plan shape;
    the shared scan dedups through the statement-level plan, not here."""
    all_keys = list(sel.group_by)
    grouping_keys = _grouping_key_set(sel)
    branches = []
    for gset in sel.grouping_sets:
        omitted = [k for k in all_keys
                   if not any(_same_key(k, g) for g in gset)]

        def leaf(e, omitted=omitted):
            if any(_same_key(e, o) for o in omitted):
                return ast.NullLit()
            if isinstance(e, ast.FuncCall) and e.name == "grouping":
                # grouping(a, b) -> bitmask: bit i set where arg i is
                # NOT part of this branch's grouping set — a per-branch
                # CONSTANT, which is the whole point of the rewrite
                _check_grouping_args(e, grouping_keys)
                bits = 0
                for a in e.args:
                    bits = (bits << 1) | int(
                        any(_same_key(a, o) for o in omitted))
                return ast.NumberLit(str(bits))
            if isinstance(e, ast.FuncCall) and e.name in AGG_FUNCS:
                # aggregate ARGUMENTS stay intact: count(region) in the
                # grand-total row counts all non-NULL regions — the key
                # is NULL only as a GROUP LABEL, never inside aggregation
                return e
            return None

        def repl(e, leaf=leaf):
            return _rewrite_ast(e, leaf)

        items = [ast.SelectItem(repl(i.expr),
                                i.alias or _default_name(i.expr))
                 for i in sel.items]
        having = repl(sel.having) if sel.having is not None else None
        b = ast.Select(
            # keep the ORIGINAL output name on NULL-replaced items (the
            # union's column names come from the left branch, and ORDER
            # BY must resolve them)
            items=items,
            from_refs=sel.from_refs,
            where=sel.where,
            group_by=list(gset),
            having=having)
        if not gset and not any(_has_agg(i.expr) for i in items) \
                and (having is None or not _has_agg(having)):
            # the () branch with no aggregates selected: every item is a
            # constant label — GROUP BY () means ONE group, which
            # DISTINCT over constants reproduces
            b.distinct = True
        branches.append(b)
    _check_branch_windows(branches)
    out: ast.Node = branches[0]
    if len(branches) == 1:
        # never CLEAR the one-group distinct a constant () branch set
        out.distinct = out.distinct or sel.distinct
    for b in branches[1:]:
        # SELECT DISTINCT over grouping sets dedups the COMBINED result:
        # plain UNION (not ALL) chains do exactly that
        out = ast.SetOp("union", not sel.distinct, out, b)
    out.order_by = list(sel.order_by)
    out.limit = sel.limit
    out.offset = sel.offset
    return out


def _normalize_frame(frame):
    """Validate + canonicalize a frame clause.

    Returns None (the SQL default), ("whole",) (the whole partition),
    ("rows", lo, hi) with row offsets, or ("rangeoff", lo, hi) with
    value-distance offsets (None = unbounded on that side; CURRENT ROW
    in RANGE mode is exactly offset 0 — the search lands on the peer
    group's boundary either way). The key-count/type checks rangeoff
    needs happen at PWindow construction where the ORDER BY is bound."""
    if frame is None:
        return None
    kind, lo, hi = frame
    if lo == ("unbounded", 1):
        raise BindError("frame cannot start at UNBOUNDED FOLLOWING")
    if hi == ("unbounded", -1):
        raise BindError("frame cannot end at UNBOUNDED PRECEDING")
    if lo == ("unbounded", -1) and hi == ("unbounded", 1):
        return ("whole",)
    if kind == "range":
        if lo == ("unbounded", -1) and hi == ("current", 0):
            return None  # exactly the SQL default frame
        if lo[0] != "offset" and hi[0] != "offset":
            # positional shapes: CURRENT ROW bounds are peer-group
            # edges, needing no key search — PG restricts RANGE to one
            # numeric ORDER BY key only when an offset bound appears.
            # lo is always CURRENT ROW here (the UNBOUNDED-lo shapes
            # reduced to None/whole above)
            return ("rangepos", "peer",
                    "peer" if hi[0] == "current" else "end")
        lo_off = None if lo[0] == "unbounded" else lo[1]
        hi_off = None if hi[0] == "unbounded" else hi[1]
        # calendar ("months", n) offsets skip the static ordering check
        # (mixed-unit bounds have no static comparison; an inverted
        # frame just produces empty frames at runtime, PG semantics)
        if isinstance(lo_off, (int, float)) \
                and isinstance(hi_off, (int, float)) and lo_off > hi_off:
            raise BindError("frame start is after frame end")
        return ("rangeoff", lo_off, hi_off)
    for b in (lo, hi):
        if b[0] != "unbounded" and b[1] != int(b[1]):
            raise BindError("ROWS frame offsets must be integers")
    lo_off = None if lo[0] == "unbounded" else int(lo[1])
    hi_off = None if hi[0] == "unbounded" else int(hi[1])
    if lo_off is not None and hi_off is not None and lo_off > hi_off:
        raise BindError("frame start is after frame end")
    return ("rows", lo_off, hi_off)


def _check_rangeoff(frame, order_asts, okeys):
    """RANGE offset frames need exactly one numeric ORDER BY key (PG:
    "RANGE with offset PRECEDING/FOLLOWING requires exactly one ORDER BY
    column", nodeWindowAgg.c frame validation). DECIMAL keys scale the
    offset into their fixed-point representation; integer/date keys
    require integral offsets (a fractional distance on a discrete domain
    would silently truncate). Returns the executable 4-tuple
    ("rangeoff", lo, hi, key_is_nullable) — the nullable flag tells the
    executor the ORDER BY lowered to a (validity, masked-value) pair."""
    if len(order_asts) != 1:
        raise BindError(
            "RANGE with offset PRECEDING/FOLLOWING requires exactly "
            "one ORDER BY column")
    kb = okeys[-1][0]
    if _expr_dict(kb) is not None or kb.dtype.base not in (
            DType.INT32, DType.INT64, DType.FLOAT64, DType.DECIMAL,
            DType.DATE):
        raise BindError(
            "RANGE offsets need a numeric or date ORDER BY key")

    def scale(o):
        if o is None:
            return None
        if isinstance(o, tuple):  # ("months", n): calendar distance
            if kb.dtype.base != DType.DATE:
                raise BindError(
                    "INTERVAL MONTH/YEAR frame offsets need a date "
                    "ORDER BY key")
            return o
        raw = o
        if kb.dtype.base == DType.DECIMAL:
            # exact fixed-point scaling: 0.07 on a scale-2 key must
            # become 7, not 7.000000000000001 (binary float multiply)
            o = decimal.Decimal(str(o)).scaleb(kb.dtype.scale)
            if o != int(o):
                raise BindError(
                    f"RANGE offset {raw} is not representable at "
                    f"scale {kb.dtype.scale} of the decimal ORDER BY "
                    "key")
            return int(o)
        if kb.dtype.base != DType.FLOAT64:
            if o != int(o):
                raise BindError(
                    f"RANGE offset {raw} must be an integer for "
                    f"{kb.dtype.base.value} ORDER BY keys")
            return int(o)
        return float(o)

    return ("rangeoff", scale(frame[1]), scale(frame[2]),
            len(okeys) == 2)


def _one_row_guaranteed(sel: ast.Select) -> bool:
    """An ungrouped aggregate SELECT always returns exactly one row (no
    GROUP BY, no HAVING — which could filter that row away — and no
    LIMIT/OFFSET games): the common TPC shape ``(SELECT avg(x) FROM t)``,
    which needs no presence-validity subquery."""
    return (not sel.group_by and sel.having is None
            and sel.limit is None and not sel.offset
            and any(not isinstance(i.expr, ast.Star)
                    and _has_agg(i.expr) for i in sel.items))


def _has_agg(node: ast.ExprNode) -> bool:
    if isinstance(node, (ast.ScalarSubquery, ast.InSubquery, ast.Exists)):
        return False  # subquery aggregates belong to the subquery
    if isinstance(node, ast.FuncCall) and node.name in AGG_FUNCS:
        return True
    for v in vars(node).values():
        if isinstance(v, ast.ExprNode) and _has_agg(v):
            return True
        if isinstance(v, (list, tuple)):
            for x in v:
                # OrderItem wraps an expr (OVER(ORDER BY sum(x)) must
                # route through the aggregation path — same recursion
                # the agg extract() applies)
                if isinstance(x, ast.OrderItem) and _has_agg(x.expr):
                    return True
                if isinstance(x, ast.ExprNode) and _has_agg(x):
                    return True
                if isinstance(x, tuple) and any(
                        isinstance(y, ast.ExprNode) and _has_agg(y) for y in x):
                    return True
    return False


def _ast_key(node: ast.Node) -> str:
    parts = [type(node).__name__]
    for k, v in sorted(vars(node).items()):
        if isinstance(v, ast.Node):
            parts.append(f"{k}={_ast_key(v)}")
        elif isinstance(v, list):
            parts.append(f"{k}=[" + ",".join(
                _ast_key(x) if isinstance(x, ast.Node) else repr(x)
                for x in v) + "]")
        else:
            parts.append(f"{k}={v!r}")
    return "(" + " ".join(parts) + ")"


def _masked_key(bound: ex.Expr, v: ex.Expr) -> ex.Expr:
    """Canonicalize a nullable grouping key's NULL lanes to zero (its
    validity rides as a separate key column)."""
    z = 0.0 if bound.dtype.base == DType.FLOAT64 else \
        (False if bound.dtype.base == DType.BOOL else 0)
    masked = ex.CaseWhen(((v, bound),), ex.Literal(z, bound.dtype),
                         bound.dtype)
    d = _expr_dict(bound)
    if d is not None:
        object.__setattr__(masked, "_out_dict", d)
    return masked


def _attach_validity_outputs(binder, exprs, fields):
    """For output exprs that can be NULL, materialize the validity as a
    hidden bool output ("$vm…") and point the field's null_mask at it —
    the plan-boundary form of expression-level validity."""
    mask_out: dict = {}   # dedup key -> hidden column name
    new_fields = []
    for (name, bound), f in zip(list(exprs), fields):
        v = _valid_of(bound)
        if v is None:
            new_fields.append(N.PlanField(f.name, f.type, f.sdict,
                                          _is_null_col=f._is_null_col))
            continue
        key = (("iv", v.mask_names, v.negate)
               if isinstance(v, ex.IsValid) else id(v))
        hidden = mask_out.get(key)
        if hidden is None:
            hidden = binder.gensym("vm")
            mask_out[key] = hidden
            exprs.append((hidden, v))
        new_fields.append(N.PlanField(f.name, f.type, f.sdict,
                                      null_mask=(hidden,),
                                      _is_null_col=f._is_null_col))
    for hidden in mask_out.values():
        new_fields.append(N.PlanField(hidden, T.BOOL, None))
    return exprs, new_fields


def _uniquify(name: str, taken: set[str]) -> str:
    out = name
    i = 1
    while out in taken:
        out = f"{name}_{i}"
        i += 1
    taken.add(out)
    return out


def _default_name(node: ast.ExprNode) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.parts[-1]
    if isinstance(node, ast.FuncCall):
        return node.name
    return None


def _bind_number(text: str) -> ex.Literal:
    if "e" in text.lower():
        return ex.Literal(float(text), T.FLOAT64)
    if "." in text:
        frac = text.split(".")[1]
        scale = len(frac)
        return ex.Literal(int(text.replace(".", "")), T.DECIMAL(scale))
    return ex.Literal(int(text), T.INT64)


def _literal_cast(e: ex.Literal, t: SqlType) -> ex.Literal:
    v = e.value
    if t.base == DType.DECIMAL:
        if e.dtype.base == DType.DECIMAL:
            diff = t.scale - e.dtype.scale
            return ex.Literal(int(v) * 10 ** diff if diff >= 0
                              else int(round(v / 10 ** (-diff))), t)
        if e.dtype.base in (DType.INT32, DType.INT64):
            return ex.Literal(int(v) * 10 ** t.scale, t)
        if e.dtype.base == DType.FLOAT64:
            return ex.Literal(int(round(v * 10 ** t.scale)), t)
    if t.base == DType.FLOAT64:
        if e.dtype.base == DType.DECIMAL:
            return ex.Literal(v / 10 ** e.dtype.scale, t)
        return ex.Literal(float(v), t)
    if t.base in (DType.INT32, DType.INT64):
        return ex.Literal(int(v), t)
    return ex.Literal(v, t)


def _common_type(ts: list[SqlType]) -> SqlType:
    if any(t.base == DType.FLOAT64 for t in ts):
        return T.FLOAT64
    if any(t.base == DType.DECIMAL for t in ts):
        scale = max(t.scale for t in ts if t.base == DType.DECIMAL)
        return T.DECIMAL(scale)
    if any(t.base == DType.INT64 for t in ts):
        return T.INT64
    return ts[0]


def _flip_op(op: str) -> str:
    return {"=": "=", "<>": "<>", "<": ">", "<=": ">=",
            ">": "<", ">=": "<="}[op]


def _str_cmp(op: str, a: str, b: str) -> bool:
    return {"<": a < b, "<=": a <= b, ">": a > b, ">=": a >= b,
            "=": a == b, "<>": a != b}[op]


def _require_dict(e: ex.Expr) -> StringDictionary:
    d = _expr_dict(e)
    if d is None:
        raise BindError("string operation requires a dictionary-encoded column")
    return d


def _expr_dict(e: ex.Expr) -> Optional[StringDictionary]:
    """The dictionary governing a STRING-typed expression's codes."""
    if e.dtype.base != DType.STRING:
        return None
    if hasattr(e, "_out_dict"):
        return e._out_dict  # substring-produced dictionary
    if isinstance(e, ex.ColumnRef):
        return getattr(e, "_sdict", None)
    if isinstance(e, ex.CaseWhen):
        for _, v in e.whens:
            d = _expr_dict(v)
            if d is not None:
                return d
    return None


def _shift_date(d: datetime.date, n: int, unit: str) -> datetime.date:
    if unit == "day":
        return d + datetime.timedelta(days=n)
    if unit == "month":
        m = d.month - 1 + n
        y = d.year + m // 12
        m = m % 12 + 1
        day = min(d.day, _days_in_month(y, m))
        return datetime.date(y, m, day)
    if unit == "year":
        return _shift_date(d, 12 * n, "month")
    raise BindError(f"unsupported interval unit {unit}")


def _days_in_month(y: int, m: int) -> int:
    if m == 12:
        return 31
    return (datetime.date(y, m + 1, 1) - datetime.date(y, m, 1)).days
