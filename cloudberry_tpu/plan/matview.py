"""Materialized views: AQUMV query rewrite + incremental maintenance.

Three reference subsystems re-expressed for this engine:

- CREATE/REFRESH/DROP MATERIALIZED VIEW (src/backend/commands/matview.c):
  the view body materializes into an ordinary table through the same
  machinery as CREATE TABLE AS; the defining query persists in the store's
  ``_MATVIEWS.json`` so every session on a root sees the same definitions.

- AQUMV — answer-query-using-matview (optimizer/plan/aqumv.c): a SELECT
  whose shape is subsumed by a FRESH aggregate matview rewrites to read the
  matview instead of the base table: group keys a subset of the view's,
  predicates over view keys only, and each aggregate derivable by
  re-aggregation (sum of sums, sum of counts, min of mins, max of maxs) —
  correct because the view partitions base rows by its full key set.

- IVM — incremental view maintenance (matview.c IMMV triggers,
  gp_matview_aux): CREATE INCREMENTAL MATERIALIZED VIEW restricts the body
  to one-table aggregates over NOT NULL keys/args; INSERT/COPY then merge
  the appended rows' delta aggregation into the stored view (no triggers —
  the DML paths call ``maintain_on_append`` directly, this engine's
  statement loop being single-process). UPDATE/DELETE fall back to an
  immediate full refresh, and transaction ROLLBACK conservatively marks
  every view stale (AQUMV then skips them until refreshed).

Shape analysis and the delta merge run host-side on the PHYSICAL column
representation (int64 fixed-point decimals, day-number dates), so
re-aggregation is bit-exact; string keys decode through their side's
dictionary for the merge and re-encode into the view's.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from cloudberry_tpu.sql import ast

_AGG_FUNCS = ("sum", "count", "min", "max")


@dataclass
class MatViewDef:
    name: str
    sql: str                      # defining query text (re-parsed on load)
    query: ast.Node               # parsed defining query
    incremental: bool = False
    # aggregate shape (None = opaque body: refresh-only, no AQUMV/IVM)
    base_table: Optional[str] = None
    keys: list = field(default_factory=list)   # [(mv_alias, base_col)]
    aggs: list = field(default_factory=list)   # [(mv_alias, func, argcol)]
    # freshness: the base table's in-session _version as of the last
    # materialize/maintain; None = stale (AQUMV skips)
    fresh_token: Optional[int] = None
    base_store_version: int = 0


class MatViewError(ValueError):
    pass


# --------------------------------------------------------------- definition


def analyze_shape(q: ast.Node):
    """(base_table, keys, aggs) when the body is a one-table aggregate the
    rewriter/maintainer understands, else (None, [], [])."""
    if not isinstance(q, ast.Select) or q.distinct or q.having is not None \
            or q.where is not None or q.limit is not None or q.offset:
        return None, [], []
    if len(q.from_refs) != 1 or not isinstance(q.from_refs[0], ast.TableName):
        return None, [], []
    base = q.from_refs[0].name
    group_names = []
    for g in q.group_by:
        if not (isinstance(g, ast.Name) and len(g.parts) == 1):
            return None, [], []
        group_names.append(g.parts[0])
    keys, aggs = [], []
    for item in q.items:
        e = item.expr
        if isinstance(e, ast.Name) and len(e.parts) == 1 \
                and e.parts[0] in group_names:
            keys.append((item.alias or e.parts[0], e.parts[0]))
        elif isinstance(e, ast.FuncCall) and e.name in _AGG_FUNCS \
                and not e.distinct:
            if e.star or not e.args:
                if e.name != "count":
                    return None, [], []
                aggs.append((item.alias or "count", "count", None))
            elif isinstance(e.args[0], ast.Name) and len(e.args[0].parts) == 1:
                aggs.append((item.alias or f"{e.name}_{e.args[0].parts[0]}",
                             e.name, e.args[0].parts[0]))
            else:
                return None, [], []
        else:
            return None, [], []
    if len(keys) != len(group_names) or not aggs:
        return None, [], []
    return base, keys, aggs


def _check_incremental(session, d: MatViewDef) -> None:
    """INCREMENTAL views need the exact-delta property: a recognized
    aggregate shape over NOT NULL keys and args, with no string aggregate
    arguments (string extremes compare by collation — not mergeable on
    physical codes)."""
    from cloudberry_tpu.types import DType

    if d.base_table is None:
        raise MatViewError(
            "INCREMENTAL MATERIALIZED VIEW requires a one-table "
            "sum/count/min/max aggregate body (the IMMV restriction)")
    try:
        t = session.catalog.table(d.base_table)
    except KeyError:
        raise MatViewError(f"unknown table {d.base_table!r}")
    for _, col in d.keys:
        if t.schema.field(col).nullable:
            raise MatViewError(
                f"INCREMENTAL view key {col!r} must be NOT NULL")
    for _, func, col in d.aggs:
        if col is None:
            continue
        f = t.schema.field(col)
        if f.nullable:
            raise MatViewError(
                f"INCREMENTAL view aggregate argument {col!r} must be "
                "NOT NULL")
        if func in ("min", "max") and f.dtype == DType.STRING:
            raise MatViewError(
                "INCREMENTAL min/max over a string column is not "
                "maintainable (collation vs code order)")


def create_matview(session, stmt) -> str:
    cat = session.catalog
    name = stmt.name.lower()
    if name in cat.tables or name in cat.views:
        raise MatViewError(f"{stmt.name!r} already exists")
    base, keys, aggs = analyze_shape(stmt.query)
    d = MatViewDef(name, getattr(stmt, "_sql_text", ""), stmt.query,
                   stmt.incremental, base, keys, aggs)
    if stmt.incremental:
        _check_incremental(session, d)
    _materialize(session, d)
    cat.matviews[name] = d
    _persist_defs(session)
    cat.bump_ddl()
    kind = "INCREMENTAL MATERIALIZED VIEW" if stmt.incremental \
        else "MATERIALIZED VIEW"
    return f"CREATE {kind} {stmt.name}"


def drop_matview(session, name: str, if_exists: bool = False) -> str:
    cat = session.catalog
    name = name.lower()
    if name not in cat.matviews:
        if if_exists:
            return "DROP MATERIALIZED VIEW"
        raise MatViewError(f"unknown materialized view {name!r}")
    del cat.matviews[name]
    if name in cat.tables:
        cat.drop_table(name)
    _persist_defs(session)
    cat.bump_ddl()
    return f"DROP MATERIALIZED VIEW {name}"


def refresh_matview(session, name: str) -> str:
    from cloudberry_tpu.utils.faultinject import fault_point

    fault_point("matview_refresh")
    cat = session.catalog
    name = name.lower()
    d = cat.matviews.get(name)
    if d is None:
        raise MatViewError(f"unknown materialized view {name!r}")
    if name in cat.tables:
        cat.drop_table(name)
    _materialize(session, d)
    _persist_defs(session)
    cat.bump_ddl()
    return f"REFRESH MATERIALIZED VIEW {name}"


def _materialize(session, d: MatViewDef) -> None:
    """Run the defining query and store the result as the view's table."""
    from cloudberry_tpu.catalog.catalog import DistributionPolicy
    from cloudberry_tpu.plan.planner import _run_internal

    batch = _run_internal(session, d.query)
    t = session.catalog.create_table(d.name, batch.schema,
                                     DistributionPolicy.random())
    sel = np.asarray(batch.sel)
    data, validity = {}, {}
    for f in batch.schema.fields:
        data[f.name] = np.asarray(batch.columns[f.name])[sel] \
            .astype(f.type.np_dtype)
        vm = batch.validity.get(f.name)
        if vm is not None:
            validity[f.name] = np.asarray(vm).astype(np.bool_)[sel]
    t.set_data(data, dict(batch.dicts), validity=validity)
    d.fresh_token = _base_token(session, d)
    if session.store is not None and d.base_table:
        d.base_store_version = session.store.current_version(d.base_table)


def _base_token(session, d: MatViewDef):
    if d.base_table is None:
        return None
    try:
        return getattr(session.catalog.table(d.base_table), "_version", None)
    except KeyError:
        return None


# -------------------------------------------------------------- persistence


def _persist_defs(session) -> None:
    if session.store is None:
        return
    if not session.store.autocommit:
        # inside BEGIN..COMMIT: definitions must not outlive a ROLLBACK —
        # Session.txn flushes them after the store commit succeeds
        session._matviews_dirty = True
        return
    session.store.save_matviews({
        n: {"sql": d.sql, "incremental": d.incremental,
            "base_store_version": d.base_store_version}
        for n, d in session.catalog.matviews.items()})


def load_defs(session) -> None:
    """Register store-persisted definitions (session start / store sync).
    Freshness carries over only when the base table's store version still
    matches what the definition last saw."""
    if session.store is None:
        return
    from cloudberry_tpu.sql.parser import parse_sql

    for name, j in session.store.load_matviews().items():
        try:
            ddl = parse_sql(j["sql"])
        except Exception:
            continue
        if not isinstance(ddl, ast.CreateMatView):
            continue
        q = ddl.query
        base, keys, aggs = analyze_shape(q)
        d = MatViewDef(name, j["sql"], q, ddl.incremental,
                       base, keys, aggs,
                       base_store_version=j.get("base_store_version", 0))
        if base is not None and session.store.current_version(base) \
                == d.base_store_version:
            d.fresh_token = _base_token(session, d)
        session.catalog.matviews[name] = d


# -------------------------------------------------------------- maintenance


def maintain_on_append(session, table_name: str, n_new: int) -> None:
    """INSERT/COPY hook: merge the appended rows' delta aggregation into
    every INCREMENTAL view on this base; others go stale."""
    if n_new <= 0:
        return
    from cloudberry_tpu.utils.faultinject import fault_point

    fault_point("matview_maintain")
    changed = False
    for d in list(session.catalog.matviews.values()):
        if d.base_table != table_name.lower():
            continue
        if not d.incremental:
            d.fresh_token = None
            continue
        _merge_delta(session, d, n_new)
        d.fresh_token = _base_token(session, d)
        if session.store is not None:
            d.base_store_version = session.store.current_version(
                d.base_table)
            changed = True
    if changed:
        _persist_defs(session)


def maintain_full(session, table_name: str) -> None:
    """UPDATE/DELETE hook without captured deltas: re-materialize
    INCREMENTAL views (correct for any DML), mark plain views stale."""
    for d in list(session.catalog.matviews.values()):
        if d.base_table != table_name.lower():
            continue
        if d.incremental:
            refresh_matview(session, d.name)
        else:
            d.fresh_token = None


def delta_columns(session, table_name: str):
    """Union of key/argument columns the INCREMENTAL views on this base
    need for a DML delta, or None when none watch it (the DML paths then
    skip the capture entirely)."""
    need: set = set()
    found = False
    for d in session.catalog.matviews.values():
        if d.base_table == table_name.lower() and d.incremental:
            found = True
            need.update(c for _, c in d.keys)
            need.update(c for _, _, c in d.aggs if c is not None)
    return sorted(need) if found else None


def maintain_on_dml(session, table_name: str, sub, add) -> None:
    """UPDATE/DELETE hook WITH captured delta frames — the IMMV delta
    discipline (reference: src/backend/commands/matview.c:594-640,
    IVM_immediate_maintenance's old/new transition tables): subtract the
    old rows' contribution, add the new rows'. A view falls back to a
    full re-materialization when its aggregates are not invertible
    under deletion (min/max), when a sum runs on floats (subtraction
    would break the bit-exact discipline int64/decimal deltas keep), or
    when it carries no count (an emptied group would be undetectable) —
    correctness always wins over incrementality."""
    from cloudberry_tpu.utils.faultinject import fault_point

    fault_point("matview_maintain")
    changed = False
    for d in list(session.catalog.matviews.values()):
        if d.base_table != table_name.lower():
            continue
        if not d.incremental:
            d.fresh_token = None
            continue
        if _delta_invertible(session, d) \
                and _merge_dml_delta(session, d, sub, add):
            d.fresh_token = _base_token(session, d)
            if session.store is not None:
                d.base_store_version = session.store.current_version(
                    d.base_table)
                changed = True
        else:
            refresh_matview(session, d.name)
    if changed:
        _persist_defs(session)


def _delta_invertible(session, d: MatViewDef) -> bool:
    from cloudberry_tpu.types import DType

    if any(f in ("min", "max") for _, f, _ in d.aggs):
        return False  # deletion cannot un-take an extreme
    if not any(f == "count" for _, f, _ in d.aggs):
        return False  # emptied groups would be undetectable
    base = session.catalog.table(d.base_table)
    for _, f, c in d.aggs:
        if f == "sum" and c is not None:
            fld = next(x for x in base.schema.fields if x.name == c)
            if fld.dtype == DType.FLOAT64:
                return False  # float subtraction is not bit-exact
    return True


def _merge_dml_delta(session, d: MatViewDef, sub, add) -> bool:
    """Signed delta merge: every affected row contributes ±1 to counts
    and ±value to sums, grouped by the view keys; groups whose count
    reaches zero leave the view. False = the delta cannot express the
    result (a keyless view emptied out: its sums become SQL NULL, which
    only a re-materialization produces) — the caller refreshes."""
    import pandas as pd

    from cloudberry_tpu.columnar.batch import encode_column
    from cloudberry_tpu.types import DType

    key_aliases = [a for a, _ in d.keys]
    key_cols = [c for _, c in d.keys]
    parts = []
    for df, sign in ((sub, -1), (add, 1)):
        if df is None or not len(df):
            continue
        p = pd.DataFrame({a: df[c].to_numpy()
                          for a, c in zip(key_aliases, key_cols)})
        for alias, func, col in d.aggs:
            p[alias] = sign if func == "count" \
                else sign * df[col].to_numpy()
        parts.append(p)
    mv = session.catalog.table(d.name)
    mv.ensure_loaded()
    if not parts:
        return True  # zero affected rows: the view already matches
    delta = pd.concat(parts, ignore_index=True)
    agg_aliases = [a for a, _, _ in d.aggs]
    if key_aliases:
        dagg = delta.groupby(key_aliases, sort=False)[agg_aliases] \
            .sum().reset_index()
    else:
        dagg = delta[agg_aliases].sum().to_frame().T

    mv_df = _frame(mv, [f.name for f in mv.schema.fields], 0, mv.num_rows)
    merged = pd.concat([mv_df, dagg], ignore_index=True)
    if key_aliases:
        merged = merged.groupby(key_aliases, sort=False)[agg_aliases] \
            .sum().reset_index()
    else:
        merged = merged[agg_aliases].sum().to_frame().T
    count_alias = next(a for a, f, _ in d.aggs if f == "count")
    if key_aliases:
        merged = merged[merged[count_alias] > 0]
    elif int(merged[count_alias].iloc[0]) == 0:
        return False  # emptied keyless view: sums must become NULL

    data = {}
    for f in mv.schema.fields:
        arr = merged[f.name].to_numpy()
        data[f.name] = encode_column(arr, f, mv.dicts) \
            if f.dtype == DType.STRING else arr.astype(f.type.np_dtype)
    mv.set_data(data, mv.dicts)
    return True


def invalidate_all(session) -> None:
    """Transaction ROLLBACK: data snapshots restored under the views'
    feet — every view is conservatively stale until refreshed."""
    for d in session.catalog.matviews.values():
        d.fresh_token = None


def _frame(table, cols: list[str], lo: int, hi: int):
    """Physical-representation DataFrame slice (strings decoded)."""
    import pandas as pd

    out = {}
    for c in cols:
        arr = table.data[c][lo:hi]
        d = table.dicts.get(c)
        if d is not None:
            arr = np.asarray(d.values, dtype=object)[arr]
        out[c] = arr
    return pd.DataFrame(out)


def _merge_delta(session, d: MatViewDef, n_new: int) -> None:
    import pandas as pd

    from cloudberry_tpu.columnar.batch import encode_column

    base = session.catalog.table(d.base_table)
    base.ensure_loaded()
    mv = session.catalog.table(d.name)
    mv.ensure_loaded()
    n = base.num_rows
    need = [c for _, c in d.keys] + sorted(
        {c for _, _, c in d.aggs if c is not None})
    delta = _frame(base, need, n - n_new, n)
    key_aliases = [a for a, _ in d.keys]
    delta = delta.rename(columns=dict(zip([c for _, c in d.keys],
                                          key_aliases)))

    # per-key delta aggregation on physical values (bit-exact)
    gb = delta.groupby(key_aliases, sort=False) if key_aliases else None
    parts = {}
    for alias, func, col in d.aggs:
        if func == "count":
            s = gb.size() if gb is not None else pd.Series([len(delta)])
        else:
            s = getattr(gb[col] if gb is not None else delta[col], func)()
            if gb is None:
                s = pd.Series([s])
        parts[alias] = s
    dagg = pd.DataFrame(parts)
    if key_aliases:
        dagg = dagg.reset_index()

    mv_df = _frame(mv, [f.name for f in mv.schema.fields], 0, mv.num_rows)
    merged = pd.concat([mv_df, dagg], ignore_index=True)
    if key_aliases:
        g2 = merged.groupby(key_aliases, sort=False)
        rules = {a: ("sum" if f in ("sum", "count") else f)
                 for a, f, _ in d.aggs}
        merged = g2.agg(rules).reset_index()
    else:
        rules = {a: ("sum" if f in ("sum", "count") else f)
                 for a, f, _ in d.aggs}
        merged = merged.agg(rules).to_frame().T

    from cloudberry_tpu.types import DType

    data = {}
    for f in mv.schema.fields:
        arr = merged[f.name].to_numpy()
        data[f.name] = encode_column(arr, f, mv.dicts) \
            if f.dtype == DType.STRING else arr.astype(f.type.np_dtype)
    mv.set_data(data, mv.dicts)


# ------------------------------------------------------------------- AQUMV


def aqumv_rewrite(session, sel: ast.Select):
    """Try to answer ``sel`` from a fresh matview; returns (select,
    view_name_or_None)."""
    cat = session.catalog
    if not cat.matviews or len(sel.from_refs) != 1 \
            or not isinstance(sel.from_refs[0], ast.TableName) or sel.distinct:
        return sel, None
    base = sel.from_refs[0].name.lower()
    for d in cat.matviews.values():
        if d.base_table != base or d.fresh_token is None:
            continue
        if d.name not in cat.tables:
            continue  # definition without a table (e.g. rolled-back CREATE)
        if d.fresh_token != _base_token(session, d):
            continue  # base moved since the view last materialized
        out = _try_rewrite(sel, d)
        if out is not None:
            return out, d.name
    return sel, None


def _try_rewrite(sel: ast.Select, d: MatViewDef):
    key_of = {c: a for a, c in d.keys}          # base col -> mv alias
    agg_of = {}                                  # (func, argcol) -> mv alias
    for alias, func, col in d.aggs:
        agg_of[(func, col)] = alias

    group_cols = []
    for g in sel.group_by:
        if not (isinstance(g, ast.Name) and len(g.parts) == 1
                and g.parts[0] in key_of):
            return None
        group_cols.append(g.parts[0])
    if sel.where is not None \
            and not _refs_only(sel.where, set(key_of)):
        return None

    items = []
    item_aliases = set()
    for item in sel.items:
        e = item.expr
        if isinstance(e, ast.Name) and len(e.parts) == 1 \
                and e.parts[0] in key_of and e.parts[0] in group_cols:
            alias = item.alias or e.parts[0]
            items.append(ast.SelectItem(ast.Name((key_of[e.parts[0]],)),
                                        alias))
            item_aliases.add(alias)
            continue
        rw = _rewrite_agg(e, key_of, agg_of, global_agg=not group_cols)
        if rw is None:
            return None
        alias = item.alias or _agg_name(e)
        items.append(ast.SelectItem(rw, alias))
        if alias:
            item_aliases.add(alias)

    def rw_post(e):
        """HAVING / ORDER BY exprs: aggregates re-derive from the view,
        key names rename, output aliases stay; None = not rewritable."""
        if isinstance(e, ast.Name) and len(e.parts) == 1:
            if e.parts[0] in item_aliases:
                return e
            if e.parts[0] in key_of:
                return ast.Name((key_of[e.parts[0]],))
            return None
        if isinstance(e, ast.FuncCall) and e.name in _AGG_FUNCS:
            return _rewrite_agg(e, key_of, agg_of,
                                global_agg=not group_cols)
        if isinstance(e, (ast.ScalarSubquery, ast.InSubquery, ast.Exists)):
            return None
        if not isinstance(e, ast.Node):
            return e
        out = e.__class__(**vars(e))
        for k, v in vars(e).items():
            if isinstance(v, ast.ExprNode):
                r = rw_post(v)
                if r is None:
                    return None
                setattr(out, k, r)
            elif isinstance(v, list):
                new = []
                for x in v:
                    if isinstance(x, ast.ExprNode):
                        r = rw_post(x)
                        if r is None:
                            return None
                        new.append(r)
                    else:
                        new.append(x)
                setattr(out, k, new)
        return out

    having = None
    if sel.having is not None:
        having = rw_post(sel.having)
        if having is None:
            return None
    order_by = []
    for oi in sel.order_by:
        r = rw_post(oi.expr)
        if r is None:
            return None
        order_by.append(ast.OrderItem(r, oi.ascending))
    return ast.Select(
        items=items,
        from_refs=[ast.TableName(d.name)],
        where=_rename(sel.where, key_of) if sel.where is not None else None,
        group_by=[ast.Name((key_of[c],)) for c in group_cols],
        having=having, order_by=order_by,
        limit=sel.limit, offset=sel.offset)


def _agg_name(e: ast.ExprNode) -> Optional[str]:
    return e.name if isinstance(e, ast.FuncCall) else None


def _rewrite_agg(e: ast.ExprNode, key_of, agg_of, global_agg: bool):
    """sum(x)→sum(mv.sum_x); count→sum(mv.count) [coalesced to 0 for a
    global aggregate over a possibly-empty view]; min/max→min/max of the
    view's extreme. None = not derivable."""
    if not (isinstance(e, ast.FuncCall) and e.name in _AGG_FUNCS
            and not e.distinct):
        return None
    if e.star or not e.args:
        col = None
    elif isinstance(e.args[0], ast.Name) and len(e.args[0].parts) == 1:
        col = e.args[0].parts[0]
    else:
        return None
    alias = agg_of.get((e.name, col))
    if alias is None:
        return None
    inner = ast.Name((alias,))
    if e.name in ("min", "max"):
        return ast.FuncCall(e.name, [inner])
    out = ast.FuncCall("sum", [inner])
    if e.name == "count" and global_agg:
        out = ast.FuncCall("coalesce", [out, ast.NumberLit("0")])
    return out


def _refs_only(e: ast.ExprNode, allowed: set) -> bool:
    if isinstance(e, ast.Name):
        return len(e.parts) == 1 and e.parts[0] in allowed
    if isinstance(e, (ast.ScalarSubquery, ast.InSubquery, ast.Exists)):
        return False
    ok = True
    for v in vars(e).values():
        if isinstance(v, ast.ExprNode):
            ok = ok and _refs_only(v, allowed)
        elif isinstance(v, (list, tuple)):
            for x in v:
                if isinstance(x, ast.ExprNode):
                    ok = ok and _refs_only(x, allowed)
    return ok


def _rename(e: ast.ExprNode, key_of: dict):
    if isinstance(e, ast.Name) and len(e.parts) == 1 \
            and e.parts[0] in key_of:
        return ast.Name((key_of[e.parts[0]],))
    if not isinstance(e, ast.Node):
        return e
    out = e.__class__(**vars(e))
    for k, v in vars(e).items():
        if isinstance(v, ast.ExprNode):
            setattr(out, k, _rename(v, key_of))
        elif isinstance(v, list):
            setattr(out, k, [
                _rename(x, key_of) if isinstance(x, ast.ExprNode) else x
                for x in v])
    return out
