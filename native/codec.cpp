// Native columnar codec — the varblock / PAX-encoding analog.
//
// The reference keeps its storage codecs native (AO varblock bit-packed
// headers in src/backend/cdb/cdbappendonlystorageformat.c; PAX's C++
// encoding stack in contrib/pax_storage). Here the hot byte-level work —
// delta+zigzag+LEB128 varint for int64 key/date columns, plus a fast CSV
// field splitter for parallel ingest (the gpfdist-class loader path) — is
// C++ behind a C ABI, loaded via ctypes (no pybind11 in the image).
//
// Build: g++ -O3 -march=native -shared -fPIC codec.cpp -o libcbcodec.so

#include <cstdint>
#include <cstring>
#include <cstdlib>

extern "C" {

// ---------------------------------------------------------------- varint

// Encode int64 column as zigzag(delta) LEB128 varints.
// out must hold >= n * 10 bytes. Returns encoded byte count.
int64_t cb_dvarint_encode(const int64_t* src, int64_t n, uint8_t* out) {
    uint8_t* p = out;
    uint64_t prev = 0;
    for (int64_t i = 0; i < n; i++) {
        // unsigned arithmetic: wraparound is defined (no signed-overflow UB
        // for adjacent values near int64 extremes)
        uint64_t cur = static_cast<uint64_t>(src[i]);
        uint64_t du = cur - prev;
        prev = cur;
        int64_t d = static_cast<int64_t>(du);
        uint64_t z = (du << 1) ^ static_cast<uint64_t>(d >> 63);
        while (z >= 0x80) {
            *p++ = static_cast<uint8_t>(z) | 0x80;
            z >>= 7;
        }
        *p++ = static_cast<uint8_t>(z);
    }
    return p - out;
}

// Decode n values; returns bytes consumed, or -1 on truncated input.
int64_t cb_dvarint_decode(const uint8_t* src, int64_t nbytes, int64_t n,
                          int64_t* out) {
    const uint8_t* p = src;
    const uint8_t* end = src + nbytes;
    uint64_t prev = 0;
    for (int64_t i = 0; i < n; i++) {
        uint64_t z = 0;
        int shift = 0;
        while (true) {
            if (p >= end) return -1;
            uint8_t b = *p++;
            z |= static_cast<uint64_t>(b & 0x7F) << shift;
            if (!(b & 0x80)) break;
            shift += 7;
            if (shift > 63) return -1;
        }
        uint64_t du = (z >> 1) ^ (~(z & 1) + 1);  // un-zigzag, unsigned
        prev += du;
        out[i] = static_cast<int64_t>(prev);
    }
    return p - src;
}

// ------------------------------------------------------------- CSV ingest

// Split one CSV buffer into int64 values for a single column index.
// Simple dialect: no quoted delimiters (TPC-H .tbl style '|' files).
// Returns number of rows parsed, or -1 on a malformed number.
int64_t cb_parse_int64_column(const char* buf, int64_t nbytes, char delim,
                              int32_t col_index, int64_t* out,
                              int64_t max_rows) {
    int64_t rows = 0;
    const char* p = buf;
    const char* end = buf + nbytes;
    while (p < end && rows < max_rows) {
        // seek to column col_index of this line
        int32_t col = 0;
        while (col < col_index && p < end && *p != '\n') {
            if (*p == delim) col++;
            p++;
        }
        if (p >= end) break;
        if (col != col_index) { // short line
            while (p < end && *p != '\n') p++;
            p++;
            continue;
        }
        bool neg = false;
        if (p < end && *p == '-') { neg = true; p++; }
        int64_t v = 0;
        bool any = false;
        while (p < end && *p >= '0' && *p <= '9') {
            v = v * 10 + (*p - '0');
            any = true;
            p++;
        }
        if (!any) return -1;
        out[rows++] = neg ? -v : v;
        while (p < end && *p != '\n') p++;
        p++;
    }
    return rows;
}

// Parse a decimal(2)-style column into int64 hundredths (fixed point).
int64_t cb_parse_decimal_column(const char* buf, int64_t nbytes, char delim,
                                int32_t col_index, int32_t scale,
                                int64_t* out, int64_t max_rows) {
    int64_t pow10 = 1;
    for (int32_t i = 0; i < scale; i++) pow10 *= 10;
    int64_t rows = 0;
    const char* p = buf;
    const char* end = buf + nbytes;
    while (p < end && rows < max_rows) {
        int32_t col = 0;
        while (col < col_index && p < end && *p != '\n') {
            if (*p == delim) col++;
            p++;
        }
        if (p >= end) break;
        if (col != col_index) {
            while (p < end && *p != '\n') p++;
            p++;
            continue;
        }
        bool neg = false;
        if (p < end && *p == '-') { neg = true; p++; }
        int64_t whole = 0;
        bool any = false;
        while (p < end && *p >= '0' && *p <= '9') {
            whole = whole * 10 + (*p - '0');
            any = true;
            p++;
        }
        int64_t frac = 0;
        int64_t seen = 1;
        if (p < end && *p == '.') {
            p++;
            while (p < end && *p >= '0' && *p <= '9' && seen < pow10) {
                frac = frac * 10 + (*p - '0');
                seen *= 10;
                p++;
            }
            while (p < end && *p >= '0' && *p <= '9') p++; // extra digits
        }
        if (!any) return -1;
        int64_t v = whole * pow10 + frac * (pow10 / seen);
        out[rows++] = neg ? -v : v;
        while (p < end && *p != '\n') p++;
        p++;
    }
    return rows;
}

}  // extern "C"
