"""Benchmark: TPC-H Q1 + Q3 on the TPU chip vs the same engine on host CPU.

BASELINE.md staged configs #1 and #2: "TPC-H SF1 Q1 — single-segment
lineitem scan + HashAgg" and "TPC-H SF1 Q3 — 3-table HashJoin + Agg".
Both sides run the identical optimized plan (this engine); only the
executing device differs — so the number isolates the hardware +
XLA-backend difference the way the reference's north star ("≥5× the CPU
executor") intends. Q3 exercises the join path (sorted-build lookup with
stats-proven 32-bit key packing), Q1 the scan+aggregate path.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
where value = geomean TPU speedup over the CPU executor across q1+q3 and
vs_baseline = value / 5.0 (fraction of the ≥5× target); per-query
speedups ride in the unit string.

Robustness (round-2 hardening): the TPU sits behind an axon relay that can
wedge so hard device init hangs forever. Every stage that could touch the
relay runs in a subprocess with a hard timeout; the device probe retries with
backoff (a busy relay can take minutes to accept a session). When no live
measurement is possible, the bench replays the last committed good
measurement from BENCH_LAST_GOOD.json with its provenance spelled out in the
unit — a replayed number is never presented as a live one.

Env knobs: BENCH_SF (default 1.0), BENCH_REPS (default 3),
BENCH_TIMEOUT (child wall-clock budget, default 1800s),
BENCH_PROBE_TIMEOUTS (comma list, default "60,120,240").
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
LAST_GOOD = os.path.join(REPO, "BENCH_LAST_GOOD.json")
# Sentinel child exit code: "no TPU device in the child" — environmental,
# not an engine failure.
NO_TPU_RC = 42


def log(*a):
    print(*a, file=sys.stderr, flush=True)


# Roofline context (VERDICT r5 item 8): every speedup ships with its
# denominator — bytes the query scans ÷ best TPU wall time, as a fraction
# of nominal HBM bandwidth — so "10.58×" is readable as near-roofline or
# 10× off. Nominal bandwidth defaults to a TPU v4 chip (1228 GB/s);
# override with BENCH_HBM_GBPS for other parts.
HBM_GBPS_NOMINAL = float(os.environ.get("BENCH_HBM_GBPS", "1228"))

# Static per-row scanned-byte widths (the columns the engine's projected
# scans actually read; dtype widths from cloudberry_tpu.types: int64/
# decimal 8B, date/string-code/int32 4B). Used when no live catalog is
# available (REPLAY mode); live runs measure the real loaded arrays.
_TPCH_SF1_ROWS = {
    "lineitem": 6_001_215, "orders": 1_500_000, "customer": 150_000,
    "part": 200_000, "partsupp": 800_000, "supplier": 10_000,
    "nation": 25, "region": 5,
}
_FIXED_TABLES = {"nation", "region"}  # size does not scale with SF
_QUERY_SCAN_WIDTHS = {
    # q1: returnflag+linestatus (4+4) + 4 decimals (32) + shipdate (4)
    "q1": {"lineitem": 44},
    "q3": {"customer": 12, "orders": 24, "lineitem": 28},
    "q6": {"lineitem": 28},
    "q9": {"part": 12, "supplier": 16, "lineitem": 48, "partsupp": 24,
           "orders": 12, "nation": 12},
}


def static_scan_bytes(qname: str, sf: float):
    """Schema-derived bytes-scanned estimate for REPLAY mode (no data
    generated, no device touched); None for queries without a width
    table."""
    widths = _QUERY_SCAN_WIDTHS.get(qname)
    if not widths:
        return None
    return int(sum(
        _TPCH_SF1_ROWS[t] * (1.0 if t in _FIXED_TABLES else sf) * w
        for t, w in widths.items()))


def roofline_context(qnames, sf: float, bytes_by_q: dict | None = None,
                     wall_by_q: dict | None = None) -> dict:
    """The roofline record: scanned bytes per query (measured when given,
    else static estimate) + the nominal-bandwidth denominator; live runs
    add achieved GB/s and the HBM fraction."""
    out = {"hbm_gbps_nominal": HBM_GBPS_NOMINAL, "per_query": {}}
    for qn in qnames:
        b = (bytes_by_q or {}).get(qn)
        if b is None:
            b = static_scan_bytes(qn, sf)
        if b is None:
            continue
        rec = {"bytes_scanned": int(b)}
        w = (wall_by_q or {}).get(qn)
        if w:
            gbps = b / w / 1e9
            rec["scan_gbps"] = round(gbps, 1)
            rec["hbm_frac"] = round(gbps / HBM_GBPS_NOMINAL, 4)
        out["per_query"][qn] = rec
    return out


def interconnect_context(session, qnames, nseg: int = 8) -> dict:
    """The interconnect denominator next to the roofline record: plan each
    bench query as it would run on an ``nseg`` segment mesh (metadata-only
    — the counts-only shard layout, no arrays materialized) and total
    every Motion's wire footprint: collective launches and bytes-on-wire
    under the packed format (exec/kernels.py wire_layout) vs the legacy
    per-column launches, so the perf trajectory captures shuffle volume,
    not just scan bytes."""
    import copy

    import numpy as np

    from cloudberry_tpu.exec import kernels as K
    from cloudberry_tpu.exec.executor import all_nodes
    from cloudberry_tpu.plan import nodes as PN
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql
    from tools.tpch_queries import QUERIES

    from cloudberry_tpu.parallel.mesh import host_topology

    clone = copy.copy(session)
    clone.config = session.config.with_overrides(n_segments=nseg)
    # dcn/ici split model (ISSUE 14): per motion, bytes crossing host
    # boundaries vs staying on-host under the live HostTopology (one
    # host -> everything is ICI/local and dcn stays 0; a simulated or
    # real multi-host grouping splits by the block's source/destination
    # hosts the way the two-level transport would route them)
    try:
        topo = host_topology(nseg)
        n_hosts = topo.n_hosts if topo.uniform_contiguous() else 1
    except Exception:
        n_hosts = 1
    S = nseg // n_hosts if n_hosts > 1 else nseg
    out = {"n_segments": nseg, "n_hosts": n_hosts, "per_query": {}}
    for qn in qnames:
        plan = plan_statement(parse_sql(QUERIES[qn]), clone, {}).plan
        rec = {"motions": 0, "launches_packed": 0, "launches_percol": 0,
               "wire_bytes_packed": 0, "wire_bytes_percol": 0,
               "dcn_bytes": 0, "ici_bytes": 0}
        seen: set = set()
        for node in all_nodes(plan):
            # shared (PShare/CTE) subtrees appear once per reference in
            # the walk but lower — and ship — exactly once
            if not isinstance(node, PN.PMotion) or id(node) in seen:
                continue
            seen.add(id(node))
            layout = K.wire_layout(
                {f.name: f.type.np_dtype for f in node.fields})
            rows = max(int(node.out_capacity), 1)
            rb = layout.row_bytes()
            rec["motions"] += 1
            rec["launches_packed"] += 1
            rec["launches_percol"] += len(node.fields) + 1  # + sel buffer
            rec["wire_bytes_packed"] += rows * rb
            rec["wire_bytes_percol"] += rows * (
                sum(np.dtype(f.type.np_dtype).itemsize
                    for f in node.fields) + 1)
            if n_hosts > 1:
                from cloudberry_tpu.parallel.transport import (
                    flat_wire_model, two_level_wire_model)

                if node.kind == "redistribute" \
                        and node.host_bucket_cap > 0 \
                        and node.hier_hosts == n_hosts:
                    # two-level: one aggregated block per host pair at
                    # the host rung; lane staging rides ICI
                    m = two_level_wire_model(
                        nseg, n_hosts, node.bucket_cap,
                        node.host_bucket_cap, rb)
                else:
                    # flat: every cross-host per-segment block pays DCN
                    m = flat_wire_model(nseg, n_hosts, rows // nseg, rb)
                rec["dcn_bytes"] += m["dcn_bytes"]
                rec["ici_bytes"] += m["ici_bytes"]
            else:
                rec["ici_bytes"] += rows * rb
        out["per_query"][qn] = rec
    # live skew telemetry (ISSUE 12): what THIS process's distributed
    # executions observed per redistribute — rows-per-destination
    # max/mean ratio histogram + the skew_events alarm counter
    # (config.obs.skew_ratio), riding next to the static wire totals
    log_ = session.stmt_log
    out["skew"] = {
        "skew_events": log_.counter("skew_events"),
        "ratio_hist": log_.registry.hist("motion_skew_ratio"),
        "seg_rows_max_hist": log_.registry.hist("motion_seg_rows_max"),
        # per-HOST skew (ISSUE 14): the shape two-level motion makes
        # WORSE — one hot host pair's rung pads every host pair
        "host_skew_events": log_.counter("host_skew_events"),
        "host_ratio_hist": log_.registry.hist("motion_host_skew_ratio"),
    }
    return out


def join_filter_context(session, qnames, nseg: int = 8) -> dict:
    """The join-path record next to the interconnect one: per bench query
    at the ``nseg``-segment plan shape, the runtime join filters the
    planner would insert above probe-side redistributes (exact vs bloom
    digest — plan/nodes.py PRuntimeFilter) with their statically
    estimated probe-row reduction, plus how many joins ride the
    sorted-build join-index cache (exec/joinindex.py). Metadata-only
    plans; the live counters block reports what THIS process's actual
    executions observed (cache hits, filter pre/post rows)."""
    import copy

    from cloudberry_tpu.exec.executor import all_nodes
    from cloudberry_tpu.plan import nodes as PN
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql
    from tools.tpch_queries import QUERIES

    clone = copy.copy(session)
    clone.config = session.config.with_overrides(n_segments=nseg)
    out = {"n_segments": nseg, "per_query": {}}
    for qn in qnames:
        plan = plan_statement(parse_sql(QUERIES[qn]), clone, {}).plan
        rec = {"filters_exact": 0, "filters_digest": 0,
               "est_rows_in": 0, "est_rows_out": 0, "indexed_joins": 0}
        seen: set = set()
        for node in all_nodes(plan):
            if id(node) in seen:
                continue
            seen.add(id(node))
            if isinstance(node, PN.PRuntimeFilter):
                rec["filters_exact" if node.mode == "exact"
                    else "filters_digest"] += 1
                if getattr(node, "_est_in", None) is not None:
                    rec["est_rows_in"] += int(node._est_in)
                    rec["est_rows_out"] += int(node._est_out)
            elif isinstance(node, PN.PJoin) \
                    and getattr(node, "_jix", None) is not None:
                rec["indexed_joins"] += 1
        out["per_query"][qn] = rec
    log_ = session.stmt_log
    out["counters"] = {
        "join_index_builds": log_.counter("join_index_builds"),
        "join_index_hits": log_.counter("join_index_hits"),
        "jf_rows_in": log_.counter("jf_rows_in"),
        "jf_rows_out": log_.counter("jf_rows_out"),
    }
    return out


def scan_ladder_context() -> dict:
    """The data-scale ladder record (ROADMAP item 1): per-SF cold tiled
    scan throughput through the asynchronous scan pipeline
    (tools/scan_bench.py) — rows/sec/chip, pipeline stall time,
    decode-vs-compute overlap fraction, and the 8-segment wire-byte
    model. SF points under BENCH_SCAN_SFS (default 0.1,1) run LIVE in
    this process (CPU or TPU host — the scan path is host+device work
    either way); the SF10 point replays the committed SCAN_SF10.json
    artifact with its provenance spelled out — never presented as a
    live number (the honest-REPLAY rules of the headline metric,
    unchanged)."""
    rec: dict = {"points": [], "sf10": None}
    try:
        import shutil
        import tempfile

        from tools import scan_bench

        sfs = [float(x) for x in
               os.environ.get("BENCH_SCAN_SFS", "0.1,1").split(",")
               if x.strip()]
        for sf in sfs:  # per-point isolation: one bad SF never hides
            # one shared store root per SF: the A/B at the largest SF
            # reuses the ladder point's stream-loaded data instead of
            # regenerating it (the load dominates the record's cost)
            root = tempfile.mkdtemp(prefix="cbtpu_ladder_")
            try:
                try:
                    p = scan_bench.ladder_point(sf, root=root)
                    p["provenance"] = "live"
                except Exception as e:  # noqa: BLE001 — recorded
                    p = {"sf": sf, "error": f"{type(e).__name__}: {e}"}
                rec["points"].append(p)
                if sf != max(sfs):
                    continue
                # the on/off A/B at the LARGEST live SF: the win is an
                # overlap effect — sub-second scans are thread-overhead
                # noise; the claim lives where streams are long enough
                # to amortize the reader
                try:
                    ab = scan_bench.run_ab(sf, root=root, reps=1)
                    rec["ab"] = {"rows": ab, **scan_bench.summarize(ab)}
                except Exception as e:  # noqa: BLE001
                    rec["ab"] = {"error": f"{type(e).__name__}: {e}"}
                # windowed tile-dispatch A/B (exec/tilepipe.py) on the
                # same store root: inflight_tiles 1 vs 4 — wall-clock
                # honest on CPU (~1×), the overlap evidence is the
                # drain-stall-vs-step-wall split the record carries
                try:
                    rec["window_ab"] = scan_bench.window_ab(
                        sf, root=root, reps=1)
                except Exception as e:  # noqa: BLE001
                    rec["window_ab"] = {
                        "error": f"{type(e).__name__}: {e}"}
            finally:
                shutil.rmtree(root, ignore_errors=True)
    except Exception as e:  # the bench must never die on its metadata
        rec["error"] = f"{type(e).__name__}: {e}"
    try:
        sf10_path = os.path.join(REPO, "SCAN_SF10.json")
        if os.path.exists(sf10_path):
            with open(sf10_path) as f:
                p = json.load(f)
            p["provenance"] = (
                f"REPLAY of {p.get('measured_utc', 'unknown date')} "
                "committed measurement (tools/scan_bench.py "
                "--ladder-json)")
            rec["sf10"] = p
    except Exception as e:
        rec["sf10"] = {"error": f"{type(e).__name__}: {e}"}
    return rec


def bufferpool_context() -> dict:
    """The HBM buffer-pool record (ISSUE 16) next to the scan ladder:
    per-SF SECOND-PASS hit-rate points (tools/scan_bench.py
    hot_point — scan 1 cold, scan 2 admits, scan 3 served from the
    pool) at the same live SFs as the ladder, each reporting pool-pass
    hit rate, host decodes (zero when the hot set is resident), cold
    vs pool rows/s, and bit identity. The SF10 row is annotated from
    the committed cold-scan artifact: it PREDATES the pool, so its hit
    rate is stated as not-measured rather than invented — commit one
    with ``tools/scan_bench.py --sf 10 --hot-json`` on hardware."""
    rec: dict = {"points": [], "sf10": None}
    try:
        import shutil
        import tempfile

        from tools import scan_bench

        sfs = [float(x) for x in
               os.environ.get("BENCH_SCAN_SFS", "0.1,1").split(",")
               if x.strip()]
        for sf in sfs:  # per-point isolation, same as the scan ladder
            root = tempfile.mkdtemp(prefix="cbtpu_bufpool_")
            try:
                try:
                    p = scan_bench.hot_point(sf, root=root)
                    p["provenance"] = "live"
                except Exception as e:  # noqa: BLE001 — recorded
                    p = {"sf": sf, "error": f"{type(e).__name__}: {e}"}
                rec["points"].append(p)
            finally:
                shutil.rmtree(root, ignore_errors=True)
    except Exception as e:  # the bench must never die on its metadata
        rec["error"] = f"{type(e).__name__}: {e}"
    try:
        hot_path = os.path.join(REPO, "SCAN_SF10_HOT.json")
        if os.path.exists(hot_path):
            # committed SF10 hot_point artifact (scan_bench --hot-json):
            # a MEASURED second-pass pool record, replayed verbatim
            with open(hot_path) as f:
                p = json.load(f)
            p["provenance"] = (
                f"REPLAY of {p.get('measured_utc', 'unknown date')} "
                "committed hot_point measurement (SCAN_SF10_HOT.json)")
            rec["sf10"] = p
            return rec
        sf10_path = os.path.join(REPO, "SCAN_SF10.json")
        if os.path.exists(sf10_path):
            with open(sf10_path) as f:
                p = json.load(f)
            rec["sf10"] = {
                "sf": p.get("sf", 10.0),
                "rows_per_s_cold": p.get("rows_per_s_chip"),
                "bufpool_hit_rate": None,
                "provenance": (
                    f"REPLAY of {p.get('measured_utc', 'unknown date')} "
                    "committed COLD-scan measurement; it predates the "
                    "buffer pool, so no SF10 second-pass hit rate "
                    "exists — not presented as measured"),
            }
    except Exception as e:
        rec["sf10"] = {"error": f"{type(e).__name__}: {e}"}
    return rec


def writepath_context() -> dict:
    """The streaming-ingest + compaction record (ISSUE 18): one short
    serve_bench ``--mix readwrite`` closed loop (3 reads : 1 wire append
    per client) with the background compaction service folding the delta
    debt live, next to its ``--no-compact`` A/B baseline (same loop and
    append share, debt left unfolded). ``read_qps_held`` is the
    acceptance ratio — reads under compaction vs reads with the debt
    accumulating — and ``delta_parts_max`` vs the baseline's shows the
    bounded-delta invariant doing its job."""
    rec: dict = {}
    try:
        from tools import serve_bench

        on = serve_bench.run_mode("direct", "readwrite", clients=4,
                                  duration_s=1.5, rows=20_000,
                                  tick_s=0.002, max_batch=8)
        off = serve_bench.run_mode("direct", "readwrite", clients=4,
                                   duration_s=1.5, rows=20_000,
                                   tick_s=0.002, max_batch=8,
                                   compact_off=True)
        rec = {
            "qps": on["qps"],
            "read_qps": on["_read_qps"],
            "ingest_qps": on["ingest_qps"],
            "flush_ms_p95": on["flush_ms_p95"],
            "compact_chunks": on["compact_chunks"],
            "delta_parts_max": on["delta_parts_max"],
            "nocompact_read_qps": off["_read_qps"],
            "nocompact_delta_parts_max": off["delta_parts_max"],
            "read_qps_held": round(
                on["_read_qps"] / max(off["_read_qps"], 1e-9), 4),
            "provenance": "live",
        }
    except Exception as e:  # the bench must never die on its metadata
        rec["error"] = f"{type(e).__name__}: {e}"
    return rec


def durability_context() -> dict:
    """The crash-only storage record (ISSUE 19) next to the perf ones:
    which durability seams the process-kill torture matrix covers (the
    crash matrix itself is tests/test_crash_torture.py — minutes of
    subprocess wall, not bench work), an fsck verdict over a scratch
    store written through the real append path, and the checksum
    verification overhead A/B on the partition decode path (the
    acceptance bound is <3% on scans). Runs identically on live and
    replay rounds: CPU-only, storage-layer work."""
    rec: dict = {}
    try:
        import shutil
        import tempfile

        import numpy as np

        from cloudberry_tpu import types as T
        from cloudberry_tpu.storage.fsck import fsck
        from cloudberry_tpu.storage.table_store import TableStore
        from cloudberry_tpu.types import Schema
        from cloudberry_tpu.utils.faultinject import INVENTORY
        from tools.crash_torture import MATRIX_SEAMS

        seams = [s for s, _ in MATRIX_SEAMS]
        rec["seams_covered"] = len(seams)
        rec["seams_in_inventory"] = sum(
            1 for s in seams if s in INVENTORY)
        d = tempfile.mkdtemp(prefix="bench-durability-")
        try:
            store = TableStore(os.path.join(d, "store"))
            n = 1_500_000
            rng = np.random.default_rng(19)
            store.append(
                "t", {"k": np.arange(n, dtype=np.int64),
                      "v": rng.integers(0, 1 << 30, n, dtype=np.int64)},
                Schema.of(k=T.INT64, v=T.INT64),
                rows_per_partition=1 << 18)
            rep = fsck(store.root, deep=True)
            rec["fsck_clean"] = rep["clean"]
            rec["fsck_problems"] = len(rep["problems"])
            parts = store.read_manifest("t")["partitions"]
            reps = 3

            def _scan_wall(verify: bool) -> float:
                store.verify_checksums = verify
                store.read_partitions("t", parts)  # warm page cache
                t0 = time.perf_counter()
                for _ in range(reps):
                    store.read_partitions("t", parts)
                return time.perf_counter() - t0

            # interleave + best-of-three per mode: the loops are ~100ms
            # and allocator/thermal drift across a run-then-run A/B
            # reads as fake overhead otherwise
            offs, ons = [], []
            for _ in range(3):
                offs.append(_scan_wall(False))
                ons.append(_scan_wall(True))
            off, on = min(offs), min(ons)
            rec["scan_verify_off_s"] = round(off / reps, 4)
            rec["scan_verify_on_s"] = round(on / reps, 4)
            rec["checksum_overhead_pct"] = round(
                (on - off) / max(off, 1e-9) * 100.0, 2)
        finally:
            shutil.rmtree(d, ignore_errors=True)
    except Exception as e:  # the bench must never die on its metadata
        rec["error"] = f"{type(e).__name__}: {e}"
    return rec


def lint_context() -> dict:
    """The static-analysis record next to the perf ones: graftlint's
    verdict on the CURRENT tree (rule counts, suppression count, files)
    so invariant drift — a new finding, a creeping suppression pile —
    is visible in the bench trajectory. Purely static: runs identically
    on live and replay rounds, never touches a device."""
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "lint_gate", os.path.join(REPO, "tools", "lint_gate.py"))
        gate = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(gate)
        # ONE record shape, owned by tools/lint_gate.py — the CI gate
        # and the bench trajectory must never drift apart
        rec = gate.gate_record()
        rec["findings"] = len(rec["findings"])
        rec.pop("suppression_sites", None)
        return rec
    except Exception as e:  # the bench must never die on its metadata
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}


def planverify_context() -> dict:
    """The plan-soundness record next to the perf ones (ISSUE 11): run
    the planck verifier (plan/verify.py) over the whole TPC-H + TPC-DS
    golden corpus at 1 and 8 segments — nodes checked, rule-table rows
    hit, findings, wall. Plans only, never compiles or executes, so it
    runs identically on live and replay rounds."""
    try:
        from tools.golden_plans import verify_corpus

        rec = verify_corpus()
        return {"ok": not rec["findings"],
                "plans": rec["plans"],
                "nodes": rec["nodes"],
                "rules_hit": len(rec["rules_hit"]),
                "findings": len(rec["findings"]),
                "wall_s": round(rec["wall_s"], 3)}
    except Exception as e:  # the bench must never die on its metadata
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}


def recovery_context(session) -> dict:
    """The robustness record next to the lifecycle/join-path ones: the
    mid-statement recovery configuration (exec/recovery.py) and what
    THIS process's executions actually did — device-loss retries, tile
    checkpoints/resumes, and the replay cost. Counter-only: never plans,
    compiles, or executes."""
    cfg = session.config.recovery
    h = session.config.health
    lg = session.stmt_log
    return {
        "enabled": bool(cfg.enabled),
        "checkpoint_every": int(cfg.checkpoint_every),
        "retries": int(h.retries),
        "retry_budget_s": float(h.retry_budget_s),
        "counters": {k: lg.counter(k) for k in (
            "recoveries", "tile_checkpoints", "tile_resumes",
            "tiles_replayed", "tile_resume_declined",
            "recovery_wall_ms")},
    }


def adaptive_context(session=None) -> dict:
    """The feedback-driven re-optimization record (ISSUE 17) next to
    the robustness one: the bench session's learned-sketch store and
    adaptation counters, plus a SELF-CONTAINED first-vs-second A/B on a
    mis-stated-skew workload — the first execution learns (and, tiled,
    adapts mid-statement); the second plans against the folded sketch.
    Runs on whatever backend this process has (engine vs itself), so it
    rides live and replay rounds identically."""
    import numpy as np

    import cloudberry_tpu as cb
    from cloudberry_tpu.config import get_config

    rec: dict = {}
    if session is not None:
        from cloudberry_tpu.plan import feedback as FB

        store = FB.store_for(session)
        if store is not None:
            rec["store"] = store.snapshot()
        lg = session.stmt_log
        rec["counters"] = {k: lg.counter(k) for k in (
            "feedback_folds", "feedback_seeded", "feedback_gen_bumps",
            "rung_downgrades", "rung_upgrades", "adaptive_replans",
            "tile_replans", "tile_deferred_overflows",
            "tile_window_replays", "tile_stat_syncs")}
    try:
        s = cb.Session(get_config().with_overrides(**{
            "n_segments": 8, "planner.broadcast_threshold": 0,
            "resource.query_mem_bytes": 2 << 20}))
        rng = np.random.default_rng(7)
        s.sql("create table adim (d bigint, g bigint) "
              "distributed by (g)")
        s.sql("create table afact (k bigint, d bigint, v bigint) "
              "distributed by (k)")
        n_dim, n_fact = 400, 200_000
        s.catalog.table("adim").set_data(
            {"d": np.arange(n_dim), "g": np.arange(n_dim) % 7})
        # mis-stated skew: the planner's stats see a uniform d, the
        # data sends 80% of probe rows to one dim key's segment
        d = rng.integers(0, n_dim, n_fact)
        d[rng.random(n_fact) < 0.8] = 3
        s.catalog.table("afact").set_data(
            {"k": np.arange(n_fact) % 997, "d": d,
             "v": rng.integers(0, 100, n_fact)})
        q = ("select g, sum(v) as sv, count(*) as c from afact "
             "join adim on afact.d = adim.d group by g order by g")
        lg = s.stmt_log
        keys = ("compiles", "tile_replans", "adaptive_replans",
                "feedback_seeded", "rung_downgrades", "rung_upgrades",
                "tile_deferred_overflows", "tile_window_replays",
                "tile_stat_syncs")

        def snap():
            return {k: lg.counter(k) for k in keys}

        b0 = snap()
        r1 = s.sql(q).to_pandas()
        b1 = snap()
        r2 = s.sql(q).to_pandas()
        b2 = snap()
        rec["ab"] = {
            "bit_identical": bool(r1.equals(r2)),
            "first": {k: b1[k] - b0[k] for k in keys},
            "second": {k: b2[k] - b1[k] for k in keys},
        }
        from cloudberry_tpu.plan import feedback as FB

        store = FB.store_for(s)
        if store is not None:
            rec["ab_store"] = store.snapshot()
    except Exception as e:  # the bench must never die on its metadata
        rec["ab_error"] = f"{type(e).__name__}: {e}"
    return rec


def obs_context(session=None) -> dict:
    """The observability record next to the perf ones (ISSUE 9): the
    engine registry's series cardinality + trace/statement-table
    occupancy for the bench session, plus a SELF-CONTAINED on-vs-off
    overhead A/B — the same repeated-skeleton workload run with
    telemetry on and with config.obs.enabled=False — so the <3% budget
    is measured every round, live and replay alike (the A/B runs on
    whatever backend this process has; it compares obs against itself,
    not hardware against hardware)."""
    import time as _t

    import numpy as np

    import cloudberry_tpu as cb
    from cloudberry_tpu.config import Config

    rec: dict = {}
    if session is not None:
        snap = session.stmt_log.registry.snapshot()
        rec.update({
            "enabled": bool(session.config.obs.enabled),
            "series": snap["series"],
            "series_dropped": snap["series_dropped"],
            "histograms": len(snap["histograms"]),
            "trace_statements": snap["counters"].get(
                "trace_statements", 0),
            "statement_rows": len(session.stmt_log.statements),
            # capacity & forensics plane (ISSUE 12): statement memory
            # accounting + skew alarms + flight captures over the run
            "stmt_device_bytes": session.stmt_log.registry.hist(
                "stmt_device_bytes"),
            "peak_stmt_bytes": snap["gauges"].get(
                "stmt_device_bytes_peak", 0.0),
            "skew_events": snap["counters"].get("skew_events", 0),
            "flight_captures": snap["counters"].get(
                "flight_captures", 0),
        })

    def build_side(enabled: bool):
        cfg = Config().with_overrides(**{"obs.enabled": enabled})
        s = cb.Session(cfg)
        s.sql("create table obs_ab (k bigint, v double) "
              "distributed by (k)")
        n = 400_000
        s.catalog.table("obs_ab").set_data({
            "k": np.arange(n, dtype=np.int64) % 1024,
            "v": np.arange(n, dtype=np.float64)}, {})
        # a grouped aggregate over 400k rows: several ms per statement,
        # like the bench queries the <3% budget is defined over (the
        # obs cost is per STATEMENT, so sub-ms statements exaggerate it)
        qs = [f"select k, sum(v) as s from obs_ab where k < {900 + i} "
              "group by k" for i in range(4)]
        for q in qs:  # warm: compiles out of the measured window
            s.sql(q)
        return s, qs

    def run_side(s, qs, reps: int = 4) -> float:
        t0 = _t.perf_counter()
        for _rep in range(reps):
            for q in qs:
                s.sql(q)
        return _t.perf_counter() - t0

    try:
        # min-of-3 alternating rounds on persistent sessions: the A/B
        # compares steady-state dispatch, not allocator/GC noise (a
        # single-shot measurement of ~ms statements swamps the delta)
        s_on, qs = build_side(True)
        s_off, _ = build_side(False)
        on_s, off_s = [], []
        for _round in range(3):
            on_s.append(run_side(s_on, qs))
            off_s.append(run_side(s_off, qs))
        rec["ab_on_s"] = round(min(on_s), 4)
        rec["ab_off_s"] = round(min(off_s), 4)
        rec["overhead_pct"] = round(
            (min(on_s) / min(off_s) - 1.0) * 100, 2) \
            if min(off_s) else None
    except Exception as e:  # the bench must never die on its metadata
        rec["ab_error"] = f"{type(e).__name__}: {e}"
    return rec


def compile_cache_context(session, qnames) -> dict:
    """The compile-cache record next to the roofline/interconnect records:
    per query, how the generic-plan layer (sched/paramplan.py) sees it —
    how many literal tokens the skeleton hoists, how many plan slots bind
    as device inputs, and whether the statement is generic-eligible (a
    repeat with different literals reuses the compiled program, zero
    recompiles). Metadata-only: plans, never compiles or executes."""
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sched import paramplan
    from cloudberry_tpu.sql.parser import parse_sql
    from tools.tpch_queries import QUERIES

    out = {"per_query": {}}
    for qn in qnames:
        q = QUERIES[qn]
        norm = paramplan.normalize(q)
        rec = {"params": len(norm[1]) if norm else 0,
               "slots": 0, "generic": False}
        try:
            plan = plan_statement(parse_sql(q), session, {}).plan
            _, bindings, _, slots = paramplan.analyze(session, plan)
            rec["slots"] = len(slots)
            rec["generic"] = bool(
                norm and norm[1]
                and not getattr(plan, "_no_stmt_cache", False))
        except Exception as e:  # metadata must never fail the bench
            rec["error"] = f"{type(e).__name__}: {e}"
        out["per_query"][qn] = rec
    return out


# tables each bench query touches (generation cost scales with SF — load
# only what the selected queries scan)
QUERY_TABLES = {
    "q1": ["lineitem"],
    "q3": ["lineitem", "orders", "customer"],
    "q5": ["lineitem", "orders", "customer", "supplier", "nation",
           "region"],
    "q6": ["lineitem"],
    "q9": ["lineitem", "orders", "part", "partsupp", "supplier", "nation"],
    "q10": ["lineitem", "orders", "customer", "nation"],
    "q18": ["lineitem", "orders", "customer"],
}


def bench_queries() -> list[str]:
    """Default staged set: Q1 (scan+agg), Q3 (3-way join), Q9 (the
    BASELINE.md config-#3 multi-join shape — 6 tables, the heaviest join
    tree; its Motion-heavy variant is benched by tools/ic_bench.py since
    one chip cannot shard). Override with BENCH_QUERIES / BENCH_SF
    (e.g. BENCH_QUERIES=q5,q9 BENCH_SF=10 for the full config #3)."""
    return [q.strip() for q in
            os.environ.get("BENCH_QUERIES", "q1,q3,q9").split(",")
            if q.strip()]


def metric_name() -> str:
    sf = float(os.environ.get("BENCH_SF", "1.0"))
    return (f"tpch_sf{sf:g}_{'_'.join(bench_queries())}"
            "_geomean_speedup_vs_cpu_executor")


def tpu_reachable() -> bool:
    """Probe device init in a subprocess with hard timeouts + backoff — a
    dead accelerator tunnel hangs PJRT init forever, which must not hang the
    benchmark driver; a merely busy relay can need a retry."""
    try:
        timeouts = [
            float(t) for t in
            os.environ.get("BENCH_PROBE_TIMEOUTS", "60,120,240").split(",")
            if t.strip()
        ]
        assert timeouts
    except (ValueError, AssertionError):
        log("bad BENCH_PROBE_TIMEOUTS; using defaults")
        timeouts = [60.0, 120.0, 240.0]
    code = "import jax; d = jax.devices(); print(d[0].platform)"
    for i, t_s in enumerate(timeouts):
        try:
            out = subprocess.run([sys.executable, "-c", code],
                                 capture_output=True, text=True,
                                 timeout=t_s)
            plat = out.stdout.strip().splitlines()[-1] if out.stdout else ""
            if out.returncode == 0 and plat not in ("", "cpu"):
                log(f"TPU probe ok on attempt {i+1}: platform={plat}")
                return True
            log(f"TPU probe attempt {i+1}: rc={out.returncode} "
                f"platform={plat!r}")
        except subprocess.TimeoutExpired:
            log(f"TPU probe attempt {i+1}: timed out after {t_s:.0f}s")
        except Exception as e:
            log(f"TPU probe attempt {i+1}: {type(e).__name__}: {e}")
        if i + 1 < len(timeouts):
            back = 15.0 * (i + 1)
            log(f"backing off {back:.0f}s before re-probe")
            time.sleep(back)
    return False


def emit(record: dict) -> None:
    print(json.dumps(record), flush=True)


def replay_last_good(reason: str) -> None:
    """No live measurement possible — replay the last committed one with its
    provenance in the unit string, or report an unambiguous zero. The
    roofline denominator (bytes scanned, nominal HBM GB/s) is schema-
    derived, so the replayed speedup still carries its MFU-style context."""
    try:
        with open(LAST_GOOD) as f:
            lg = json.load(f)
        # the denominator must describe the REPLAYED measurement, not the
        # current env: recover its SF and query set from the metric name
        # (current BENCH_SF/BENCH_QUERIES may differ from the last-good's)
        import re

        m = re.match(r"tpch_sf([0-9.]+)_(.+)_geomean", lg["metric"])
        lg_sf = float(m.group(1)) if m else 1.0
        lg_queries = m.group(2).split("_") if m else bench_queries()
        emit({
            "metric": lg["metric"],
            "value": lg["value"],
            "unit": (f"x (REPLAY of {lg['provenance']}; "
                     f"no live measurement: {reason}; roofline denominator "
                     f"vs {HBM_GBPS_NOMINAL:g} GB/s HBM nominal)"),
            "vs_baseline": round(lg["value"] / 5.0, 3),
            "roofline": roofline_context(
                lg_queries, lg_sf,
                bytes_by_q=lg.get("scan_bytes"),
                wall_by_q=lg.get("tpu_wall_s")),
            "interconnect": lg.get("interconnect"),
            "compile_cache": lg.get("compile_cache"),
            "join_filter": lg.get("join_filter"),
            "recovery": lg.get("recovery"),
            "lint": lint_context(),
            "planverify": planverify_context(),
            "obs": obs_context(),
            "adaptive": adaptive_context(),
            "scan_ladder": scan_ladder_context(),
            "bufferpool": bufferpool_context(),
            "writepath": writepath_context(),
            "durability": durability_context(),
        })
    except Exception:
        emit({
            "metric": metric_name(),
            "value": 0.0,
            "unit": f"x (NO MEASUREMENT: {reason}; no committed last-good)",
            "vs_baseline": 0.0,
            "roofline": roofline_context(
                bench_queries(), float(os.environ.get("BENCH_SF", "1.0"))),
            "lint": lint_context(),
            "planverify": planverify_context(),
            "obs": obs_context(),
            "adaptive": adaptive_context(),
            "scan_ladder": scan_ladder_context(),
            "bufferpool": bufferpool_context(),
            "writepath": writepath_context(),
            "durability": durability_context(),
        })


def measure() -> None:
    """The actual measurement; runs in a child with the relay env intact."""
    import jax

    try:
        # allow both the TPU (default) and host CPU backends in one process
        jax.config.update("jax_platforms", None)
    except Exception:
        pass

    import cloudberry_tpu as cb
    from cloudberry_tpu.exec.executor import compile_plan
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql
    from tools.tpch_queries import QUERIES
    from tools.tpchgen import load_tpch

    sf = float(os.environ.get("BENCH_SF", "1.0"))
    reps = int(os.environ.get("BENCH_REPS", "3"))
    qnames = bench_queries()

    t0 = time.time()
    session = cb.Session()
    needed = sorted({t for q in qnames
                     for t in QUERY_TABLES.get(q, ["lineitem", "orders",
                                                   "customer"])})
    load_tpch(session, sf=sf, seed=1, tables=needed)
    n_rows = session.catalog.table("lineitem").num_rows
    log(f"generated sf={sf}: lineitem {n_rows} rows "
        f"in {time.time()-t0:.1f}s")

    tpu_devices = [d for d in jax.devices() if d.platform != "cpu"]
    if not tpu_devices:
        # The parent's probe saw a TPU but this child does not: the relay
        # dropped between probe and measurement. Exit with the sentinel rc
        # so the parent treats this as environmental (replay), never as a
        # live 1.0 "speedup" that would clobber the real last-good.
        log("no TPU visible in measurement child (relay dropped?)")
        sys.exit(NO_TPU_RC)
    cpu = jax.devices("cpu")[0]

    def bench_on(plan, device, use_pallas: bool = False) -> tuple:
        # compile per executing platform so each backend gets its best
        # kernel formulation (honest baseline: best-CPU vs best-TPU)
        sess = session
        if use_pallas:
            import copy

            sess = copy.copy(session)
            sess.config = session.config.with_overrides(
                **{"exec.use_pallas": True})
        exe = compile_plan(plan, sess, platform=device.platform)
        from cloudberry_tpu.exec.executor import prepare_inputs

        with jax.default_device(device):
            tables = {
                key: {c: jax.device_put(v, device)
                      for c, v in cols.items()}
                for key, cols in prepare_inputs(exe, sess).items()
            }
            out = exe.fn(tables)  # warmup/compile
            jax.block_until_ready(out)
            best = float("inf")
            for _ in range(reps):
                t = time.time()
                out = exe.fn(tables)
                jax.block_until_ready(out)
                best = min(best, time.time() - t)
        return best, out

    def outputs_match(a, b) -> bool:
        # selected lanes only: unselected lanes legitimately hold
        # path-dependent garbage
        import numpy as np

        acols, asel, _ = a
        bcols, bsel, _ = b
        m = np.asarray(asel)
        if set(acols) != set(bcols)                 or not np.array_equal(m, np.asarray(bsel)):
            return False
        for k in acols:
            x, y = np.asarray(acols[k])[m], np.asarray(bcols[k])[m]
            if x.dtype.kind == "f" or y.dtype.kind == "f":
                if not np.allclose(x.astype(np.float64),
                                   y.astype(np.float64),
                                   rtol=1e-5, atol=1e-6, equal_nan=True):
                    return False
            elif not np.array_equal(x, y):
                return False
        return True

    # data-driven Pallas choice: A/B each query's TPU run with the fused
    # kernels (dense agg + probe join) and keep whichever is faster —
    # BENCH_PALLAS=off skips the B side, =on forces it
    def plan_scan_bytes(plan) -> int:
        """Bytes the plan's projected scans read — the roofline numerator,
        measured off the actual loaded arrays."""
        from cloudberry_tpu.exec.executor import scans_of
        import numpy as np

        total = 0
        for s in scans_of(plan):
            t = session.catalog.table(s.table_name)
            for phys in set(s.column_map) | set(s.mask_map):
                arr = t.data.get(phys)
                if arr is not None:
                    total += np.asarray(arr).nbytes
        return total

    pallas_mode = os.environ.get("BENCH_PALLAS", "ab")
    pallas_won = []
    speedups = {}
    rows_s = {}
    scan_bytes = {}
    tpu_wall = {}
    for qn in qnames:
        # the full optimizer path (pruning, pack-bits proof) — the same
        # plan a session would execute, minus admission/dispatch
        plan = plan_statement(parse_sql(QUERIES[qn]), session, {}).plan
        scan_bytes[qn] = plan_scan_bytes(plan)
        cpu_t, _ = bench_on(plan, cpu)
        log(f"{qn} cpu executor: {cpu_t*1000:.1f} ms")
        tpu_t, tpu_out = bench_on(plan, tpu_devices[0],
                                  use_pallas=(pallas_mode == "on"))
        log(f"{qn} tpu executor: {tpu_t*1000:.1f} ms")
        if pallas_mode == "ab":
            try:
                tp, p_out = bench_on(plan, tpu_devices[0],
                                     use_pallas=True)
                log(f"{qn} tpu executor (pallas): {tp*1000:.1f} ms")
                # a fast-but-wrong kernel must never win: only a
                # result-identical Pallas run can replace the XLA time
                if not outputs_match(tpu_out, p_out):
                    log(f"{qn} PALLAS PARITY FAILURE — results differ "
                        "from the XLA path; pallas time discarded")
                elif tp < tpu_t:
                    tpu_t = tp
                    pallas_won.append(qn)
            except Exception as e:  # never fail the bench on the B side
                log(f"{qn} pallas path failed on hardware "
                    f"({type(e).__name__}: {e}); XLA path kept")
        speedups[qn] = cpu_t / tpu_t
        tpu_wall[qn] = tpu_t
        # rows/sec/chip (BASELINE.md's second metric): the biggest
        # scanned table's rows over the TPU executor time
        big = max(QUERY_TABLES.get(qn, ["lineitem"]),
                  key=lambda t: session.catalog.table(t).num_rows)
        rows_s[qn] = session.catalog.table(big).num_rows / tpu_t

    geo = 1.0
    for s in speedups.values():
        geo *= s
    geo = geo ** (1.0 / len(speedups))
    roofline = roofline_context(qnames, sf, bytes_by_q=scan_bytes,
                                wall_by_q=tpu_wall)
    try:
        # shuffle volume next to the scan denominator: launches and
        # bytes-on-wire per query at the 8-segment plan shape
        interconnect = interconnect_context(session, qnames)
    except Exception as e:  # never fail the bench on the metadata pass
        log(f"interconnect context failed: {type(e).__name__}: {e}")
        interconnect = None
    try:
        # plan-cache view: parameterization/generic eligibility per query
        compile_cache = compile_cache_context(session, qnames)
    except Exception as e:
        log(f"compile_cache context failed: {type(e).__name__}: {e}")
        compile_cache = None
    try:
        # join-path view: runtime filters (eligible joins + estimated
        # reduction) and join-index cache usage observed this run
        join_filter = join_filter_context(session, qnames)
    except Exception as e:
        log(f"join_filter context failed: {type(e).__name__}: {e}")
        join_filter = None
    try:
        # robustness view: recovery config + per-run recovery counters
        recovery = recovery_context(session)
    except Exception as e:
        log(f"recovery context failed: {type(e).__name__}: {e}")
        recovery = None
    try:
        # observability view: registry cardinality + the on/off A/B
        obs = obs_context(session)
    except Exception as e:
        log(f"obs context failed: {type(e).__name__}: {e}")
        obs = None
    try:
        # adaptation view: learned-sketch store + first-vs-second A/B
        adaptive = adaptive_context(session)
    except Exception as e:
        log(f"adaptive context failed: {type(e).__name__}: {e}")
        adaptive = None
    per_q = ", ".join(
        f"{q}={s:.2f}x/{rows_s[q]/1e6:.0f}Mrows_s_chip"
        f"/{roofline['per_query'].get(q, {}).get('hbm_frac', 0):.3f}HBM"
        for q, s in speedups.items())
    if pallas_won:
        per_q += f"; pallas won: {','.join(pallas_won)}"
    emit({
        "metric": metric_name(),
        "value": round(geo, 3),
        "unit": (f"x ({per_q}; roofline vs "
                 f"{HBM_GBPS_NOMINAL:g} GB/s HBM nominal)"),
        "vs_baseline": round(geo / 5.0, 3),
        "roofline": roofline,
        "interconnect": interconnect,
        "compile_cache": compile_cache,
        "join_filter": join_filter,
        "recovery": recovery,
        "lint": lint_context(),
        "planverify": planverify_context(),
        "obs": obs,
        "adaptive": adaptive,
        "scan_ladder": scan_ladder_context(),
        "bufferpool": bufferpool_context(),
        "writepath": writepath_context(),
        "durability": durability_context(),
        "scan_bytes": scan_bytes,
        "tpu_wall_s": {q: round(t, 6) for q, t in tpu_wall.items()},
    })


def main() -> None:
    if not tpu_reachable():
        replay_last_good("TPU relay unreachable after probe retries")
        return

    budget = float(os.environ.get("BENCH_TIMEOUT", "1800"))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"), "--child"],
            capture_output=True, text=True, timeout=budget, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        replay_last_good(f"measurement child exceeded {budget:.0f}s "
                         f"(relay likely wedged mid-run)")
        return
    sys.stderr.write(proc.stderr[-8000:])
    if proc.returncode == NO_TPU_RC:
        replay_last_good("TPU disappeared between probe and measurement")
        return
    # Engine failure (crash, traceback) is NOT environmental: report an
    # honest zero so a real regression can never masquerade as the stale
    # last-good number.
    if proc.returncode != 0:
        emit({
            "metric": metric_name(),
            "value": 0.0,
            "unit": (f"x (ENGINE FAILURE rc={proc.returncode} — "
                     f"see stderr; not an environment problem)"),
            "vs_baseline": 0.0,
        })
        return
    rec = None
    for ln in reversed(proc.stdout.strip().splitlines()):
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                rec = json.loads(ln)
                break
            except json.JSONDecodeError:
                continue
    if rec is None or rec.get("value", 0.0) <= 0.0:
        replay_last_good("measurement child rc=0 but no parsable result")
        return
    # a genuine live measurement: record it as the new last-good
    try:
        lg = {
            "metric": rec["metric"],
            "value": rec["value"],
            "provenance": (
                f"live driver measurement "
                f"{time.strftime('%Y-%m-%d', time.gmtime())}"),
        }
        # measured roofline inputs ride along so a later REPLAY can
        # attach the real denominator instead of the schema estimate
        for k in ("scan_bytes", "tpu_wall_s", "interconnect",
                  "compile_cache", "join_filter", "recovery"):
            if k in rec and rec[k] is not None:
                lg[k] = rec[k]
        with open(LAST_GOOD, "w") as f:
            json.dump(lg, f, indent=1)
            f.write("\n")
    except Exception as e:
        log(f"could not persist last-good: {e}")
    emit(rec)


if __name__ == "__main__":
    if "--child" in sys.argv:
        measure()
    else:
        main()
