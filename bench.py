"""Benchmark: TPC-H Q1 on the TPU chip vs the same engine pinned to host CPU.

BASELINE.md staged config #1: "TPC-H SF1 Q1 — single-segment lineitem scan +
HashAgg (CPU baseline)". Both sides run the identical compiled plan (this
engine); only the executing device differs — so the number isolates the
hardware + XLA-backend difference the way the reference's north star
("≥5× the CPU executor") intends.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
where value = TPU speedup over CPU executor and vs_baseline = value / 5.0
(fraction of the ≥5× target).

Env knobs: BENCH_SF (default 1.0), BENCH_REPS (default 3).
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def tpu_reachable(timeout_s: float = 180.0) -> bool:
    """Probe device init in a subprocess with a hard timeout — a dead
    accelerator tunnel hangs PJRT init forever, which must not hang the
    benchmark driver."""
    import subprocess

    code = "import jax; d = jax.devices(); print(d[0].platform)"
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
        plat = out.stdout.strip().splitlines()[-1] if out.stdout else ""
        return out.returncode == 0 and plat not in ("", "cpu")
    except Exception:
        return False


def main() -> None:
    if not tpu_reachable():
        log("TPU unreachable (device init timed out) — reporting a zero "
            "measurement rather than hanging; the last committed real "
            "measurement was 8.65x at SF1 (see README)")
        print(json.dumps({
            "metric": "tpch_sf1_q1_speedup_vs_cpu_executor",
            "value": 0.0,
            "unit": "x (TPU UNREACHABLE - no measurement)",
            "vs_baseline": 0.0,
        }))
        return

    import jax

    try:
        # allow both the TPU (default) and host CPU backends in one process
        jax.config.update("jax_platforms", None)
    except Exception:
        pass

    import cloudberry_tpu as cb
    from cloudberry_tpu.exec.executor import compile_plan, prepare_tables
    from cloudberry_tpu.plan.binder import Binder
    from cloudberry_tpu.sql.parser import parse_sql
    from tools.tpch_queries import QUERIES
    from tools.tpchgen import load_tpch

    sf = float(os.environ.get("BENCH_SF", "1.0"))
    reps = int(os.environ.get("BENCH_REPS", "3"))

    t0 = time.time()
    session = cb.Session()
    load_tpch(session, sf=sf, seed=1, tables=["lineitem"])
    n_rows = session.catalog.table("lineitem").num_rows
    log(f"generated lineitem sf={sf}: {n_rows} rows in {time.time()-t0:.1f}s")

    plan = Binder(session.catalog).bind_select(parse_sql(QUERIES["q1"]))

    def bench_on(device) -> float:
        # compile per executing platform so each backend gets its best
        # kernel formulation (honest baseline: best-CPU vs best-TPU)
        exe = compile_plan(plan, session, platform=device.platform)
        with jax.default_device(device):
            tables = {
                name: {c: jax.device_put(v, device)
                       for c, v in session.catalog.table(name).data.items()}
                for name in exe.table_names
            }
            # warmup/compile
            out = exe.fn(tables)
            jax.block_until_ready(out)
            best = float("inf")
            for _ in range(reps):
                t = time.time()
                out = exe.fn(tables)
                jax.block_until_ready(out)
                best = min(best, time.time() - t)
        return best

    tpu_devices = [d for d in jax.devices() if d.platform != "cpu"]
    cpu = jax.devices("cpu")[0]

    cpu_t = bench_on(cpu)
    log(f"cpu executor: {cpu_t*1000:.1f} ms "
        f"({n_rows/cpu_t/1e6:.2f}M rows/s)")

    if tpu_devices:
        tpu_t = bench_on(tpu_devices[0])
        log(f"tpu executor: {tpu_t*1000:.1f} ms "
            f"({n_rows/tpu_t/1e6:.2f}M rows/s)")
    else:
        log("no TPU visible; reporting cpu-vs-cpu (=1.0)")
        tpu_t = cpu_t

    speedup = cpu_t / tpu_t
    print(json.dumps({
        "metric": f"tpch_sf{sf:g}_q1_speedup_vs_cpu_executor",
        "value": round(speedup, 3),
        "unit": "x",
        "vs_baseline": round(speedup / 5.0, 3),
    }))


if __name__ == "__main__":
    main()
