"""scan_bench — pipeline on/off A/B over a cold tiled scan + the per-SF
roofline ladder.

The out-of-core scan path (exec/tiled.py `_store_tiles`) is the only
path that matters once tables exceed per-device memory; this bench
makes its throughput claims measurable:

- **A/B** (``--sf N``): stream-load TPC-H lineitem at the given SF into
  a store root (tools/tpchgen.py stream_load_tpch — chunked, never a
  whole-SF table in RAM), then run the Q1-shaped cold tiled aggregate
  with the scan pipeline OFF, ON with serial decode, and ON with the
  configured decode pool — reporting wall, stall %, decode-parallel
  speedup, and an exact result checksum (bit-identity pinned per run).
- **ladder** (``ladder_point(sf)`` / ``--ladder-json``): one
  pipeline-on cold run per SF emitting the roofline ladder record —
  rows/sec/chip, wire bytes (live at 1 segment the merge is motion-
  free, so an 8-segment plan MODEL rides along, clearly labeled),
  decode-vs-compute overlap fraction, and pipeline stall time. bench.py
  attaches these records per round (SF0.1/SF1 live; SF10 replayed from
  a committed artifact with its provenance spelled out — the honest
  REPLAY labeling rules unchanged).
- **hot ladder** (``hot_point(sf)`` / ``--hot-json``): the HBM
  buffer-pool second-pass record — the same ladder query three times
  in ONE session so scan 3 is served from the pool (exec/bufferpool),
  reporting cold vs pool rows/s, the pool pass's hit rate, its
  host-decode count (zero when the hot set is resident), and bit
  identity between passes. bench.py attaches these as its
  "bufferpool" record.

Caveats stated rather than hidden: "cold" means the TABLE is cold (the
scan streams micro-partition files); the OS page cache may still be
warm, so the A/B isolates decode+staging overlap, not disk latency.
On a single-core host the decode-parallel column honestly reports ~1×.

Usage:
    python tools/scan_bench.py --sf 1 --reps 2 --csv out.csv
    python tools/scan_bench.py --sf 10 --ladder-json SCAN_SF10.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # direct script invocation
    sys.path.insert(0, REPO)

Q = ("select l_returnflag, l_linestatus, sum(l_quantity) as sq, "
     "sum(l_extendedprice) as se, count(*) as c from lineitem "
     "group by l_returnflag, l_linestatus "
     "order by l_returnflag, l_linestatus")

CSV_HEADER = ("sf,mode,wall_s,n_tiles,tile_rows,rows,rows_per_s,"
              "feed_s,stall_s,stall_pct,decode_s,read_s,overlap_frac,"
              "parts_read,tile_window,inflight_depth,drain_stall_s,"
              "step_wall_s,checksum")


def _session(root: str, budget: int | None = None, pipeline: bool = True,
             decode_workers: int | None = None, extra: dict | None = None):
    import cloudberry_tpu as cb
    from cloudberry_tpu.config import get_config

    ov: dict = {"storage.root": root,
                "scan_pipeline.enabled": pipeline}
    if budget is not None:
        ov["resource.query_mem_bytes"] = budget
    if decode_workers is not None:
        ov["scan_pipeline.decode_workers"] = decode_workers
    if extra:
        ov.update(extra)
    return cb.Session(get_config().with_overrides(**ov))


def ensure_data(root: str, sf: float, seed: int = 1,
                chunk_rows: int = 1_000_000) -> int:
    """Stream-load lineitem (+orders for realism of the manifest) into
    ``root`` unless already there; returns lineitem rows. A reused root
    must actually hold the requested SF — ~4 lineitems per order in the
    generator's model — or the record would carry a wrong sf label."""
    from tools.tpchgen import _sizes, stream_load_tpch

    s = _session(root)
    try:
        t = s.catalog.table("lineitem")
        expect = 4.0 * _sizes(sf)["n_ord"]
        if not 0.8 * expect <= t.num_rows <= 1.2 * expect:
            raise ValueError(
                f"store root {root!r} holds {t.num_rows} lineitem rows "
                f"but sf={sf} expects ~{int(expect)}: refusing to label "
                "a mismatched dataset — pass a fresh --root")
        return t.num_rows
    except KeyError:
        pass
    counts = stream_load_tpch(s, sf=sf, seed=seed, tables=["lineitem"],
                              chunk_rows=chunk_rows)
    return counts.get("lineitem", 0)


def _checksum(df) -> int:
    """Process-stable exact result digest: the committed SF10 artifact's
    checksum must verify against any later replay, so string columns go
    through sha256 (Python's builtin hash() is salted per process)."""
    import hashlib

    import numpy as np

    acc = 0
    for col in df.columns:
        v = df[col].to_numpy()
        if v.dtype.kind in "iuf":
            acc ^= int(np.asarray(v, dtype=np.float64).view(np.uint64)
                       .sum() & 0xFFFFFFFFFFFFFFFF)
        else:
            digest = hashlib.sha256(
                "\x1f".join(map(str, v.tolist())).encode()).digest()
            acc ^= int.from_bytes(digest[:8], "little")
    return acc


def _one_run(root: str, sf: float, budget: int, pipeline: bool,
             decode_workers: int | None = None,
             window: int | None = None) -> dict:
    """One COLD-SCAN run: a fresh session (the table binds cold), one
    compile statement, then the TIMED statement through the cached
    tiled runner — the stream re-reads and re-decodes every
    micro-partition per statement (tiled streams never warm the
    table), so the measured wall is read+decode+stage+compute with
    compilation excluded from the A/B. ``window`` pins
    ``tile_pipeline.inflight_tiles`` (the windowed dispatch A/B)."""
    extra = ({"tile_pipeline.inflight_tiles": window}
             if window is not None else None)
    s = _session(root, budget=budget, pipeline=pipeline,
                 decode_workers=decode_workers, extra=extra)
    rows = s.catalog.table("lineitem").num_rows
    s.sql(Q)  # compile + first stream (not timed)
    assert s.catalog.table("lineitem").cold  # still the cold path
    t0 = time.perf_counter()
    df = s.sql(Q).to_pandas()
    wall = time.perf_counter() - t0
    rep = s.last_tiled_report
    if rep is None:
        raise RuntimeError(
            "statement did not take the tiled path — shrink --budget")
    pl = rep.get("pipeline", {})
    feed = float(pl.get("feed_s", 0.0) or pl.get("read_s", 0.0) or 0.0)
    stall = float(pl.get("stall_s", 0.0))
    return {
        "sf": sf, "wall_s": round(wall, 4),
        "n_tiles": rep["n_tiles"], "tile_rows": rep["tile_rows"],
        "rows": rows, "rows_per_s": int(rows / wall) if wall else 0,
        "feed_s": round(feed, 4), "stall_s": round(stall, 4),
        "stall_pct": round(100.0 * stall / wall, 2) if wall else 0.0,
        "decode_s": round(float(pl.get("decode_s", 0.0)), 4),
        "read_s": round(float(pl.get("read_s", 0.0)), 4),
        "overlap_frac": float(pl.get("overlap_frac", 0.0)),
        "parts_read": int(pl.get("parts_read", 0)),
        # windowed tile dispatch (exec/tilepipe.py): the window that
        # actually ran, its in-flight high-water mark, the host seconds
        # blocked forcing drained scalars, and the summed device step
        # wall it overlaps against
        "tile_window": int(rep.get("tile_window", 1)),
        "inflight_depth": int(rep.get("inflight_depth", 0)),
        "drain_stall_s": round(float(rep.get("drain_stall_s", 0.0)), 4),
        "step_wall_s": round(
            float(rep["tile_time"]["mean"] * rep["tile_time"]["count"])
            if rep.get("tile_time") else 0.0, 4),
        "checksum": _checksum(df),
    }


def run_ab(sf: float, root: str | None = None, reps: int = 2,
           budget: int = 8 << 20, seed: int = 1,
           chunk_rows: int = 1_000_000) -> list[dict]:
    """The A/B matrix: off / on-serial-decode / on. Best-of-``reps``
    per mode (fresh cold session each rep); exact checksums pin
    bit-identity across modes."""
    own = root is None
    root = root or tempfile.mkdtemp(prefix="cbtpu_scanbench_")
    try:
        ensure_data(root, sf, seed=seed, chunk_rows=chunk_rows)
        # one discarded warmup: backend init + first-compile noise must
        # not land on whichever mode happens to run first
        _one_run(root, sf, budget, True)
        out = []
        for mode, pipe, workers in (("off", False, None),
                                    ("on1", True, 1),
                                    ("on", True, None)):
            best = None
            for _ in range(max(int(reps), 1)):
                r = _one_run(root, sf, budget, pipe, workers)
                if best is None or r["wall_s"] < best["wall_s"]:
                    best = r
            best["mode"] = mode
            out.append(best)
        return out
    finally:
        if own:
            import shutil

            shutil.rmtree(root, ignore_errors=True)


def window_ab(sf: float, root: str | None = None, reps: int = 2,
              budget: int = 8 << 20, seed: int = 1,
              chunk_rows: int = 1_000_000, window: int = 4) -> dict:
    """Windowed-dispatch A/B (exec/tilepipe.py): the same cold tiled
    run at ``inflight_tiles=1`` (the legacy synchronous loop) vs
    ``window``, scan pipeline on in both arms so only the dispatch
    window moves. Best-of-``reps`` per arm; the record carries the
    overlap evidence the ISSUE asks for — counter-pinned in-flight
    depth and the drain stall vs device step wall — plus bit identity
    across the arms. On a single-core CPU host the wall-clock verdict
    is honestly ~1×: there is no second execution stream to overlap
    with, so the win shows up as drain_stall_s ≪ step_wall_s, not as
    wall time."""
    own = root is None
    root = root or tempfile.mkdtemp(prefix="cbtpu_scanwin_")
    try:
        ensure_data(root, sf, seed=seed, chunk_rows=chunk_rows)
        _one_run(root, sf, budget, True, window=1)  # discarded warmup
        arms = {}
        for label, w in (("w1", 1), ("on", window)):
            best = None
            for _ in range(max(int(reps), 1)):
                r = _one_run(root, sf, budget, True, window=w)
                if best is None or r["wall_s"] < best["wall_s"]:
                    best = r
            arms[label] = best
        w1, on = arms["w1"], arms["on"]
        return {
            "sf": sf, "window": on["tile_window"],
            "inflight_depth": on["inflight_depth"],
            "wall_s_w1": w1["wall_s"], "wall_s_on": on["wall_s"],
            "speedup_window": round(w1["wall_s"] / on["wall_s"], 3)
            if on["wall_s"] else None,
            "drain_stall_s_w1": w1["drain_stall_s"],
            "drain_stall_s_on": on["drain_stall_s"],
            "step_wall_s": on["step_wall_s"],
            "stall_frac_of_step": round(
                on["drain_stall_s"] / on["step_wall_s"], 4)
            if on["step_wall_s"] else None,
            "bit_identical": w1["checksum"] == on["checksum"],
            "checksum": on["checksum"],
        }
    finally:
        if own:
            import shutil

            shutil.rmtree(root, ignore_errors=True)


def summarize(rows: list[dict]) -> dict:
    by = {r["mode"]: r for r in rows}
    rec = {"speedup_pipeline": None, "speedup_decode_parallel": None,
           "bit_identical": None}
    if "on" in by and "off" in by:
        rec["speedup_pipeline"] = round(
            by["off"]["wall_s"] / by["on"]["wall_s"], 3) \
            if by["on"]["wall_s"] else None
        rec["bit_identical"] = by["on"]["checksum"] == by["off"]["checksum"]
    if "on" in by and "on1" in by and by["on"]["wall_s"]:
        rec["speedup_decode_parallel"] = round(
            by["on1"]["wall_s"] / by["on"]["wall_s"], 3)
    return rec


def to_csv(rows: list[dict]) -> str:
    lines = [CSV_HEADER]
    for r in rows:
        lines.append(",".join(str(r.get(k, ""))
                              for k in CSV_HEADER.split(",")))
    return "\n".join(lines) + "\n"


def _wire_model_8seg(root: str) -> int:
    """Static 8-segment wire-byte MODEL for the ladder query (the
    single-chip live run has no motions): plan at nseg=8 and total
    every Motion's packed-wire footprint — the same arithmetic
    bench.py's interconnect record uses."""
    import copy

    from cloudberry_tpu.exec import kernels as K
    from cloudberry_tpu.exec.executor import all_nodes
    from cloudberry_tpu.plan import nodes as PN
    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.sql.parser import parse_sql

    s = _session(root)
    clone = copy.copy(s)
    clone.config = s.config.with_overrides(n_segments=8)
    plan = plan_statement(parse_sql(Q), clone, {}).plan
    total = 0
    seen: set = set()
    for node in all_nodes(plan):
        if not isinstance(node, PN.PMotion) or id(node) in seen:
            continue
        seen.add(id(node))
        layout = K.wire_layout(
            {f.name: f.type.np_dtype for f in node.fields})
        total += max(int(node.out_capacity), 1) * layout.row_bytes()
    return total


def ladder_point(sf: float, root: str | None = None,
                 budget: int = 8 << 20, seed: int = 1,
                 chunk_rows: int = 1_000_000) -> dict:
    """One roofline-ladder record at ``sf``: a single pipeline-on cold
    tiled run plus the 8-segment wire model."""
    own = root is None
    root = root or tempfile.mkdtemp(prefix="cbtpu_scanladder_")
    try:
        t0 = time.perf_counter()
        rows = ensure_data(root, sf, seed=seed, chunk_rows=chunk_rows)
        load_s = time.perf_counter() - t0
        _one_run(root, sf, budget, True)  # discarded process warmup
        r = _one_run(root, sf, budget, True)  # cold table, warm process
        try:
            wire_model = _wire_model_8seg(root)
        except Exception:  # noqa: BLE001 — the model must never kill a run
            wire_model = None
        return {
            "sf": sf, "rows": rows,
            "rows_per_s_chip": r["rows_per_s"],
            "wall_s": r["wall_s"], "load_s": round(load_s, 2),
            "n_tiles": r["n_tiles"], "tile_rows": r["tile_rows"],
            "stall_s": r["stall_s"], "stall_pct": r["stall_pct"],
            "decode_s": r["decode_s"],
            "overlap_frac": r["overlap_frac"],
            "wire_bytes_live_1seg": 0,
            "wire_bytes_8seg_model": wire_model,
            "checksum": r["checksum"],
        }
    finally:
        if own:
            import shutil

            shutil.rmtree(root, ignore_errors=True)


def hot_point(sf: float, root: str | None = None,
              budget: int = 8 << 20, seed: int = 1,
              chunk_rows: int = 1_000_000,
              pool_bytes: int = 1 << 30) -> dict:
    """One SECOND-PASS buffer-pool record at ``sf`` (ISSUE 16): ONE
    session runs the ladder query three times against the HBM buffer
    pool — scan 1 is cold (misses, admission frequency 1), scan 2
    still decodes but admits every chunk, scan 3 is served from the
    pool. The record compares the admission pass (full host
    read+decode) with the pool pass on the SAME container: rows/s
    each, the pool pass's hit rate and host-decode count (the ZERO
    claim, pinned by counters rather than clocks), and bit identity
    between the passes. ``pool_bytes`` must exceed the SF's decoded
    working set (the 1 GiB default covers SF1, NOT SF10 — pass
    ``--pool-bytes`` there) — this record measures hit-rate behavior,
    not budget pressure (tests/test_bufferpool.py owns the eviction
    story)."""
    own = root is None
    root = root or tempfile.mkdtemp(prefix="cbtpu_scanhot_")
    try:
        rows = ensure_data(root, sf, seed=seed, chunk_rows=chunk_rows)
        s = _session(root, budget=budget,
                     extra={"bufferpool.max_bytes": pool_bytes})
        log = s.stmt_log
        s.sql(Q)  # compile + scan 1: cold, counts each chunk once
        if s.last_tiled_report is None:
            # a one-shot scan warms the TABLE in this session and the
            # later passes would measure RAM, not the pool — the record
            # only means something on the tiled streaming path
            raise RuntimeError(
                "statement did not take the tiled path — shrink --budget")
        passes = []
        for _ in range(2):  # scan 2 admits, scan 3 serves from HBM
            before = {c: log.counter(c) for c in
                      ("bufpool_hits", "bufpool_misses", "bufpool_admits",
                       "host_decodes")}
            t0 = time.perf_counter()
            df = s.sql(Q).to_pandas()
            wall = time.perf_counter() - t0
            passes.append({
                "wall_s": wall, "checksum": _checksum(df),
                **{c: log.counter(c) - v for c, v in before.items()}})
        admit, pool = passes
        seen = pool["bufpool_hits"] + pool["bufpool_misses"]
        return {
            "sf": sf, "rows": rows,
            "rows_per_s_cold": int(rows / admit["wall_s"])
            if admit["wall_s"] else 0,
            "rows_per_s_pool": int(rows / pool["wall_s"])
            if pool["wall_s"] else 0,
            "speedup_pool": round(admit["wall_s"] / pool["wall_s"], 3)
            if pool["wall_s"] else None,
            "bufpool_hit_rate": round(pool["bufpool_hits"] / seen, 4)
            if seen else 0.0,
            "host_decodes_pool_pass": pool["host_decodes"],
            "bufpool_admits": admit["bufpool_admits"],
            "bit_identical": admit["checksum"] == pool["checksum"],
            "checksum": pool["checksum"],
        }
    finally:
        if own:
            import shutil

            shutil.rmtree(root, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--sf", type=float, default=1.0)
    ap.add_argument("--root", default=None,
                    help="store root to (re)use; default: temp dir")
    ap.add_argument("--reps", type=int, default=2)
    ap.add_argument("--budget", type=int, default=8 << 20)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--chunk-rows", type=int, default=1_000_000)
    ap.add_argument("--csv", default=None, help="write CSV here")
    ap.add_argument("--ladder-json", default=None,
                    help="emit ONE ladder_point record to this file "
                         "(skips the A/B matrix)")
    ap.add_argument("--hot-json", default=None,
                    help="emit ONE hot_point record (second-pass HBM "
                         "buffer-pool hit rate) to this file — how an "
                         "SF10 pool point gets committed on hardware")
    ap.add_argument("--pool-bytes", type=int, default=1 << 30,
                    help="bufferpool.max_bytes for --hot-json; must "
                         "exceed the SF's decoded working set or the "
                         "record measures eviction, not hit rate "
                         "(SF10 needs ~8 GiB)")
    ap.add_argument("--window-ab", action="store_true",
                    help="run the windowed tile-dispatch A/B "
                         "(inflight_tiles 1 vs --window) instead of "
                         "the pipeline matrix")
    ap.add_argument("--window", type=int, default=4,
                    help="in-flight window for --window-ab's on arm")
    args = ap.parse_args(argv)

    if args.window_ab:
        rec = window_ab(args.sf, root=args.root, reps=args.reps,
                        budget=args.budget, seed=args.seed,
                        chunk_rows=args.chunk_rows, window=args.window)
        print(json.dumps(rec))
        if args.csv:
            with open(args.csv, "w") as f:
                json.dump(rec, f, indent=1)
                f.write("\n")
        return 0

    if args.hot_json:
        rec = hot_point(args.sf, root=args.root, budget=args.budget,
                        seed=args.seed, chunk_rows=args.chunk_rows,
                        pool_bytes=args.pool_bytes)
        rec["measured_utc"] = time.strftime("%Y-%m-%d", time.gmtime())
        with open(args.hot_json, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
        print(json.dumps(rec))
        return 0

    if args.ladder_json:
        rec = ladder_point(args.sf, root=args.root, budget=args.budget,
                           seed=args.seed, chunk_rows=args.chunk_rows)
        rec["measured_utc"] = time.strftime("%Y-%m-%d",
                                            time.gmtime())
        with open(args.ladder_json, "w") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
        print(json.dumps(rec))
        return 0

    rows = run_ab(args.sf, root=args.root, reps=args.reps,
                  budget=args.budget, seed=args.seed,
                  chunk_rows=args.chunk_rows)
    csv = to_csv(rows)
    print(csv, end="")
    print(json.dumps(summarize(rows)))
    if args.csv:
        with open(args.csv, "w") as f:
            f.write(csv)
    return 0


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
