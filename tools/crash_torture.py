"""Process-kill torture harness — crash-only storage, proven by killing.

The crash-recovery tests that matter run against a REAL server process
over the wire, not a mocked store (ISSUE 19): this harness launches
``python -m cloudberry_tpu ... serve`` with ``CBTPU_INJECT`` arming one
durability seam with the ``crash`` action (``os._exit(137)`` — the
in-process SIGKILL), drives a mixed workload (multi-row INSERTs, DELETEs
of previously-acked rows, sequence nextval, wire appends through the
ingest plane) while recording exactly which statements were
ACKNOWLEDGED, waits for the kill, restarts the server clean, and
verifies the crash-only contract:

- every acked write is durable and bit-identical (v == k * 7 for every
  row the workload wrote — a flipped bit or truncated blob cannot hide);
- unacked statements are all-or-nothing (both rows of the statement or
  neither — never a torn half-statement);
- acked DELETEs stay deleted; unacked DELETEs are all-or-nothing;
- an acked ``nextval`` value is never handed out again after restart;
- ``fsck`` finds zero corruption (orphans — crash residue — are
  expected, collectable, and gone after ``--gc``);
- recovery_ms: restart-to-first-answered-query wall clock.

Run one seam or the whole matrix:

    python -m tools.crash_torture --seam io_manifest_write
    python -m tools.crash_torture --matrix --json

Exit 0 iff every run verified clean. tests/test_crash_torture.py drives
the matrix in the slow tier and one seam as the tier-1 smoke.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the matrix: every durability seam the wire workload reaches, with a
# hit count late enough that setup DDL and a few acked writes precede
# the kill (the interesting state is acked-then-killed, not empty-store)
MATRIX_SEAMS = [
    ("io_partition_write", 14),
    ("io_manifest_write", 14),
    ("storage_commit_before_current", 14),
    ("storage_commit_after_current", 14),
    ("io_atomic_json", 6),
    ("io_feedback_write", 2),
    ("io_journal_write", 2),
    ("compact_chunk", 1),
    ("compact_commit", 1),
    ("ingest_flush", 2),
    ("dml_delete", 2),
]

# compaction must run (and run often) for its seams to be reachable
# from a short workload; broadcast off so the periodic join plans
# redistribute motions — the material feedback folds that reach the
# io_feedback_write seam
_SERVE_OVERRIDES = ("compact.enabled=true", "compact.interval_s=0.1",
                    "compact.max_delta_parts=2", "ingest.flush_ms=10",
                    "planner.broadcast_threshold=0")

# two segments so redistribute motions exist at all (a singleton store
# gathers everything); the subprocess fakes the devices on CPU
_N_SEGMENTS = 2
_XLA_FLAGS = f"--xla_force_host_platform_device_count={_N_SEGMENTS}"

_V_FACTOR = 7  # v = k * _V_FACTOR — the bit-identity invariant


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ServerProc:
    """One server subprocess on a store, banner-synchronized."""

    def __init__(self, store: str, inject: str | None = None,
                 timeout_s: float = 60.0):
        self.port = _free_port()
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = _XLA_FLAGS
        env.pop("CBTPU_INJECT", None)
        if inject:
            env["CBTPU_INJECT"] = inject
        cmd = [sys.executable, "-m", "cloudberry_tpu", "--store", store,
               "serve", "--port", str(self.port)]
        for kv in _SERVE_OVERRIDES:
            cmd += ["--set", kv]
        self.proc = subprocess.Popen(
            cmd, cwd=REPO, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        self.banner = False
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            line = self.proc.stdout.readline()
            if not line and self.proc.poll() is not None:
                return  # died during startup (an armed seam fired early)
            if "serving on" in line:
                self.banner = True
                return
        raise TimeoutError("server did not print its banner in time")

    def client(self):
        from cloudberry_tpu.serve.client import Client

        return Client("127.0.0.1", self.port, timeout=30.0)

    def wait_dead(self, timeout_s: float = 30.0) -> int | None:
        try:
            return self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            return None

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
            self.proc.wait(timeout=10)


def _drive(server: ServerProc, state: dict, max_stmts: int,
           wall_s: float) -> None:
    """Run the mixed workload until the server dies (the armed seam
    fired) or the budget runs out. Records acks as they arrive —
    state is only ever updated AFTER a response, so it is exactly the
    client's knowledge at the moment of the crash."""
    from cloudberry_tpu.serve.client import ServerError

    c = None
    i = 0
    deadline = time.monotonic() + wall_s
    while i < max_stmts and time.monotonic() < deadline:
        if server.proc.poll() is not None:
            break
        try:
            if c is None:
                c = server.client()
            if not state["setup"]:
                c.sql("create table tort (k bigint, v bigint) "
                      "distributed by (k)")
                c.sql("create table ing (k bigint, v bigint) "
                      "distributed by (k)")
                c.sql("create sequence tseq")
                state["setup"] = True
                continue
            i += 1
            a, b = 2 * i, 2 * i + 1
            if i % 7 == 3 and state["inserted"]:
                # delete a previously ACKED statement's rows
                ka = sorted(state["inserted"])[0]
                kb = ka + 1
                state["delete_submitted"].add((ka, kb))
                c.sql(f"DELETE FROM tort WHERE k >= {ka} AND k <= {kb}")
                state["deleted"].add((ka, kb))
                for k in (ka, kb):
                    state["inserted"].pop(k, None)
            elif i % 5 == 4:
                out = c.sql("SELECT nextval('tseq') AS v")
                state["seq_acked"] = max(state["seq_acked"],
                                         int(out["rows"][0][0]))
            elif i % 9 == 5 and state["inserted"]:
                # a self-join on the NON-distribution key: plans two
                # redistribute motions, whose observed stats fold as
                # material feedback → _FEEDBACK.json persists (the
                # io_feedback_write seam)
                c.sql("SELECT count(a.k) AS n FROM tort a "
                      "JOIN tort b ON a.v = b.v")
            elif i % 4 == 1:
                state["append_submitted"].add(a)
                c.append("ing", [[a, a * _V_FACTOR]], ["k", "v"])
                state["appended"].add(a)
            else:
                state["submitted"].add((a, b))
                c.sql(f"INSERT INTO tort VALUES "
                      f"({a}, {a * _V_FACTOR}), ({b}, {b * _V_FACTOR})")
                for k in (a, b):
                    state["inserted"][k] = k * _V_FACTOR
        except (ServerError, OSError, ValueError):
            # connection severed (the kill) or a refused statement —
            # anything unacked stays unacked; try once more in case the
            # server is still alive (e.g. a retryable refusal)
            try:
                if c is not None:
                    c.close()
            except Exception:  # noqa: BLE001
                pass
            c = None
            if server.proc.poll() is not None:
                break
            time.sleep(0.05)
    if c is not None:
        try:
            c.close()
        except Exception:  # noqa: BLE001
            pass


def _fresh_state() -> dict:
    return {"setup": False, "inserted": {}, "submitted": set(),
            "deleted": set(), "delete_submitted": set(),
            "appended": set(), "append_submitted": set(), "seq_acked": 0}


def _verify(server: ServerProc, state: dict, problems: list) -> None:
    """The restart-side checks, over the wire against the clean server."""
    c = server.client()
    try:
        rows = c.sql("SELECT k, v FROM tort ORDER BY k")["rows"] \
            if state["setup"] else []
        have = {int(r[0]): int(r[1]) for r in rows}
        # 1. every ACKED insert row durable + bit-identical (unless a
        # DELETE was submitted for it — an unacked delete may have
        # committed before the kill)
        del_sub_ks = {k for ab in state["delete_submitted"] for k in ab}
        for k, v in state["inserted"].items():
            if k not in have:
                if k not in del_sub_ks:
                    problems.append(f"ACKED ROW LOST: k={k}")
            elif have[k] != v:
                problems.append(f"ACKED ROW CORRUPT: k={k} "
                                f"v={have[k]} != {v}")
        # 2. no row the workload never submitted
        submitted = {k for ab in state["submitted"] for k in ab}
        for k in have:
            if k not in submitted:
                problems.append(f"PHANTOM ROW: k={k}")
        # 3. bit-identity + all-or-nothing for UNACKED statements
        acked_ks = set(state["inserted"])
        deleted_ks = {k for ab in state["deleted"] for k in ab} \
            | {k for ab in state["delete_submitted"] for k in ab}
        for (a, b) in state["submitted"]:
            if a in acked_ks or a in deleted_ks or b in deleted_ks:
                continue
            ina, inb = a in have, b in have
            if ina != inb:
                problems.append(f"TORN STATEMENT: k={a} present={ina}, "
                                f"k={b} present={inb}")
            for k in (a, b):
                if k in have and have[k] != k * _V_FACTOR:
                    problems.append(f"UNACKED ROW CORRUPT: k={k} "
                                    f"v={have[k]}")
        # 4. acked DELETEs stay deleted; unacked all-or-nothing
        for (ka, kb) in state["deleted"]:
            for k in (ka, kb):
                if k in have:
                    problems.append(f"ACKED DELETE UNDONE: k={k}")
        for (ka, kb) in state["delete_submitted"] - state["deleted"]:
            if (ka in have) != (kb in have):
                problems.append(f"TORN DELETE: k={ka},{kb}")
        # 5. acked ingest appends durable + intact
        if state["setup"]:
            ing = {int(r[0]): int(r[1]) for r in
                   c.sql("SELECT k, v FROM ing ORDER BY k")["rows"]}
            for k in state["appended"]:
                if k not in ing:
                    problems.append(f"ACKED APPEND LOST: k={k}")
            for k, v in ing.items():
                if k not in state["append_submitted"]:
                    problems.append(f"PHANTOM APPEND: k={k}")
                elif v != k * _V_FACTOR:
                    problems.append(f"APPEND CORRUPT: k={k} v={v}")
        # 6. an acked sequence value is never reissued
        if state["setup"] and state["seq_acked"]:
            nxt = int(c.sql("SELECT nextval('tseq') AS v")["rows"][0][0])
            if nxt <= state["seq_acked"]:
                problems.append(f"SEQUENCE REWOUND: nextval {nxt} after "
                                f"acked {state['seq_acked']}")
    finally:
        c.close()


def run_seam(seam: str, hit: int = 6, store: str | None = None,
             max_stmts: int = 200, wall_s: float = 30.0) -> dict:
    """Torture one seam end to end. Returns the verdict record; the run
    passed iff ``rec['problems'] == []``."""
    from cloudberry_tpu.storage.fsck import fsck

    tmp = None
    if store is None:
        tmp = tempfile.mkdtemp(prefix=f"tort-{seam}-")
        store = os.path.join(tmp, "store")
    rec = {"seam": seam, "hit": hit, "fired": False, "exit_code": None,
           "acked_inserts": 0, "acked_lost": 0, "problems": [],
           "recovery_ms": None, "fsck_clean": None, "orphans": 0}
    problems = rec["problems"]
    try:
        os.makedirs(store, exist_ok=True)
        subprocess.run(
            [sys.executable, "-m", "cloudberry_tpu", "--store", store,
             "init", "--segments", str(_N_SEGMENTS), "--force"],
            cwd=REPO, check=True, capture_output=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "XLA_FLAGS": _XLA_FLAGS})
        state = _fresh_state()
        srv = ServerProc(store, inject=f"{seam}=crash@{hit}")
        try:
            _drive(srv, state, max_stmts, wall_s)
            code = srv.wait_dead(timeout_s=20.0)
        finally:
            srv.kill()
        rec["exit_code"] = code if code is not None else srv.proc.poll()
        rec["fired"] = rec["exit_code"] == 137
        rec["acked_inserts"] = len(state["inserted"])
        if not rec["fired"]:
            problems.append(
                f"seam {seam!r} never fired (exit {rec['exit_code']}) — "
                "the workload does not reach it")
        # restart CLEAN (no injection) and verify over the wire
        t0 = time.monotonic()
        srv2 = ServerProc(store)
        try:
            if not srv2.banner:
                problems.append("restart failed: no banner")
            else:
                _verify(srv2, state, problems)
                rec["recovery_ms"] = round(
                    (time.monotonic() - t0) * 1000.0, 1)
        finally:
            srv2.kill()
        rec["acked_lost"] = sum(
            1 for p in problems
            if p.startswith(("ACKED ROW LOST", "ACKED APPEND LOST")))
        # offline integrity: corruption-free, orphans collectable
        rep = fsck(store, deep=True)
        rec["fsck_clean"] = rep["clean"]
        rec["orphans"] = len(rep["orphans"])
        if not rep["clean"]:
            problems.extend(f"fsck: {p}" for p in rep["problems"])
        rep2 = fsck(store, deep=True, grace_s=0.0, gc=True)
        if rep2["orphans"]:
            problems.append(f"fsck --gc left {len(rep2['orphans'])} "
                            "orphan(s) behind")
        if not fsck(store, deep=True)["clean"]:
            problems.append("fsck not clean after GC")
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)
    return rec


def run_matrix(seams=None) -> list[dict]:
    out = []
    for seam, hit in (seams or MATRIX_SEAMS):
        rec = run_seam(seam, hit=hit)
        status = "PASS" if not rec["problems"] else "FAIL"
        print(f"{status} {seam}@{hit}: exit={rec['exit_code']} "
              f"acked={rec['acked_inserts']} lost={rec['acked_lost']} "
              f"recovery={rec['recovery_ms']}ms "
              f"orphans={rec['orphans']}", flush=True)
        for p in rec["problems"]:
            print(f"  {p}", flush=True)
        out.append(rec)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seam", default=None,
                    help="torture one seam (see MATRIX_SEAMS)")
    ap.add_argument("--hit", type=int, default=None,
                    help="fire on the Nth hit (default: the matrix's)")
    ap.add_argument("--matrix", action="store_true",
                    help="run every matrix seam")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    if args.seam:
        hit = args.hit if args.hit is not None else dict(MATRIX_SEAMS).get(
            args.seam, 6)
        recs = [run_seam(args.seam, hit=hit)]
    elif args.matrix:
        recs = run_matrix()
    else:
        ap.error("pick --seam NAME or --matrix")
    if args.json:
        print(json.dumps(recs, indent=2))
    failed = [r for r in recs if r["problems"]]
    print(f"crash torture: {len(recs) - len(failed)}/{len(recs)} seams "
          f"clean", flush=True)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
