"""TPC-DS join-heavy subset (standard benchmark SQL; BASELINE config #5).
q17 includes the stddev_samp aggregates of the official query."""

DS_QUERIES: dict[str, str] = {}

DS_QUERIES["q17"] = """
select
    i_item_id, i_item_desc, s_state,
    count(ss_quantity) as store_sales_quantitycount,
    avg(ss_quantity) as store_sales_quantityave,
    stddev_samp(ss_quantity) as store_sales_quantitystdev,
    count(sr_return_quantity) as store_returns_quantitycount,
    avg(sr_return_quantity) as store_returns_quantityave,
    stddev_samp(sr_return_quantity) as store_returns_quantitystdev,
    count(cs_quantity) as catalog_sales_quantitycount,
    avg(cs_quantity) as catalog_sales_quantityave,
    stddev_samp(cs_quantity) as catalog_sales_quantitystdev
from
    store_sales, store_returns, catalog_sales,
    date_dim d1, date_dim d2, date_dim d3, store, item
where
    d1.d_quarter_name = '2000Q1'
    and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk
    and s_store_sk = ss_store_sk
    and ss_customer_sk = sr_customer_sk
    and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and sr_returned_date_sk = d2.d_date_sk
    and d2.d_quarter_name in ('2000Q1', '2000Q2', '2000Q3')
    and sr_customer_sk = cs_bill_customer_sk
    and sr_item_sk = cs_item_sk
    and cs_sold_date_sk = d3.d_date_sk
    and d3.d_quarter_name in ('2000Q1', '2000Q2', '2000Q3')
group by i_item_id, i_item_desc, s_state
order by i_item_id, i_item_desc, s_state
limit 100
"""

DS_QUERIES["q25"] = """
select
    i_item_id, i_item_desc, s_store_id, s_store_name,
    sum(ss_net_profit) as store_sales_profit,
    sum(sr_net_loss) as store_returns_loss,
    sum(cs_net_profit) as catalog_sales_profit
from
    store_sales, store_returns, catalog_sales,
    date_dim d1, date_dim d2, date_dim d3, store, item
where
    d1.d_moy = 4
    and d1.d_year = 2000
    and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk
    and s_store_sk = ss_store_sk
    and ss_customer_sk = sr_customer_sk
    and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and sr_returned_date_sk = d2.d_date_sk
    and d2.d_moy between 4 and 10
    and d2.d_year = 2000
    and sr_customer_sk = cs_bill_customer_sk
    and sr_item_sk = cs_item_sk
    and cs_sold_date_sk = d3.d_date_sk
    and d3.d_moy between 4 and 10
    and d3.d_year = 2000
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""

DS_QUERIES["q29"] = """
select
    i_item_id, i_item_desc, s_store_id, s_store_name,
    sum(ss_quantity) as store_sales_quantity,
    sum(sr_return_quantity) as store_returns_quantity,
    sum(cs_quantity) as catalog_sales_quantity
from
    store_sales, store_returns, catalog_sales,
    date_dim d1, date_dim d2, date_dim d3, store, item
where
    d1.d_moy = 4
    and d1.d_year = 1999
    and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk
    and s_store_sk = ss_store_sk
    and ss_customer_sk = sr_customer_sk
    and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and sr_returned_date_sk = d2.d_date_sk
    and d2.d_moy between 4 and 7
    and d2.d_year = 1999
    and sr_customer_sk = cs_bill_customer_sk
    and sr_item_sk = cs_item_sk
    and cs_sold_date_sk = d3.d_date_sk
    and d3.d_year in (1999, 2000, 2001)
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""

# -------- star-schema reporting subset (round 4): q3/q42/q52/q55/q98 —
# single-fact joins over brand/category/manager dimensions; q98 adds the
# revenue-ratio window over a grouped aggregate.

DS_QUERIES["q3"] = """
select d_year, i_brand_id, i_brand, sum(ss_net_profit) as sum_agg
from date_dim dt join store_sales on dt.d_date_sk = ss_sold_date_sk
     join item on ss_item_sk = i_item_sk
where i_manufact_id = 7 and dt.d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, i_brand_id
limit 100
"""

DS_QUERIES["q42"] = """
select d_year, i_category, sum(ss_ext_sales_price) as total
from date_dim dt join store_sales on dt.d_date_sk = ss_sold_date_sk
     join item on ss_item_sk = i_item_sk
where d_moy = 11 and d_year = 2000
group by d_year, i_category
order by total desc, d_year, i_category
limit 100
"""

DS_QUERIES["q52"] = """
select d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) as ext_price
from date_dim dt join store_sales on dt.d_date_sk = ss_sold_date_sk
     join item on ss_item_sk = i_item_sk
where i_manager_id = 1 and d_moy = 12 and d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, i_brand_id
limit 100
"""

DS_QUERIES["q55"] = """
select i_brand_id, i_brand, sum(ss_ext_sales_price) as ext_price
from date_dim join store_sales on d_date_sk = ss_sold_date_sk
     join item on ss_item_sk = i_item_sk
where i_manager_id = 3 and d_moy = 11 and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, i_brand_id
limit 100
"""

DS_QUERIES["q98"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) as itemrevenue,
       sum(ss_ext_sales_price) * 100.0
           / sum(sum(ss_ext_sales_price)) over (partition by i_class)
           as revenueratio
from store_sales join item on ss_item_sk = i_item_sk
     join date_dim on ss_sold_date_sk = d_date_sk
where i_category in ('Books', 'Music')
  and d_date between date '2000-02-01' and date '2000-03-01'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

# -------- web/inventory family (round 4): q12/q21/q86 over the
# web_sales + inventory + warehouse tables.

DS_QUERIES["q12"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) as itemrevenue,
       sum(ws_ext_sales_price) * 100 / sum(sum(ws_ext_sales_price))
         over (partition by i_class) as revenueratio
from web_sales join item on ws_item_sk = i_item_sk
     join date_dim on ws_sold_date_sk = d_date_sk
where i_category in ('Sports', 'Books')
  and d_date between date '1999-02-22' and date '1999-03-24'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

DS_QUERIES["q20"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) as itemrevenue,
       sum(cs_ext_sales_price) * 100 / sum(sum(cs_ext_sales_price))
         over (partition by i_class) as revenueratio
from catalog_sales join item on cs_item_sk = i_item_sk
     join date_dim on cs_sold_date_sk = d_date_sk
where i_category in ('Sports', 'Music')
  and d_date between date '1999-02-22' and date '1999-03-24'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

# q21 (adapted: price band widened to the generated price range)
DS_QUERIES["q21"] = """
select * from (
  select w_warehouse_name, i_item_id,
         sum(case when d_date < date '2000-03-11'
                  then inv_quantity_on_hand else 0 end) as inv_before,
         sum(case when d_date >= date '2000-03-11'
                  then inv_quantity_on_hand else 0 end) as inv_after
  from inventory join warehouse on inv_warehouse_sk = w_warehouse_sk
       join item on i_item_sk = inv_item_sk
       join date_dim on inv_date_sk = d_date_sk
  where i_current_price between 0.99 and 10.00
    and d_date between date '2000-03-11' - interval '30' day
                   and date '2000-03-11' + interval '30' day
  group by w_warehouse_name, i_item_id) x
where case when inv_before > 0
           then 1.0 * inv_after / inv_before else null end
      between 2.0 / 3.0 and 3.0 / 2.0
order by w_warehouse_name, i_item_id
limit 100
"""

# q86 (adapted: ws_net_paid -> ws_net_profit, d_month_seq -> d_year)
DS_QUERIES["q86"] = """
select sum(ws_net_profit) as total_sum, i_category, i_class,
       grouping(i_category) + grouping(i_class) as lochierarchy,
       rank() over (
         partition by grouping(i_category) + grouping(i_class),
           case when grouping(i_class) = 0 then i_category end
         order by sum(ws_net_profit) desc
       ) as rank_within_parent
from web_sales join date_dim d1 on d1.d_date_sk = ws_sold_date_sk
     join item on i_item_sk = ws_item_sk
where d1.d_year = 2000
group by rollup (i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
"""

# q65 (adapted: d_month_seq window -> d_year, ss_sales_price ->
# ss_ext_sales_price, i_wholesale_cost dropped — tpcds-lite does not
# generate them; the shape is the point: two aggregated derived tables
# joined with a cross-derived-table arithmetic predicate)
DS_QUERIES["q65"] = """
select s_store_name, i_item_desc, sc.revenue, i_current_price, i_brand
from store join
     (select ss_store_sk, avg(revenue) as ave
      from (select ss_store_sk, ss_item_sk,
                   sum(ss_ext_sales_price) as revenue
            from store_sales join date_dim on ss_sold_date_sk = d_date_sk
            where d_year = 2000
            group by ss_store_sk, ss_item_sk) sa
      group by ss_store_sk) sb on s_store_sk = sb.ss_store_sk
     join
     (select ss_store_sk, ss_item_sk,
             sum(ss_ext_sales_price) as revenue
      from store_sales join date_dim on ss_sold_date_sk = d_date_sk
      where d_year = 2000
      group by ss_store_sk, ss_item_sk) sc
     on sb.ss_store_sk = sc.ss_store_sk
     join item on i_item_sk = sc.ss_item_sk
where sc.revenue <= 0.1 * sb.ave
order by s_store_name, i_item_desc, revenue, i_current_price, i_brand
limit 100
"""

# q36 (adapted: s_state list uses generated states; the shape is the
# point — ROLLUP + grouping() driving a rank() window over aggregate
# outputs, ordered by the grouping level)
DS_QUERIES["q36"] = """
select sum(ss_net_profit) / sum(ss_ext_sales_price) as gross_margin,
       i_category, i_class,
       grouping(i_category) + grouping(i_class) as lochierarchy,
       rank() over (
         partition by grouping(i_category) + grouping(i_class),
           case when grouping(i_class) = 0 then i_category end
         order by sum(ss_net_profit) / sum(ss_ext_sales_price)
       ) as rank_within_parent
from store_sales join date_dim on d_date_sk = ss_sold_date_sk
     join item on i_item_sk = ss_item_sk
     join store on s_store_sk = ss_store_sk
where d_year = 2001 and s_state in ('TN', 'CA', 'TX', 'WA')
group by rollup (i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
"""

# q27 (adapted: the official query filters on customer_demographics,
# which tpcds-lite does not generate — the grouping shape, the rollup,
# and grouping() are the point here; avgs run over the generated
# measure columns)
DS_QUERIES["q27"] = """
select i_item_id, s_state, grouping(s_state) as g_state,
       avg(ss_quantity) as agg1,
       avg(ss_ext_sales_price) as agg2,
       avg(ss_net_profit) as agg3
from store_sales join date_dim on ss_sold_date_sk = d_date_sk
     join store on ss_store_sk = s_store_sk
     join item on ss_item_sk = i_item_sk
where d_year = 2000
group by rollup (i_item_id, s_state)
order by i_item_id, s_state
limit 100
"""

# -------- round 5: families that force NEW binder/executor surface —
# mixed distinct aggregates + EXISTS/NOT EXISTS (q16/q94), INTERSECT
# count (q38), CASE day-of-week pivots (q43/q59), cross-channel CTE
# unions with IN-subqueries (q33/q56/q60), year-over-year CTE self-joins
# (q74), DQA-in-scalar-subquery ratio (q90), LEFT-join actual-sales
# (q93), FULL-join channel overlap (q97), ship-delay buckets (q99),
# correlated-average item filter (q6), zip/state OR filters (q15).
# Adaptations from the official text (columns tpcds-lite does not
# generate: call centers, ship modes, web sites, demographics, gmt
# offsets; d_month_seq windows -> d_year) are noted per query.

# q6 (adapted: month filter via d_year/d_moy; the correlated average
# is compared as "avg < price / 1.2" — same predicate, in the shape the
# decorrelator recognizes)
DS_QUERIES["q6"] = """
select a.ca_state as state, count(*) as cnt
from customer_address a join customer c
       on a.ca_address_sk = c.c_current_addr_sk
     join store_sales s on c.c_customer_sk = s.ss_customer_sk
     join date_dim d on s.ss_sold_date_sk = d.d_date_sk
     join item i on s.ss_item_sk = i.i_item_sk
where d.d_year = 2000 and d.d_moy = 5
  and (select avg(j.i_current_price) from item j
       where j.i_category = i.i_category) < i.i_current_price / 1.2
group by a.ca_state
having count(*) >= 10
order by cnt, a.ca_state
limit 100
"""

# q15 (adapted: qoy -> d_moy, sales-price threshold over generated range)
DS_QUERIES["q15"] = """
select ca_zip, sum(cs_ext_sales_price) as total
from catalog_sales join customer on cs_bill_customer_sk = c_customer_sk
     join customer_address on c_current_addr_sk = ca_address_sk
     join date_dim on cs_sold_date_sk = d_date_sk
where (substring(ca_zip, 1, 3) in ('850', '856', '859', '834')
       or ca_state in ('CA', 'WA', 'GA')
       or cs_ext_sales_price > 480)
  and d_year = 2001 and d_moy = 1
group by ca_zip
order by ca_zip
limit 100
"""

# q16 (adapted: no call-center dimension; ship-date window via d_date)
DS_QUERIES["q16"] = """
select count(distinct cs_order_number) as order_count,
       sum(cs_ext_ship_cost) as total_shipping_cost,
       sum(cs_net_profit) as total_net_profit
from catalog_sales cs1
     join date_dim on cs1.cs_ship_date_sk = d_date_sk
     join warehouse on cs1.cs_warehouse_sk = w_warehouse_sk
where d_date between date '1999-02-01'
                 and date '1999-02-01' + interval '60' day
  and exists (select 1 from catalog_sales cs2
              where cs1.cs_order_number = cs2.cs_order_number
                and cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  and not exists (select 1 from catalog_returns cr1
                  where cs1.cs_order_number = cr1.cr_order_number)
limit 100
"""

# q33 (adapted: no ca_gmt_offset; manufacturer set from the Books
# category, May 1998)
DS_QUERIES["q33"] = """
with ss as (
  select i_manufact_id, sum(ss_ext_sales_price) as total_sales
  from store_sales join date_dim on ss_sold_date_sk = d_date_sk
       join item on ss_item_sk = i_item_sk
  where i_manufact_id in (select it2.i_manufact_id from item it2
                          where it2.i_category = 'Books')
    and d_year = 1998 and d_moy = 5
  group by i_manufact_id),
cs as (
  select i_manufact_id, sum(cs_ext_sales_price) as total_sales
  from catalog_sales join date_dim on cs_sold_date_sk = d_date_sk
       join item on cs_item_sk = i_item_sk
  where i_manufact_id in (select it2.i_manufact_id from item it2
                          where it2.i_category = 'Books')
    and d_year = 1998 and d_moy = 5
  group by i_manufact_id),
ws as (
  select i_manufact_id, sum(ws_ext_sales_price) as total_sales
  from web_sales join date_dim on ws_sold_date_sk = d_date_sk
       join item on ws_item_sk = i_item_sk
  where i_manufact_id in (select it2.i_manufact_id from item it2
                          where it2.i_category = 'Books')
    and d_year = 1998 and d_moy = 5
  group by i_manufact_id)
select i_manufact_id, sum(total_sales) as total_sales
from (select * from ss union all select * from cs
      union all select * from ws) tmp1
group by i_manufact_id
order by total_sales, i_manufact_id
limit 100
"""

# q38 (adapted: d_month_seq window -> d_year)
DS_QUERIES["q38"] = """
select count(*) as cnt from (
  (select distinct c_last_name, c_first_name, d_date
   from store_sales join date_dim on ss_sold_date_sk = d_date_sk
        join customer on ss_customer_sk = c_customer_sk
   where d_year = 1999)
  intersect
  (select distinct c_last_name, c_first_name, d_date
   from catalog_sales join date_dim on cs_sold_date_sk = d_date_sk
        join customer on cs_bill_customer_sk = c_customer_sk
   where d_year = 1999)
  intersect
  (select distinct c_last_name, c_first_name, d_date
   from web_sales join date_dim on ws_sold_date_sk = d_date_sk
        join customer on ws_bill_customer_sk = c_customer_sk
   where d_year = 1999)
) hot_cust
limit 100
"""

# q43 (adapted: gmt offset dropped; measure is ss_ext_sales_price)
DS_QUERIES["q43"] = """
select s_store_name, s_store_id,
  sum(case when d_day_name = 'Sunday' then ss_ext_sales_price
           else null end) as sun_sales,
  sum(case when d_day_name = 'Monday' then ss_ext_sales_price
           else null end) as mon_sales,
  sum(case when d_day_name = 'Tuesday' then ss_ext_sales_price
           else null end) as tue_sales,
  sum(case when d_day_name = 'Wednesday' then ss_ext_sales_price
           else null end) as wed_sales,
  sum(case when d_day_name = 'Thursday' then ss_ext_sales_price
           else null end) as thu_sales,
  sum(case when d_day_name = 'Friday' then ss_ext_sales_price
           else null end) as fri_sales,
  sum(case when d_day_name = 'Saturday' then ss_ext_sales_price
           else null end) as sat_sales
from date_dim join store_sales on d_date_sk = ss_sold_date_sk
     join store on s_store_sk = ss_store_sk
where d_year = 2000
group by s_store_name, s_store_id
order by s_store_name, s_store_id
limit 100
"""

# q56 (adapted: i_color -> i_class filter; September 2000)
DS_QUERIES["q56"] = """
with ss as (
  select i_item_id, sum(ss_ext_sales_price) as total_sales
  from store_sales join date_dim on ss_sold_date_sk = d_date_sk
       join item on ss_item_sk = i_item_sk
  where i_item_id in (select it2.i_item_id from item it2
                      where it2.i_class in ('alpha', 'beta'))
    and d_year = 2000 and d_moy = 9
  group by i_item_id),
cs as (
  select i_item_id, sum(cs_ext_sales_price) as total_sales
  from catalog_sales join date_dim on cs_sold_date_sk = d_date_sk
       join item on cs_item_sk = i_item_sk
  where i_item_id in (select it2.i_item_id from item it2
                      where it2.i_class in ('alpha', 'beta'))
    and d_year = 2000 and d_moy = 9
  group by i_item_id),
ws as (
  select i_item_id, sum(ws_ext_sales_price) as total_sales
  from web_sales join date_dim on ws_sold_date_sk = d_date_sk
       join item on ws_item_sk = i_item_sk
  where i_item_id in (select it2.i_item_id from item it2
                      where it2.i_class in ('alpha', 'beta'))
    and d_year = 2000 and d_moy = 9
  group by i_item_id)
select i_item_id, sum(total_sales) as total_sales
from (select * from ss union all select * from cs
      union all select * from ws) tmp1
group by i_item_id
order by total_sales, i_item_id
limit 100
"""

# q59 (adapted: the d_month_seq windows become explicit week ranges and
# the year-over-year match is d_week_seq = d_week_seq2 - 52; measure is
# ss_ext_sales_price)
DS_QUERIES["q59"] = """
with wss as (
  select d_week_seq, ss_store_sk,
    sum(case when d_day_name = 'Sunday' then ss_ext_sales_price
             else null end) as sun_sales,
    sum(case when d_day_name = 'Monday' then ss_ext_sales_price
             else null end) as mon_sales,
    sum(case when d_day_name = 'Friday' then ss_ext_sales_price
             else null end) as fri_sales,
    sum(case when d_day_name = 'Saturday' then ss_ext_sales_price
             else null end) as sat_sales
  from store_sales join date_dim on d_date_sk = ss_sold_date_sk
  group by d_week_seq, ss_store_sk)
select y.s_store_name1, y.s_store_id1, y.d_week_seq1,
       y.sun_sales1 / x.sun_sales2 as sun_r,
       y.mon_sales1 / x.mon_sales2 as mon_r,
       y.fri_sales1 / x.fri_sales2 as fri_r,
       y.sat_sales1 / x.sat_sales2 as sat_r
from (select s_store_name as s_store_name1, wss.d_week_seq as d_week_seq1,
             s_store_id as s_store_id1, sun_sales as sun_sales1,
             mon_sales as mon_sales1, fri_sales as fri_sales1,
             sat_sales as sat_sales1
      from wss join store on ss_store_sk = s_store_sk
      where d_week_seq between 27 and 52) y
     join
     (select s_store_name as s_store_name2, wss.d_week_seq as d_week_seq2,
             s_store_id as s_store_id2, sun_sales as sun_sales2,
             mon_sales as mon_sales2, fri_sales as fri_sales2,
             sat_sales as sat_sales2
      from wss join store on ss_store_sk = s_store_sk
      where d_week_seq between 79 and 104) x
     on y.s_store_id1 = x.s_store_id2
    and y.d_week_seq1 = x.d_week_seq2 - 52
order by y.s_store_name1, y.s_store_id1, y.d_week_seq1
limit 100
"""

# q60 (adapted: no gmt offset; Music category, September 1999)
DS_QUERIES["q60"] = """
with ss as (
  select i_item_id, sum(ss_ext_sales_price) as total_sales
  from store_sales join date_dim on ss_sold_date_sk = d_date_sk
       join item on ss_item_sk = i_item_sk
  where i_item_id in (select it2.i_item_id from item it2
                      where it2.i_category = 'Music')
    and d_year = 1999 and d_moy = 9
  group by i_item_id),
cs as (
  select i_item_id, sum(cs_ext_sales_price) as total_sales
  from catalog_sales join date_dim on cs_sold_date_sk = d_date_sk
       join item on cs_item_sk = i_item_sk
  where i_item_id in (select it2.i_item_id from item it2
                      where it2.i_category = 'Music')
    and d_year = 1999 and d_moy = 9
  group by i_item_id),
ws as (
  select i_item_id, sum(ws_ext_sales_price) as total_sales
  from web_sales join date_dim on ws_sold_date_sk = d_date_sk
       join item on ws_item_sk = i_item_sk
  where i_item_id in (select it2.i_item_id from item it2
                      where it2.i_category = 'Music')
    and d_year = 1999 and d_moy = 9
  group by i_item_id)
select i_item_id, sum(total_sales) as total_sales
from (select * from ss union all select * from cs
      union all select * from ws) tmp1
group by i_item_id
order by i_item_id, total_sales
limit 100
"""

# q74 (adapted: the sale-type discriminator is numeric (1 = store,
# 2 = web) — the shape under test is the 4-instance CTE self-join with
# the guarded ratio comparison)
DS_QUERIES["q74"] = """
with year_total as (
  select c_customer_id as customer_id, c_first_name, c_last_name,
         d_year as year_, sum(ss_ext_sales_price) as year_total,
         1 as sale_type
  from customer join store_sales on c_customer_sk = ss_customer_sk
       join date_dim on ss_sold_date_sk = d_date_sk
  where d_year in (1999, 2000)
  group by c_customer_id, c_first_name, c_last_name, d_year
  union all
  select c_customer_id as customer_id, c_first_name, c_last_name,
         d_year as year_, sum(ws_ext_sales_price) as year_total,
         2 as sale_type
  from customer join web_sales on c_customer_sk = ws_bill_customer_sk
       join date_dim on ws_sold_date_sk = d_date_sk
  where d_year in (1999, 2000)
  group by c_customer_id, c_first_name, c_last_name, d_year)
select t_s_secyear.customer_id, t_s_secyear.c_first_name,
       t_s_secyear.c_last_name
from year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
where t_s_secyear.customer_id = t_s_firstyear.customer_id
  and t_s_firstyear.customer_id = t_w_secyear.customer_id
  and t_s_firstyear.customer_id = t_w_firstyear.customer_id
  and t_s_firstyear.sale_type = 1 and t_w_firstyear.sale_type = 2
  and t_s_secyear.sale_type = 1 and t_w_secyear.sale_type = 2
  and t_s_firstyear.year_ = 1999 and t_s_secyear.year_ = 2000
  and t_w_firstyear.year_ = 1999 and t_w_secyear.year_ = 2000
  and t_s_firstyear.year_total > 0 and t_w_firstyear.year_total > 0
  and case when t_w_firstyear.year_total > 0
           then t_w_secyear.year_total / t_w_firstyear.year_total
           else null end
      > case when t_s_firstyear.year_total > 0
             then t_s_secyear.year_total / t_s_firstyear.year_total
             else null end
order by t_s_secyear.customer_id, t_s_secyear.c_first_name,
         t_s_secyear.c_last_name
limit 100
"""

# q90 (adapted: the am/pm ratio is expressed through uncorrelated
# scalar subqueries — the cross join of two one-row derived tables is
# the same computation)
DS_QUERIES["q90"] = """
select (select count(distinct ws_order_number)
        from web_sales join time_dim on ws_sold_time_sk = t_time_sk
             join web_page on ws_web_page_sk = wp_web_page_sk
        where t_hour between 8 and 9
          and wp_char_count between 2000 and 5000)
       / (select count(distinct ws_order_number)
          from web_sales join time_dim on ws_sold_time_sk = t_time_sk
               join web_page on ws_web_page_sk = wp_web_page_sk
          where t_hour between 19 and 20
            and wp_char_count between 2000 and 5000) as am_pm_ratio
"""

# q93 (adapted: no reason dimension — returned lines subtract their
# returned quantity; measure is ss_ext_sales_price as the unit price
# proxy)
DS_QUERIES["q93"] = """
select ss_customer_sk, sum(act_sales) as sumsales
from (select ss_customer_sk,
             case when sr_return_quantity is not null
                  then (ss_quantity - sr_return_quantity)
                       * ss_ext_sales_price
                  else ss_quantity * ss_ext_sales_price end as act_sales
      from store_sales left join store_returns
           on sr_item_sk = ss_item_sk
          and sr_ticket_number = ss_ticket_number) t
group by ss_customer_sk
order by sumsales, ss_customer_sk
limit 100
"""

# q94 (adapted: no web_site dimension; ship-date window via d_date)
DS_QUERIES["q94"] = """
select count(distinct ws_order_number) as order_count,
       sum(ws_ext_ship_cost) as total_shipping_cost,
       sum(ws_net_profit) as total_net_profit
from web_sales ws1
     join date_dim on ws1.ws_ship_date_sk = d_date_sk
     join warehouse on ws1.ws_warehouse_sk = w_warehouse_sk
where d_date between date '1999-02-01'
                 and date '1999-02-01' + interval '60' day
  and exists (select 1 from web_sales ws2
              where ws1.ws_order_number = ws2.ws_order_number
                and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  and not exists (select 1 from web_returns wr1
                  where ws1.ws_order_number = wr1.wr_order_number)
limit 100
"""

# q97
DS_QUERIES["q97"] = """
with ssci as (
  select ss_customer_sk as customer_sk, ss_item_sk as item_sk
  from store_sales join date_dim on ss_sold_date_sk = d_date_sk
  where d_year = 2000
  group by ss_customer_sk, ss_item_sk),
csci as (
  select cs_bill_customer_sk as customer_sk, cs_item_sk as item_sk
  from catalog_sales join date_dim on cs_sold_date_sk = d_date_sk
  where d_year = 2000
  group by cs_bill_customer_sk, cs_item_sk)
select sum(case when ssci.customer_sk is not null
                 and csci.customer_sk is null then 1 else 0 end)
         as store_only,
       sum(case when ssci.customer_sk is null
                 and csci.customer_sk is not null then 1 else 0 end)
         as catalog_only,
       sum(case when ssci.customer_sk is not null
                 and csci.customer_sk is not null then 1 else 0 end)
         as store_and_catalog
from ssci full join csci
     on ssci.customer_sk = csci.customer_sk
    and ssci.item_sk = csci.item_sk
limit 100
"""

# q99 (adapted: warehouse replaces the call-center/ship-mode grouping;
# the delay buckets are the official 30/60/90/120-day CASE pivot)
DS_QUERIES["q99"] = """
select w_warehouse_name,
  sum(case when cs_ship_date_sk - cs_sold_date_sk <= 30
           then 1 else 0 end) as d30,
  sum(case when cs_ship_date_sk - cs_sold_date_sk > 30
            and cs_ship_date_sk - cs_sold_date_sk <= 60
           then 1 else 0 end) as d60,
  sum(case when cs_ship_date_sk - cs_sold_date_sk > 60
            and cs_ship_date_sk - cs_sold_date_sk <= 90
           then 1 else 0 end) as d90,
  sum(case when cs_ship_date_sk - cs_sold_date_sk > 90
            and cs_ship_date_sk - cs_sold_date_sk <= 120
           then 1 else 0 end) as d120,
  sum(case when cs_ship_date_sk - cs_sold_date_sk > 120
           then 1 else 0 end) as dmore
from catalog_sales join warehouse on cs_warehouse_sk = w_warehouse_sk
group by w_warehouse_name
order by w_warehouse_name
limit 100
"""
