"""TPC-DS join-heavy subset (standard benchmark SQL; BASELINE config #5).
q17 includes the stddev_samp aggregates of the official query."""

DS_QUERIES: dict[str, str] = {}

DS_QUERIES["q17"] = """
select
    i_item_id, i_item_desc, s_state,
    count(ss_quantity) as store_sales_quantitycount,
    avg(ss_quantity) as store_sales_quantityave,
    stddev_samp(ss_quantity) as store_sales_quantitystdev,
    count(sr_return_quantity) as store_returns_quantitycount,
    avg(sr_return_quantity) as store_returns_quantityave,
    stddev_samp(sr_return_quantity) as store_returns_quantitystdev,
    count(cs_quantity) as catalog_sales_quantitycount,
    avg(cs_quantity) as catalog_sales_quantityave,
    stddev_samp(cs_quantity) as catalog_sales_quantitystdev
from
    store_sales, store_returns, catalog_sales,
    date_dim d1, date_dim d2, date_dim d3, store, item
where
    d1.d_quarter_name = '2000Q1'
    and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk
    and s_store_sk = ss_store_sk
    and ss_customer_sk = sr_customer_sk
    and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and sr_returned_date_sk = d2.d_date_sk
    and d2.d_quarter_name in ('2000Q1', '2000Q2', '2000Q3')
    and sr_customer_sk = cs_bill_customer_sk
    and sr_item_sk = cs_item_sk
    and cs_sold_date_sk = d3.d_date_sk
    and d3.d_quarter_name in ('2000Q1', '2000Q2', '2000Q3')
group by i_item_id, i_item_desc, s_state
order by i_item_id, i_item_desc, s_state
limit 100
"""

DS_QUERIES["q25"] = """
select
    i_item_id, i_item_desc, s_store_id, s_store_name,
    sum(ss_net_profit) as store_sales_profit,
    sum(sr_net_loss) as store_returns_loss,
    sum(cs_net_profit) as catalog_sales_profit
from
    store_sales, store_returns, catalog_sales,
    date_dim d1, date_dim d2, date_dim d3, store, item
where
    d1.d_moy = 4
    and d1.d_year = 2000
    and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk
    and s_store_sk = ss_store_sk
    and ss_customer_sk = sr_customer_sk
    and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and sr_returned_date_sk = d2.d_date_sk
    and d2.d_moy between 4 and 10
    and d2.d_year = 2000
    and sr_customer_sk = cs_bill_customer_sk
    and sr_item_sk = cs_item_sk
    and cs_sold_date_sk = d3.d_date_sk
    and d3.d_moy between 4 and 10
    and d3.d_year = 2000
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""

DS_QUERIES["q29"] = """
select
    i_item_id, i_item_desc, s_store_id, s_store_name,
    sum(ss_quantity) as store_sales_quantity,
    sum(sr_return_quantity) as store_returns_quantity,
    sum(cs_quantity) as catalog_sales_quantity
from
    store_sales, store_returns, catalog_sales,
    date_dim d1, date_dim d2, date_dim d3, store, item
where
    d1.d_moy = 4
    and d1.d_year = 1999
    and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk
    and s_store_sk = ss_store_sk
    and ss_customer_sk = sr_customer_sk
    and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and sr_returned_date_sk = d2.d_date_sk
    and d2.d_moy between 4 and 7
    and d2.d_year = 1999
    and sr_customer_sk = cs_bill_customer_sk
    and sr_item_sk = cs_item_sk
    and cs_sold_date_sk = d3.d_date_sk
    and d3.d_year in (1999, 2000, 2001)
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""

# -------- star-schema reporting subset (round 4): q3/q42/q52/q55/q98 —
# single-fact joins over brand/category/manager dimensions; q98 adds the
# revenue-ratio window over a grouped aggregate.

DS_QUERIES["q3"] = """
select d_year, i_brand_id, i_brand, sum(ss_net_profit) as sum_agg
from date_dim dt join store_sales on dt.d_date_sk = ss_sold_date_sk
     join item on ss_item_sk = i_item_sk
where i_manufact_id = 7 and dt.d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, i_brand_id
limit 100
"""

DS_QUERIES["q42"] = """
select d_year, i_category, sum(ss_ext_sales_price) as total
from date_dim dt join store_sales on dt.d_date_sk = ss_sold_date_sk
     join item on ss_item_sk = i_item_sk
where d_moy = 11 and d_year = 2000
group by d_year, i_category
order by total desc, d_year, i_category
limit 100
"""

DS_QUERIES["q52"] = """
select d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) as ext_price
from date_dim dt join store_sales on dt.d_date_sk = ss_sold_date_sk
     join item on ss_item_sk = i_item_sk
where i_manager_id = 1 and d_moy = 12 and d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, i_brand_id
limit 100
"""

DS_QUERIES["q55"] = """
select i_brand_id, i_brand, sum(ss_ext_sales_price) as ext_price
from date_dim join store_sales on d_date_sk = ss_sold_date_sk
     join item on ss_item_sk = i_item_sk
where i_manager_id = 3 and d_moy = 11 and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, i_brand_id
limit 100
"""

DS_QUERIES["q98"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) as itemrevenue,
       sum(ss_ext_sales_price) * 100.0
           / sum(sum(ss_ext_sales_price)) over (partition by i_class)
           as revenueratio
from store_sales join item on ss_item_sk = i_item_sk
     join date_dim on ss_sold_date_sk = d_date_sk
where i_category in ('Books', 'Music')
  and d_date between date '2000-02-01' and date '2000-03-01'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

# -------- web/inventory family (round 4): q12/q21/q86 over the
# web_sales + inventory + warehouse tables.

DS_QUERIES["q12"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) as itemrevenue,
       sum(ws_ext_sales_price) * 100 / sum(sum(ws_ext_sales_price))
         over (partition by i_class) as revenueratio
from web_sales join item on ws_item_sk = i_item_sk
     join date_dim on ws_sold_date_sk = d_date_sk
where i_category in ('Sports', 'Books')
  and d_date between date '1999-02-22' and date '1999-03-24'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

DS_QUERIES["q20"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) as itemrevenue,
       sum(cs_ext_sales_price) * 100 / sum(sum(cs_ext_sales_price))
         over (partition by i_class) as revenueratio
from catalog_sales join item on cs_item_sk = i_item_sk
     join date_dim on cs_sold_date_sk = d_date_sk
where i_category in ('Sports', 'Music')
  and d_date between date '1999-02-22' and date '1999-03-24'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

# q21 (adapted: price band widened to the generated price range)
DS_QUERIES["q21"] = """
select * from (
  select w_warehouse_name, i_item_id,
         sum(case when d_date < date '2000-03-11'
                  then inv_quantity_on_hand else 0 end) as inv_before,
         sum(case when d_date >= date '2000-03-11'
                  then inv_quantity_on_hand else 0 end) as inv_after
  from inventory join warehouse on inv_warehouse_sk = w_warehouse_sk
       join item on i_item_sk = inv_item_sk
       join date_dim on inv_date_sk = d_date_sk
  where i_current_price between 0.99 and 10.00
    and d_date between date '2000-03-11' - interval '30' day
                   and date '2000-03-11' + interval '30' day
  group by w_warehouse_name, i_item_id) x
where case when inv_before > 0
           then 1.0 * inv_after / inv_before else null end
      between 2.0 / 3.0 and 3.0 / 2.0
order by w_warehouse_name, i_item_id
limit 100
"""

# q86 (adapted: ws_net_paid -> ws_net_profit, d_month_seq -> d_year)
DS_QUERIES["q86"] = """
select sum(ws_net_profit) as total_sum, i_category, i_class,
       grouping(i_category) + grouping(i_class) as lochierarchy,
       rank() over (
         partition by grouping(i_category) + grouping(i_class),
           case when grouping(i_class) = 0 then i_category end
         order by sum(ws_net_profit) desc
       ) as rank_within_parent
from web_sales join date_dim d1 on d1.d_date_sk = ws_sold_date_sk
     join item on i_item_sk = ws_item_sk
where d1.d_year = 2000
group by rollup (i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
"""

# q65 (adapted: d_month_seq window -> d_year, ss_sales_price ->
# ss_ext_sales_price, i_wholesale_cost dropped — tpcds-lite does not
# generate them; the shape is the point: two aggregated derived tables
# joined with a cross-derived-table arithmetic predicate)
DS_QUERIES["q65"] = """
select s_store_name, i_item_desc, sc.revenue, i_current_price, i_brand
from store join
     (select ss_store_sk, avg(revenue) as ave
      from (select ss_store_sk, ss_item_sk,
                   sum(ss_ext_sales_price) as revenue
            from store_sales join date_dim on ss_sold_date_sk = d_date_sk
            where d_year = 2000
            group by ss_store_sk, ss_item_sk) sa
      group by ss_store_sk) sb on s_store_sk = sb.ss_store_sk
     join
     (select ss_store_sk, ss_item_sk,
             sum(ss_ext_sales_price) as revenue
      from store_sales join date_dim on ss_sold_date_sk = d_date_sk
      where d_year = 2000
      group by ss_store_sk, ss_item_sk) sc
     on sb.ss_store_sk = sc.ss_store_sk
     join item on i_item_sk = sc.ss_item_sk
where sc.revenue <= 0.1 * sb.ave
order by s_store_name, i_item_desc, revenue, i_current_price, i_brand
limit 100
"""

# q36 (adapted: s_state list uses generated states; the shape is the
# point — ROLLUP + grouping() driving a rank() window over aggregate
# outputs, ordered by the grouping level)
DS_QUERIES["q36"] = """
select sum(ss_net_profit) / sum(ss_ext_sales_price) as gross_margin,
       i_category, i_class,
       grouping(i_category) + grouping(i_class) as lochierarchy,
       rank() over (
         partition by grouping(i_category) + grouping(i_class),
           case when grouping(i_class) = 0 then i_category end
         order by sum(ss_net_profit) / sum(ss_ext_sales_price)
       ) as rank_within_parent
from store_sales join date_dim on d_date_sk = ss_sold_date_sk
     join item on i_item_sk = ss_item_sk
     join store on s_store_sk = ss_store_sk
where d_year = 2001 and s_state in ('TN', 'CA', 'TX', 'WA')
group by rollup (i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
"""

# q27 (adapted: the official query filters on customer_demographics,
# which tpcds-lite does not generate — the grouping shape, the rollup,
# and grouping() are the point here; avgs run over the generated
# measure columns)
DS_QUERIES["q27"] = """
select i_item_id, s_state, grouping(s_state) as g_state,
       avg(ss_quantity) as agg1,
       avg(ss_ext_sales_price) as agg2,
       avg(ss_net_profit) as agg3
from store_sales join date_dim on ss_sold_date_sk = d_date_sk
     join store on ss_store_sk = s_store_sk
     join item on ss_item_sk = i_item_sk
where d_year = 2000
group by rollup (i_item_id, s_state)
order by i_item_id, s_state
limit 100
"""
