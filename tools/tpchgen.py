"""tpchgen-lite: numpy TPC-H data generator.

Approximates dbgen's distributions (dense keys instead of sparse, simplified
comment text) — correctness tests validate against a pandas oracle over the
SAME generated data, so exact dbgen fidelity is unnecessary; what matters is
realistic cardinalities, value ranges, and the derived-column rules (return
flags, statuses, date chains) that the queries' predicates exercise.
"""

from __future__ import annotations

import numpy as np

from cloudberry_tpu import types as T
from cloudberry_tpu.types import Schema, date_to_days

_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
_INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
_CONTAINERS = [f"{a} {b}" for a in ["SM", "LG", "MED", "JUMBO", "WRAP"]
               for b in ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]]
_TYPE_1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
_TYPE_2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
_TYPE_3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
_P_NAMES = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
            "black", "blanched", "blue", "blush", "brown", "burlywood",
            "burnished", "chartreuse", "chiffon", "chocolate", "coral",
            "cornflower", "cornsilk", "cream", "cyan", "dark", "deep", "dim",
            "dodger", "drab", "firebrick", "floral", "forest", "frosted",
            "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
            "hot", "hotpink", "indian", "ivory", "khaki", "lace", "lavender",
            "lawn", "lemon", "light", "lime", "linen", "magenta", "maroon",
            "medium", "metallic", "midnight", "mint", "misty", "moccasin",
            "navajo", "navy", "olive", "orange", "orchid", "pale", "papaya",
            "peach", "peru", "pink", "plum", "powder", "puff", "purple",
            "red", "rose", "rosy", "royal", "saddle", "salmon", "sandy",
            "seashell", "sienna", "sky", "slate", "smoke", "snow", "spring",
            "steel", "tan", "thistle", "tomato", "turquoise", "violet",
            "wheat", "white", "yellow"]
_NATIONS = [("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
            ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
            ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
            ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
            ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
            ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
            ("UNITED KINGDOM", 3), ("UNITED STATES", 1)]
_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_WORDS = ["carefully", "quickly", "furiously", "slyly", "blithely", "ironic",
          "final", "special", "pending", "regular", "express", "bold",
          "even", "silent", "daring", "unusual", "packages", "deposits",
          "requests", "accounts", "theodolites", "instructions", "platelets",
          "foxes", "ideas", "dependencies", "pinto beans", "warhorses"]

D = date_to_days


def _comments(rng, n, nwords=4):
    idx = rng.integers(0, len(_WORDS), size=(n, nwords))
    w = np.asarray(_WORDS, dtype=object)
    out = w[idx[:, 0]]
    for k in range(1, nwords):
        out = out + " " + w[idx[:, k]]
    return out


def _dec(rng, lo, hi, n):
    """decimal(2) values in [lo, hi] as float (encode_column rescales)."""
    return rng.integers(int(lo * 100), int(hi * 100) + 1, n) / 100.0


SCHEMAS: dict[str, Schema] = {
    "region": Schema.of(r_regionkey=T.INT64, r_name=T.STRING,
                        r_comment=T.STRING),
    "nation": Schema.of(n_nationkey=T.INT64, n_name=T.STRING,
                        n_regionkey=T.INT64, n_comment=T.STRING),
    "supplier": Schema.of(s_suppkey=T.INT64, s_name=T.STRING,
                          s_address=T.STRING, s_nationkey=T.INT64,
                          s_phone=T.STRING, s_acctbal=T.DECIMAL(2),
                          s_comment=T.STRING),
    "customer": Schema.of(c_custkey=T.INT64, c_name=T.STRING,
                          c_address=T.STRING, c_nationkey=T.INT64,
                          c_phone=T.STRING, c_acctbal=T.DECIMAL(2),
                          c_mktsegment=T.STRING, c_comment=T.STRING),
    "part": Schema.of(p_partkey=T.INT64, p_name=T.STRING, p_mfgr=T.STRING,
                      p_brand=T.STRING, p_type=T.STRING, p_size=T.INT32,
                      p_container=T.STRING, p_retailprice=T.DECIMAL(2),
                      p_comment=T.STRING),
    "partsupp": Schema.of(ps_partkey=T.INT64, ps_suppkey=T.INT64,
                          ps_availqty=T.INT32, ps_supplycost=T.DECIMAL(2),
                          ps_comment=T.STRING),
    "orders": Schema.of(o_orderkey=T.INT64, o_custkey=T.INT64,
                        o_orderstatus=T.STRING, o_totalprice=T.DECIMAL(2),
                        o_orderdate=T.DATE, o_orderpriority=T.STRING,
                        o_clerk=T.STRING, o_shippriority=T.INT32,
                        o_comment=T.STRING),
    "lineitem": Schema.of(l_orderkey=T.INT64, l_partkey=T.INT64,
                          l_suppkey=T.INT64, l_linenumber=T.INT32,
                          l_quantity=T.DECIMAL(2),
                          l_extendedprice=T.DECIMAL(2),
                          l_discount=T.DECIMAL(2), l_tax=T.DECIMAL(2),
                          l_returnflag=T.STRING, l_linestatus=T.STRING,
                          l_shipdate=T.DATE, l_commitdate=T.DATE,
                          l_receiptdate=T.DATE, l_shipinstruct=T.STRING,
                          l_shipmode=T.STRING, l_comment=T.STRING),
}

DIST_KEYS = {
    "region": None, "nation": None,           # replicated
    "supplier": ("s_suppkey",), "customer": ("c_custkey",),
    "part": ("p_partkey",), "partsupp": ("ps_partkey",),
    "orders": ("o_orderkey",), "lineitem": ("l_orderkey",),
}


def generate(sf: float = 0.01, seed: int = 0) -> dict[str, dict[str, np.ndarray]]:
    rng = np.random.default_rng(seed)
    n_supp = max(int(10_000 * sf), 10)
    n_cust = max(int(150_000 * sf), 30)
    n_part = max(int(200_000 * sf), 40)
    n_ord = max(int(1_500_000 * sf), 150)

    data: dict[str, dict[str, np.ndarray]] = {}

    data["region"] = {
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": np.asarray(_REGIONS, dtype=object),
        "r_comment": _comments(rng, 5),
    }
    data["nation"] = {
        "n_nationkey": np.arange(25, dtype=np.int64),
        "n_name": np.asarray([n for n, _ in _NATIONS], dtype=object),
        "n_regionkey": np.asarray([r for _, r in _NATIONS], dtype=np.int64),
        "n_comment": _comments(rng, 25),
    }
    sk = np.arange(1, n_supp + 1, dtype=np.int64)
    data["supplier"] = {
        "s_suppkey": sk,
        "s_name": np.asarray([f"Supplier#{i:09d}" for i in sk], dtype=object),
        "s_address": _comments(rng, n_supp, 2),
        "s_nationkey": rng.integers(0, 25, n_supp).astype(np.int64),
        "s_phone": np.asarray([f"{rng.integers(10,35)}-{i%1000:03d}-{i%10000:04d}"
                               for i in sk], dtype=object),
        "s_acctbal": _dec(rng, -999.99, 9999.99, n_supp),
        "s_comment": _comments(rng, n_supp),
    }
    ck = np.arange(1, n_cust + 1, dtype=np.int64)
    data["customer"] = {
        "c_custkey": ck,
        "c_name": np.asarray([f"Customer#{i:09d}" for i in ck], dtype=object),
        "c_address": _comments(rng, n_cust, 2),
        "c_nationkey": rng.integers(0, 25, n_cust).astype(np.int64),
        "c_phone": np.asarray([f"{10 + i % 25}-{i%1000:03d}-{i%10000:04d}"
                               for i in ck], dtype=object),
        "c_acctbal": _dec(rng, -999.99, 9999.99, n_cust),
        "c_mktsegment": np.asarray(_SEGMENTS, dtype=object)[
            rng.integers(0, 5, n_cust)],
        "c_comment": _comments(rng, n_cust),
    }
    pk = np.arange(1, n_part + 1, dtype=np.int64)
    nm1 = np.asarray(_P_NAMES, dtype=object)
    p_name = (nm1[rng.integers(0, len(_P_NAMES), n_part)] + " "
              + nm1[rng.integers(0, len(_P_NAMES), n_part)] + " "
              + nm1[rng.integers(0, len(_P_NAMES), n_part)])
    mfgr = rng.integers(1, 6, n_part)
    brand = mfgr * 10 + rng.integers(1, 6, n_part)
    t1 = np.asarray(_TYPE_1, dtype=object)[rng.integers(0, 6, n_part)]
    t2 = np.asarray(_TYPE_2, dtype=object)[rng.integers(0, 5, n_part)]
    t3 = np.asarray(_TYPE_3, dtype=object)[rng.integers(0, 5, n_part)]
    data["part"] = {
        "p_partkey": pk,
        "p_name": p_name,
        "p_mfgr": np.asarray([f"Manufacturer#{m}" for m in mfgr], dtype=object),
        "p_brand": np.asarray([f"Brand#{b}" for b in brand], dtype=object),
        "p_type": t1 + " " + t2 + " " + t3,
        "p_size": rng.integers(1, 51, n_part).astype(np.int32),
        "p_container": np.asarray(_CONTAINERS, dtype=object)[
            rng.integers(0, len(_CONTAINERS), n_part)],
        "p_retailprice": (90000 + (pk % 20001) + 100 * (pk % 1000)) / 100.0,
        "p_comment": _comments(rng, n_part, 2),
    }
    ps_pk = np.repeat(pk, 4)
    n_ps = len(ps_pk)
    ps_sk = ((ps_pk + (np.tile(np.arange(4), n_part)
                       * (n_supp // 4 + 1))) % n_supp) + 1
    data["partsupp"] = {
        "ps_partkey": ps_pk,
        "ps_suppkey": ps_sk.astype(np.int64),
        "ps_availqty": rng.integers(1, 10_000, n_ps).astype(np.int32),
        "ps_supplycost": _dec(rng, 1.00, 1000.00, n_ps),
        "ps_comment": _comments(rng, n_ps),
    }

    ok = np.arange(1, n_ord + 1, dtype=np.int64)
    # dbgen rule: customers with custkey % 3 == 0 place no orders — keeps
    # anti-join queries (Q13 zero-order bucket, Q22 NOT EXISTS) non-vacuous
    cust_pool = np.asarray([k for k in range(1, n_cust + 1) if k % 3 != 0],
                           dtype=np.int64)
    o_custkey = cust_pool[rng.integers(0, len(cust_pool), n_ord)]
    start, end = D("1992-01-01"), D("1998-08-02")
    o_orderdate = rng.integers(start, end + 1, n_ord).astype(np.int64)
    n_lines_per = rng.integers(1, 8, n_ord)
    l_ok = np.repeat(ok, n_lines_per)
    n_li = len(l_ok)
    l_odate = np.repeat(o_orderdate, n_lines_per)
    l_shipdate = l_odate + rng.integers(1, 122, n_li)
    l_commitdate = l_odate + rng.integers(30, 91, n_li)
    l_receiptdate = l_shipdate + rng.integers(1, 31, n_li)
    current = D("1995-06-17")
    returnflag = np.where(
        l_receiptdate <= current,
        np.where(rng.random(n_li) < 0.5, "R", "A"), "N").astype(object)
    linestatus = np.where(l_shipdate > current, "O", "F").astype(object)
    l_qty = rng.integers(1, 51, n_li).astype(np.float64)
    l_pk = rng.integers(1, n_part + 1, n_li).astype(np.int64)
    # supplier chosen among the part's 4 partsupp suppliers
    which = rng.integers(0, 4, n_li)
    l_sk = ((l_pk + which * (n_supp // 4 + 1)) % n_supp) + 1
    retail = (90000 + (l_pk % 20001) + 100 * (l_pk % 1000)) / 100.0
    l_price = np.round(l_qty * retail, 2)

    o_status = np.full(n_ord, "P", dtype=object)
    all_f = np.ones(n_ord, dtype=bool)
    any_f = np.zeros(n_ord, dtype=bool)
    np.logical_and.at(all_f, l_ok - 1, linestatus == "F")
    np.logical_or.at(any_f, l_ok - 1, linestatus == "F")
    o_status[all_f] = "F"
    o_status[~any_f] = "O"

    o_total = np.zeros(n_ord)
    np.add.at(o_total, l_ok - 1, l_price)
    data["orders"] = {
        "o_orderkey": ok,
        "o_custkey": o_custkey,
        "o_orderstatus": o_status,
        "o_totalprice": np.round(o_total, 2),
        "o_orderdate": o_orderdate.astype(np.int64),
        "o_orderpriority": np.asarray(_PRIORITIES, dtype=object)[
            rng.integers(0, 5, n_ord)],
        "o_clerk": np.asarray(
            [f"Clerk#{i:09d}" for i in rng.integers(1, max(n_ord // 1000, 2),
                                                    n_ord)], dtype=object),
        "o_shippriority": np.zeros(n_ord, dtype=np.int32),
        "o_comment": _comments(rng, n_ord),
    }
    lineno = np.concatenate([np.arange(1, k + 1) for k in n_lines_per])
    data["lineitem"] = {
        "l_orderkey": l_ok,
        "l_partkey": l_pk,
        "l_suppkey": l_sk.astype(np.int64),
        "l_linenumber": lineno.astype(np.int32),
        "l_quantity": l_qty,
        "l_extendedprice": l_price,
        "l_discount": _dec(rng, 0.00, 0.10, n_li),
        "l_tax": _dec(rng, 0.00, 0.08, n_li),
        "l_returnflag": returnflag,
        "l_linestatus": linestatus,
        "l_shipdate": l_shipdate.astype(np.int64),
        "l_commitdate": l_commitdate.astype(np.int64),
        "l_receiptdate": l_receiptdate.astype(np.int64),
        "l_shipinstruct": np.asarray(_INSTRUCTS, dtype=object)[
            rng.integers(0, 4, n_li)],
        "l_shipmode": np.asarray(_SHIPMODES, dtype=object)[
            rng.integers(0, 7, n_li)],
        "l_comment": _comments(rng, n_li, 2),
    }
    return data


def load_tables(session, schemas, dist_keys, raw,
                only: list[str] | None = None) -> None:
    """Create + populate benchmark tables (shared by tpch/tpcds loaders)."""
    from cloudberry_tpu.catalog.catalog import DistributionPolicy
    from cloudberry_tpu.columnar.batch import encode_column

    for name, schema in schemas.items():
        if only is not None and name not in only:
            continue
        keys = dist_keys[name]
        policy = (DistributionPolicy.replicated() if keys is None
                  else DistributionPolicy.hashed(*keys))
        t = session.catalog.create_table(name, schema, policy)
        encoded = {}
        for f in schema.fields:
            encoded[f.name] = encode_column(raw[name][f.name], f, t.dicts)
        t.set_data(encoded, t.dicts)


def load_tpch(session, sf: float = 0.01, seed: int = 0,
              tables: list[str] | None = None) -> None:
    """Create + populate TPC-H tables in a session's catalog."""
    load_tables(session, SCHEMAS, DIST_KEYS, generate(sf, seed), tables)


# ------------------------------------------------------ streaming loader
# SF10-class generation cannot materialize whole tables (60M lineitem
# rows) in RAM: the streaming loader below generates KEY-RANGE CHUNKS
# and appends each straight into micro-partition files — the
# generator-as-table-scan path of ROADMAP item 1. Distributions mirror
# generate() (same ranges, same derived-column rules, statuses/totals
# derived from each chunk's own lineitems) but RNG streams are
# per-chunk, so the dataset is self-consistent without being byte-equal
# to the non-streaming generator — correctness tests always compare the
# engine against an oracle over the SAME data, so that is the contract
# that matters.

_TBL_ID = {"region": 0, "nation": 1, "supplier": 2, "customer": 3,
           "part": 4, "partsupp": 5, "orders": 6}


def _crng(seed: int, table: str, chunk: int):
    return np.random.default_rng([seed, 0xC8, _TBL_ID[table], chunk])


def _sizes(sf: float) -> dict:
    return {"n_supp": max(int(10_000 * sf), 10),
            "n_cust": max(int(150_000 * sf), 30),
            "n_part": max(int(200_000 * sf), 40),
            "n_ord": max(int(1_500_000 * sf), 150)}


def _tag(prefix: str, arr) -> np.ndarray:
    """Vectorized 'Name#000000123' formatting (np.char beats a Python
    f-string loop ~20× — the loader's inner strings must keep up with
    the chunked writer)."""
    return np.char.mod(prefix + "#%09d", arr).astype(object)


def _phone(keys: np.ndarray, lead) -> np.ndarray:
    a = np.char.mod("%d", lead)
    b = np.char.mod("-%03d", keys % 1000)
    c = np.char.mod("-%04d", keys % 10000)
    return np.char.add(np.char.add(a, b), c).astype(object)


def _supplier_chunk(rng, lo, hi):
    sk = np.arange(lo + 1, hi + 1, dtype=np.int64)
    n = len(sk)
    return {"s_suppkey": sk, "s_name": _tag("Supplier", sk),
            "s_address": _comments(rng, n, 2),
            "s_nationkey": rng.integers(0, 25, n).astype(np.int64),
            "s_phone": _phone(sk, rng.integers(10, 35, n)),
            "s_acctbal": _dec(rng, -999.99, 9999.99, n),
            "s_comment": _comments(rng, n)}


def _customer_chunk(rng, lo, hi):
    ck = np.arange(lo + 1, hi + 1, dtype=np.int64)
    n = len(ck)
    return {"c_custkey": ck, "c_name": _tag("Customer", ck),
            "c_address": _comments(rng, n, 2),
            "c_nationkey": rng.integers(0, 25, n).astype(np.int64),
            "c_phone": _phone(ck, 10 + ck % 25),
            "c_acctbal": _dec(rng, -999.99, 9999.99, n),
            "c_mktsegment": np.asarray(_SEGMENTS, dtype=object)[
                rng.integers(0, 5, n)],
            "c_comment": _comments(rng, n)}


def _part_chunk(rng, lo, hi):
    pk = np.arange(lo + 1, hi + 1, dtype=np.int64)
    n = len(pk)
    nm1 = np.asarray(_P_NAMES, dtype=object)
    p_name = (nm1[rng.integers(0, len(_P_NAMES), n)] + " "
              + nm1[rng.integers(0, len(_P_NAMES), n)] + " "
              + nm1[rng.integers(0, len(_P_NAMES), n)])
    mfgr = rng.integers(1, 6, n)
    t1 = np.asarray(_TYPE_1, dtype=object)[rng.integers(0, 6, n)]
    t2 = np.asarray(_TYPE_2, dtype=object)[rng.integers(0, 5, n)]
    t3 = np.asarray(_TYPE_3, dtype=object)[rng.integers(0, 5, n)]
    return {"p_partkey": pk, "p_name": p_name,
            "p_mfgr": np.char.mod("Manufacturer#%d", mfgr).astype(object),
            "p_brand": np.char.mod(
                "Brand#%d", mfgr * 10 + rng.integers(1, 6, n))
            .astype(object),
            "p_type": t1 + " " + t2 + " " + t3,
            "p_size": rng.integers(1, 51, n).astype(np.int32),
            "p_container": np.asarray(_CONTAINERS, dtype=object)[
                rng.integers(0, len(_CONTAINERS), n)],
            "p_retailprice": (90000 + (pk % 20001)
                              + 100 * (pk % 1000)) / 100.0,
            "p_comment": _comments(rng, n, 2)}


def _partsupp_chunk(rng, lo, hi, n_supp):
    pk = np.arange(lo + 1, hi + 1, dtype=np.int64)
    n = len(pk)
    ps_pk = np.repeat(pk, 4)
    n_ps = len(ps_pk)
    ps_sk = ((ps_pk + (np.tile(np.arange(4), n)
                       * (n_supp // 4 + 1))) % n_supp) + 1
    return {"ps_partkey": ps_pk, "ps_suppkey": ps_sk.astype(np.int64),
            "ps_availqty": rng.integers(1, 10_000, n_ps).astype(np.int32),
            "ps_supplycost": _dec(rng, 1.00, 1000.00, n_ps),
            "ps_comment": _comments(rng, n_ps)}


def _orders_lineitem_chunk(rng, lo, hi, sz):
    """One order-key-range chunk of orders AND its lineitems: statuses,
    totals and date chains derive from the chunk's own rows, so every
    chunk is independently self-consistent."""
    ok = np.arange(lo + 1, hi + 1, dtype=np.int64)
    n_ord = len(ok)
    # custkey % 3 == 0 places no orders (the dbgen rule): index the
    # non-multiples-of-3 sequence directly — no pool materialization
    pool = sz["n_cust"] - sz["n_cust"] // 3
    idx = rng.integers(0, pool, n_ord)
    o_custkey = 3 * (idx // 2) + 1 + (idx % 2)
    start, end = D("1992-01-01"), D("1998-08-02")
    o_orderdate = rng.integers(start, end + 1, n_ord).astype(np.int64)
    n_lines_per = rng.integers(1, 8, n_ord)
    l_ok = np.repeat(ok, n_lines_per)
    n_li = len(l_ok)
    l_odate = np.repeat(o_orderdate, n_lines_per)
    l_shipdate = l_odate + rng.integers(1, 122, n_li)
    l_commitdate = l_odate + rng.integers(30, 91, n_li)
    l_receiptdate = l_shipdate + rng.integers(1, 31, n_li)
    current = D("1995-06-17")
    returnflag = np.where(
        l_receiptdate <= current,
        np.where(rng.random(n_li) < 0.5, "R", "A"), "N").astype(object)
    linestatus = np.where(l_shipdate > current, "O", "F").astype(object)
    l_qty = rng.integers(1, 51, n_li).astype(np.float64)
    l_pk = rng.integers(1, sz["n_part"] + 1, n_li).astype(np.int64)
    which = rng.integers(0, 4, n_li)
    l_sk = ((l_pk + which * (sz["n_supp"] // 4 + 1)) % sz["n_supp"]) + 1
    retail = (90000 + (l_pk % 20001) + 100 * (l_pk % 1000)) / 100.0
    l_price = np.round(l_qty * retail, 2)

    base = l_ok - ok[0]  # chunk-local order index
    o_status = np.full(n_ord, "P", dtype=object)
    all_f = np.ones(n_ord, dtype=bool)
    any_f = np.zeros(n_ord, dtype=bool)
    np.logical_and.at(all_f, base, linestatus == "F")
    np.logical_or.at(any_f, base, linestatus == "F")
    o_status[all_f] = "F"
    o_status[~any_f] = "O"
    o_total = np.zeros(n_ord)
    np.add.at(o_total, base, l_price)

    orders = {
        "o_orderkey": ok, "o_custkey": o_custkey,
        "o_orderstatus": o_status,
        "o_totalprice": np.round(o_total, 2),
        "o_orderdate": o_orderdate,
        "o_orderpriority": np.asarray(_PRIORITIES, dtype=object)[
            rng.integers(0, 5, n_ord)],
        "o_clerk": _tag("Clerk", rng.integers(
            1, max(sz["n_ord"] // 1000, 2), n_ord)),
        "o_shippriority": np.zeros(n_ord, dtype=np.int32),
        "o_comment": _comments(rng, n_ord),
    }
    lineno = (np.arange(n_li)
              - np.repeat(np.cumsum(n_lines_per) - n_lines_per,
                          n_lines_per) + 1)
    lineitem = {
        "l_orderkey": l_ok, "l_partkey": l_pk,
        "l_suppkey": l_sk.astype(np.int64),
        "l_linenumber": lineno.astype(np.int32),
        "l_quantity": l_qty, "l_extendedprice": l_price,
        "l_discount": _dec(rng, 0.00, 0.10, n_li),
        "l_tax": _dec(rng, 0.00, 0.08, n_li),
        "l_returnflag": returnflag, "l_linestatus": linestatus,
        "l_shipdate": l_shipdate.astype(np.int64),
        "l_commitdate": l_commitdate.astype(np.int64),
        "l_receiptdate": l_receiptdate.astype(np.int64),
        "l_shipinstruct": np.asarray(_INSTRUCTS, dtype=object)[
            rng.integers(0, 4, n_li)],
        "l_shipmode": np.asarray(_SHIPMODES, dtype=object)[
            rng.integers(0, 7, n_li)],
        "l_comment": _comments(rng, n_li, 2),
    }
    return orders, lineitem


def stream_load_tpch(session, sf: float = 1.0, seed: int = 0,
                     tables: list[str] | None = None,
                     chunk_rows: int = 1_000_000,
                     workers: int = 2) -> dict:
    """Partition-parallel streaming TPC-H loader: key-range chunks are
    generated on a small worker pool (chunk k+1 generates while chunk k
    encodes and writes) and appended STRAIGHT into micro-partition
    files — no whole-SF table ever materializes in host RAM, which is
    what makes SF10+ loadable on a laptop-class host. Requires a
    store-backed session (``config.storage.root``); tables land COLD
    (the next statement's scan streams the files). Returns per-table
    row counts.

    Caveat: at big SF the unique-string columns (c_name/c_phone) grow
    the table dictionary with table size — pass ``tables`` to load only
    what the workload scans (the scan ladder needs lineitem/orders)
    until first-class varlen strings land (ROADMAP item 4)."""
    from concurrent.futures import ThreadPoolExecutor

    from cloudberry_tpu.catalog.catalog import DistributionPolicy
    from cloudberry_tpu.columnar.batch import encode_column

    store = session.catalog.store
    if store is None:
        raise ValueError("stream_load_tpch needs config.storage.root")
    sz = _sizes(sf)
    want = list(tables) if tables is not None else list(SCHEMAS)
    rpp = session.config.storage.rows_per_partition
    counts: dict[str, int] = {}
    first: set[str] = set(want)
    dicts_by_table: dict[str, dict] = {t: {} for t in SCHEMAS}

    def _append(name: str, raw: dict) -> None:
        if name not in want:
            return
        schema = SCHEMAS[name]
        dicts = dicts_by_table[name]
        enc = {f.name: encode_column(np.asarray(raw[f.name]), f, dicts)
               for f in schema.fields}
        keys = DIST_KEYS[name]
        policy = (DistributionPolicy.replicated() if keys is None
                  else DistributionPolicy.hashed(*keys))
        store.append(name, enc, schema, dicts=dicts,
                     rows_per_partition=rpp, policy=policy,
                     replace=name in first)
        first.discard(name)
        counts[name] = counts.get(name, 0)
        counts[name] += len(next(iter(enc.values()))) if enc else 0

    def _ranges(total: int, step: int):
        return [(lo, min(lo + step, total))
                for lo in range(0, total, step)]

    if {"region", "nation"} & set(want):
        rng = _crng(seed, "region", 0)
        _append("region", {
            "r_regionkey": np.arange(5, dtype=np.int64),
            "r_name": np.asarray(_REGIONS, dtype=object),
            "r_comment": _comments(rng, 5)})
        rng = _crng(seed, "nation", 0)
        _append("nation", {
            "n_nationkey": np.arange(25, dtype=np.int64),
            "n_name": np.asarray([n for n, _ in _NATIONS], dtype=object),
            "n_regionkey": np.asarray([r for _, r in _NATIONS],
                                      dtype=np.int64),
            "n_comment": _comments(rng, 25)})

    jobs = []  # (table, chunk_fn(chunk_idx) -> {name: raw})
    if "supplier" in want:
        jobs += [("supplier", i, lo, hi) for i, (lo, hi) in
                 enumerate(_ranges(sz["n_supp"], chunk_rows))]
    if "customer" in want:
        jobs += [("customer", i, lo, hi) for i, (lo, hi) in
                 enumerate(_ranges(sz["n_cust"], chunk_rows))]
    if "part" in want:
        jobs += [("part", i, lo, hi) for i, (lo, hi) in
                 enumerate(_ranges(sz["n_part"], chunk_rows))]
    if "partsupp" in want:
        jobs += [("partsupp", i, lo, hi) for i, (lo, hi) in
                 enumerate(_ranges(sz["n_part"], max(chunk_rows // 4,
                                                     1)))]
    if {"orders", "lineitem"} & set(want):
        step = max(chunk_rows // 4, 1)  # ~4 lineitems per order
        jobs += [("orders", i, lo, hi) for i, (lo, hi) in
                 enumerate(_ranges(sz["n_ord"], step))]

    def _gen(job):
        table, i, lo, hi = job
        rng = _crng(seed, table, i)
        if table == "supplier":
            return {"supplier": _supplier_chunk(rng, lo, hi)}
        if table == "customer":
            return {"customer": _customer_chunk(rng, lo, hi)}
        if table == "part":
            return {"part": _part_chunk(rng, lo, hi)}
        if table == "partsupp":
            return {"partsupp": _partsupp_chunk(rng, lo, hi,
                                                sz["n_supp"])}
        orders, lineitem = _orders_lineitem_chunk(rng, lo, hi, sz)
        return {"orders": orders, "lineitem": lineitem}

    # the pipeline shape: workers generate ahead, the main thread owns
    # encode + append (dictionary growth and manifest commits stay
    # single-threaded — OCC discipline without cross-thread locks)
    with ThreadPoolExecutor(max_workers=max(int(workers), 1)) as pool:
        ahead = max(int(workers), 1) + 1
        pending = []
        for job in jobs:
            pending.append(pool.submit(_gen, job))
            if len(pending) >= ahead:
                for name, raw in pending.pop(0).result().items():
                    _append(name, raw)
        for fut in pending:
            for name, raw in fut.result().items():
                _append(name, raw)

    session._sync_store()
    return counts
