"""Standalone relational-kernel benchmark — per-primitive timing.

The reference benchmarks its executor primitives outside the engine
(contrib/pax_storage's pax_gbench.cc, ic_bench.c for the transport); this
is the same stance for the TPU kernels in exec/kernels.py: time each hot
primitive — sorted-build lookup join (u64 and stats-proven u32 packing),
many-to-many expansion, sort-based grouped aggregation, sort — on whatever
backend is live (real TPU under the terminal default, CPU with
JAX_PLATFORMS=cpu), one JSON line per measurement.

Usage:
  python -m tools.kernel_bench [--build N] [--probe N] [--reps R]
  python -m tools.kernel_bench grouped-agg [--rows N] [--ladder LO,HI]
      [--reps R] [--interpret] [--csv PATH]

``grouped-agg`` sweeps a group-cardinality ladder (2^LO … 2^HI, default
2^4 … 2^20) through BOTH grouped-aggregation strategies — the XLA sort
path (kernels.group_aggregate) and the fused sorted-segment Pallas
kernel (pallas_kernels.sorted_segment_aggregate) — so the XLA-vs-Pallas
crossover is measured, not guessed. ``--interpret`` runs the Pallas side
in interpreter mode so the sweep smoke-runs on CPU without hardware.
"""

from __future__ import annotations

import argparse
import json
import os
import time


def _setup_jax():
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # sitecustomize presets the axon relay before this script runs;
        # re-assert the requested platform (tests/conftest.py note)
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    jax.config.update("jax_enable_x64", True)
    return jax


def _bench_loop(jax, fn, *xs, reps: int):
    out = jax.block_until_ready(fn(*xs))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.time()
        out = jax.block_until_ready(fn(*xs))
        best = min(best, time.time() - t0)
    return best, out


def grouped_agg_sweep(args) -> None:
    """Cardinality ladder for grouped aggregation, one JSON line (and
    optional CSV row) per (groups, strategy) point."""
    jax = _setup_jax()
    import functools

    import jax.numpy as jnp
    import numpy as np

    from cloudberry_tpu.exec import kernels as K
    from cloudberry_tpu.exec import pallas_kernels as PK

    try:
        lo, hi = (int(x) for x in args.ladder.split(","))
        assert lo <= hi
    except (ValueError, AssertionError):
        raise SystemExit(
            f"--ladder must be LO,HI with LO <= HI (got {args.ladder!r})")
    dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    n = args.rows
    specs = [K.AggSpec("sum", "s"), K.AggSpec("count", "c")]
    v = jnp.asarray(rng.integers(-10**12, 10**12, n))
    sel = jnp.ones(n, bool)
    rows_out = []
    for lg in range(lo, hi + 1, args.step):
        groups = 1 << lg
        keys = jnp.asarray(rng.integers(0, groups, n).astype(np.int64))
        cap = min(max(2 * groups, 1024), max(n, 1024))

        def make_fn(agg_fn):
            # specs/cap close over the trace: AggSpec is static config,
            # not a traced argument
            @jax.jit
            def f(k, vv, s):
                return agg_fn({"k": k}, {"s": vv, "c": None}, specs, s)
            return f

        strategies = {
            "xla_sort": make_fn(functools.partial(
                K.group_aggregate, out_capacity=cap)),
            "pallas_sorted_segment": make_fn(functools.partial(
                PK.sorted_segment_aggregate, out_capacity=cap,
                interpret=args.interpret)),
        }
        for name, fn in strategies.items():
            best, _ = _bench_loop(jax, fn, keys, v, sel, reps=args.reps)
            rec = {
                "kernel": "grouped_agg", "strategy": name,
                "groups": groups, "rows": n, "device": str(dev),
                "interpret": bool(args.interpret),
                "wall_ms": round(best * 1e3, 2),
                "mrows_per_s": round(n / best / 1e6, 1),
            }
            rows_out.append(rec)
            print(json.dumps(rec), flush=True)
    if args.csv:
        import csv

        with open(args.csv, "w", newline="") as fh:
            w = csv.DictWriter(fh, fieldnames=list(rows_out[0]))
            w.writeheader()
            w.writerows(rows_out)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", nargs="?", default="primitives",
                    choices=["primitives", "grouped-agg"])
    ap.add_argument("--build", type=int, default=1_500_000)
    ap.add_argument("--probe", type=int, default=6_000_000)
    ap.add_argument("--groups", type=int, default=4_000_000)
    ap.add_argument("--reps", type=int, default=3)
    # grouped-agg sweep knobs
    ap.add_argument("--rows", type=int, default=2_000_000,
                    help="grouped-agg: rows per measurement")
    ap.add_argument("--ladder", default="4,20",
                    help="grouped-agg: log2 group-count range LO,HI")
    ap.add_argument("--step", type=int, default=2,
                    help="grouped-agg: log2 ladder stride")
    ap.add_argument("--interpret", action="store_true",
                    help="grouped-agg: Pallas interpret mode (CPU smoke)")
    ap.add_argument("--csv", default=None,
                    help="grouped-agg: also write a CSV table here")
    args = ap.parse_args()

    if args.mode == "grouped-agg":
        grouped_agg_sweep(args)
        return

    jax = _setup_jax()

    import jax.numpy as jnp
    import numpy as np

    from cloudberry_tpu.exec import kernels as K

    dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    NB, NP = args.build, args.probe

    def bench(label, fn, *xs, rows):
        best, out = _bench_loop(jax, fn, *xs, reps=args.reps)
        print(json.dumps({
            "kernel": label, "rows": rows, "device": str(dev),
            "wall_ms": round(best * 1e3, 2),
            "mrows_per_s": round(rows / best / 1e6, 1),
        }), flush=True)
        return out

    bk = jnp.asarray(rng.permutation(NB).astype(np.int64))
    bs = jnp.ones(NB, bool)
    pk = jnp.asarray(rng.integers(0, NB, NP).astype(np.int64))
    ps = jnp.ones(NP, bool)

    for bits in (64, 32):
        bench(f"join_lookup_u{bits}",
              jax.jit(lambda b, s, p, q, _bits=bits:
                      K.join_lookup([b], s, [p], q, bits=_bits)),
              bk, bs, pk, ps, rows=NP)

    dup = jnp.asarray(rng.integers(0, NB // 8, NB).astype(np.int64))
    cap = NP + NB
    for bits in (64, 32):
        bench(f"join_expand_u{bits}",
              jax.jit(lambda b, s, p, q, _bits=bits:
                      K.join_expand([b], s, [p], q, cap, bits=_bits)),
              dup, bs, pk, ps, rows=NP)

    gk = jnp.asarray(rng.integers(0, args.groups, NP).astype(np.int64))
    gv = jnp.asarray(rng.integers(0, 1000, NP).astype(np.int64))
    bench("group_aggregate",
          jax.jit(lambda k, v, s: K.group_aggregate(
              {"k": k}, {"s": v, "c": None},
              [K.AggSpec("sum", "s"), K.AggSpec("count", "c")],
              s, args.groups)),
          gk, gv, ps, rows=NP)

    bench("sort_indices",
          jax.jit(lambda k, s: K.sort_indices([k], s)),
          pk, ps, rows=NP)


if __name__ == "__main__":
    main()
