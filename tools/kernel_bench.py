"""Standalone relational-kernel benchmark — per-primitive timing.

The reference benchmarks its executor primitives outside the engine
(contrib/pax_storage's pax_gbench.cc, ic_bench.c for the transport); this
is the same stance for the TPU kernels in exec/kernels.py: time each hot
primitive — sorted-build lookup join (u64 and stats-proven u32 packing),
many-to-many expansion, sort-based grouped aggregation, sort — on whatever
backend is live (real TPU under the terminal default, CPU with
JAX_PLATFORMS=cpu), one JSON line per measurement.

Usage: python -m tools.kernel_bench [--build N] [--probe N] [--reps R]
"""

from __future__ import annotations

import argparse
import json
import os
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--build", type=int, default=1_500_000)
    ap.add_argument("--probe", type=int, default=6_000_000)
    ap.add_argument("--groups", type=int, default=4_000_000)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        # sitecustomize presets the axon relay before this script runs;
        # re-assert the requested platform (tests/conftest.py note)
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    jax.config.update("jax_enable_x64", True)

    import jax.numpy as jnp
    import numpy as np

    from cloudberry_tpu.exec import kernels as K

    dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    NB, NP = args.build, args.probe

    def bench(label, fn, *xs, rows):
        out = jax.block_until_ready(fn(*xs))  # compile + warm
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.time()
            out = jax.block_until_ready(fn(*xs))
            best = min(best, time.time() - t0)
        print(json.dumps({
            "kernel": label, "rows": rows, "device": str(dev),
            "wall_ms": round(best * 1e3, 2),
            "mrows_per_s": round(rows / best / 1e6, 1),
        }), flush=True)
        return out

    bk = jnp.asarray(rng.permutation(NB).astype(np.int64))
    bs = jnp.ones(NB, bool)
    pk = jnp.asarray(rng.integers(0, NB, NP).astype(np.int64))
    ps = jnp.ones(NP, bool)

    for bits in (64, 32):
        bench(f"join_lookup_u{bits}",
              jax.jit(lambda b, s, p, q, _bits=bits:
                      K.join_lookup([b], s, [p], q, bits=_bits)),
              bk, bs, pk, ps, rows=NP)

    dup = jnp.asarray(rng.integers(0, NB // 8, NB).astype(np.int64))
    cap = NP + NB
    for bits in (64, 32):
        bench(f"join_expand_u{bits}",
              jax.jit(lambda b, s, p, q, _bits=bits:
                      K.join_expand([b], s, [p], q, cap, bits=_bits)),
              dup, bs, pk, ps, rows=NP)

    gk = jnp.asarray(rng.integers(0, args.groups, NP).astype(np.int64))
    gv = jnp.asarray(rng.integers(0, 1000, NP).astype(np.int64))
    bench("group_aggregate",
          jax.jit(lambda k, v, s: K.group_aggregate(
              {"k": k}, {"s": v, "c": None},
              [K.AggSpec("sum", "s"), K.AggSpec("count", "c")],
              s, args.groups)),
          gk, gv, ps, rows=NP)

    bench("sort_indices",
          jax.jit(lambda k, s: K.sort_indices([k], s)),
          pk, ps, rows=NP)


if __name__ == "__main__":
    main()
